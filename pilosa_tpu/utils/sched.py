"""Deterministic cooperative scheduler — the interleaving explorer's
runtime (tools/interleave.py is the scenario corpus + CLI over it).

Loom-style model checking for the host-side concurrency planes: a
scenario's threads are real ``threading.Thread``s, but exactly ONE
ever runs at a time — every other worker is parked on a private gate
event, and control round-trips through the scheduler at every lock
acquire/release and condition wait/notify (plus explicit
:func:`checkpoint` calls for unlocked shared access). Because the
scheduler picks who runs at every such yield point, a run is fully
determined by its *schedule* — the sequence of choices — and the
explorer can enumerate or sample schedules deterministically:

- :func:`explore_dfs` walks the schedule tree systematically
  (depth-first, incrementing the deepest incrementable choice) under a
  schedule budget — exhaustive for small scenarios.
- :func:`rng_decider` drives a seeded random walk;
  ``(seed, index)`` reconstructs the exact schedule, the same
  reproducer contract as ``roaring_fuzz``/``plan_fuzz``.
- :func:`schedule_decider` replays a pinned schedule (corpus entries).

The third factory mode: while a :class:`Scheduler` is active (its
``with`` body), ``make_lock``/``make_rlock``/``make_condition`` in
:mod:`pilosa_tpu.utils.locks` return :class:`SchedLock` /
:class:`SchedRLock` / :class:`SchedCondition` instead of the plain or
Debug* primitives, so scenario code exercises REAL pilosa_tpu modules
(ResultCache, LayoutManager, Cluster) with no source changes — lock
construction is already centralized (graftlint GL001), which makes the
factory the natural instrumentation seam.

Lock state is plain Python data (owner / count / waiter lists), not OS
primitives: with one runner at a time there is no data race on it, and
keeping it host-visible is what lets the scheduler compute the
wait-for graph for deadlock detection (no runnable worker + live
blocked workers = deadlock; the report names who waits on what and who
holds it). Operations from threads the scheduler does not manage
(scenario setup/teardown on the controller thread) execute atomically
without yielding.

Timed condition waits are modeled as "eventually": a ``wait(timeout)``
only times out when NOTHING else can run — this keeps
timeout-protected loops live without exploding the schedule space with
spurious-wakeup branches, and a deadlock that a real timeout would
paper over still surfaces as the timed-out wait's return value.
"""

from __future__ import annotations

# graftlint: disable-file=GL001 — like utils/locks.py, this module
# IMPLEMENTS the lock protocol (Sched* wrappers forward
# acquire/release for the factories); the discipline rules apply to
# lock users.

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

__all__ = [
    "DeadlockError",
    "Outcome",
    "SchedCondition",
    "SchedLock",
    "SchedRLock",
    "Scheduler",
    "active_scheduler",
    "checkpoint",
    "explore_dfs",
    "rng_decider",
    "schedule_decider",
]

# decide(step_index, runnable_worker_idxs) -> position in that list.
# The runnable list is sorted by spawn index, so a decider can
# implement priority policies (the sequential oracle) as well as
# positional replay (schedule_decider).
Decider = Callable[[int, Sequence[int]], int]

_CONTROLLER = object()  # owner marker for unmanaged-thread acquisitions

# Hard per-run step ceiling: a scenario spinning without blocking
# (livelock) must terminate the run with a diagnosis, not hang the
# explorer. Generous — corpus scenarios run in tens of steps.
MAX_STEPS = 20_000


class DeadlockError(RuntimeError):
    """No runnable worker while blocked workers remain; the message is
    the wait-for graph."""


class _Abort(BaseException):
    """Injected into parked workers to unwind them after the run is
    over (deadlock, failure, or budget stop). BaseException so scenario
    ``except Exception`` blocks cannot swallow it."""


class _Worker:
    def __init__(self, idx: int, name: str,
                 fn: Callable[[], None]) -> None:
        self.idx = idx
        self.name = name
        self.fn = fn
        self.gate = threading.Event()
        self.thread: Optional[threading.Thread] = None
        self.done = False
        self.exc: Optional[BaseException] = None
        # What this worker is parked on: a lock (waiting for release),
        # a condition (waiting for notify), or None (runnable).
        self.blocked_on: Optional[Union["SchedLock", "SchedCondition"]] = None
        self.waiting_in: Optional["SchedCondition"] = None
        self.timed = False       # the current cond wait has a timeout
        self.timed_out = False   # scheduler fired that timeout

    def __repr__(self) -> str:
        return f"<worker {self.name!r}>"


class Outcome:
    """One run's result: the schedule actually taken (``(choice,
    n_runnable)`` per step — replayable via the choices alone), worker
    errors, and the deadlock report if one was detected."""

    def __init__(self) -> None:
        self.trace: List[Tuple[int, int]] = []
        self.errors: List[str] = []
        self.deadlock: Optional[str] = None
        self.steps = 0

    @property
    def schedule(self) -> List[int]:
        return [c for c, _ in self.trace]

    @property
    def failed(self) -> bool:
        return bool(self.errors) or self.deadlock is not None

    def __repr__(self) -> str:
        return (f"<Outcome steps={self.steps} errors={len(self.errors)} "
                f"deadlock={self.deadlock is not None}>")


_ACTIVE: Optional["Scheduler"] = None


def active_scheduler() -> Optional["Scheduler"]:
    """The scheduler the ``make_*`` lock factories should instrument
    for, or None (normal operation)."""
    return _ACTIVE


def checkpoint() -> None:
    """Explicit yield point for UNLOCKED shared access: scenario code
    calls this between a racy read and its dependent use so the
    explorer can interleave there. No-op outside a scheduler run (and
    for threads the scheduler does not manage)."""
    sch = _ACTIVE
    if sch is None:
        return
    w = sch._worker_for_current()
    if w is not None:
        sch._switch_from(w)


class Scheduler:
    """One exploration run: activate (``with``), build scenario state
    (its ``make_*`` locks become Sched* wrappers), :meth:`spawn` the
    workers, :meth:`run`, read :attr:`outcome`."""

    def __init__(self, decide: Decider,
                 max_steps: int = MAX_STEPS) -> None:
        self._decide = decide
        self._max_steps = max_steps
        self._workers: List[_Worker] = []
        self._by_ident: Dict[int, _Worker] = {}
        self._main_gate = threading.Event()
        self._aborting = False
        self.outcome = Outcome()

    # ------------------------------------------------------ activation

    def __enter__(self) -> "Scheduler":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a Scheduler is already active")
        _ACTIVE = self
        return self

    def __exit__(self, *exc: object) -> None:
        global _ACTIVE
        _ACTIVE = None

    # --------------------------------------------------------- workers

    def spawn(self, name: str, fn: Callable[[], None]) -> None:
        if self._by_ident:
            raise RuntimeError("spawn() after run()")
        # graftlint: disable=GL008 — a Scheduler lives for ONE run;
        # workers are bounded by the scenario's spawn count.
        self._workers.append(_Worker(len(self._workers), name, fn))

    def _worker_main(self, w: _Worker) -> None:
        # graftlint: disable=GL008 — one entry per spawned worker,
        # per single-run Scheduler.
        self._by_ident[threading.get_ident()] = w
        w.gate.wait()
        w.gate.clear()
        try:
            if not self._aborting:
                w.fn()
        except _Abort:
            pass
        except BaseException as e:  # noqa: BLE001 — reported, not lost
            w.exc = e
        finally:
            w.done = True
            self._main_gate.set()

    def _worker_for_current(self) -> Optional[_Worker]:
        return self._by_ident.get(threading.get_ident())

    # --------------------------------------------- worker-side switches

    def _switch_from(self, w: _Worker) -> None:
        """Hand the run token back to the scheduler; returns when this
        worker is next scheduled. Called on the WORKER's thread."""
        if self._aborting:
            # The run is over; do not wait for a schedule slot that
            # will never come (an aborting worker unwinds through
            # lock releases, which yield).
            raise _Abort()
        self._main_gate.set()
        w.gate.wait()
        w.gate.clear()
        if self._aborting:
            raise _Abort()

    def _park(self, w: _Worker,
              on: Union["SchedLock", "SchedCondition"]) -> None:
        """Block this worker on a lock/condition until another worker
        makes it runnable again (release / notify / timeout)."""
        w.blocked_on = on
        self._switch_from(w)

    # ------------------------------------------------------------- run

    def run(self) -> Outcome:
        out = self.outcome
        for w in self._workers:
            w.thread = threading.Thread(
                target=self._worker_main, args=(w,),
                name=w.name, daemon=True)
            w.thread.start()
        while True:
            live = [w for w in self._workers if not w.done]
            if any(w.exc is not None for w in self._workers):
                break  # a worker failed: the run's verdict is known
            if not live:
                break
            runnable = [w for w in live if w.blocked_on is None]
            if not runnable:
                timed = [w for w in live if w.timed]
                if not timed:
                    out.deadlock = self._wait_for_report(live)
                    break
                # "Eventually": fire a timeout only at quiescence.
                runnable = timed
            if out.steps >= self._max_steps:
                out.errors.append(
                    f"step budget exceeded ({self._max_steps}): "
                    "livelock or runaway scenario")
                break
            k = self._decide(out.steps, [w.idx for w in runnable])
            if not 0 <= k < len(runnable):
                k %= len(runnable)
            out.trace.append((k, len(runnable)))
            out.steps += 1
            w = runnable[k]
            if w.timed and w.blocked_on is not None:
                self._fire_timeout(w)
            w.gate.set()
            self._main_gate.wait()
            self._main_gate.clear()
        self._abort_rest()
        for w in self._workers:
            if w.exc is not None:
                out.errors.append(
                    f"{w.name}: {type(w.exc).__name__}: {w.exc}")
        return out

    def _fire_timeout(self, w: _Worker) -> None:
        cond = w.waiting_in
        if cond is not None and w in cond._waiting:
            cond._waiting.remove(w)
        w.timed_out = True
        w.blocked_on = None

    def _abort_rest(self) -> None:
        self._aborting = True
        for w in self._workers:
            if not w.done:
                w.gate.set()
        for w in self._workers:
            if w.thread is not None:
                w.thread.join(timeout=5.0)

    def _wait_for_report(self, blocked: List[_Worker]) -> str:
        parts = []
        for w in blocked:
            on = w.blocked_on
            if isinstance(on, SchedCondition):
                parts.append(f"{w.name} waits on condition "
                             f"{on.name!r} (no notifier can run)")
            elif isinstance(on, SchedLock):
                owner = on._owner
                holder = (owner.name if isinstance(owner, _Worker)
                          else "controller")
                parts.append(f"{w.name} waits on lock {on.name!r} "
                             f"held by {holder}")
            else:
                parts.append(f"{w.name} blocked")
        return "deadlock: " + "; ".join(parts)


# -------------------------------------------------------------- locks


class SchedLock:
    """Scheduler-instrumented mutex. State is plain data — only one
    worker runs at a time. Non-reentrant: a worker re-acquiring parks
    on itself and the wait-for graph reports the self-deadlock."""

    _reentrant = False

    def __init__(self, name: str, sch: Scheduler) -> None:
        self.name = name
        self._sch = sch
        self._owner: Optional[object] = None  # _Worker | _CONTROLLER
        self._count = 0
        self._waiters: List[_Worker] = []

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        w = self._sch._worker_for_current()
        if w is None:
            if self._owner is None:
                self._owner = _CONTROLLER
                self._count = 1
            elif self._owner is _CONTROLLER and self._reentrant:
                self._count += 1
            else:
                raise RuntimeError(
                    f"controller thread acquiring contended lock "
                    f"{self.name!r} (scenario setup must not race "
                    f"workers)")
            return True
        self._sch._switch_from(w)  # preemption point before acquire
        while not (self._owner is None
                   or (self._reentrant and self._owner is w)):
            self._waiters.append(w)
            self._sch._park(w, self)
        self._owner = w
        self._count += 1
        return True

    def release(self) -> None:
        w = self._sch._worker_for_current()
        expected: object = w if w is not None else _CONTROLLER
        if self._owner is not expected:
            raise RuntimeError(f"release of {self.name!r} by "
                               f"non-owner")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            for ww in self._waiters:
                ww.blocked_on = None
            self._waiters.clear()
        if w is not None:
            self._sch._switch_from(w)  # others may grab it first

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class SchedRLock(SchedLock):
    _reentrant = True


class SchedCondition:
    """Scheduler-instrumented condition over a :class:`SchedRLock`."""

    def __init__(self, name: str, sch: Scheduler) -> None:
        self.name = name
        self._sch = sch
        self._lock = SchedRLock(name, sch)
        self._waiting: List[_Worker] = []

    # lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "SchedCondition":
        self._lock.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self._lock.release()

    # condition protocol
    def _check_owned(self, w: Optional[_Worker]) -> None:
        expected: object = w if w is not None else _CONTROLLER
        if self._lock._owner is not expected:
            raise RuntimeError(
                f"condition {self.name!r} used without owning its lock")

    def wait(self, timeout: Optional[float] = None) -> bool:
        w = self._sch._worker_for_current()
        if w is None:
            raise RuntimeError("controller thread cannot wait() under "
                               "the scheduler")
        self._check_owned(w)
        saved = self._lock._count
        # Full release (RLock semantics), waking lock waiters.
        self._lock._count = 0
        self._lock._owner = None
        for ww in self._lock._waiters:
            ww.blocked_on = None
        self._lock._waiters.clear()
        self._waiting.append(w)
        w.waiting_in = self
        w.timed = timeout is not None
        self._sch._park(w, self)
        timed_out = w.timed_out
        w.timed = False
        w.timed_out = False
        w.waiting_in = None
        # Re-acquire, restoring the recursion count.
        while self._lock._owner is not None and self._lock._owner is not w:
            self._lock._waiters.append(w)
            self._sch._park(w, self._lock)
        self._lock._owner = w
        self._lock._count += saved
        return not timed_out

    def wait_for(self, predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        while not predicate():
            if not self.wait(timeout):
                return predicate()
        return True

    def notify(self, n: int = 1) -> None:
        w = self._sch._worker_for_current()
        self._check_owned(w)
        woken = self._waiting[:n]
        del self._waiting[:len(woken)]
        for ww in woken:
            ww.blocked_on = None
        if w is not None:
            self._sch._switch_from(w)

    def notify_all(self) -> None:
        self.notify(len(self._waiting) or 1)

    def __repr__(self) -> str:
        return f"<SchedCondition {self.name!r}>"


# -------------------------------------------------------- exploration


def schedule_decider(schedule: Sequence[int]) -> Decider:
    """Replay a pinned schedule; past its end, KEEP RUNNING the worker
    the last choice landed on (falling back to the lowest index when
    it blocks or finishes). Sticky continuation means one divergence
    choice expresses "preempt here and let the other thread run to
    completion" — so the breadth-first sweep covers every
    single-preemption interleaving at divergence depth 1, where most
    check-then-act races live."""
    state: Dict[str, Optional[int]] = {"last": None}

    def decide(step: int, runnable: Sequence[int]) -> int:
        if step < len(schedule):
            k = min(schedule[step], len(runnable) - 1)
        else:
            last = state["last"]
            k = runnable.index(last) if last in runnable else 0
        state["last"] = runnable[k]
        return k

    return decide


def rng_decider(rng: "object") -> Decider:
    """Random walk driven by a numpy Generator (``default_rng([seed,
    index])`` — the (seed, index) reproducer contract)."""

    def decide(step: int, runnable: Sequence[int]) -> int:
        n = len(runnable)
        return int(rng.integers(0, n))  # type: ignore[attr-defined]

    return decide


def explore_dfs(run_with: Callable[[Decider], Outcome],
                max_schedules: int
                ) -> List[Tuple[List[int], Outcome]]:
    """Systematic exploration of the schedule tree: run a prefix
    (choices beyond it default to 0), then enqueue every untaken
    branch along its trace — breadth-first, so schedules diverging at
    EARLY steps (single preemptions — where most atomicity races live)
    are covered first, and deeper divergences later (the CHESS
    preemption-bounding insight). Each schedule runs exactly once:
    children only branch at positions at or past their parent's pinned
    prefix. Exhaustive when the tree fits in ``max_schedules``; a
    truncated sweep is still deterministic (same order every time)."""
    results: List[Tuple[List[int], Outcome]] = []
    queue: List[List[int]] = [[]]
    head = 0
    while head < len(queue) and len(results) < max_schedules:
        prefix = queue[head]
        head += 1
        outcome = run_with(schedule_decider(prefix))
        results.append((outcome.schedule, outcome))
        trace = outcome.trace
        for i in range(len(prefix), len(trace)):
            chosen, n = trace[i]
            stem = [c for c, _ in trace[:i]]
            for c in range(n):
                if c != chosen:
                    queue.append(stem + [c])
    return results
