"""Stable query fingerprints shared by every identity-keyed surface.

One request identity, one digest function. Before this module the
coalescer's read-dedup, the workload recorder's rolling repeat window
and the hotspots signature digests each built their own tuple shape (or
hashed with process-salted ``hash()``), so "the same query" meant
subtly different things to different planes. The generation-keyed
result cache (executor/result_cache.py) keys on exactly these
identities, so they are defined ONCE here:

- ``request_key(index, query, shards)``: the canonical identity of one
  serving-path request — the key the coalescer dedups on, the workload
  recorder windows on, and the request tier of the result cache caches
  under (plus its generation validation).
- ``digest(obj)``: a short stable blake2s digest of any repr-able key.
  NOT ``hash()``: str hashing is salted per process (PYTHONHASHSEED),
  and fingerprints must name the same identity across cluster nodes
  and restarts (drain dumps, /cluster/hotspots correlation).

Pure host-side helpers — no jax, no locks, no state.
"""

from __future__ import annotations

import hashlib
from typing import Any, Optional, Sequence, Tuple


def digest(obj: Any, size: int = 8) -> str:
    """Stable short hex digest of a repr-able key (blake2s)."""
    return hashlib.blake2s(repr(obj).encode(),
                           digest_size=size).hexdigest()


def request_key(index: str, query: Any,
                shards: Optional[Sequence[int]]
                ) -> Tuple[str, str, Optional[Tuple[int, ...]]]:
    """The canonical (index, pql-text, shards) identity of one request.
    Parsed Call/Query trees serialize back through pql_text so a string
    and its parsed form key identically; an explicit shard list is
    order- and type-normalized."""
    if isinstance(query, str):
        q = query
    else:
        from pilosa_tpu.utils.profile import pql_text
        q = pql_text(query)
    return (str(index), q,
            tuple(int(s) for s in shards) if shards is not None else None)
