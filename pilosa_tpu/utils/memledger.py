"""HBM memory ledger + always-on telemetry watchdog.

The whole design keeps the index resident as packed device banks in
HBM, which makes device memory the resource that decides how many
shards a node can hold — yet before this module nothing could answer
"what is occupying HBM right now, and how much of it is padding?".
PR 3/PR 4 gave *per-query* visibility (profiler, fusion attribution);
this is the *per-resource* counterpart:

- ``MemoryLedger``: a process-wide registry every long-lived device
  (and host-cache) allocation registers with — view banks (tagged
  index/field/view/shard), positions banks, the executor's LRU jit
  cache, fusion pad lanes, pending result arrays, host block caches.
  Each entry carries live bytes AND padded bytes, so pow2 padding
  waste is a first-class number instead of folklore. Served at
  ``GET /debug/memory`` and exported as ``pilosa_memory_bytes{category}``
  / ``pilosa_memory_padding_bytes{category}`` gauges.
- ``MemoryWatchdog``: an always-on sampling thread (Monarch-style
  continuous low-overhead collection; cf. PAPERS.md) that snapshots
  the ledger + a few queue gauges into a bounded flight-recorder ring,
  logs a pressure warning with the top-K banks when a configurable HBM
  watermark is crossed, and dumps the ring to the log on SIGTERM so
  post-mortems always have the last N snapshots.

Pure host-side module: NO jax imports, no device fencing — sampling a
dict of integers can never stall the dispatch queue (graftlint GL003
stays clean by construction).

Registration contract: keys are scoped to an ``owner`` object (a View,
Fragment, Executor, ...) whenever one exists; the ledger drops every
entry of a garbage-collected owner via ``weakref.finalize``, so objects
without an explicit close() cannot leak ledger rows after they — and
their device arrays — are gone.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from pilosa_tpu.utils.locks import make_rlock

# Categories whose bytes live in host RAM, not device HBM: excluded
# from the watchdog's HBM watermark (but still ledgered + exported).
# "telemetry" covers the tracer span ring and the request-timeline
# ring (utils/tracing.py / utils/timeline.py register themselves);
# "result_cache" is the generation-keyed query result cache's host
# values (executor/result_cache.py). The device-resident TopN rank
# cache ("rank_cache") is HBM and deliberately NOT listed here.
HOST_CATEGORIES = frozenset({"host_block", "telemetry", "result_cache"})


class _Entry:
    __slots__ = ("category", "key", "nbytes", "padded", "meta", "oid")

    def __init__(self, category: str, key: Any, nbytes: int,
                 padded: int, meta: Dict[str, Any],
                 oid: Optional[int] = None) -> None:
        self.category = category
        self.key = key
        self.nbytes = int(nbytes)
        self.padded = int(padded)
        self.meta = meta
        # id() of the owner the entry was registered under (None for
        # unowned entries): unregistration must clean the owner's
        # key-set HOWEVER it was reached — eviction paths unregister
        # by bare scoped key, without the owner in hand.
        self.oid = oid


class MemoryLedger:
    """Thread-safe registry of live allocations, grouped by category.

    ``register`` replaces an existing (category, key) entry in place
    (the bank-replace path re-registers under the same key; totals
    never double-count). ``unregister`` is idempotent — eviction paths
    race with close() and both may fire for the same key.

    GC discipline: ``weakref.finalize`` callbacks run at arbitrary
    allocation points — potentially while the current thread holds ANY
    lock (including, under PILOSA_TPU_LOCK_CHECK, the order checker's
    own non-reentrant mutex). A finalizer that takes the ledger lock
    can therefore deadlock the process. So finalizers here are
    lock-free: they append to ``_dead`` (deque.append is atomic) and
    every public ledger operation drains that queue before doing its
    own work."""

    TOP_K = 10

    def __init__(self) -> None:
        self._lock = make_rlock("MemoryLedger._lock")
        self._entries: Dict[Tuple[str, Any], _Entry] = {}
        # category -> [bytes, padded, count]; categories persist at
        # zero once seen so exported gauges drop to 0 instead of
        # disappearing from /metrics.
        self._totals: Dict[str, List[int]] = {}
        # id(owner) -> set of (category, key) to purge when the owner
        # is collected.
        self._owned: Dict[int, set] = {}
        # Deaths reported by GC finalizers, pending processing:
        # ("entry", (category, key)) | ("owner", oid).
        self._dead: deque = deque()

    # ------------------------------------------------------------ mutation

    def _scoped(self, key: Any, owner: Optional[Any]) -> Any:
        return (id(owner), key) if owner is not None else key

    def _note_dead(self, kind: str, payload: Any) -> None:
        """weakref.finalize target — MUST stay lock-free (see class
        docstring); the next ledger operation applies it."""
        self._dead.append((kind, payload))

    def _drain_dead(self) -> None:
        while True:
            try:
                kind, payload = self._dead.popleft()
            except IndexError:
                return
            if kind == "owner":
                self._purge_owner(payload)
            else:
                category, key = payload
                self._unregister_now(category, key, None)

    def register(self, category: str, key: Any, nbytes: int,
                 padded_bytes: int = 0, owner: Optional[Any] = None,
                 **meta: Any) -> None:
        """Track (or replace) one allocation. `owner` scopes the key to
        a live object and auto-purges on its collection."""
        self._drain_dead()
        k = self._scoped(key, owner)
        entry = _Entry(category, k, max(0, int(nbytes)),
                       max(0, int(padded_bytes)), meta,
                       oid=id(owner) if owner is not None else None)
        with self._lock:
            if owner is not None:
                oid = id(owner)
                owned = self._owned.get(oid)
                if owned is None:
                    owned = self._owned[oid] = set()
                    weakref.finalize(owner, self._note_dead, "owner",
                                     oid)
                owned.add((category, k))
            old = self._entries.get((category, k))
            # graftlint: disable=GL008 — closed key space: one entry
            # per allocation category (bank, jit, telemetry, ...);
            # totals persist at zero BY DESIGN so /debug/memory shows a
            # category emptied rather than silently vanishing.
            tot = self._totals.setdefault(category, [0, 0, 0])
            if old is not None:
                tot[0] -= old.nbytes
                tot[1] -= old.padded
                tot[2] -= 1
            self._entries[(category, k)] = entry
            tot[0] += entry.nbytes
            tot[1] += entry.padded
            tot[2] += 1

    def unregister(self, category: str, key: Any,
                   owner: Optional[Any] = None) -> None:
        self._drain_dead()
        self._unregister_now(category, key, owner)

    def _unregister_now(self, category: str, key: Any,
                        owner: Optional[Any]) -> None:
        k = self._scoped(key, owner)
        with self._lock:
            old = self._entries.pop((category, k), None)
            if old is None:
                return
            tot = self._totals.get(category)
            if tot is not None:
                tot[0] -= old.nbytes
                tot[1] -= old.padded
                tot[2] -= 1
            # Clean the owner's key-set via the id recorded at
            # registration: eviction paths unregister by bare scoped
            # key (no owner in hand), and cache_rows keys embed whole
            # row-id tuples — leaving them in the set would grow a
            # long-lived view's bookkeeping without bound.
            if old.oid is not None:
                owned = self._owned.get(old.oid)
                if owned is not None:
                    owned.discard((category, k))

    def _purge_owner(self, oid: int) -> None:
        with self._lock:
            for category, k in self._owned.pop(oid, ()):
                old = self._entries.pop((category, k), None)
                if old is None:
                    continue
                tot = self._totals.get(category)
                if tot is not None:
                    tot[0] -= old.nbytes
                    tot[1] -= old.padded
                    tot[2] -= 1

    def track(self, obj: Any, category: str, nbytes: int,
              padded_bytes: int = 0, **meta: Any) -> None:
        """Register an allocation that lives exactly as long as `obj`
        (fusion groups, pending result sets): keyed on the object,
        unregistered automatically when it is collected. Deliberately
        skips the per-owner key-set bookkeeping of `owner=` — this
        runs per query result on the serving hot path, and a tracked
        object has exactly one entry, so a direct finalize suffices.
        (The finalize fires at collection, before the id can be
        recycled, so the key cannot alias a successor object.)"""
        key = ("obj", id(obj))
        self.register(category, key, nbytes, padded_bytes, **meta)
        weakref.finalize(obj, self._note_dead, "entry", (category, key))

    # ------------------------------------------------------------- reading

    def totals(self) -> Dict[str, Dict[str, int]]:
        self._drain_dead()
        with self._lock:
            return {c: {"bytes": t[0], "paddedBytes": t[1], "count": t[2]}
                    for c, t in sorted(self._totals.items())}

    def total_bytes(self, device_only: bool = False) -> int:
        self._drain_dead()
        with self._lock:
            return sum(t[0] for c, t in self._totals.items()
                       if not (device_only and c in HOST_CATEGORIES))

    def top(self, k: int = TOP_K,
            device_only: bool = False) -> List[Dict[str, Any]]:
        """The k largest live entries (the "what is actually occupying
        HBM" list for /debug/memory and pressure warnings).
        `device_only` drops host-RAM categories — the pressure warning
        must name what contributes to the DEVICE number it fired on."""
        self._drain_dead()
        with self._lock:
            entries = sorted(
                (e for e in self._entries.values() if e.nbytes > 0
                 and not (device_only
                          and e.category in HOST_CATEGORIES)),
                key=lambda e: e.nbytes, reverse=True)[:k]
            return [{"category": e.category, "bytes": e.nbytes,
                     "paddedBytes": e.padded, **e.meta}
                    for e in entries]

    def entry_info(self, categories, key: Any) -> Optional[Dict[str, Any]]:
        """Bytes/padding/meta of the live entry for `key` under any of
        `categories`, or None. `key` is the already-SCOPED key ((id(
        owner), key) for owned entries) — the BankBudget eviction
        scorer holds exactly that form."""
        self._drain_dead()
        with self._lock:
            for c in categories:
                e = self._entries.get((c, key))
                if e is not None:
                    return {"category": c, "bytes": e.nbytes,
                            "paddedBytes": e.padded, **e.meta}
        return None

    def entries(self, *categories: str) -> List[Dict[str, Any]]:
        """Every live entry of the given categories, with bytes/padding
        and registration meta — the workload plane joins bank entries
        against access rates for its density-vs-access quadrants."""
        self._drain_dead()
        with self._lock:
            return [{"category": e.category, "bytes": e.nbytes,
                     "paddedBytes": e.padded, **e.meta}
                    for e in self._entries.values()
                    if e.category in categories]

    def snapshot(self, top_k: int = TOP_K) -> Dict[str, Any]:
        """The /debug/memory document. `totalBytes` is the exact sum of
        the per-category byte totals (asserted by test); `deviceBytes`
        derives from the SAME totals snapshot, so the two can never
        disagree within one document."""
        cats = self.totals()
        return {
            "totalBytes": sum(c["bytes"] for c in cats.values()),
            "deviceBytes": sum(c["bytes"] for name, c in cats.items()
                               if name not in HOST_CATEGORIES),
            "paddingBytes": sum(c["paddedBytes"] for c in cats.values()),
            "categories": cats,
            "top": self.top(top_k),
        }

    def publish(self, stats: Optional[Any]) -> None:
        """Export per-category gauges: pilosa_memory_bytes{category},
        pilosa_memory_padding_bytes{category}, pilosa_memory_objects.
        Totals are snapshotted under the lock; the stats client (its
        own lock) is called outside it."""
        if stats is None:
            return
        for cat, t in self.totals().items():
            tagged = stats.with_tags(f"category:{cat}")
            tagged.gauge("memory.bytes", t["bytes"])
            tagged.gauge("memory.padding_bytes", t["paddedBytes"])
            tagged.gauge("memory.objects", t["count"])


# The process-wide ledger every allocation site registers with (the
# memory analog of core.view.BANK_BUDGET — one process, one HBM).
LEDGER = MemoryLedger()


class MemoryWatchdog:
    """Always-on, near-zero-overhead sampler: every `sample_every_s`
    it snapshots the ledger (+ caller-supplied gauges: coalescer queue
    depth, jit-cache size, ...) into a bounded flight-recorder ring,
    publishes the memory gauges, and warns — with the top-K largest
    banks — when device bytes cross `watermark_bytes`. The warning
    re-arms only after pressure falls below 90% of the watermark, so a
    hovering workload logs one line, not one per sample.

    `dump()` writes the ring to the log; the server's SIGTERM drain
    calls it so post-mortems always have the last N snapshots."""

    def __init__(self, ledger: MemoryLedger = LEDGER,
                 stats: Optional[Any] = None,
                 logger: Optional[Any] = None,
                 sample_every_s: float = 10.0,
                 ring: int = 360, watermark_bytes: int = 0,
                 top_k: int = 5,
                 extra_gauges: Optional[Callable[[], Dict[str, Any]]]
                 = None) -> None:
        self.ledger = ledger
        self.stats = stats
        self.logger = logger
        self.sample_every_s = max(0.05, float(sample_every_s))
        self.watermark_bytes = int(watermark_bytes)
        self.top_k = top_k
        self.extra_gauges = extra_gauges
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._ring_lock = make_rlock("MemoryWatchdog._ring_lock")
        self._over_watermark = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_taken = 0
        self.last_sample_at: Optional[float] = None

    # ------------------------------------------------------------ sampling

    def sample_once(self) -> Dict[str, Any]:
        """One flight-recorder snapshot: ledger totals + extra gauges.
        Host-side dict arithmetic only — never touches the device."""
        snap: Dict[str, Any] = {
            "t": time.time(),
            "totalBytes": 0,
            "deviceBytes": 0,
            "paddingBytes": 0,
            "categories": {},
        }
        # One totals() read: every derived number in the snapshot is
        # internally consistent.
        for cat, t in self.ledger.totals().items():
            snap["categories"][cat] = t["bytes"]
            snap["totalBytes"] += t["bytes"]
            snap["paddingBytes"] += t["paddedBytes"]
            if cat not in HOST_CATEGORIES:
                snap["deviceBytes"] += t["bytes"]
        if self.extra_gauges is not None:
            try:
                snap.update(self.extra_gauges() or {})
            except Exception:
                pass  # gauges must never kill the watchdog
        with self._ring_lock:
            self._ring.append(snap)
            self.samples_taken += 1
            self.last_sample_at = snap["t"]
        self.ledger.publish(self.stats)
        self._check_watermark(snap)
        return snap

    def _check_watermark(self, snap: Dict[str, Any]) -> None:
        if self.watermark_bytes <= 0:
            return
        device = snap["deviceBytes"]
        if device >= self.watermark_bytes:
            if not self._over_watermark:
                self._over_watermark = True
                if self.logger is not None:
                    top = self.ledger.top(self.top_k,
                                          device_only=True)
                    self.logger.printf(
                        "HBM pressure: %d bytes ledgered on device "
                        "(watermark %d); top banks: %s",
                        device, self.watermark_bytes, top)
        elif device < int(self.watermark_bytes * 0.9):
            self._over_watermark = False

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()  # restartable after stop()

        def loop() -> None:
            while not self._stop.wait(self.sample_every_s):
                try:
                    self.sample_once()
                except Exception:
                    pass  # a bad sample must not end always-on telemetry

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="mem-watchdog")
        self._thread.start()

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.sample_every_s + 5)
            self._thread = None

    def snapshots(self) -> List[Dict[str, Any]]:
        """Oldest-first copy of the flight-recorder ring."""
        with self._ring_lock:
            return list(self._ring)

    def dump(self, logger: Optional[Any] = None, last: int = 10) -> int:
        """Write the last `last` ring snapshots to the log (the SIGTERM
        post-mortem path). Returns how many were written."""
        logger = logger or self.logger
        snaps = self.snapshots()[-max(0, int(last)):]
        if logger is not None and snaps:
            logger.printf("memory watchdog: dumping last %d of %d "
                          "snapshots", len(snaps), self.samples_taken)
            for s in snaps:
                logger.printf("memory watchdog: %s", s)
        return len(snaps)
