"""Fault-injection plane: named failpoint sites on the seams that can
actually fail in production.

The resilience work (ROADMAP item 3) needs faults that are *cheap to
inject and exact to place*: a connect refusal on one RPC, a torn
response body, a slow resize pull, a dead heartbeat target. Process
signals (SIGSTOP/SIGKILL, tests/test_cluster_procs.py) prove the
end-to-end story but cannot aim at one seam; this registry can. The
design follows the failpoint idiom the reference ecosystem uses for
exactly this (pingcap/failpoint, gofail): sites are *registered once at
module import* and *fired* at the seam; a disarmed site is one
attribute read.

Sites (the catalog, mirrored in docs/architecture.md):

=====================  ====================================================
``client.connect``     ``InternalClient._req`` before the connection is
                       acquired — error/partition surface as transport
                       ``ClientError``.
``client.read``        after the request is written, before the response
                       is read — a reused keep-alive connection takes the
                       stale-retry path first, exactly like a real
                       mid-flight close.
``client.5xx``         forces a synthetic ``500`` ``ClientError`` as if
                       the peer answered it.
``client.torn_body``   truncates the response payload in half — the
                       parse below raises a NON-``ClientError``
                       (``ValueError``/``WireError``), the class of
                       failure that silently undercounted before this PR.
``resize.pull``        ``ResizePuller._maybe_pull`` per (peer, shard)
                       fragment fetch — error fails the pull pass (the
                       resize job stays RESIZING), delay holds the
                       cluster mid-resize so chaos can strike inside the
                       window.
``resize.job.rpc``     the coordinator's per-node resize-pull RPC in the
                       resize job (``server/api.py _start_resize_job``).
``heartbeat.probe``    one heartbeat probe about to be sent — error
                       counts as a failed probe (drives ``mark_down``),
                       drop skips the probe entirely.
``api.status``         the ``/status`` answer (what heartbeat probes
                       hit): arming ``error`` here makes THIS node look
                       dead to every prober without stopping its data
                       plane.
``api.query``          the query entry on THIS node — arming ``error``
                       makes every query leg routed here fail (the
                       failpoint "kill": coordinators must fail over).
=====================  ====================================================

Spec syntax (env ``PILOSA_TPU_FAILPOINTS``, ``[failpoints]`` config
table, ``POST /internal/failpoints``)::

    site=mode[(arg)][xN] [; site=... ]

    client.connect=error                # every fire raises
    client.read=errorx2                 # first 2 fires raise, then disarm
    resize.pull=delay(1.5)              # sleep 1.5 s per fire
    client.connect=partition(:10102)    # raise only when the target URI
                                        # contains ":10102"
    heartbeat.probe=drop                # silently swallow the operation

Modes: ``error`` raises :class:`FailpointError` (a ``ConnectionError``
subclass, so client seams surface it exactly like a real transport
failure); ``drop`` raises :class:`FailpointDrop` (sites that can lose an
operation silently interpret it; everywhere else it's an error);
``delay(seconds)`` sleeps and continues; ``partition(substr)`` raises
only when the fire context's ``uri``/``url`` contains ``substr`` — a
directional network partition.

Zero overhead disarmed: ``Site.fire()`` returns on one ``self.spec is
None`` read; no lock, no dict lookup, no string work. The registry lock
guards arm/disarm only.

The HTTP surface is test-only: ``cli/main.py`` enables it when any
failpoint configuration is present at boot (env or config) —
production servers that never opt in answer 403. graftlint GL013 pins
that every site name is registered exactly once, at module level.
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, Dict, List, Optional

from pilosa_tpu.utils.locks import make_lock

ENV_VAR = "PILOSA_TPU_FAILPOINTS"


class FailpointError(ConnectionError):
    """An injected failure. Subclasses ConnectionError so the client
    seams it fires on treat it exactly like a real transport error."""


class FailpointDrop(FailpointError):
    """An injected silent loss: sites that can drop an operation
    (heartbeat probes) swallow it; everywhere else it is an error."""


_SPEC_RE = re.compile(
    r"^(?P<mode>error|drop|delay|partition)"
    r"(?:\((?P<arg>[^)]*)\))?"
    r"(?:x(?P<count>\d+))?$")


class _Spec:
    __slots__ = ("mode", "arg", "remaining", "raw")

    def __init__(self, mode: str, arg: str, remaining: int,
                 raw: str) -> None:
        self.mode = mode
        self.arg = arg
        self.remaining = remaining  # fires left; -1 = unlimited
        self.raw = raw


def parse_spec(text: str) -> _Spec:
    m = _SPEC_RE.match(text.strip())
    if m is None:
        raise ValueError(f"bad failpoint spec {text!r} (expected "
                         f"mode[(arg)][xN], mode in error/drop/delay/"
                         f"partition)")
    mode = m.group("mode")
    arg = m.group("arg") or ""
    if mode == "delay":
        try:
            float(arg or "x")
        except ValueError:
            raise ValueError(
                f"delay spec needs numeric seconds: {text!r}") from None
    if mode == "partition" and not arg:
        raise ValueError(
            f"partition spec needs a URI substring: {text!r}")
    count = m.group("count")
    return _Spec(mode, arg, int(count) if count else -1, text.strip())


class Site:
    """One registered failpoint site. ``spec`` is None when disarmed —
    the ONLY state the hot path reads."""

    __slots__ = ("name", "spec", "hits", "_registry")

    def __init__(self, name: str, registry: "FailpointRegistry") -> None:
        self.name = name
        self.spec: Optional[_Spec] = None
        self.hits = 0
        self._registry = registry

    def fire(self, **ctx: Any) -> None:
        """Evaluate the site. Disarmed: one attribute read, return.
        Armed: sleep (delay), raise FailpointError (error /
        partition-on-match) or FailpointDrop (drop). Count-limited
        specs self-disarm after their last fire."""
        spec = self.spec
        if spec is None:
            return
        self._registry._fire(self, spec, ctx)


class FailpointRegistry:
    """Process-wide site registry. Sites register at module import
    (exactly once — GL013 pins it); activation comes from the env, the
    ``[failpoints]`` config table, or the test-only HTTP surface."""

    def __init__(self) -> None:
        self._lock = make_lock("FailpointRegistry._lock")
        self._sites: Dict[str, Site] = {}
        # Test-only HTTP surface gate (POST /internal/failpoints):
        # cli/main.py sets this when any failpoint config is present at
        # boot; in-process tests set it directly.
        self.http_enabled = False
        self.fired_total = 0

    # ------------------------------------------------------- registration

    def register(self, name: str) -> Site:
        """Register a site name (module-import time). Raises on
        duplicates: two sites sharing a name would make arm() ambiguous
        and the catalog a lie."""
        with self._lock:
            if name in self._sites:
                raise ValueError(f"failpoint {name!r} registered twice")
            site = Site(name, self)
            # graftlint: disable=GL008 — bounded by the static site
            # catalog: register() runs once per site at module import
            # (GL013 pins exactly-once), never on a request path.
            self._sites[name] = site
            return site

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._sites)

    # --------------------------------------------------------- activation

    def arm(self, name: str, spec: str) -> None:
        parsed = parse_spec(spec)
        with self._lock:
            site = self._sites.get(name)
            if site is None:
                raise KeyError(f"unknown failpoint {name!r} "
                               f"(registered: {sorted(self._sites)})")
            site.spec = parsed

    def disarm(self, name: str) -> None:
        with self._lock:
            site = self._sites.get(name)
            if site is None:
                raise KeyError(f"unknown failpoint {name!r}")
            site.spec = None

    def disarm_all(self) -> None:
        with self._lock:
            for site in self._sites.values():
                site.spec = None

    def configure(self, mapping: Optional[Dict[str, str]] = None,
                  env: Optional[str] = None) -> None:
        """Boot-time activation: a ``[failpoints]`` config table and/or
        the ``PILOSA_TPU_FAILPOINTS`` env string
        (``site=spec;site=spec``). Env wins on conflicts, matching the
        config precedence everywhere else. Unknown site names raise —
        a typo must not silently disarm a chaos run."""
        specs: Dict[str, str] = dict(mapping or {})
        text = os.environ.get(ENV_VAR, "") if env is None else env
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"bad {ENV_VAR} entry {part!r} (want site=spec)")
            name, spec = part.split("=", 1)
            specs[name.strip()] = spec.strip()
        for name, spec in specs.items():
            self.arm(name, str(spec))

    # -------------------------------------------------------------- fire

    def _fire(self, site: Site, spec: _Spec, ctx: Dict[str, Any]) -> None:
        if spec.mode == "partition":
            target = str(ctx.get("uri") or ctx.get("url") or "")
            if spec.arg not in target:
                return
        with self._lock:
            # Re-read under the lock: a concurrent disarm wins.
            if site.spec is not spec:
                return
            if spec.remaining == 0:
                site.spec = None
                return
            if spec.remaining > 0:
                spec.remaining -= 1
                if spec.remaining == 0:
                    site.spec = None
            site.hits += 1
            self.fired_total += 1
        if spec.mode == "delay":
            time.sleep(float(spec.arg))
            return
        if spec.mode == "drop":
            raise FailpointDrop(f"failpoint {site.name}: drop")
        raise FailpointError(
            f"failpoint {site.name}: {spec.mode}"
            + (f"({spec.arg})" if spec.arg else ""))

    # --------------------------------------------------------- reporting

    def snapshot(self) -> Dict[str, Any]:
        """The GET /internal/failpoints document + the health-plane
        stanza: every registered site, its armed spec (or null) and
        cumulative hit count."""
        with self._lock:
            sites = {
                name: {"armed": s.spec.raw if s.spec else None,
                       "hits": s.hits}
                for name, s in sorted(self._sites.items())
            }
            armed = sum(1 for s in self._sites.values()
                        if s.spec is not None)
            return {"sites": sites, "armed": armed,
                    "fired": self.fired_total}


FAILPOINTS = FailpointRegistry()
