"""Pilosa roaring bitmap: host implementation + file format codec.

File format (binary-compatible with the reference; spec per
/root/reference/docs/architecture.md "Roaring bitmap storage format" and
writer/reader /root/reference/roaring/roaring.go:963-1126):

    bytes 0-1   magic number 12348 (little-endian uint16)
    bytes 2-3   storage version (0)
    bytes 4-7   container count N (uint32)
    N x 12      descriptive header: uint64 key, uint16 container type
                (1=array, 2=bitmap, 3=run), uint16 cardinality-1
    N x 4       offset header: absolute uint32 byte offset of each container
    ...         container payloads:
                  array : n x uint16 sorted values
                  bitmap: 1024 x uint64 words
                  run   : uint16 run count, then (uint16 start, uint16 last)*
    ...         ops log until EOF (op format roaring.go:3628-3691):
                  byte type (0 add, 1 remove, 2 addBatch, 3 removeBatch)
                  uint64 value-or-count, uint32 fnv1a checksum,
                  batch ops: count x uint64 values
                Extension type 4 (addRoaring; NOT in the reference's
                format — reference-written files never contain it, so
                read compatibility is unaffected): uint64 payload byte
                length, uint32 zlib-crc32 over header+payload, then a
                self-contained roaring snapshot of the batch. ~2 bytes
                per sparse bit vs 8 for addBatch, and crc32 streams at
                GB/s where byte-serial fnv1a was the import bottleneck.

In-memory representation: every non-empty container is held *dense* as
uint64[1024] in a dict keyed by the 48-bit container key. Dense-only is a
deliberate divergence from the reference's three-encoding polymorphism: the
host bitmap exists for mutation, durability and the CPU baseline, not as the
query hot path (that's HBM), and dense numpy makes every mutation a vector op.
The three encodings are still produced on write (smallest wins, mirroring
Optimize, roaring.go:1745) and accepted on read.
"""

from __future__ import annotations

import io
import struct
from typing import (Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple)

import numpy as np

from pilosa_tpu import native

MAGIC_NUMBER = 12348
STORAGE_VERSION = 0
COOKIE = MAGIC_NUMBER | (STORAGE_VERSION << 16)
HEADER_BASE_SIZE = 8

CONTAINER_ARRAY = 1
CONTAINER_BITMAP = 2
CONTAINER_RUN = 3

CONTAINER_BITS = 1 << 16
CONTAINER_WORDS = CONTAINER_BITS // 64  # 1024 uint64 words
ARRAY_MAX_SIZE = 4096  # below this an array encoding beats a bitmap
RUN_COUNT_HEADER_SIZE = 2
MAX_CONTAINER_KEY = (1 << 48) - 1

OP_ADD = 0
OP_REMOVE = 1
OP_ADD_BATCH = 2
OP_REMOVE_BATCH = 3
OP_ADD_ROARING = 4  # extension: roaring-snapshot payload, crc32 checksum

# Maximum OP_ADD_ROARING nesting depth. A roaring-record payload is a
# self-contained file, so crafted input can nest records inside records;
# unbounded recursion would exhaust the stack on attacker-controlled
# depth. Legitimate writers emit snapshot-only payloads (depth 1). The
# native codec enforces the same bound (pilosa_native.cpp kMaxOpNesting)
# so both readers agree on adversarial input.
MAX_OP_NESTING = 4

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193


def fnv1a32(*chunks: bytes) -> int:
    """FNV-1a 32-bit, matching Go's hash/fnv.New32a used for op checksums
    (roaring.go:3647-3650). The native C++ path matters: large batch ops
    hash their whole payload, and the Python loop dominates bulk-import
    time otherwise."""
    if native.available():
        h = native.fnv1a32(chunks)
        if h is not None:
            return h
    h = _FNV_OFFSET
    for chunk in chunks:
        for byte in chunk:
            h = ((h ^ byte) * _FNV_PRIME) & 0xFFFFFFFF
    return h


# numpy >= 2.0 has a native popcount ufunc; keep a table fallback.
if hasattr(np, "bitwise_count"):
    def _popcount_words(words: np.ndarray) -> int:
        return int(np.bitwise_count(words).sum())
else:  # pragma: no cover
    _POP_TABLE = np.array([bin(i).count("1") for i in range(256)], dtype=np.uint16)

    def _popcount_words(words: np.ndarray) -> int:
        return int(_POP_TABLE[words.view(np.uint8)].sum())


def _write_all(w: io.RawIOBase, data: bytes) -> None:
    """Write the whole record or raise. The op log is an UNBUFFERED
    raw file (one syscall per op, Go file-write durability), and raw
    writes may be short (e.g. ENOSPC writes what fits): an
    acknowledged op must never be a truncated record, so loop and
    fail loudly on no progress."""
    view = memoryview(data)
    while view:
        n = w.write(view)
        if not n:
            raise OSError("op-log write made no progress "
                          f"({len(view)} bytes unwritten)")
        view = view[n:]


def _new_container() -> np.ndarray:
    return np.zeros(CONTAINER_WORDS, dtype=np.uint64)


# Cardinality at or below which a container may use the sorted-u16 array
# encoding in memory (reference ArrayMaxSize, roaring.go:55). In-memory
# containers are DENSE u64[1024] (dtype uint64) or ARRAY-encoded sorted
# positions (dtype uint16) — the second, update-optimized-for-sparse
# backend of SURVEY component #3 (reference Containers implementations,
# roaring/containers.go). Mutations materialize dense via _container();
# reads handle both; optimize() re-compresses (reference Bitmap.Optimize,
# roaring.go:1745).
ARRAY_MAX_SIZE = 4096


def _is_array(c: np.ndarray) -> bool:
    return c.dtype == np.uint16


def _as_dense(c: np.ndarray) -> np.ndarray:
    return _array_to_dense(c) if c.dtype == np.uint16 else c


def _dense_to_array(dense: np.ndarray) -> np.ndarray:
    bits = np.unpackbits(dense.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint16)


def _low_mask(low: np.ndarray) -> np.ndarray:
    """Dense u64[1024] mask from in-container positions. Size-adaptive:
    bool-scatter + packbits beats np.bitwise_or.at (~100 ns/element)
    once groups get dense — the fragment bulk-import hot path."""
    if len(low) >= 256:
        bits = np.zeros(CONTAINER_BITS, dtype=bool)
        bits[low] = True
        return np.packbits(bits, bitorder="little").view(np.uint64).copy()
    dense = _new_container()
    if len(low):
        v = low.astype(np.uint32)
        np.bitwise_or.at(
            dense, v >> 6, np.left_shift(np.uint64(1), (v & 63).astype(np.uint64))
        )
    return dense


def _array_to_dense(values: np.ndarray) -> np.ndarray:
    return _low_mask(np.asarray(values))


def _runs_to_dense(runs: np.ndarray) -> np.ndarray:
    """runs: (n, 2) uint16 [start, last] inclusive pairs."""
    dense = _new_container()
    bits = np.zeros(CONTAINER_BITS, dtype=np.uint8)
    for start, last in runs:
        bits[int(start) : int(last) + 1] = 1
    dense |= np.packbits(bits, bitorder="little").view(np.uint64)
    return dense


def _dense_to_runs(dense: np.ndarray) -> np.ndarray:
    bits = np.unpackbits(dense.view(np.uint8), bitorder="little")
    diff = np.diff(np.concatenate(([0], bits, [0])).astype(np.int8))
    starts = np.nonzero(diff == 1)[0]
    ends = np.nonzero(diff == -1)[0] - 1
    return np.stack([starts, ends], axis=1).astype(np.uint16)


class Bitmap:
    """A 64-bit-keyed roaring bitmap, dense-container host implementation.

    Mirrors the public surface of the reference's roaring.Bitmap
    (roaring.go:119) that the rest of the framework uses: Add/Remove/Contains/
    Count/CountRange/Max/Slice/ForEach, set algebra, OffsetRange, Shift, Flip,
    serialization, and the append-only ops log (OpWriter, roaring.go:1128).
    """

    __slots__ = ("containers", "_counts", "op_writer", "op_n",
                 "op_n_small", "oplog_bytes", "snapshot_bytes",
                 "tail_dropped")

    def __init__(self, positions: Optional[Iterable[int]] = None) -> None:
        self.containers: Dict[int, np.ndarray] = {}
        self._counts: Dict[int, int] = {}
        self.op_writer: Optional[io.RawIOBase] = None
        self.op_n = 0
        self.op_n_small = 0   # single-bit op records (types 0/1) only
        self.oplog_bytes = 0  # bytes of op records (replayed + appended)
        self.snapshot_bytes = 0  # size of the snapshot section on read
        self.tail_dropped = 0  # torn-tail bytes discarded by read_bytes
        if positions is not None:
            self.direct_add_n(np.asarray(list(positions), dtype=np.uint64))

    # -- container plumbing -------------------------------------------------

    def _container(self, key: int, create: bool = False) -> Optional[np.ndarray]:
        """Mutable (dense) view of a container: array-encoded containers
        materialize in place, so every existing mutation path works
        unchanged."""
        c = self.containers.get(key)
        if c is None:
            if not create:
                return None
            c = _new_container()
            self.containers[key] = c
        elif c.dtype == np.uint16:
            c = _array_to_dense(c)
            self.containers[key] = c
        return c

    def _invalidate(self, key: int) -> None:
        self._counts.pop(key, None)

    def container_count(self, key: int) -> int:
        n = self._counts.get(key)
        if n is None:
            c = self.containers.get(key)
            if c is None:
                n = 0
            elif c.dtype == np.uint16:
                n = len(c)
            else:
                n = _popcount_words(c)
            self._counts[key] = n
        return n

    def optimize(self) -> int:
        """Re-encode low-cardinality dense containers as sorted-u16
        arrays (reference Bitmap.Optimize, roaring.go:1745): 16-80x less
        host memory for sparse rows (a 48-bit fingerprint container costs
        96 B instead of 8 KiB). Returns the number converted."""
        # Gather candidates first, then extract every position in ONE
        # native ctz sweep and split per container — the per-container
        # unpackbits+nonzero loop made open() O(200 ms) on a 1600-dense-
        # container fragment.
        cand_keys: List[int] = []
        cand_words: List[np.ndarray] = []
        counts: List[int] = []
        converted = 0
        for key, c in list(self.containers.items()):
            if c.dtype == np.uint16:
                continue
            n = self.container_count(key)
            if n == 0:
                del self.containers[key]
                self._invalidate(key)
            elif n <= ARRAY_MAX_SIZE:
                cand_keys.append(key)
                cand_words.append(c)
                counts.append(n)
        if not cand_keys:
            return 0
        pos = native.dense_positions_of(
            cand_words, np.zeros(len(cand_words), np.uint64))
        if pos is None:
            for key, c in zip(cand_keys, cand_words):
                self.containers[key] = _dense_to_array(c)
                converted += 1
            return converted
        # bases were zero, so every value is the in-container position.
        for key, arr in zip(cand_keys,
                            np.split(pos, np.cumsum(counts)[:-1])):
            self.containers[key] = arr.astype(np.uint16)
            converted += 1
        return converted

    def _drop_empty(self, key: int) -> None:
        if key in self.containers and self.container_count(key) == 0:
            del self.containers[key]
            self._invalidate(key)

    # -- point ops ----------------------------------------------------------

    def add(self, *positions: int) -> bool:
        """Add with op-log append (reference Add, roaring.go:161)."""
        changed = False
        for p in positions:
            if self._direct_add(int(p)):
                changed = True
                self._write_op(OP_ADD, value=p)
        return changed

    def _direct_add(self, p: int) -> bool:
        key, low = p >> 16, p & 0xFFFF
        c = self._container(key, create=True)
        w, b = low >> 6, np.uint64(1 << (low & 63))
        if c[w] & b:
            return False
        c[w] |= b
        self._invalidate(key)
        return True

    def direct_add(self, p: int) -> bool:
        return self._direct_add(int(p))

    def remove(self, *positions: int) -> bool:
        changed = False
        for p in positions:
            if self._direct_remove(int(p)):
                changed = True
                self._write_op(OP_REMOVE, value=p)
        return changed

    def _direct_remove(self, p: int) -> bool:
        key, low = p >> 16, p & 0xFFFF
        if key not in self.containers:
            return False
        if not self.contains(p):
            # No-op remove must not materialize an array-encoded
            # container dense (mutex clear_bit probes do this per write).
            return False
        c = self._container(key)
        w, b = low >> 6, np.uint64(1 << (low & 63))
        if not (c[w] & b):
            return False
        c[w] &= ~b
        self._invalidate(key)
        self._drop_empty(key)
        return True

    def contains(self, p: int) -> bool:
        p = int(p)
        c = self.containers.get(p >> 16)
        if c is None:
            return False
        low = p & 0xFFFF
        if c.dtype == np.uint16:
            i = int(np.searchsorted(c, low))
            return i < len(c) and int(c[i]) == low
        return bool(c[low >> 6] & np.uint64(1 << (low & 63)))

    # -- batch ops (the import path; reference DirectAddN / bulkImport) -----

    def direct_add_n(self, positions: np.ndarray,
                     presorted: bool = False) -> int:
        """Bulk add without op-log (reference DirectAddN). Returns
        #changed. presorted=True asserts positions are already sorted
        unique uint64 (bulk_import sorts once and reuses it for the
        touched-row scan)."""
        if len(positions) == 0:
            return 0
        if presorted:
            # Contract: sorted unique; the dtype half is enforced here
            # (an int64 array would break the uint64 shifts below).
            positions = np.ascontiguousarray(positions, dtype=np.uint64)
        else:
            positions = np.unique(np.asarray(positions, dtype=np.uint64))
        changed = 0
        keys = (positions >> np.uint64(16)).astype(np.int64)
        # positions are sorted, so group boundaries come from one
        # unique(return_index) pass — O(N), not O(N x keys).
        uniq, starts = np.unique(keys, return_index=True)
        bounds = np.append(starts, len(positions))
        # Native path: ONE C pass builds every group's dense mask (the
        # data-loader hot loop); Python then only merges per container.
        masks = None
        # Gate on group density and count: the mask block is m x 8 KiB,
        # so a key-sparse import (a bit or two per container) must keep
        # the in-place scatter path instead of allocating gigabytes.
        if len(positions) >= 4096 and len(uniq) <= 65536 and \
                len(positions) >= 64 * len(uniq):
            built = native.build_masks(positions, len(uniq))
            if built is not None:
                masks = built[1]
        for i, key in enumerate(uniq.tolist()):
            group_len = int(bounds[i + 1] - bounds[i])
            if key not in self.containers:
                # New container + unique positions: count is group_len,
                # no popcounts needed.
                if masks is not None:
                    self.containers[key] = masks[i].copy()
                else:
                    group = positions[bounds[i]:bounds[i + 1]]
                    low = (group & np.uint64(0xFFFF)).astype(np.uint32)
                    self.containers[key] = _low_mask(low)
                self._counts[key] = group_len
                changed += group_len
                continue
            c = self._container(key)
            before = self.container_count(key)
            if masks is not None:
                c |= masks[i]
            elif group_len >= 256:
                group = positions[bounds[i]:bounds[i + 1]]
                c |= _low_mask((group & np.uint64(0xFFFF))
                               .astype(np.uint32))
            else:
                group = positions[bounds[i]:bounds[i + 1]]
                low = (group & np.uint64(0xFFFF)).astype(np.uint32)
                # Sparse group into an existing container: scatter in
                # place, no 8 KiB temp mask.
                np.bitwise_or.at(
                    c, low >> 6,
                    np.left_shift(np.uint64(1),
                                  (low & 63).astype(np.uint64)))
            self._invalidate(key)
            changed += self.container_count(key) - before
        return changed

    def direct_remove_n(self, positions: np.ndarray) -> int:
        if len(positions) == 0:
            return 0
        positions = np.unique(np.asarray(positions, dtype=np.uint64))
        changed = 0
        keys = (positions >> np.uint64(16)).astype(np.int64)
        uniq, starts = np.unique(keys, return_index=True)
        bounds = np.append(starts, len(positions))
        for i, key in enumerate(uniq.tolist()):
            if key not in self.containers:
                continue
            c = self._container(key)
            group = positions[bounds[i]:bounds[i + 1]]
            low = (group & np.uint64(0xFFFF)).astype(np.uint32)
            mask = _low_mask(low)
            before = self.container_count(key)
            c &= ~mask
            self._invalidate(key)
            changed += before - self.container_count(key)
            self._drop_empty(key)
        return changed

    def add_batch(self, positions: np.ndarray,
                  presorted: bool = False, log_op: bool = True) -> int:
        """Bulk add *with* one batch op-log record (op type 2).
        log_op=False skips the record — only valid when the caller
        synchronously snapshots before returning (the record would be
        rewritten away immediately; see Fragment.bulk_import)."""
        n = self.direct_add_n(positions, presorted=presorted)
        if len(positions):
            if log_op:
                self._write_op(OP_ADD_BATCH,
                               values=np.asarray(positions,
                                                 dtype=np.uint64))
            else:
                self.op_n += len(positions)
        return n

    def remove_batch(self, positions: np.ndarray) -> int:
        n = self.direct_remove_n(positions)
        if len(positions):
            self._write_op(OP_REMOVE_BATCH, values=np.asarray(positions, dtype=np.uint64))
        return n

    def import_batch(self, row_ids: np.ndarray, col_ids: np.ndarray,
                     swidth_exp: int) -> np.ndarray:
        """Fused bulk import (replaces the reference's bulkImportStandard
        sort + DirectAddN shape, fragment.go:1494-1604): scatter
        (row, col) pairs into dense per-container masks WITHOUT sorting
        (native radix bucket; numpy unique-group fallback), append ONE
        compact OP_ADD_ROARING record whose payload is the batch's own
        roaring snapshot, then merge the masks in. Returns the sorted
        touched container keys. Duplicates within the batch are
        harmless (mask OR)."""
        row_ids = np.ascontiguousarray(row_ids, dtype=np.uint64)
        col_ids = np.ascontiguousarray(col_ids, dtype=np.uint64)
        if len(row_ids) == 0:
            return np.empty(0, dtype=np.uint64)
        nat = None
        if native.available():
            nat = native.import_build(row_ids, col_ids, swidth_exp)
        if nat is not None:
            keys, masks, counts, payload, n_bits = nat
            self._append_roaring_record(payload, n_bits)
            # Merge. Rows of `masks` are views into one freshly-allocated
            # block no one else holds, so when most keys are NEW the
            # containers adopt the views copy-free; when most keys
            # already exist, adopted rows are copied instead so a few
            # survivors don't pin the whole m x 8 KiB parent alive.
            key_list = [int(k) for k in keys.tolist()]
            n_new = sum(1 for k in key_list if k not in self.containers)
            adopt_views = n_new * 2 >= len(key_list)
            count_list = counts.tolist()
            for i, key in enumerate(key_list):
                if key not in self.containers:
                    self.containers[key] = (masks[i] if adopt_views
                                            else masks[i].copy())
                    # Batch cardinality is exact for a fresh container —
                    # seed the count cache instead of re-popcounting on
                    # the row_count pass that follows every import.
                    self._counts[key] = int(count_list[i])
                else:
                    c = self._container(key)
                    c |= masks[i]
                    self._invalidate(key)
            return keys
        # Grouped path (no native library, or a batch shape unsuited to
        # dense scatter — sparse/wide row ranges): sort+unique once,
        # then work per group as sorted-u16 arrays — no dense mask
        # block, so a pathologically sparse batch (a bit per container)
        # stays O(batch) in memory.
        positions = np.unique(
            (row_ids << np.uint64(swidth_exp))
            + (col_ids & np.uint64((1 << swidth_exp) - 1)))
        gkeys = (positions >> np.uint64(16)).astype(np.int64)
        starts = np.concatenate(
            ([0], np.flatnonzero(gkeys[1:] != gkeys[:-1]) + 1))
        bounds = np.append(starts, len(positions)).astype(np.uint64)
        keys = positions[starts] >> np.uint64(16)
        key_list = [int(k) for k in keys.tolist()]
        lows = (positions & np.uint64(0xFFFF)).astype(np.uint16)
        counts_arr = np.diff(bounds.astype(np.int64))
        groups = [lows[bounds[i]:bounds[i + 1]]
                  for i in range(len(starts))]
        payload = None
        if native.available():
            payload = native.serialize_groups(keys, lows, bounds)
        if payload is None:
            payload = _serialize_container_seq(
                ((k, g, len(g)) for k, g in zip(key_list, groups)),
                len(key_list))
        self._append_roaring_record(payload, len(positions))
        if self.containers.keys().isdisjoint(key_list) and \
                int(counts_arr.max(initial=0)) <= ARRAY_MAX_SIZE:
            # All-new sorted-unique array containers (the
            # fingerprint-import shape: a million one-container rows):
            # one C-level dict build instead of a per-key Python loop,
            # counts seeded from the group lengths.
            self.containers.update(zip(key_list, groups))
            self._counts.update(zip(key_list, counts_arr.tolist()))
            return keys
        for k, g in zip(key_list, groups):
            if k not in self.containers:
                if len(g) <= ARRAY_MAX_SIZE:
                    # Sorted unique in-container positions — a valid
                    # array-encoded container as-is.
                    self.containers[k] = g
                else:
                    # Above the array bound the u16 encoding costs up
                    # to 16x a dense container — keep the invariant.
                    self.containers[k] = _low_mask(g.astype(np.uint32))
            else:
                c = self._container(k)
                c |= _low_mask(g.astype(np.uint32))
            self._invalidate(k)
        return keys

    def _append_roaring_record(self, payload: bytes, n_bits: int) -> None:
        """Append an OP_ADD_ROARING record for an already-built batch
        payload; bumps the op accounting the snapshot policy reads."""
        rec = encode_op_roaring(payload)
        self.op_n += n_bits
        self.oplog_bytes += len(rec)
        if self.op_writer is not None:
            _write_all(self.op_writer, rec)

    # -- queries ------------------------------------------------------------

    def count(self) -> int:
        return sum(self.container_count(k) for k in self.containers)

    def any(self) -> bool:
        return any(self.container_count(k) for k in self.containers)

    @staticmethod
    def _positions(c: np.ndarray) -> np.ndarray:
        """Sorted in-container positions for either encoding."""
        return c if c.dtype == np.uint16 else _dense_to_array(c)

    def max(self) -> int:
        if not self.containers:
            return 0
        key = max(self.containers)
        arr = self._positions(self.containers[key])
        return (key << 16) | int(arr[-1])

    def min(self) -> int:
        if not self.containers:
            return 0
        key = min(self.containers)
        arr = self._positions(self.containers[key])
        return (key << 16) | int(arr[0])

    def slice(self) -> np.ndarray:
        """All set positions, sorted (reference Slice, roaring.go:393).
        Runs of consecutive dense containers extract through one native
        ctz sweep (pn_dense_positions_ptrs) instead of per-container
        unpackbits+nonzero — the anti-entropy checksum hot path."""
        keys = sorted(self.containers)
        out: List[np.ndarray] = []
        i = 0
        while i < len(keys):
            c = self.containers[keys[i]]
            if _is_array(c):
                if len(c):
                    out.append(np.uint64(keys[i] << 16)
                               + c.astype(np.uint64))
                i += 1
                continue
            j = i
            while j < len(keys) and not _is_array(self.containers[keys[j]]):
                j += 1
            run = keys[i:j]
            pos = native.dense_positions_of(
                [self.containers[k] for k in run],
                np.array(run, np.uint64) << np.uint64(16))
            if pos is None:  # numpy fallback
                for k in run:
                    arr = _dense_to_array(self.containers[k])
                    if len(arr):
                        out.append(np.uint64(k << 16)
                                   + arr.astype(np.uint64))
            elif len(pos):
                out.append(pos)
            i = j
        if not out:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(out)

    def __iter__(self) -> Iterator[int]:
        return iter(self.slice().tolist())

    def count_range(self, start: int, end: int) -> int:
        """Count of bits in [start, end) (reference CountRange, roaring.go:335)."""
        if end <= start:
            return 0
        total = 0
        k0, k1 = start >> 16, (end - 1) >> 16
        # Walk whichever key set is smaller: the range span (row reads are
        # 16 containers) or the populated containers — never both.
        if k1 - k0 + 1 <= len(self.containers):
            keys = (k for k in range(k0, k1 + 1) if k in self.containers)
        else:
            keys = (k for k in self.containers if k0 <= k <= k1)
        for key in keys:
            lo = start - (key << 16) if key == k0 else 0
            hi = end - (key << 16) if key == k1 else CONTAINER_BITS
            lo, hi = max(lo, 0), min(hi, CONTAINER_BITS)
            if lo == 0 and hi == CONTAINER_BITS:
                total += self.container_count(key)
            else:
                arr = self._positions(self.containers[key])
                total += int(np.count_nonzero((arr >= lo) & (arr < hi)))
        return total

    def offset_range(self, offset: int, start: int, end: int) -> "Bitmap":
        """Slice bits in [start, end) and rebase them at `offset` (reference
        OffsetRange, roaring.go:439 — the fragment row-read primitive,
        fragment.go:378). offset/start/end must be container-aligned."""
        assert offset & 0xFFFF == 0 and start & 0xFFFF == 0 and end & 0xFFFF == 0
        other = Bitmap()
        off_key = offset >> 16
        hi0, hi1 = start >> 16, end >> 16
        for key, c in self.containers.items():
            if hi0 <= key < hi1:
                if self.container_count(key):
                    other.containers[off_key + (key - hi0)] = c.copy()
        return other

    def dense_range(self, start: int, end: int) -> np.ndarray:
        """Dense uint64 words for bits [start, end) (container-aligned) —
        the host->HBM handoff: returns ((end-start)//64) words."""
        assert start & 0xFFFF == 0 and end & 0xFFFF == 0
        n_containers = (end - start) >> 16
        out = np.zeros(n_containers * CONTAINER_WORDS, dtype=np.uint64)
        k0 = start >> 16
        for i in range(n_containers):
            c = self.containers.get(k0 + i)
            if c is None:
                continue
            seg = out[i * CONTAINER_WORDS:(i + 1) * CONTAINER_WORDS]
            if c.dtype == np.uint16:
                # Decode straight into the output — no 8 KiB temp.
                v = c.astype(np.uint32)
                np.bitwise_or.at(
                    seg, v >> 6,
                    np.left_shift(np.uint64(1), (v & 63).astype(np.uint64)))
            else:
                seg[:] = c
        return out

    def set_dense_range(self, start: int, dense: np.ndarray) -> None:
        """Overwrite container-aligned region from dense uint64 words."""
        assert start & 0xFFFF == 0 and len(dense) % CONTAINER_WORDS == 0
        k0 = start >> 16
        for i in range(len(dense) // CONTAINER_WORDS):
            chunk = dense[i * CONTAINER_WORDS : (i + 1) * CONTAINER_WORDS]
            key = k0 + i
            if chunk.any():
                self.containers[key] = np.array(chunk, dtype=np.uint64)
                self._invalidate(key)
            elif key in self.containers:
                del self.containers[key]
                self._invalidate(key)

    def for_each_range(self, start: int, end: int) -> np.ndarray:
        # Touch only containers intersecting [start, end): block-scoped
        # callers (checksum_blocks walks 100-row blocks) must not pay a
        # whole-bitmap extraction per block.
        k0, k1 = start >> 16, (end - 1) >> 16
        sub = Bitmap()
        sub.containers = {k: c for k, c in self.containers.items()
                          if k0 <= k <= k1}
        s = sub.slice()
        if len(s) and (start & 0xFFFF or end & 0xFFFF):
            s = s[(s >= start) & (s < end)]
        return s

    # -- set algebra (host path / CPU baseline) -----------------------------

    def _binary(self, other: "Bitmap", op: Callable[..., np.ndarray],
                keys: Iterable[int]) -> "Bitmap":
        out = Bitmap()
        zero = None
        for key in keys:
            a = self.containers.get(key)
            b = other.containers.get(key)
            if a is None or b is None:
                if zero is None:
                    zero = _new_container()
                a = a if a is not None else zero
                b = b if b is not None else zero
            res = op(_as_dense(a), _as_dense(b))
            if res.any():
                out.containers[key] = res
        return out

    def intersect(self, other: "Bitmap") -> "Bitmap":
        keys = self.containers.keys() & other.containers.keys()
        return self._binary(other, np.bitwise_and, keys)

    def union(self, other: "Bitmap") -> "Bitmap":
        keys = self.containers.keys() | other.containers.keys()
        return self._binary(other, np.bitwise_or, keys)

    def difference(self, other: "Bitmap") -> "Bitmap":
        keys = self.containers.keys()
        return self._binary(other, lambda a, b: a & ~b, keys)

    def xor(self, other: "Bitmap") -> "Bitmap":
        keys = self.containers.keys() | other.containers.keys()
        return self._binary(other, np.bitwise_xor, keys)

    def intersection_count(self, other: "Bitmap") -> int:
        total = 0
        for key in self.containers.keys() & other.containers.keys():
            a, b = self.containers[key], other.containers[key]
            if a.dtype == np.uint16 and b.dtype != np.uint16:
                a, b = b, a
            if b.dtype == np.uint16:
                if a.dtype == np.uint16:
                    total += len(np.intersect1d(a, b, assume_unique=True))
                else:
                    # Probe the dense side at the array's positions.
                    v = b.astype(np.uint32)
                    bits = (a[v >> 6] >> (v & 63).astype(np.uint64)) \
                        & np.uint64(1)
                    total += int(bits.sum())
            else:
                total += _popcount_words(a & b)
        return total

    def union_in_place(self, *others: "Bitmap") -> None:
        """N-way in-place union (reference UnionInPlace, roaring.go:536)."""
        for other in others:
            for key, b in other.containers.items():
                if key not in self.containers:
                    self.containers[key] = b.copy()
                else:
                    a = self._container(key)
                    a |= _as_dense(b)
                self._invalidate(key)

    def copy(self) -> "Bitmap":
        out = Bitmap()
        out.containers = {k: v.copy() for k, v in self.containers.items()}
        out._counts = dict(self._counts)
        return out

    def shift(self, n: int = 1) -> "Bitmap":
        """Shift all bit positions up by n (reference Shift, roaring.go:865)."""
        return Bitmap(self.slice() + np.uint64(n))

    def flip(self, start: int, end: int) -> "Bitmap":
        """Flip bits in [start, end] inclusive (reference Flip, roaring.go:1185).
        Vectorized: XOR each touched container with a range mask; only the two
        boundary containers need partial masks."""
        out = self.copy()
        k0, k1 = start >> 16, end >> 16
        for key in range(k0, k1 + 1):
            lo = start - (key << 16) if key == k0 else 0
            hi = end - (key << 16) + 1 if key == k1 else CONTAINER_BITS
            c = out._container(key, create=True)
            if lo == 0 and hi == CONTAINER_BITS:
                c ^= np.uint64(0xFFFFFFFFFFFFFFFF)
            else:
                bits = np.zeros(CONTAINER_BITS, dtype=np.uint8)
                bits[lo:hi] = 1
                c ^= np.packbits(bits, bitorder="little").view(np.uint64)
            out._invalidate(key)
            out._drop_empty(key)
        return out

    # -- ops log ------------------------------------------------------------

    def _write_op(self, typ: int, value: int = 0,
                  values: Optional[np.ndarray] = None) -> None:
        self.op_n += 1 if values is None else len(values)
        if values is None:
            self.op_n_small += 1
        # Record length is closed-form — don't encode (fnv over the
        # whole payload) just for accounting when nothing is logging.
        self.oplog_bytes += 13 if values is None else 13 + 8 * len(values)
        if self.op_writer is None:
            return
        _write_all(self.op_writer, encode_op(typ, value, values))

    # -- serialization ------------------------------------------------------

    def write_bytes(self) -> bytes:
        """Serialize in the reference's file format (roaring.go:963).
        Uses the native C++ codec (native/pilosa_native.cpp
        rb_serialize_ptrs — per-container pointers, no stacking copy)
        when available; the Python path is the reference semantics and
        produces byte-identical output."""
        keys = [k for k in sorted(self.containers) if self.container_count(k) > 0]
        n_u16 = sum(1 for k in keys
                    if self.containers[k].dtype == np.uint16)
        if native.available() and n_u16 * 4 > len(keys):
            # u16-heavy (fingerprint-shaped) bitmaps: serialize from
            # sorted position groups — densifying every array container
            # first costs ~30 us each and dominated snapshot time at
            # ~16k sparse containers.
            out = self._write_bytes_groups(keys)
            if out is not None:
                return out
        # Dense-heavy: per-container pointers, temps only for the few
        # array-encoded ones; cap their footprint so an all-sparse
        # million-container bitmap can't materialize gigabytes at once.
        if native.available() and n_u16 * 8 * CONTAINER_WORDS <= (256 << 20):
            dense = [_as_dense(self.containers[k]) for k in keys]
            out = native.roaring_serialize_ptrs(
                np.array(keys, dtype=np.uint64), dense)
            if out is not None:
                return out
        return _serialize_container_seq(
            ((key, self.containers[key], self.container_count(key))
             for key in keys), len(keys))

    def _write_bytes_groups(self, keys: List[int]) -> Optional[bytes]:
        """Native groups serializer over mixed containers: u16 arrays
        contribute their positions verbatim; dense containers extract
        through one native ctz sweep. Returns None if unavailable.

        Note: groups with >=4096 positions are written bitmap-encoded
        (pn_serialize_groups never picks run encoding — for the dense
        side this matches rb_serialize only when runs wouldn't win, so
        this path is gated to u16-heavy bitmaps where dense containers
        are rare and byte-exactness of encoding CHOICE is not part of
        the format contract — any valid encoding reads back equal)."""
        lows_parts: List[np.ndarray] = []
        counts: List[int] = []
        dense_chunks: List[np.ndarray] = []
        dense_slots: List[int] = []
        for i, k in enumerate(keys):
            c = self.containers[k]
            if c.dtype == np.uint16:
                lows_parts.append(c)
                counts.append(len(c))
            else:
                lows_parts.append(None)  # patched below
                dense_chunks.append(c)
                dense_slots.append(i)
                counts.append(self.container_count(k))
        if dense_chunks:
            pos = native.dense_positions_of(
                dense_chunks, np.zeros(len(dense_chunks), np.uint64))
            if pos is None:
                return None
            dcounts = [self.container_count(keys[i]) for i in dense_slots]
            for arr, slot in zip(
                    np.split(pos.astype(np.uint16),
                             np.cumsum(dcounts)[:-1]), dense_slots):
                lows_parts[slot] = arr
        lows = (np.concatenate(lows_parts) if lows_parts
                else np.empty(0, dtype=np.uint16))
        bounds = np.concatenate(
            ([0], np.cumsum(counts, dtype=np.uint64)))
        return native.serialize_groups(
            np.array(keys, dtype=np.uint64), lows, bounds)

    @classmethod
    def from_bytes(cls, data: bytes,
                   tolerate_torn_tail: bool = False,
                   _depth: int = 0) -> "Bitmap":
        """Deserialize (reference unmarshalPilosaRoaring, roaring.go:1037),
        including ops-log replay from the file tail."""
        b = cls()
        b.read_bytes(data, tolerate_torn_tail=tolerate_torn_tail,
                     _depth=_depth)
        return b

    def read_bytes(self, data: bytes,
                   tolerate_torn_tail: bool = False,
                   _depth: int = 0) -> None:
        """Deserialize. tolerate_torn_tail=True (Fragment.open recovering
        its OWN file after a crash) drops a final op record torn at EOF
        and reports it via self.tail_dropped; the default keeps fail-hard
        semantics for wire-received bytes (a truncated import payload
        must error, not silently half-apply)."""
        self.tail_dropped = 0
        if native.available():
            # Encoding-split load: array-eligible containers arrive as
            # u16 position spans of ONE compact buffer (the in-memory
            # encoding optimize() would produce anyway), dense ones as
            # rows of one block — a sparse fingerprint-shaped fragment
            # loads its ~2 MB of real data instead of materializing
            # 8 KiB per tiny container and re-optimizing.
            loaded = native.roaring_load_ex(bytes(data),
                                            split_max_card=ARRAY_MAX_SIZE)
            if loaded is not None:
                if loaded["tail_dropped"] and not tolerate_torn_tail:
                    raise OpTruncatedError(
                        f"op data truncated ({loaded['tail_dropped']} "
                        "tail bytes)")
                counts = loaded["counts"]
                lows, dense = loaded["lows"], loaded["dense"]
                # Containers are VIEWS into the two exactly-sized load
                # blocks (deliberate: per-container copies were the
                # sparse-open bottleneck). Trade-off: dropping a
                # container keeps its parent block alive while any
                # sibling view survives — acceptable because the blocks
                # hold only real data and fragments rarely shrink;
                # mutation is safe (u16 views densify into fresh arrays
                # via _container(); dense rows are disjoint).
                self.containers = {}
                self._counts = {}
                lo = dn = 0
                for i, k in enumerate(loaded["keys"]):
                    c = int(counts[i])
                    if c <= ARRAY_MAX_SIZE:
                        self.containers[k] = lows[lo:lo + c]
                        lo += c
                    else:
                        self.containers[k] = dense[dn]
                        dn += 1
                    self._counts[k] = c
                self.op_n = loaded["op_n"]
                self.op_n_small = loaded["op_n_small"]
                self.oplog_bytes = loaded["ops_bytes"]
                self.snapshot_bytes = loaded["snapshot_bytes"]
                self.tail_dropped = loaded["tail_dropped"]
                return
        if len(data) < HEADER_BASE_SIZE:
            raise ValueError("data too small")
        magic, version = struct.unpack_from("<HH", data, 0)
        if magic != MAGIC_NUMBER:
            raise ValueError(f"invalid roaring file, magic number {magic}")
        if version != STORAGE_VERSION:
            raise ValueError(f"wrong roaring version v{version}")
        (n,) = struct.unpack_from("<I", data, 4)
        self.containers.clear()
        self._counts.clear()
        metas: List[Tuple[int, int, int]] = []
        pos = HEADER_BASE_SIZE
        prev_key = -1
        for _ in range(n):
            key, typ, card_minus_1 = struct.unpack_from("<QHH", data, pos)
            # Strictly-increasing keys are a format invariant; a
            # duplicate would make "last container wins" semantics that
            # the native reader (and the reference) reject. Fuzz corpus
            # div-unsorted-keys pinned the divergence where this reader
            # silently accepted out-of-order keys.
            if key <= prev_key:
                raise ValueError("container keys not sorted")
            prev_key = key
            metas.append((key, typ, card_minus_1 + 1))
            pos += 12
        ops_offset = pos + 4 * n
        for i, (key, typ, card) in enumerate(metas):
            (offset,) = struct.unpack_from("<I", data, pos + 4 * i)
            if offset >= len(data):
                raise ValueError(f"offset out of bounds: {offset}")
            if typ == CONTAINER_ARRAY:
                vals = np.frombuffer(data, dtype="<u2", count=card, offset=offset)
                # Stays array-encoded in memory: a snapshot full of
                # sparse rows opens at ~its file size, not 8 KiB per
                # container. unique() enforces the sorted-distinct
                # invariant the encoding relies on (untrusted input).
                self.containers[key] = np.unique(vals).astype(np.uint16)
                end = offset + 2 * card
            elif typ == CONTAINER_BITMAP:
                words = np.frombuffer(
                    data, dtype="<u8", count=CONTAINER_WORDS, offset=offset
                )
                self.containers[key] = np.array(words, dtype=np.uint64)
                end = offset + 8 * CONTAINER_WORDS
            elif typ == CONTAINER_RUN:
                (run_n,) = struct.unpack_from("<H", data, offset)
                runs = np.frombuffer(
                    data, dtype="<u2", count=run_n * 2,
                    offset=offset + RUN_COUNT_HEADER_SIZE,
                ).reshape(-1, 2)
                self.containers[key] = _runs_to_dense(runs)
                end = offset + RUN_COUNT_HEADER_SIZE + 4 * run_n
            else:
                raise ValueError(f"unknown container type {typ}")
            del card  # header cardinality untrusted; payload is authoritative
            c = self.containers[key]
            if (len(c) == 0 if c.dtype == np.uint16 else not c.any()):
                # Never materialize empty containers (max/min assume every
                # present container has at least one bit).
                del self.containers[key]
            ops_offset = max(ops_offset, end)
        # Ops log replay. A record extending past EOF is a torn tail
        # append (crash mid-write): tolerated, dropped, and reported via
        # tail_dropped so the owner can truncate the file. Checksum
        # mismatches on complete records still raise (data corruption;
        # reference fails on both, op.UnmarshalBinary roaring.go:3659).
        self.op_n = 0
        self.op_n_small = 0
        self.oplog_bytes = 0
        self.snapshot_bytes = ops_offset
        buf = memoryview(data)[ops_offset:]
        while len(buf):
            try:
                op_typ, value, values, size = decode_op(buf)
            except OpTruncatedError:
                if not tolerate_torn_tail:
                    raise
                self.tail_dropped = len(buf)
                break
            if op_typ == OP_ADD:
                self._direct_add(value)
                self.op_n += 1
                self.op_n_small += 1
            elif op_typ == OP_REMOVE:
                self._direct_remove(value)
                self.op_n += 1
                self.op_n_small += 1
            elif op_typ == OP_ADD_BATCH:
                self.direct_add_n(values)
                self.op_n += len(values)
            elif op_typ == OP_REMOVE_BATCH:
                self.direct_remove_n(values)
                self.op_n += len(values)
            elif op_typ == OP_ADD_ROARING:
                if _depth + 1 >= MAX_OP_NESTING:
                    raise ValueError("op nesting too deep")
                batch = Bitmap.from_bytes(values, _depth=_depth + 1)
                self.op_n += batch.count()
                self.union_in_place(batch)
            self.oplog_bytes += size
            buf = buf[size:]


def _serialize_container_seq(items: Iterable[Tuple[int, np.ndarray, int]],
                             n: int) -> bytes:
    """Serialize (key, container, count) triples — sorted, non-empty —
    to the file format, one dense temp at a time (the Python writer
    shared by write_bytes and the import-batch fallback). Encoding
    choice mirrors Optimize, roaring.go:1745-1805."""
    header = io.BytesIO()
    header.write(struct.pack("<II", COOKIE, n))
    payloads: List[bytes] = []
    for key, c, card in items:
        dense = _as_dense(c)  # 8 KiB temp at most
        runs = _dense_to_runs(dense)
        run_size = RUN_COUNT_HEADER_SIZE + 4 * len(runs)
        array_size = 2 * card
        if run_size < min(array_size, 8192):
            typ = CONTAINER_RUN
            payloads.append(
                struct.pack("<H", len(runs)) + runs.astype("<u2").tobytes())
        elif array_size < 8192:
            typ = CONTAINER_ARRAY
            payloads.append(_dense_to_array(dense).astype("<u2").tobytes())
        else:
            typ = CONTAINER_BITMAP
            payloads.append(dense.astype("<u8").tobytes())
        header.write(struct.pack("<QHH", int(key), typ, card - 1))
    offset = HEADER_BASE_SIZE + n * 12 + n * 4
    for p in payloads:
        header.write(struct.pack("<I", offset))
        offset += len(p)
    return header.getvalue() + b"".join(payloads)


def encode_op(typ: int, value: int = 0, values: Optional[np.ndarray] = None) -> bytes:
    """Encode one ops-log record (reference op.WriteTo, roaring.go:3628)."""
    if typ in (OP_ADD, OP_REMOVE):
        head = struct.pack("<BQ", typ, int(value))
        chk = fnv1a32(head)
        return head + struct.pack("<I", chk)
    vals = np.asarray(values, dtype="<u8").tobytes()
    head = struct.pack("<BQ", typ, len(values))
    chk = fnv1a32(head, vals)
    return head + struct.pack("<I", chk) + vals


def encode_op_roaring(payload: bytes) -> bytes:
    """Encode an OP_ADD_ROARING record: crc32 (zlib) over head+payload —
    fnv1a is byte-serial and too slow for multi-MB batch payloads."""
    import zlib

    head = struct.pack("<BQ", OP_ADD_ROARING, len(payload))
    chk = zlib.crc32(payload, zlib.crc32(head))
    return head + struct.pack("<I", chk) + payload


class OpTruncatedError(ValueError):
    """An op record extends past EOF — a torn tail append."""


def decode_op(buf: bytes) -> Tuple[int, int, Optional[np.ndarray], int]:
    """Decode one op record; returns (type, value, values, encoded_size).
    For OP_ADD_ROARING, `values` is the raw payload bytes."""
    if len(buf) < 13:
        raise OpTruncatedError(f"op data out of bounds: len={len(buf)}")
    typ, value = struct.unpack_from("<BQ", buf, 0)
    (chk,) = struct.unpack_from("<I", buf, 9)
    if typ in (OP_ADD, OP_REMOVE):
        if chk != fnv1a32(bytes(buf[0:9])):
            raise ValueError("op checksum mismatch")
        return typ, value, None, 13
    if typ in (OP_ADD_BATCH, OP_REMOVE_BATCH):
        n = value
        size = 13 + 8 * n
        if len(buf) < size:
            raise OpTruncatedError("op data truncated")
        if chk != fnv1a32(bytes(buf[0:9]), bytes(buf[13:size])):
            raise ValueError("op checksum mismatch")
        values = np.frombuffer(buf, dtype="<u8", count=n, offset=13).copy()
        return typ, 0, values, size
    if typ == OP_ADD_ROARING:
        import zlib

        size = 13 + value
        if len(buf) < size:
            raise OpTruncatedError("op data truncated")
        payload = bytes(buf[13:size])
        if chk != zlib.crc32(payload, zlib.crc32(bytes(buf[0:9]))):
            raise ValueError("op checksum mismatch")
        return typ, 0, payload, size
    raise ValueError(f"invalid op type {typ}")
