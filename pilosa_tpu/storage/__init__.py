"""Host storage layer: Pilosa-roaring-format durability + ops log.

The reference keeps roaring containers as its *in-memory compute*
representation (/root/reference/roaring/roaring.go). In the TPU rebuild the
compute representation is dense packed words in HBM; roaring survives here as
the durable interchange format (file cookie 12348) plus a numpy-dense host
bitmap used for writes, imports, and the CPU baseline path.
"""

from pilosa_tpu.storage.roaring import Bitmap, MAGIC_NUMBER  # noqa: F401
