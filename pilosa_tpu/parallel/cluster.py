"""Cluster topology and state.

Reference: /root/reference/cluster.go:172 (cluster struct), states
STARTING/DEGRADED/NORMAL/RESIZING (:44-48), `.topology` persistence
(:1611-1646), coordinator-driven joins (:1017-1148).

Divergences, by design:
- Membership is a static peer list + explicit join/remove calls over HTTP
  (no SWIM gossip): the single-controller deployment model makes an
  eventually-consistent membership protocol unnecessary; failure detection
  happens at request time with replica failover (the reference does that
  part the same way, executor.go:2313-2324).
- Resize is pull-based: after a topology change every node fetches the
  fragments it now owns from any current holder (reference pushes
  ResizeInstructions from the coordinator, cluster.go:1251-1360 — same
  data motion, simpler control flow).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pilosa_tpu.parallel.hashing import (
    DEFAULT_PARTITION_N, shard_nodes,
)

STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"


@dataclass
class Node:
    id: str
    uri: str  # http://host:port
    is_coordinator: bool = False

    def to_json(self) -> dict:
        return {"id": self.id, "uri": self.uri,
                "isCoordinator": self.is_coordinator}

    @classmethod
    def from_json(cls, d: dict) -> "Node":
        return cls(d["id"], d["uri"], d.get("isCoordinator", False))


class Cluster:
    """Node set sorted by id (reference cluster.go:589) with hashed shard
    placement and replica failover bookkeeping."""

    def __init__(self, local: Node, replica_n: int = 1,
                 partition_n: int = DEFAULT_PARTITION_N,
                 topology_path: Optional[str] = None):
        self.local = local
        self.replica_n = replica_n
        self.partition_n = partition_n
        self.topology_path = topology_path
        self.state = STATE_STARTING
        self._nodes: Dict[str, Node] = {local.id: local}
        self._lock = threading.RLock()

    # -- membership ---------------------------------------------------------

    def nodes(self) -> List[Node]:
        with self._lock:
            return [self._nodes[k] for k in sorted(self._nodes)]

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.id] = node
            self._update_state()
            self.save()

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)
            self._update_state()
            self.save()

    def node_by_id(self, node_id: str) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def _update_state(self) -> None:
        if self.state != STATE_STARTING:
            self.state = STATE_NORMAL

    def set_state(self, state: str) -> None:
        with self._lock:
            self.state = state

    # -- placement ----------------------------------------------------------

    def shard_nodes(self, index: str, shard: int) -> List[Node]:
        """Primary + replicas for a shard (reference ShardNodes,
        cluster.go:840)."""
        nodes = self.nodes()
        idxs = shard_nodes(index, shard, len(nodes), self.replica_n,
                           self.partition_n)
        return [nodes[i] for i in idxs]

    def owns_shard(self, index: str, shard: int) -> bool:
        return any(n.id == self.local.id
                   for n in self.shard_nodes(index, shard))

    def is_primary(self, index: str, shard: int) -> bool:
        sn = self.shard_nodes(index, shard)
        return bool(sn) and sn[0].id == self.local.id

    def shards_by_node(self, index: str, shards: List[int],
                       exclude_ids: Optional[set] = None
                       ) -> Dict[str, List[int]]:
        """Group shards by serving node id, preferring the primary and
        falling back down the replica chain when primaries are excluded
        (the mapReduce retry path, executor.go:2313-2324)."""
        out: Dict[str, List[int]] = {}
        for shard in shards:
            for node in self.shard_nodes(index, shard):
                if exclude_ids and node.id in exclude_ids:
                    continue
                out.setdefault(node.id, []).append(shard)
                break
            else:
                raise RuntimeError(
                    f"shard {shard} unavailable: all replicas excluded")
        return out

    # -- persistence (reference .topology, cluster.go:1611-1646) ------------

    def save(self) -> None:
        if not self.topology_path:
            return
        tmp = self.topology_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"nodes": [n.to_json() for n in self.nodes()],
                       "replicaN": self.replica_n}, f)
        os.replace(tmp, self.topology_path)

    def load(self) -> None:
        if not self.topology_path or not os.path.exists(self.topology_path):
            return
        with open(self.topology_path) as f:
            data = json.load(f)
        with self._lock:
            for nd in data.get("nodes", []):
                node = Node.from_json(nd)
                if node.id != self.local.id:
                    self._nodes[node.id] = node
            self.replica_n = data.get("replicaN", self.replica_n)

    def status(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "localID": self.local.id,
                    "replicaN": self.replica_n,
                    "nodes": [n.to_json() for n in self.nodes()]}
