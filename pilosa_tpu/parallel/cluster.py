"""Cluster topology and state.

Reference: /root/reference/cluster.go:172 (cluster struct), states
STARTING/DEGRADED/NORMAL/RESIZING (:44-48), `.topology` persistence
(:1611-1646), coordinator-driven joins (:1017-1148).

Divergences, by design:
- Membership is a static peer list + explicit join/remove calls over HTTP
  (no SWIM gossip): the single-controller deployment model makes an
  eventually-consistent membership protocol unnecessary; failure detection
  happens at request time with replica failover (the reference does that
  part the same way, executor.go:2313-2324).
- Resize is pull-based: after a topology change every node fetches the
  fragments it now owns from any current holder (reference pushes
  ResizeInstructions from the coordinator, cluster.go:1251-1360 — same
  data motion, simpler control flow).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from pilosa_tpu.utils.locks import make_rlock
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from pilosa_tpu.parallel.hashing import (
    DEFAULT_PARTITION_N, shard_nodes,
)

STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"


@dataclass
class Node:
    id: str
    uri: str  # http://host:port
    is_coordinator: bool = False

    def to_json(self) -> dict:
        return {"id": self.id, "uri": self.uri,
                "isCoordinator": self.is_coordinator}

    @classmethod
    def from_json(cls, d: dict) -> "Node":
        return cls(d["id"], d["uri"], d.get("isCoordinator", False))


class Cluster:
    """Node set sorted by id (reference cluster.go:589) with hashed shard
    placement and replica failover bookkeeping."""

    def __init__(self, local: Node, replica_n: int = 1,
                 partition_n: int = DEFAULT_PARTITION_N,
                 topology_path: Optional[str] = None):
        self.local = local
        self.replica_n = replica_n
        self.partition_n = partition_n
        self.topology_path = topology_path
        self.state = STATE_STARTING
        self._nodes: Dict[str, Node] = {local.id: local}
        # While RESIZING, reads route against this pre-change snapshot of
        # the node list — the nodes that actually hold the data — until the
        # resize job reports completion (the safety the reference gets from
        # rejecting queries in state RESIZING, api.go:76-99; here the query
        # path stays available instead).
        self.prev_nodes: Optional[List[Node]] = None
        # Members the failure detector currently believes are dead
        # (reference: memberlist SWIM drives node state, gossip/gossip.go;
        # DEGRADED when members are missing, cluster.go:522-533). Routing
        # prefers up replicas, so a down node costs zero request timeouts.
        self.down_ids: set = set()
        # Bumped on every begin_resize: an in-flight resize job refuses to
        # finalize if a newer topology change superseded it (overlapping
        # joins must not adopt the new placement until the LAST job's
        # pulls complete).
        self.resize_gen = 0
        # Monotone placement generation: bumped on every membership or
        # placement adoption (add/remove node, resize completion). The
        # serving layer keys cache invalidation on it — a result/rank
        # cache entry filled under one placement must not survive into
        # the next unexamined (the PR 10 epoch-guard pattern applied to
        # topology instead of fragments).
        self.placement_gen = 0
        # Bounded cluster lifecycle event ring: membership changes,
        # failure-detector verdicts, resize begin/complete. Served in
        # /internal/health (clusterEvents), merged fleet-wide at
        # GET /cluster/timeline, so a chaos kill/recovery is visible in
        # the same planes an operator already watches.
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=256)
        # Pinned key-translation primary. None = lexically-first member
        # (single-node / static bootstrap). Pinned before the first
        # dynamic membership change so a joiner with a smaller id cannot
        # steal primacy with an EMPTY key store and mint colliding ids
        # (the reference pins the translate source by ring position,
        # cluster.go:1908-1935).
        self.translate_primary_id: Optional[str] = None
        self._lock = make_rlock("Cluster._lock")

    def translate_primary(self) -> Node:
        with self._lock:
            if self.translate_primary_id is not None:
                n = self._nodes.get(self.translate_primary_id)
                if n is not None:
                    return n
            return self._nodes[sorted(self._nodes)[0]]

    def previous_node(self) -> Optional[Node]:
        """The node listed before the local node in id order, wrapping
        (reference unprotectedPreviousNode, cluster.go:1919-1935); None
        in a single-node cluster. This is each replica's translate-log
        streaming source: chaining from ring predecessors bounds the
        primary's replication egress to ONE stream however large the
        cluster (reference setPrimaryTranslateStore at
        cluster.go:1908-1910)."""
        with self._lock:
            ids = sorted(self._nodes)
            if len(ids) <= 1 or self.local.id not in self._nodes:
                return None
            pos = ids.index(self.local.id)
            return self._nodes[ids[pos - 1]]  # -1 wraps to the last

    def pin_translate_primary(self, node_id: Optional[str] = None) -> str:
        """Pin (or re-pin) the translation primary; defaults to the
        current effective primary. Returns the pinned id."""
        with self._lock:
            if node_id is None:
                node_id = self.translate_primary().id
            self.translate_primary_id = node_id
            self.save()
            return node_id

    # -- membership ---------------------------------------------------------

    def nodes(self) -> List[Node]:
        with self._lock:
            return [self._nodes[k] for k in sorted(self._nodes)]

    def known_nodes(self) -> List[Node]:
        """Current members ∪ pre-resize members, sorted by id — every node
        that may still hold or serve data mid-resize (pull sources, shard
        discovery, write fan-out all use this union)."""
        with self._lock:
            out = dict(self._nodes)
            for n in (self.prev_nodes or []):
                out.setdefault(n.id, n)
            return [out[k] for k in sorted(out)]

    def member_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._nodes)

    # -- lifecycle events ----------------------------------------------------

    def _note_event(self, typ: str, node_id: Optional[str] = None,
                    **detail: Any) -> None:
        """Record one lifecycle event (lock held by callers). Ring-
        bounded; pure host dict work."""
        ev: Dict[str, Any] = {"time": time.time(), "type": typ,
                              "state": self.state}
        if node_id is not None:
            ev["node"] = node_id
        ev.update(detail)
        self.events.append(ev)

    def recent_events(self, last: int = 64) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self.events)
        last = int(last)
        return evs[-last:] if last > 0 else []

    def add_node(self, node: Node) -> None:
        with self._lock:
            fresh = node.id not in self._nodes
            self._nodes[node.id] = node
            self._update_state()
            if fresh:
                self.placement_gen += 1
                self._note_event("node-join", node.id, uri=node.uri)
            self.save()

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            removed = self._nodes.pop(node_id, None)
            self.down_ids.discard(node_id)
            self._update_state()
            if removed is not None:
                self.placement_gen += 1
                self._note_event("node-leave", node_id)
            self.save()

    def node_by_id(self, node_id: str) -> Optional[Node]:
        with self._lock:
            hit = self._nodes.get(node_id)
            if hit is None and self.prev_nodes is not None:
                # A node can be routable-by-previous-placement (reads
                # during a remove-node resize) without being a member.
                hit = next((n for n in self.prev_nodes if n.id == node_id),
                           None)
            return hit

    def _update_state(self) -> None:
        if self.state in (STATE_STARTING, STATE_RESIZING):
            return
        self.state = STATE_DEGRADED if self.down_ids else STATE_NORMAL

    def set_state(self, state: str) -> None:
        with self._lock:
            self.state = state

    # -- failure detection ---------------------------------------------------

    def mark_down(self, node_id: str) -> bool:
        """Failure detector verdict: peer unreachable. DEGRADED while any
        member is down (reference cluster.go:522-533). Returns True when
        this changed the node's state."""
        with self._lock:
            if node_id == self.local.id or node_id not in self._nodes \
                    or node_id in self.down_ids:
                return False
            self.down_ids.add(node_id)
            if self.state == STATE_NORMAL:
                self.state = STATE_DEGRADED
            self._note_event("node-down", node_id)
            return True

    def mark_up(self, node_id: str) -> bool:
        with self._lock:
            if node_id not in self.down_ids:
                return False
            self.down_ids.discard(node_id)
            self.down_ids &= set(self._nodes)
            if self.state == STATE_DEGRADED and not self.down_ids:
                self.state = STATE_NORMAL
            self._note_event("node-up", node_id)
            return True

    # -- resize lifecycle ----------------------------------------------------

    def begin_resize(self, prev: Optional[List[Node]] = None) -> List[Node]:
        """Enter RESIZING, pinning the pre-change placement (reference
        broadcasts ClusterStatus{state: RESIZING}, cluster.go:1070). If a
        second topology change arrives mid-resize the ORIGINAL snapshot is
        kept — data still lives where the oldest placement says. Returns
        the pinned snapshot so callers broadcast EXACTLY what this node
        pinned (reading prev_nodes separately would race a concurrent
        end_resize clearing it)."""
        with self._lock:
            if self.prev_nodes is None:
                self.prev_nodes = (list(prev) if prev is not None
                                   else self.nodes())  # RLock: safe
            self.state = STATE_RESIZING
            self.resize_gen += 1
            self._note_event("resize-begin", gen=self.resize_gen,
                             prev=[n.id for n in self.prev_nodes])
            self.save()
            return list(self.prev_nodes)

    def end_resize(self) -> None:
        """Resize complete (or aborted): adopt the current placement for
        reads and return to NORMAL (reference broadcasts NORMAL after the
        job completes, cluster.go:1048-1060)."""
        with self._lock:
            was_resizing = self.prev_nodes is not None \
                or self.state == STATE_RESIZING
            self.prev_nodes = None
            if self.state == STATE_RESIZING:
                self.state = STATE_NORMAL
            if was_resizing:
                # The new placement takes over for reads: anything
                # keyed on the old placement is now suspect.
                self.placement_gen += 1
                self._note_event("resize-complete", gen=self.resize_gen,
                                 members=sorted(self._nodes))
            self.save()

    # -- placement ----------------------------------------------------------

    def shard_nodes(self, index: str, shard: int,
                    previous: bool = False) -> List[Node]:
        """Primary + replicas for a shard (reference ShardNodes,
        cluster.go:840). previous=True computes against the pre-resize
        snapshot (falls back to current when not resizing)."""
        with self._lock:
            if previous and self.prev_nodes is not None:
                nodes = sorted(self.prev_nodes, key=lambda n: n.id)
            else:
                nodes = self.nodes()
        idxs = shard_nodes(index, shard, len(nodes), self.replica_n,
                           self.partition_n)
        return [nodes[i] for i in idxs]

    def write_nodes(self, index: str, shard: int) -> List[Node]:
        """Nodes a write must reach: current owners, plus — during a
        resize — the pre-change owners (old owners still serve reads, new
        owners may already have pulled; writing to the union closes the
        window where a write lands only on one side)."""
        cur = self.shard_nodes(index, shard)
        with self._lock:
            resizing = self.state == STATE_RESIZING and \
                self.prev_nodes is not None
        # graftlint: disable=GL015 — widening-only guard: a resize
        # STARTING after the check loses nothing (cur is the union
        # source both sides agree on until prev_nodes is set), and
        # shard_nodes(previous=True) re-validates prev_nodes under the
        # lock — a resize FINISHING in the window falls back to the
        # current epoch. Read routing, where staleness undercounted,
        # is route_shards — check and act in ONE acquisition.
        if not resizing:
            return cur
        prev = self.shard_nodes(index, shard, previous=True)
        seen = {n.id for n in prev}
        return prev + [n for n in cur if n.id not in seen]

    def route_shards(self, index: str, shards: List[int],
                     exclude_ids: Optional[set] = None
                     ) -> "tuple[Dict[str, List[int]], bool]":
        """shards_by_node with the RESIZING check made ATOMICALLY with
        the placement computation, returning (by_node, used_previous).
        A topology change landing between a caller's separate state
        read and its placement math could otherwise route a shard to a
        just-joined owner that has not pulled yet — which answers
        without error and the merge silently undercounts (caught live
        by tools/chaos.py: a TopN missing exactly one shard during a
        join). The RLock makes the nested per-shard placement reads
        consistent with the state check."""
        with self._lock:
            previous = self.state == STATE_RESIZING
            return self.shards_by_node(index, shards,
                                       exclude_ids=exclude_ids,
                                       previous=previous), previous

    def owners_match_membership(self, member_ids: List[str]) -> bool:
        """True when this node's membership equals `member_ids` — used to
        ignore a resize-complete broadcast for a topology this node has
        already moved past (overlapping resizes)."""
        return self.member_ids() == sorted(member_ids)

    def owns_shard(self, index: str, shard: int) -> bool:
        return any(n.id == self.local.id
                   for n in self.shard_nodes(index, shard))

    def is_primary(self, index: str, shard: int) -> bool:
        sn = self.shard_nodes(index, shard)
        return bool(sn) and sn[0].id == self.local.id

    def shards_by_node(self, index: str, shards: List[int],
                       exclude_ids: Optional[set] = None,
                       previous: bool = False) -> Dict[str, List[int]]:
        """Group shards by serving node id, preferring the primary and
        falling back down the replica chain when primaries are excluded
        (the mapReduce retry path, executor.go:2313-2324). Replicas the
        failure detector marks down are deprioritized — proactive
        failover: a dead node costs zero request timeouts — but still
        usable as a last resort (the detector may be stale)."""
        with self._lock:
            down = set(self.down_ids)
        out: Dict[str, List[int]] = {}
        for shard in shards:
            cands = [n for n in self.shard_nodes(index, shard,
                                                 previous=previous)
                     if not (exclude_ids and n.id in exclude_ids)]
            pick = next((n for n in cands if n.id not in down), None)
            if pick is None and cands:
                pick = cands[0]
            if pick is None:
                raise RuntimeError(
                    f"shard {shard} unavailable: all replicas excluded")
            out.setdefault(pick.id, []).append(shard)
        return out

    # -- persistence (reference .topology, cluster.go:1611-1646) ------------

    def save(self) -> None:
        if not self.topology_path:
            return
        tmp = self.topology_path + ".tmp"
        doc = {"nodes": [n.to_json() for n in self.nodes()],
               "replicaN": self.replica_n}
        if self.translate_primary_id is not None:
            doc["translatePrimary"] = self.translate_primary_id
        if self.prev_nodes is not None:
            # Survive a restart mid-resize: reads keep the safe pre-change
            # placement until the job (or an abort) finishes.
            doc["resizing"] = True
            doc["prevNodes"] = [n.to_json() for n in self.prev_nodes]
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.topology_path)

    def load(self) -> None:
        if not self.topology_path or not os.path.exists(self.topology_path):
            return
        with open(self.topology_path) as f:
            data = json.load(f)
        with self._lock:
            for nd in data.get("nodes", []):
                node = Node.from_json(nd)
                if node.id != self.local.id:
                    self._nodes[node.id] = node
            self.replica_n = data.get("replicaN", self.replica_n)
            if data.get("translatePrimary"):
                self.translate_primary_id = data["translatePrimary"]
            if data.get("resizing"):
                self.prev_nodes = [Node.from_json(nd)
                                   for nd in data.get("prevNodes", [])]
                self.state = STATE_RESIZING

    def status(self) -> dict:
        with self._lock:
            out = {"state": self.state,
                   "localID": self.local.id,
                   "replicaN": self.replica_n,
                   "placementGen": self.placement_gen,
                   "nodes": [{**n.to_json(),
                              "state": ("DOWN" if n.id in self.down_ids
                                        else "READY")}
                             for n in self.nodes()]}
            if self.prev_nodes is not None:
                out["prevNodes"] = [n.to_json() for n in self.prev_nodes]
            # Always report the EFFECTIVE allocator (falls back to the
            # lexically-first member before any explicit pin) so an
            # operator can identify it on a static cluster too.
            out["translatePrimary"] = self.translate_primary().id
            return out
