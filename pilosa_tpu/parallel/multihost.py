"""Multi-host SPMD dryrun: two `jax.distributed` processes, one global
mesh, cross-host reductions.

Reference analog: the scatter-gather HTTP fan-out between nodes
(/root/reference/executor.go:2277-2415) and its NCCL-free HTTP data
plane. The TPU-native story (SURVEY §7 step 6): `jax.distributed`
initializes a process group, the mesh spans every host's devices, and
XLA lowers the shard-axis reductions to collectives that ride ICI
within a host/slice and DCN across hosts — no NCCL/MPI code here, just
shardings.

`python -m pilosa_tpu.parallel.multihost` runs the coordinator-side
parent: it spawns two child processes on localhost (each with 4 virtual
CPU devices), initializes jax.distributed in both, builds one
(2 hosts x 4 devices) shard-axis mesh, and runs the framework's fused
Count(Intersect) kernel over a globally-sharded bank assembled with
`jax.make_array_from_callback` — each process contributes only the
shards its addressable devices own, exactly how per-host fragment data
feeds a pod-wide query. The result is verified against a host numpy
model in every process.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np

N_PROCESSES = 2
DEVICES_PER_PROCESS = 4
ROWS = 8
SHARDS = N_PROCESSES * DEVICES_PER_PROCESS
WORDS = 512  # small: the point is the cross-process lowering


def cpu_multiprocess_supported() -> bool:
    """Whether this jax/jaxlib can run cross-process computations on
    the CPU backend: XLA:CPU only implements multi-process collectives
    through a CpuCollectives plugin (gloo over TCP), so both the
    jaxlib hooks and the jax config knob that selects them must exist.
    The dryrun (and its tier-1 test) runs where this holds and skips
    precisely where it cannot — older wheels raise
    "Multiprocess computations aren't implemented on the CPU backend"
    at dispatch time."""
    try:
        import jax
        from jaxlib import xla_client
    except Exception:
        return False
    return (hasattr(xla_client._xla, "make_gloo_tcp_collectives")
            and "jax_cpu_collectives_implementation"
            in getattr(jax.config, "values", {}))


def child(process_id: int, coordinator: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # XLA:CPU needs an explicit collectives plugin for cross-process
    # computations (TPU/GPU backends bring their own); gloo-over-TCP is
    # the portable one. Without this, dispatch fails with
    # "Multiprocess computations aren't implemented on the CPU
    # backend" on every jaxlib that doesn't default it.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=N_PROCESSES,
                               process_id=process_id)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pilosa_tpu.ops.bitset import popcount
    from pilosa_tpu.parallel import MeshContext

    assert len(jax.devices()) == SHARDS, jax.devices()
    assert len(jax.local_devices()) == DEVICES_PER_PROCESS
    mesh = MeshContext()  # all global devices, shard axis
    sharding = NamedSharding(mesh.mesh, P(None, MeshContext.SHARD_AXIS,
                                          None))

    # Every process derives the same global model data from the seed;
    # make_array_from_callback asks each process only for the blocks its
    # own devices hold (per-host fragment data in production).
    rng = np.random.default_rng(123)
    a = rng.integers(0, 2**32, (ROWS, SHARDS, WORDS), dtype=np.uint32)
    b = rng.integers(0, 2**32, (ROWS, SHARDS, WORDS), dtype=np.uint32)
    ga = jax.make_array_from_callback(a.shape, sharding,
                                      lambda idx: a[idx])
    gb = jax.make_array_from_callback(b.shape, sharding,
                                      lambda idx: b[idx])

    # graftlint: disable=GL006 — multihost dry-run probe kernel,
    # compiled once per child process; no serving executor exists here.
    @jax.jit
    def count_intersect(x, y):
        # The executor's fused hot kernel: AND + popcount reduced over
        # the sharded axis — lowers to a cross-process all-reduce.
        return popcount(jnp.bitwise_and(x, y), axis=(-2, -1))

    got = np.asarray(count_intersect(ga, gb))
    want = np.bitwise_count(a & b).sum(axis=(1, 2)) if \
        hasattr(np, "bitwise_count") else None
    if want is not None:
        assert np.array_equal(got, want), (got, want)
    print(f"multihost child {process_id}: OK counts={got[:3].tolist()}...",
          flush=True)
    jax.distributed.shutdown()


def main() -> int:
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        child(int(sys.argv[i + 1]), sys.argv[i + 2])
        return 0
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count="
                        f"{DEVICES_PER_PROCESS}").strip()
    procs = [subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.parallel.multihost",
         "--child", str(i), coordinator], env=env)
        for i in range(N_PROCESSES)]
    rc = 0
    for p in procs:
        try:
            rc |= p.wait(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            rc |= 1
    print(f"multihost dryrun: {'OK' if rc == 0 else 'FAILED'} "
          f"({N_PROCESSES} processes x {DEVICES_PER_PROCESS} devices)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
