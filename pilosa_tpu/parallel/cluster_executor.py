"""Multi-node scatter-gather execution over HTTP.

Reference: /root/reference/executor.go:2277-2415 (mapReduce): group shards
by owning node, execute local shards locally, POST the query to remote
nodes with explicit shard lists (`opt.Remote=true` so remotes do not
re-fan-out), stream-reduce responses, and on node failure re-map that
node's shards onto remaining replicas (:2313-2324).

Reduction here happens on the JSON result shapes (the wire format), one
merge rule per call type — the associative reduceFn table
(executor.go:481-488, row.go:60, cache.go:356).

This HTTP path distributes across *hosts*; within a host the local
executor still batches its shard subset on the TPU mesh. The two layers
compose: DCN-style distribution over HTTP, ICI-style reduction inside the
chip mesh.
"""

from __future__ import annotations

import threading
import time
from pilosa_tpu.utils.locks import make_lock
from pilosa_tpu.utils.timeline import LANE_REMOTE, TIMELINE
from typing import Any, Dict, List, Optional, Sequence

from pilosa_tpu.executor.results import result_to_json
from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.parallel.cluster import Cluster
from pilosa_tpu.pql import Call, parse_string_cached
from pilosa_tpu.ops.bitset import SHARD_WIDTH

_WRITE_SINGLE_COL = {"Set", "Clear"}
# Attr writes go to every node (reference executeSetRowAttrs /
# executeSetColumnAttrs fan to all nodes, executor.go:2063-2080,2225-2240),
# so any coordinator can serve columnAttrs from its local store.
_WRITE_BROADCAST = {"ClearRow", "Store", "SetRowAttrs", "SetColumnAttrs"}


def merge_results(call: Call, parts: List[Any]) -> Any:
    """Associative merge of per-node JSON results for one call."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return None
    # Options() wraps one child; per-node results have the child's shape,
    # so merge by the child's rule (reference reduces on the inner call).
    while call.name == "Options" and call.children:
        call = call.children[0]
    if len(parts) == 1:
        return parts[0]
    name = call.name
    if name == "Count":
        return sum(parts)
    if name in ("Row", "Range", "Intersect", "Union", "Difference", "Xor",
                "Not", "Shift"):
        cols = sorted(set().union(
            *[set(p.get("columns", [])) for p in parts]))
        out = {"columns": cols}
        if any("keys" in p for p in parts):
            # Keep columns[i] <-> keys[i] positional alignment: merge each
            # node's aligned pairs into one map, then emit keys in merged
            # column order.
            by_col = {c: k for p in parts
                      for c, k in zip(p.get("columns", []),
                                      p.get("keys", []))}
            out["keys"] = [by_col.get(c, str(c)) for c in cols]
        attrs = next((p["attrs"] for p in parts if p.get("attrs")), None)
        if attrs:
            out["attrs"] = attrs
        return out
    if name == "TopN":
        acc: Dict[Any, int] = {}
        keyed = any(p and isinstance(p[0], dict) and "key" in p[0]
                    for p in parts if p)
        for p in parts:
            for pair in p:
                k = pair.get("key", pair.get("id"))
                acc[k] = acc.get(k, 0) + pair["count"]
        ordered = sorted(acc.items(), key=lambda kv: (-kv[1], str(kv[0])))
        n = call.uint_arg("n") or 0
        if n:
            ordered = ordered[:n]
        if keyed:
            return [{"key": k, "count": c} for k, c in ordered]
        return [{"id": k, "count": c} for k, c in ordered]
    if name == "Rows":
        limit = call.uint_arg("limit")
        if any("keys" in p for p in parts):
            keys = sorted(set().union(*[set(p.get("keys", []))
                                        for p in parts]))
            return {"keys": keys[:limit] if limit else keys}
        rows = sorted(set().union(*[set(p.get("rows", [])) for p in parts]))
        return {"rows": rows[:limit] if limit else rows}
    if name == "GroupBy":
        acc: Dict[str, dict] = {}
        for p in parts:
            for gc in p:
                key = str(gc["group"])
                if key in acc:
                    acc[key]["count"] += gc["count"]
                else:
                    acc[key] = dict(gc)
        out = sorted(acc.values(), key=lambda g: str(g["group"]))
        limit = call.uint_arg("limit")
        return out[:limit] if limit else out
    if name == "Sum":
        return {"value": sum(p["value"] for p in parts),
                "count": sum(p["count"] for p in parts)}
    if name in ("Min", "Max"):
        nonzero = [p for p in parts if p["count"] > 0]
        if not nonzero:
            return {"value": 0, "count": 0}
        pick = min if name == "Min" else max
        best = pick(p["value"] for p in nonzero)
        return {"value": best,
                "count": sum(p["count"] for p in nonzero
                             if p["value"] == best)}
    if name in _WRITE_SINGLE_COL | _WRITE_BROADCAST:
        return any(bool(p) for p in parts)
    return parts[0]


class ClusterExecutor:
    """Coordinator-side fan-out. Wraps a local Executor; remote legs use
    InternalClient. Replica failover: a failed node's shards re-map onto
    the next replica (reference executor.go:2313-2324)."""

    def __init__(self, local_executor, cluster: Cluster,
                 client: Optional[InternalClient] = None, logger=None,
                 broadcaster=None):
        self.local = local_executor
        self.cluster = cluster
        self.client = client or InternalClient()
        self.logger = logger
        # Optional queued-retry path for the shards-changed push (a
        # briefly-down peer otherwise serves undercounts for up to the
        # TTL after it returns).
        self.broadcaster = broadcaster

    # -- shard discovery ----------------------------------------------------

    GLOBAL_SHARDS_TTL = 2.0

    def global_shards(self, index: str) -> List[int]:
        """Union of every node's locally-available shards, TTL-cached (the
        reference instead broadcasts availableShards on change,
        field.go:228 — a push model; a short pull cache gives the same
        read-path behavior without a broadcast bus)."""
        import time
        cache = getattr(self, "_shards_cache", None)
        if cache is None:
            cache = self._shards_cache = {}
        hit = cache.get(index)
        if hit is not None and time.monotonic() - hit[0] < \
                self.GLOBAL_SHARDS_TTL:
            return hit[1]
        shards = set()
        idx = self.local.holder.index(index)
        if idx is not None:
            shards.update(idx.available_shards())
        # During a resize, data may live only on a pre-change member (e.g.
        # a just-removed node) — ask the union of current and previous
        # membership so discovery cannot miss shards mid-move.
        for node in self.cluster.known_nodes():
            if node.id == self.cluster.local.id:
                continue
            try:
                per_index = self.client.local_shards(node.uri)
                shards.update(per_index.get(index, []))
            except ClientError:
                continue
        out = sorted(shards) or [0]
        cache[index] = (time.monotonic(), out)
        return out

    def invalidate_shards_cache(self, index: str) -> None:
        """Drop the cached global shard list after a write through this
        coordinator (read-your-own-writes for newly created shards)."""
        cache = getattr(self, "_shards_cache", None)
        if cache is not None:
            cache.pop(index, None)

    def note_written_shards(self, index: str, shards) -> None:
        """A completed write touched `shards`: invalidate locally and —
        when any shard is NEW to this coordinator — tell every routable
        node (current ∪ pre-resize members: a departing node still
        serving reads mid-resize needs the push too) to drop its cached
        list. Without the push, another node could serve an undercount
        for up to GLOBAL_SHARDS_TTL after the first write lands in a
        brand-new shard (the reference instead broadcasts
        CreateShardMessage on fragment creation, view.go:221).
        Suppression uses a MONOTONE per-index known-shards set — not the
        TTL cache, which this method itself invalidates — so steady-
        state writes into known shards genuinely broadcast nothing.
        Call AFTER the write has been applied/fanned out: peers
        re-discover on their next read, which must find the data."""
        known = getattr(self, "_known_shards", None)
        if known is None:
            known = self._known_shards = {}
        seen = known.setdefault(index, set())
        fresh = [int(s) for s in shards if int(s) not in seen]
        seen.update(int(s) for s in shards)
        self.invalidate_shards_cache(index)
        if not fresh:
            return
        for node in self.cluster.known_nodes():
            if node.id == self.cluster.local.id:
                continue
            msg = {"type": "shards-changed", "index": index}
            if self.broadcaster is not None:
                # Sync-first: the import ack must mean reachable peers
                # already dropped their shard caches (queue-only opened
                # a read-your-writes-via-another-node window). Down
                # peers get ONE queued copy (coalesce), not a backlog.
                self.broadcaster.send_now_or_queue(node.uri, msg,
                                                   coalesce=True)
                continue
            try:
                self.client.cluster_message(node.uri, msg)
            except ClientError:
                pass

    # -- query --------------------------------------------------------------

    def execute(self, index: str, query: str,
                shards: Optional[Sequence[int]] = None,
                profile=None) -> List[Any]:
        """Returns JSON-shaped results (one per call). `profile` (a
        utils/profile QueryProfile) records the coordinator's local leg
        in its own tree; when it is a forced profile (?profile=true)
        the flag also propagates to every remote leg and the per-node
        fragments merge under profile.nodes — a cross-node query then
        shows where its time went, node by node."""
        from pilosa_tpu.executor.executor import (
            ExecutionError, write_call_count,
        )
        q = parse_string_cached(query) if isinstance(query, str) else query
        limit = self.local.max_writes_per_request
        if limit > 0 and write_call_count(q) > limit:
            # (reference ErrTooManyWrites, executor.go:106)
            raise ExecutionError("too many write commands")
        return [self._execute_call(index, call, shards, profile=profile)
                for call in q.calls]

    def _execute_call(self, index: str, call: Call, shards,
                      profile=None) -> Any:
        inner = call
        while inner.name == "Options" and inner.children:
            # Options(shards=[...]) overrides the scatter set at the
            # coordinator (reference executeOptionsCall, executor.go:344-359).
            # The arg is *consumed* here: the forwarded call must not carry
            # it, or each node would re-override its per-node shard subset
            # with the full list and replicated shards would double-count.
            opt_shards = inner.args.pop("shards", None)
            if isinstance(opt_shards, (list, tuple)):
                shards = [int(s) for s in opt_shards]
            inner = inner.children[0]
        if inner.name in _WRITE_SINGLE_COL:
            return self._execute_write_single(index, inner)
        if inner.name in _WRITE_BROADCAST:
            self.invalidate_shards_cache(index)
            return self._execute_write_broadcast(index, inner)
        all_shards = list(shards) if shards is not None \
            else self.global_shards(index)
        return self._map_reduce(index, call, all_shards, profile=profile)

    def _map_reduce(self, index: str, call: Call, shards: List[int],
                    profile=None) -> Any:
        from pilosa_tpu.parallel.cluster import STATE_RESIZING
        # While RESIZING, route reads against the pre-change placement:
        # those nodes are guaranteed to still hold the data (pulls never
        # delete source copies), where the new placement may point at an
        # owner that has not pulled yet and would silently undercount
        # (reference instead rejects queries in RESIZING, api.go:76-99).
        previous = self.cluster.state == STATE_RESIZING
        # Remote profile propagation only for forced profiles
        # (?profile=true): passive sampling must not make every fan-out
        # leg pay device fencing on its node.
        want_profile = profile is not None and getattr(profile, "forced",
                                                       False)
        # Trace context for the fan-out: captured HERE, on the calling
        # thread (where the request's span/extracted id lives), because
        # the scatter threads below have neither — without an explicit
        # hand-off their query POSTs carry no traceparent and the
        # remote legs record under fresh trace ids (the old stitching
        # only appeared to work via a stale-thread-local side channel).
        tracer = getattr(self.client, "tracer", None)
        trace_id = getattr(profile, "trace_id", None) \
            if profile is not None else None
        if trace_id is None and hasattr(tracer, "current_trace_id"):
            trace_id = tracer.current_trace_id()
        excluded: set = set()
        last_err: Optional[Exception] = None
        for _ in range(max(1, self.cluster.replica_n)):
            try:
                by_node = self.cluster.shards_by_node(index, shards,
                                                      exclude_ids=excluded,
                                                      previous=previous)
            except RuntimeError as e:
                raise last_err or e
            parts: List[Any] = []
            failed = False
            results_lock = make_lock("ClusterExecutor.results_lock")
            threads = []

            def run_remote(node, node_shards):
                nonlocal failed, last_err
                # Scatter threads have no open span: adopt the
                # request's trace id so the outgoing leg injects the
                # SAME traceparent the coordinator received.
                if trace_id and hasattr(tracer, "adopt"):
                    tracer.adopt(trace_id)
                # Remote-leg slice on the coordinator's request
                # timeline: how long this node's scatter-gather round
                # trip took (the remote's own stage slices record on
                # ITS timeline under the same trace id and assemble
                # via /cluster/timeline).
                tl = getattr(profile, "timeline", None) \
                    if profile is not None else None
                t0 = time.perf_counter()
                try:
                    res = self.client.query_node_full(
                        node.uri, index, call.to_pql(), node_shards,
                        profile=want_profile)
                    TIMELINE.event(tl, f"remote:{node.id}", LANE_REMOTE,
                                   t0, time.perf_counter() - t0,
                                   remote=node.id,
                                   shards=len(node_shards))
                    if want_profile and res.get("profile") is not None:
                        profile.add_node_fragment(node.id,
                                                  res["profile"])
                    with results_lock:
                        parts.append(res["results"][0])
                except ClientError as e:
                    TIMELINE.event(tl, f"remote:{node.id}", LANE_REMOTE,
                                   t0, time.perf_counter() - t0,
                                   remote=node.id, error=str(e)[:200])
                    with results_lock:
                        excluded.add(node.id)
                        failed = True
                        last_err = e
                    if self.logger is not None:
                        self.logger.printf("node %s failed, failing over: %s",
                                           node.id, e)

            # Dispatch every remote leg before running the local one so the
            # local evaluation overlaps the network round trips.
            local_shards = None
            for node_id, node_shards in by_node.items():
                if node_id == self.cluster.local.id:
                    local_shards = node_shards
                else:
                    node = self.cluster.node_by_id(node_id)
                    t = threading.Thread(target=run_remote,
                                         args=(node, node_shards))
                    t.start()
                    threads.append(t)
            if local_shards is not None:
                # The coordinator's own leg records into the root
                # profile directly — its ops ARE the tree's trunk.
                local = self.local.execute(index, call.to_pql(),
                                           shards=local_shards,
                                           profile=profile)
                parts.append(result_to_json(local[0]))
            for t in threads:
                t.join()
            if not failed:
                return merge_results(call, parts)
            # retry: re-map every shard against remaining nodes
        raise last_err or RuntimeError("map_reduce failed")

    # -- writes -------------------------------------------------------------

    def _execute_write_single(self, index: str, call: Call) -> Any:
        """Route a single-column write to the owning replicas (reference
        executeSetBitField remote fan, executor.go:1959)."""
        col = call.args.get("_col")
        if isinstance(col, str):
            # Translate on the coordinator so every replica stores the
            # same id (translation stores replicate separately).
            self.local._translate_call(self.local.holder.index(index), call)
            col = call.args["_col"]
        shard = int(col) // SHARD_WIDTH
        # write_nodes = current owners ∪ pre-resize owners while RESIZING,
        # so a write can't land only on the side a reader won't consult.
        owners = self.cluster.write_nodes(index, shard)
        result = False
        applied = 0
        last_err: Optional[Exception] = None
        for node in owners:
            if node.id == self.cluster.local.id:
                (r,) = self.local.execute(index, call.to_pql())
                result = result or bool(r)
                applied += 1
            else:
                try:
                    res = self.client.query_node(node.uri, index, call.to_pql(),
                                                 [shard])
                    result = result or bool(res[0])
                    applied += 1
                except ClientError as e:
                    last_err = e
                    if self.logger is not None:
                        self.logger.printf("write to %s failed: %s",
                                           node.id, e)
        if applied == 0:
            # No replica took the write — surfacing the failure is the only
            # honest answer; anti-entropy can only heal from a copy that
            # exists.
            raise last_err or ClientError("no replica accepted the write")
        # After the write landed (keyed columns translated above): push
        # the shard-list invalidation so no peer undercounts a new shard.
        self.note_written_shards(index, [shard])
        return result

    def _execute_write_broadcast(self, index: str, call: Call) -> Any:
        """Row-scoped writes apply on every node (each owns a shard
        subset)."""
        if isinstance(call.args.get("_col"), str):
            # Translate on the coordinator so every node stores the same id.
            self.local._translate_call(self.local.holder.index(index), call)
        results = []
        for node in self.cluster.nodes():
            if node.id == self.cluster.local.id:
                (r,) = self.local.execute(index, call.to_pql())
                results.append(result_to_json(r))
            else:
                try:
                    res = self.client.query_node(node.uri, index,
                                                 call.to_pql(), [])
                    results.append(res[0])
                except ClientError as e:
                    if self.logger is not None:
                        self.logger.printf("broadcast write to %s failed: %s",
                                           node.id, e)
        return merge_results(call, results)
