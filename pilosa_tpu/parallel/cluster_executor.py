"""Multi-node scatter-gather execution over HTTP.

Reference: /root/reference/executor.go:2277-2415 (mapReduce): group shards
by owning node, execute local shards locally, POST the query to remote
nodes with explicit shard lists (`opt.Remote=true` so remotes do not
re-fan-out), stream-reduce responses, and on node failure re-map that
node's shards onto remaining replicas (:2313-2324).

Reduction here happens on the JSON result shapes (the wire format), one
merge rule per call type — the associative reduceFn table
(executor.go:481-488, row.go:60, cache.go:356).

This HTTP path distributes across *hosts*; within a host the local
executor still batches its shard subset on the TPU mesh. The two layers
compose: DCN-style distribution over HTTP, ICI-style reduction inside the
chip mesh. One process group IS one mesh leg of the fan-out: when the
local executor carries a MeshContext, its leg's shard subset runs the
mesh megakernel cohort path (executor/megakernel.py) — one verified
plan buffer SPMD over the process's devices, count/row lanes reduced
in-kernel by the collective epilogue — and only the already-final
per-leg answers meet the HTTP merge table below. HTTP is kept for the
cross-PROCESS failure domain on purpose (failover, hedged reads,
deadline budgets all operate per leg); device collectives own the
intra-process reduce domain where none of those can happen.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from pilosa_tpu.utils.locks import make_lock
from pilosa_tpu.utils.stats import NopStatsClient
from pilosa_tpu.utils.timeline import LANE_REMOTE, TIMELINE
from typing import Any, Dict, List, Optional, Sequence

from pilosa_tpu.executor.results import result_to_json
from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.parallel.cluster import Cluster
from pilosa_tpu.pql import Call, parse_string_cached
from pilosa_tpu.ops.bitset import SHARD_WIDTH

_WRITE_SINGLE_COL = {"Set", "Clear"}
# Attr writes go to every node (reference executeSetRowAttrs /
# executeSetColumnAttrs fan to all nodes, executor.go:2063-2080,2225-2240),
# so any coordinator can serve columnAttrs from its local store.
_WRITE_BROADCAST = {"ClearRow", "Store", "SetRowAttrs", "SetColumnAttrs"}


def merge_results(call: Call, parts: List[Any]) -> Any:
    """Associative merge of per-node JSON results for one call."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return None
    # Options() wraps one child; per-node results have the child's shape,
    # so merge by the child's rule (reference reduces on the inner call).
    while call.name == "Options" and call.children:
        call = call.children[0]
    if len(parts) == 1:
        return parts[0]
    name = call.name
    if name == "Count":
        return sum(parts)
    if name in ("Row", "Range", "Intersect", "Union", "Difference", "Xor",
                "Not", "Shift"):
        cols = sorted(set().union(
            *[set(p.get("columns", [])) for p in parts]))
        out = {"columns": cols}
        if any("keys" in p for p in parts):
            # Keep columns[i] <-> keys[i] positional alignment: merge each
            # node's aligned pairs into one map, then emit keys in merged
            # column order.
            by_col = {c: k for p in parts
                      for c, k in zip(p.get("columns", []),
                                      p.get("keys", []))}
            out["keys"] = [by_col.get(c, str(c)) for c in cols]
        attrs = next((p["attrs"] for p in parts if p.get("attrs")), None)
        if attrs:
            out["attrs"] = attrs
        return out
    if name == "TopN":
        acc: Dict[Any, int] = {}
        keyed = any(p and isinstance(p[0], dict) and "key" in p[0]
                    for p in parts if p)
        for p in parts:
            for pair in p:
                k = pair.get("key", pair.get("id"))
                acc[k] = acc.get(k, 0) + pair["count"]
        ordered = sorted(acc.items(), key=lambda kv: (-kv[1], str(kv[0])))
        n = call.uint_arg("n") or 0
        if n:
            ordered = ordered[:n]
        if keyed:
            return [{"key": k, "count": c} for k, c in ordered]
        return [{"id": k, "count": c} for k, c in ordered]
    if name == "Rows":
        limit = call.uint_arg("limit")
        if any("keys" in p for p in parts):
            keys = sorted(set().union(*[set(p.get("keys", []))
                                        for p in parts]))
            return {"keys": keys[:limit] if limit else keys}
        rows = sorted(set().union(*[set(p.get("rows", [])) for p in parts]))
        return {"rows": rows[:limit] if limit else rows}
    if name == "GroupBy":
        acc: Dict[str, dict] = {}
        for p in parts:
            for gc in p:
                key = str(gc["group"])
                if key in acc:
                    acc[key]["count"] += gc["count"]
                else:
                    acc[key] = dict(gc)
        out = sorted(acc.values(), key=lambda g: str(g["group"]))
        limit = call.uint_arg("limit")
        return out[:limit] if limit else out
    if name == "Sum":
        return {"value": sum(p["value"] for p in parts),
                "count": sum(p["count"] for p in parts)}
    if name in ("Min", "Max"):
        nonzero = [p for p in parts if p["count"] > 0]
        if not nonzero:
            return {"value": 0, "count": 0}
        pick = min if name == "Min" else max
        best = pick(p["value"] for p in nonzero)
        return {"value": best,
                "count": sum(p["count"] for p in nonzero
                             if p["value"] == best)}
    if name in _WRITE_SINGLE_COL | _WRITE_BROADCAST:
        return any(bool(p) for p in parts)
    return parts[0]


class _Leg:
    """Accounting for one scatter leg of a fan-out round: the shards it
    must deliver, a first-success-wins settle latch (`done` — primary
    vs hedge must never both merge), and the count of in-flight
    attempts (`pending`) so the leg only reads as failed when EVERY
    attempt for it has failed. `event` fires when the primary attempt
    concludes (the hedge monitor waits on it)."""

    __slots__ = ("node", "shards", "done", "pending", "event")

    def __init__(self, node, shards: Sequence[int]) -> None:
        self.node = node
        self.shards = list(shards)
        self.done = False
        self.pending = 1
        self.event = threading.Event()


class ClusterExecutor:
    """Coordinator-side fan-out. Wraps a local Executor; remote legs use
    InternalClient. Replica failover: a failed node's shards re-map onto
    the next replica (reference executor.go:2313-2324).

    Fan-out hardening (the resilience plane, docs/architecture.md):

    - a per-request **deadline budget** (`fanout_deadline_s`) is
      propagated to every remote leg as its RPC timeout, so one wedged
      peer can never hold a request past the budget;
    - failover rounds back off **exponentially with jitter**
      (`backoff_base_s`/`backoff_cap_s`) instead of hammering a
      recovering cluster;
    - routing honors the failure detector (heartbeat `mark_down`):
      `shards_by_node` deprioritizes down replicas per shard, so a
      known-dead node costs zero request timeouts yet stays usable as
      the last resort for a shard with no up candidate (the detector
      may be stale); the per-request skip is counted
      (`cluster.excluded_nodes`);
    - optional **hedged reads** (`hedge_quantile` > 0): a leg slower
      than that quantile of the recent leg-latency window is re-issued
      to a spare replica, first success wins;
    - **shard accounting**: every scatter leg must deliver its shards
      or the round fails over — ANY exception (not just ClientError)
      marks the leg failed, and a post-join audit confirms every shard
      merged (a lost partition can never silently undercount)."""

    FANOUT_DEADLINE_S = 30.0
    BACKOFF_BASE_S = 0.05
    BACKOFF_CAP_S = 2.0
    HEDGE_QUANTILE = 0.0  # 0 disables hedged reads
    HEDGE_FLOOR_S = 0.005
    HEDGE_MIN_SAMPLES = 8

    def __init__(self, local_executor, cluster: Cluster,
                 client: Optional[InternalClient] = None, logger=None,
                 broadcaster=None, stats=None):
        self.local = local_executor
        self.cluster = cluster
        self.client = client or InternalClient()
        self.logger = logger
        self.stats = stats or NopStatsClient()
        # Optional queued-retry path for the shards-changed push (a
        # briefly-down peer otherwise serves undercounts for up to the
        # TTL after it returns).
        self.broadcaster = broadcaster
        self.fanout_deadline_s = self.FANOUT_DEADLINE_S
        self.backoff_base_s = self.BACKOFF_BASE_S
        self.backoff_cap_s = self.BACKOFF_CAP_S
        self.hedge_quantile = self.HEDGE_QUANTILE
        # Rolling window of successful remote-leg durations; the hedge
        # trigger is a quantile of this window, so "slow" means slow
        # relative to THIS cluster's live behavior, not a magic number.
        self._leg_lat: "deque[float]" = deque(maxlen=128)
        self._leg_lat_lock = make_lock("ClusterExecutor._leg_lat_lock")

    def configure(self, fanout_deadline_s: Optional[float] = None,
                  backoff_base_s: Optional[float] = None,
                  backoff_cap_s: Optional[float] = None,
                  hedge_quantile: Optional[float] = None) -> None:
        """[cluster] config wiring (cli/main.py)."""
        if fanout_deadline_s is not None:
            self.fanout_deadline_s = float(fanout_deadline_s)
        if backoff_base_s is not None:
            self.backoff_base_s = max(0.0, float(backoff_base_s))
        if backoff_cap_s is not None:
            self.backoff_cap_s = max(0.0, float(backoff_cap_s))
        if hedge_quantile is not None:
            self.hedge_quantile = min(1.0, max(0.0,
                                               float(hedge_quantile)))

    def _hedge_delay(self) -> Optional[float]:
        """How long a leg may run before it is hedged, or None when
        hedging is off or the latency window is too thin to name a
        quantile."""
        q = self.hedge_quantile
        if not q:
            return None
        with self._leg_lat_lock:
            lats = sorted(self._leg_lat)
        if len(lats) < self.HEDGE_MIN_SAMPLES:
            return None
        return max(self.HEDGE_FLOOR_S,
                   lats[min(len(lats) - 1, int(len(lats) * q))])

    # -- shard discovery ----------------------------------------------------

    GLOBAL_SHARDS_TTL = 2.0

    def global_shards(self, index: str) -> List[int]:
        """Union of every node's locally-available shards, TTL-cached (the
        reference instead broadcasts availableShards on change,
        field.go:228 — a push model; a short pull cache gives the same
        read-path behavior without a broadcast bus)."""
        import time
        cache = getattr(self, "_shards_cache", None)
        if cache is None:
            cache = self._shards_cache = {}
        hit = cache.get(index)
        if hit is not None and time.monotonic() - hit[0] < \
                self.GLOBAL_SHARDS_TTL:
            return hit[1]
        shards = set()
        idx = self.local.holder.index(index)
        if idx is not None:
            shards.update(idx.available_shards())
        # During a resize, data may live only on a pre-change member (e.g.
        # a just-removed node) — ask the union of current and previous
        # membership so discovery cannot miss shards mid-move.
        for node in self.cluster.known_nodes():
            if node.id == self.cluster.local.id:
                continue
            try:
                per_index = self.client.local_shards(node.uri)
                shards.update(per_index.get(index, []))
            except ClientError:
                continue
        out = sorted(shards) or [0]
        cache[index] = (time.monotonic(), out)
        return out

    def invalidate_shards_cache(self, index: str) -> None:
        """Drop the cached global shard list after a write through this
        coordinator (read-your-own-writes for newly created shards)."""
        cache = getattr(self, "_shards_cache", None)
        if cache is not None:
            cache.pop(index, None)

    def note_written_shards(self, index: str, shards) -> None:
        """A completed write touched `shards`: invalidate locally and —
        when any shard is NEW to this coordinator — tell every routable
        node (current ∪ pre-resize members: a departing node still
        serving reads mid-resize needs the push too) to drop its cached
        list. Without the push, another node could serve an undercount
        for up to GLOBAL_SHARDS_TTL after the first write lands in a
        brand-new shard (the reference instead broadcasts
        CreateShardMessage on fragment creation, view.go:221).
        Suppression uses a MONOTONE per-index known-shards set — not the
        TTL cache, which this method itself invalidates — so steady-
        state writes into known shards genuinely broadcast nothing.
        Call AFTER the write has been applied/fanned out: peers
        re-discover on their next read, which must find the data."""
        known = getattr(self, "_known_shards", None)
        if known is None:
            known = self._known_shards = {}
        seen = known.setdefault(index, set())
        fresh = [int(s) for s in shards if int(s) not in seen]
        seen.update(int(s) for s in shards)
        self.invalidate_shards_cache(index)
        if not fresh:
            return
        for node in self.cluster.known_nodes():
            if node.id == self.cluster.local.id:
                continue
            msg = {"type": "shards-changed", "index": index}
            if self.broadcaster is not None:
                # Sync-first: the import ack must mean reachable peers
                # already dropped their shard caches (queue-only opened
                # a read-your-writes-via-another-node window). Down
                # peers get ONE queued copy (coalesce), not a backlog.
                self.broadcaster.send_now_or_queue(node.uri, msg,
                                                   coalesce=True)
                continue
            try:
                self.client.cluster_message(node.uri, msg)
            except ClientError:
                pass

    # -- query --------------------------------------------------------------

    def execute(self, index: str, query: str,
                shards: Optional[Sequence[int]] = None,
                profile=None) -> List[Any]:
        """Returns JSON-shaped results (one per call). `profile` (a
        utils/profile QueryProfile) records the coordinator's local leg
        in its own tree; when it is a forced profile (?profile=true)
        the flag also propagates to every remote leg and the per-node
        fragments merge under profile.nodes — a cross-node query then
        shows where its time went, node by node."""
        from pilosa_tpu.executor.executor import (
            ExecutionError, write_call_count,
        )
        q = parse_string_cached(query) if isinstance(query, str) else query
        limit = self.local.max_writes_per_request
        if limit > 0 and write_call_count(q) > limit:
            # (reference ErrTooManyWrites, executor.go:106)
            raise ExecutionError("too many write commands")
        return [self._execute_call(index, call, shards, profile=profile)
                for call in q.calls]

    def _execute_call(self, index: str, call: Call, shards,
                      profile=None) -> Any:
        inner = call
        while inner.name == "Options" and inner.children:
            # Options(shards=[...]) overrides the scatter set at the
            # coordinator (reference executeOptionsCall, executor.go:344-359).
            # The arg is *consumed* here: the forwarded call must not carry
            # it, or each node would re-override its per-node shard subset
            # with the full list and replicated shards would double-count.
            opt_shards = inner.args.pop("shards", None)
            if isinstance(opt_shards, (list, tuple)):
                shards = [int(s) for s in opt_shards]
            inner = inner.children[0]
        if inner.name in _WRITE_SINGLE_COL:
            return self._execute_write_single(index, inner)
        if inner.name in _WRITE_BROADCAST:
            self.invalidate_shards_cache(index)
            return self._execute_write_broadcast(index, inner)
        all_shards = list(shards) if shards is not None \
            else self.global_shards(index)
        return self._map_reduce(index, call, all_shards, profile=profile)

    def _map_reduce(self, index: str, call: Call, shards: List[int],
                    profile=None) -> Any:
        # While RESIZING, reads route against the pre-change placement:
        # those nodes are guaranteed to still hold the data (pulls never
        # delete source copies), where the new placement may point at an
        # owner that has not pulled yet and would silently undercount
        # (reference instead rejects queries in RESIZING, api.go:76-99).
        # The check is made atomically with the placement math inside
        # Cluster.route_shards — reading the state separately leaves a
        # window where a landing join routes a shard to the unpulled
        # joiner (a live chaos-harness find).
        # Remote profile propagation only for forced profiles
        # (?profile=true): passive sampling must not make every fan-out
        # leg pay device fencing on its node.
        want_profile = profile is not None and getattr(profile, "forced",
                                                       False)
        # Trace context for the fan-out: captured HERE, on the calling
        # thread (where the request's span/extracted id lives), because
        # the scatter threads below have neither — without an explicit
        # hand-off their query POSTs carry no traceparent and the
        # remote legs record under fresh trace ids (the old stitching
        # only appeared to work via a stale-thread-local side channel).
        tracer = getattr(self.client, "tracer", None)
        trace_id = getattr(profile, "trace_id", None) \
            if profile is not None else None
        if trace_id is None and hasattr(tracer, "current_trace_id"):
            trace_id = tracer.current_trace_id()
        deadline = (time.monotonic() + self.fanout_deadline_s) \
            if self.fanout_deadline_s > 0 else None

        def remaining() -> Optional[float]:
            return None if deadline is None \
                else deadline - time.monotonic()

        excluded: set = set()
        # Known-down nodes need no request-level exclusion here:
        # shards_by_node deprioritizes down_ids PER SHARD (a down
        # replica is picked only when no up candidate remains —
        # strictly finer than any whole-round exclusion-and-readmit),
        # so a heartbeat-marked node receives zero RPCs unless it is
        # the last resort for some shard (pinned by test). Counted so
        # /metrics shows the proactive skips.
        pre_down = set(self.cluster.down_ids)
        if pre_down:
            self.stats.count("cluster.excluded_nodes", len(pre_down))
        last_err: Optional[Exception] = None
        want_shards = {int(s) for s in shards}
        for attempt in range(max(1, self.cluster.replica_n)):
            if attempt:
                # Failover round: exponential backoff + full jitter,
                # capped and clipped to the remaining deadline budget —
                # a recovering cluster gets breathing room instead of a
                # synchronized retry stampede.
                delay = min(self.backoff_cap_s,
                            self.backoff_base_s * (2 ** (attempt - 1)))
                delay *= 0.5 + random.random() / 2
                rem = remaining()
                if rem is not None:
                    delay = min(delay, max(0.0, rem))
                if delay > 0:
                    time.sleep(delay)
            rem = remaining()
            if rem is not None and rem <= 0:
                raise last_err or ClientError(
                    f"map_reduce: fan-out deadline "
                    f"({self.fanout_deadline_s:g}s) exhausted")
            try:
                by_node, previous = self.cluster.route_shards(
                    index, shards, exclude_ids=excluded)
            except RuntimeError as e:
                raise last_err or e
            parts: List[Any] = []
            accounted: set = set()
            failed = False
            results_lock = make_lock("ClusterExecutor.results_lock")
            threads: List[threading.Thread] = []
            legs: List[_Leg] = []
            # Set once every leg has concluded (settled or all attempts
            # failed). The gather waits on THIS, not on thread joins —
            # a leg settled by its hedge must not wait out the slow
            # primary's socket.
            gather_evt = threading.Event()

            def _conclude_locked():
                if all(l.done or l.pending <= 0 for l in legs):
                    gather_evt.set()

            def run_remote(node, leg: _Leg, hedge: bool = False):
                nonlocal failed, last_err
                # Scatter threads have no open span: adopt the
                # request's trace id so the outgoing leg injects the
                # SAME traceparent the coordinator received.
                if trace_id and hasattr(tracer, "adopt"):
                    tracer.adopt(trace_id)
                # Remote-leg slice on the coordinator's request
                # timeline: how long this node's scatter-gather round
                # trip took (the remote's own stage slices record on
                # ITS timeline under the same trace id and assemble
                # via /cluster/timeline).
                tl = getattr(profile, "timeline", None) \
                    if profile is not None else None
                lane = f"hedge:{node.id}" if hedge \
                    else f"remote:{node.id}"
                t0 = time.perf_counter()
                try:
                    rem_leg = remaining()
                    if rem_leg is not None and rem_leg <= 0:
                        raise ClientError(
                            f"node {node.id}: fan-out deadline "
                            f"exhausted before dispatch")
                    res = self.client.query_node_full(
                        node.uri, index, call.to_pql(), leg.shards,
                        profile=want_profile, timeout=rem_leg)
                    dur = time.perf_counter() - t0
                    # A malformed response body must take the failure
                    # path below, not tear the thread down silently.
                    part = res["results"][0]
                    # The RPC genuinely succeeded, so its duration is
                    # real signal for the hedge quantile even when the
                    # hedge race is about to discard the result.
                    with self._leg_lat_lock:
                        self._leg_lat.append(dur)
                    with results_lock:
                        if leg.done:
                            return  # hedge race: first success merged
                        leg.done = True
                        parts.append(part)
                        accounted.update(int(s) for s in leg.shards)
                        _conclude_locked()
                    # Winner-only side effects, AFTER settling: the
                    # losing attempt of a hedge race must not add a
                    # second profile fragment (device time would
                    # double-count) or a success slice for a result
                    # that never merged.
                    TIMELINE.event(tl, lane, LANE_REMOTE, t0, dur,
                                   remote=node.id,
                                   shards=len(leg.shards))
                    if want_profile and res.get("profile") is not None:
                        profile.add_node_fragment(node.id,
                                                  res["profile"])
                except Exception as e:
                    # EVERY exception accounts the leg as failed — a
                    # non-ClientError (torn-body JSON decode, a
                    # malformed response shape) previously killed the
                    # scatter thread with `failed` still False and the
                    # merge silently undercounted the lost partition.
                    TIMELINE.event(tl, lane, LANE_REMOTE,
                                   t0, time.perf_counter() - t0,
                                   remote=node.id, error=str(e)[:200])
                    with results_lock:
                        # The node did fail its RPC: excluding it from
                        # later rounds is right either way. But the
                        # failover/loss counters fire only when the
                        # LEG actually lost the result — a late
                        # primary failure after the hedge merged is
                        # not a failover.
                        excluded.add(node.id)
                        lost = not leg.done
                        if lost:
                            leg.pending -= 1
                            if leg.pending <= 0:
                                failed = True
                                last_err = e
                        _conclude_locked()
                    if lost:
                        if not isinstance(e, ClientError):
                            self.stats.count("cluster.partition_losses",
                                             1)
                        self.stats.count("cluster.failovers", 1)
                        if self.logger is not None:
                            self.logger.printf(
                                "node %s failed (%s), failing over: %s",
                                node.id, type(e).__name__, e)
                finally:
                    if not hedge:
                        leg.event.set()

            # Build EVERY leg before starting any thread: a fast leg
            # concluding while later legs are still being appended
            # would otherwise see "all legs concluded" and fire the
            # gather early. Then dispatch every remote leg before
            # running the local one so the local evaluation overlaps
            # the network round trips.
            local_shards = None
            for node_id, node_shards in by_node.items():
                if node_id == self.cluster.local.id:
                    local_shards = node_shards
                else:
                    node = self.cluster.node_by_id(node_id)
                    legs.append(_Leg(node, node_shards))
            for leg in legs:
                t = threading.Thread(target=run_remote,
                                     args=(leg.node, leg), daemon=True)
                t.start()
                threads.append(t)
            if local_shards is not None:
                # The coordinator's own leg records into the root
                # profile directly — its ops ARE the tree's trunk.
                # Under a MeshContext this leg IS a mesh leg: the
                # shard subset reduces with device collectives inside
                # the process and only the final answer joins the
                # HTTP merge.
                if getattr(self.local, "mesh", None) is not None:
                    self.stats.count("cluster.mesh_legs", 1)
                local = self.local.execute(index, call.to_pql(),
                                           shards=local_shards,
                                           profile=profile)
                parts.append(result_to_json(local[0]))
                accounted.update(int(s) for s in local_shards)
            self._maybe_hedge(index, legs, threads, run_remote,
                              excluded, results_lock, previous)
            if legs:
                rem = remaining()
                gather_evt.wait(rem if rem is not None else None)
            with results_lock:
                # Deadline-expired stragglers (the gather timed out
                # with a leg still in flight): latch the leg done so a
                # late settle can never append into a round we have
                # already judged, and account it as a failure.
                for leg in legs:
                    if not leg.done and leg.pending > 0:
                        leg.done = True
                        excluded.add(leg.node.id)
                        failed = True
                        last_err = last_err or ClientError(
                            f"node {leg.node.id}: no response within "
                            f"the fan-out deadline")
                round_ok = not failed
                if round_ok:
                    # Defense in depth behind the Exception catch: the
                    # merge runs ONLY when every requested shard was
                    # delivered by some leg. An unaccounted shard is a
                    # lost partition, never a quiet undercount.
                    missing = want_shards - accounted
                    if missing:
                        round_ok = False
                        failed = True
                        self.stats.count("cluster.partition_losses", 1)
                        last_err = ClientError(
                            f"shards {sorted(missing)} unaccounted "
                            f"after fan-out")
                parts_snapshot = list(parts)
            if round_ok:
                return merge_results(call, parts_snapshot)
            # retry: re-map every shard against remaining nodes
        raise last_err or RuntimeError("map_reduce failed")

    def _maybe_hedge(self, index: str, legs: List[_Leg],
                     threads: List[threading.Thread], run_remote,
                     excluded: set, results_lock,
                     previous: bool) -> None:
        """Hedged reads: a leg whose primary attempt is still in
        flight past the configured latency quantile is re-issued to a
        spare replica — first success wins (the `_Leg.done` latch
        guarantees exactly one merge). Only a replica that can serve
        the WHOLE leg hedges; splitting a leg would split its merge
        accounting."""
        hedge_delay = self._hedge_delay()
        if hedge_delay is None or not legs:
            return
        hedge_at = time.monotonic() + hedge_delay
        for leg in legs:
            wait = hedge_at - time.monotonic()
            if wait > 0:
                leg.event.wait(wait)
            if leg.event.is_set():
                continue  # concluded (or failed — round handles it)
            with results_lock:
                if leg.done or leg.pending <= 0:
                    continue
                avoid = set(excluded) | {leg.node.id}
            try:
                # shards_by_node deprioritizes down-marked replicas
                # itself — no point hedging INTO a dead node.
                alt = self.cluster.shards_by_node(
                    index, leg.shards, exclude_ids=avoid,
                    previous=previous)
            except RuntimeError:
                continue  # no spare replica covers this leg
            if len(alt) != 1:
                continue
            (alt_id, _alt_shards), = alt.items()
            if alt_id == self.cluster.local.id:
                continue
            alt_node = self.cluster.node_by_id(alt_id)
            if alt_node is None:
                continue
            with results_lock:
                if leg.done or leg.pending <= 0:
                    continue
                leg.pending += 1
            self.stats.count("cluster.hedged_reads", 1)
            if self.logger is not None:
                self.logger.printf(
                    "hedging slow leg %s -> replica %s (>%.3fs)",
                    leg.node.id, alt_id, hedge_delay)
            t = threading.Thread(target=run_remote,
                                 args=(alt_node, leg, True),
                                 daemon=True)
            t.start()
            threads.append(t)

    # -- writes -------------------------------------------------------------

    def _execute_write_single(self, index: str, call: Call) -> Any:
        """Route a single-column write to the owning replicas (reference
        executeSetBitField remote fan, executor.go:1959)."""
        col = call.args.get("_col")
        if isinstance(col, str):
            # Translate on the coordinator so every replica stores the
            # same id (translation stores replicate separately).
            self.local._translate_call(self.local.holder.index(index), call)
            col = call.args["_col"]
        shard = int(col) // SHARD_WIDTH
        # write_nodes = current owners ∪ pre-resize owners while RESIZING,
        # so a write can't land only on the side a reader won't consult.
        owners = self.cluster.write_nodes(index, shard)
        result = False
        applied = 0
        last_err: Optional[Exception] = None
        for node in owners:
            if node.id == self.cluster.local.id:
                (r,) = self.local.execute(index, call.to_pql())
                result = result or bool(r)
                applied += 1
            else:
                try:
                    res = self.client.query_node(node.uri, index, call.to_pql(),
                                                 [shard])
                    result = result or bool(res[0])
                    applied += 1
                except ClientError as e:
                    last_err = e
                    if self.logger is not None:
                        self.logger.printf("write to %s failed: %s",
                                           node.id, e)
        if applied == 0:
            # No replica took the write — surfacing the failure is the only
            # honest answer; anti-entropy can only heal from a copy that
            # exists.
            raise last_err or ClientError("no replica accepted the write")
        # After the write landed (keyed columns translated above): push
        # the shard-list invalidation so no peer undercounts a new shard.
        self.note_written_shards(index, [shard])
        return result

    def _execute_write_broadcast(self, index: str, call: Call) -> Any:
        """Row-scoped writes apply on every node (each owns a shard
        subset)."""
        if isinstance(call.args.get("_col"), str):
            # Translate on the coordinator so every node stores the same id.
            self.local._translate_call(self.local.holder.index(index), call)
        results = []
        for node in self.cluster.nodes():
            if node.id == self.cluster.local.id:
                (r,) = self.local.execute(index, call.to_pql())
                results.append(result_to_json(r))
            else:
                try:
                    res = self.client.query_node(node.uri, index,
                                                 call.to_pql(), [])
                    results.append(res[0])
                except ClientError as e:
                    if self.logger is not None:
                        self.logger.printf("broadcast write to %s failed: %s",
                                           node.id, e)
        return merge_results(call, results)
