"""Anti-entropy replica sync + resize fragment movement.

Reference: holderSyncer (/root/reference/holder.go:637-858) walks the
schema and, per owned fragment, runs block-checksum reconciliation against
replicas (fragmentSyncer, fragment.go:2231-2432): fetch block lists, diff
checksums, fetch mismatched blocks' (row, col) pairs, merge locally, push
deltas back via imports. holderCleaner (holder.go:859) drops fragments no
longer owned after a resize; followResizeInstruction (cluster.go:1251)
streams newly-owned fragments from source nodes — here pull-based
(ResizePuller).
"""

from __future__ import annotations

import threading
from pilosa_tpu.utils.locks import make_lock
from typing import Dict, Optional

import numpy as np

from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.parallel.cluster import Cluster
from pilosa_tpu.utils.failpoints import FAILPOINTS

# Per-(peer, shard) fragment fetch during a resize pull: `error` fails
# the pull pass (the job stays RESIZING, reads keep the pre-change
# placement), `delay` holds the cluster mid-resize so the chaos harness
# can strike inside the window (tools/chaos.py).
_FP_RESIZE_PULL = FAILPOINTS.register("resize.pull")


class HolderSyncer:
    """(reference holderSyncer, holder.go:637)."""

    def __init__(self, holder, cluster: Cluster,
                 client: Optional[InternalClient] = None, logger=None):
        self.holder = holder
        self.cluster = cluster
        self.client = client or InternalClient()
        self.logger = logger

    def _log(self, fmt, *args):
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    def sync_holder(self) -> Dict[str, int]:
        """One full anti-entropy pass over every locally-held fragment this
        node is a replica for. Returns {"merged": bits_pulled,
        "pushed": bits_pushed} for observability."""
        stats = {"merged": 0, "pushed": 0, "attrs_merged": 0,
                 "attrs_pushed": 0}
        for iname, idx in list(self.holder.indexes.items()):
            self.sync_attrs(iname, None, idx.column_attr_store, stats)
            for fname, field in list(idx.fields.items()):
                self.sync_attrs(iname, fname, field.row_attr_store, stats)
                for vname, view in list(field.views.items()):
                    for shard, frag in list(view.fragments.items()):
                        if not self.cluster.owns_shard(iname, shard):
                            continue
                        self.sync_fragment(iname, fname, vname, shard, frag,
                                           stats)
        return stats

    def sync_attrs(self, index: str, field: Optional[str], store,
                   stats: Dict[str, int]) -> None:
        """Block-checksum attr reconciliation with every peer (reference
        holderSyncer.syncIndex/syncField, holder.go:730-824): compare 100-id
        block checksums, pull differing blocks, merge locally (attr merge is
        commutative — last-writer key-wise union), and push our copy back so
        the peer converges too."""
        peers = [n for n in self.cluster.nodes()
                 if n.id != self.cluster.local.id]
        for peer in peers:
            try:
                theirs = {b["block"]: b["checksum"]
                          for b in self.client.attr_blocks(peer.uri, index,
                                                           field)}
            except ClientError as e:
                self._log("attr sync: blocks from %s failed: %r",
                          peer.uri, e)
                continue
            ours = {b: c.hex() for b, c in store.blocks()}
            for block in set(theirs) | set(ours):
                if theirs.get(block) == ours.get(block):
                    continue
                try:
                    if block in theirs:
                        data = self.client.attr_block_data(
                            peer.uri, index, field, block)
                        if data:
                            store.set_bulk({int(i): a
                                            for i, a in data.items()})
                            stats["attrs_merged"] += len(data)
                    local = store.block_data(block)
                    if local:
                        self.client.attr_merge(
                            peer.uri, index, field,
                            {str(i): a for i, a in local.items()})
                        stats["attrs_pushed"] += len(local)
                except ClientError as e:
                    self._log("attr sync: block %d with %s failed: %r",
                              block, peer.uri, e)

    def sync_fragment(self, index: str, field: str, view: str, shard: int,
                      frag, stats: Dict[str, int]) -> None:
        """(reference fragmentSyncer.syncFragment, fragment.go:2253)."""
        peers = [n for n in self.cluster.shard_nodes(index, shard)
                 if n.id != self.cluster.local.id]
        if not peers:
            return
        local_blocks = dict(frag.checksum_blocks())
        for peer in peers:
            try:
                their = {b["block"]: bytes.fromhex(b["checksum"])
                         for b in self.client.fragment_blocks(
                             peer.uri, index, field, view, shard)}
            except ClientError:
                # Peer lacks the fragment entirely: push ours wholesale,
                # creating missing schema first (heals a peer that was
                # unreachable during a schema broadcast).
                try:
                    self._ensure_remote_schema(peer, index, field)
                    self.client.import_roaring_node(
                        peer.uri, index, field, shard, frag.write_bytes(),
                        view=view)
                    stats["pushed"] += frag.storage.count()
                except ClientError as e:
                    self._log("sync push to %s failed: %s", peer.id, e)
                continue
            for block in set(local_blocks) | set(their):
                if local_blocks.get(block) == their.get(block):
                    continue
                self._sync_block(index, field, view, shard, frag, peer,
                                 block, stats)

    def _ensure_remote_schema(self, peer, index: str, field: str) -> None:
        idx = self.holder.index(index)
        if idx is None:
            return
        f = idx.field(field)
        self.client.create_index_node(
            peer.uri, index, {"keys": idx.keys,
                              "trackExistence": idx.track_existence})
        if f is not None and not field.startswith("_"):
            # internal fields (_exists) auto-create with the index
            o = f.options
            self.client.create_field_node(
                peer.uri, index, field,
                {"type": o.type, "cacheType": o.cache_type,
                 "cacheSize": o.cache_size, "min": o.min, "max": o.max,
                 "timeQuantum": o.time_quantum, "keys": o.keys,
                 "noStandardView": o.no_standard_view,
                 "maxColumns": o.max_columns})

    def _sync_block(self, index, field, view, shard, frag, peer, block,
                    stats) -> None:
        """(reference syncBlock, fragment.go:2333)."""
        try:
            data = self.client.block_data(peer.uri, index, field, view,
                                          shard, block)
        except ClientError:
            data = {"rows": [], "columns": []}
        (here_r, here_c), (there_r, there_c) = frag.merge_block(
            block, np.asarray(data["rows"], dtype=np.uint64),
            np.asarray(data["columns"], dtype=np.uint64))
        stats["merged"] += len(here_r)
        if len(there_r):
            try:
                self.client.import_node(
                    peer.uri, index, field,
                    {"rowIDs": [int(r) for r in there_r],
                     "columnIDs": [int(c) for c in there_c]})
                stats["pushed"] += len(there_r)
            except ClientError as e:
                self._log("block push to %s failed: %s", peer.id, e)


class ResizePuller:
    """Pull-based resize: after a topology change, fetch every fragment
    this node now owns but does not hold (the data motion of
    followResizeInstruction, cluster.go:1251-1360), then drop fragments no
    longer owned (holderCleaner, holder.go:859-910)."""

    def __init__(self, holder, cluster: Cluster,
                 client: Optional[InternalClient] = None, logger=None):
        self.holder = holder
        self.cluster = cluster
        self.client = client or InternalClient()
        self.logger = logger
        # Overlapping resize jobs may both ask this node to pull; the
        # passes are idempotent but their schema-discovery writes race
        # (create_field "already exists"), so serialize them.
        self._pull_lock = make_lock("ResizePuller._pull_lock")

    def _log(self, fmt, *args):
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    def pull_owned(self) -> int:
        """Returns number of fragments fetched. Cluster state is owned by
        the resize job protocol (server/api.py _start_resize_job), not
        here: during the pull the cluster stays RESIZING so reads keep
        routing against the pre-change placement."""
        with self._pull_lock:
            # graftlint: disable=GL009 — the only blocking sink on this
            # path is the resize.pull failpoint's `delay` mode
            # (utils/failpoints.py), whose purpose IS to hold the pull
            # pass open so the chaos harness can strike mid-resize;
            # disarmed (production) the site is one attribute read.
            return self._pull_owned_locked()

    def _pull_owned_locked(self) -> int:
        # Pull sources: current members ∪ pre-resize members. After a
        # remove-node resize the only holder of a shard may be the node
        # being removed (alive, detached) — it is still reachable via the
        # prev snapshot, exactly like the reference sourcing resize
        # instructions from the pre-change owners (cluster.go:741-826).
        peers = [n for n in self.cluster.known_nodes()
                 if n.id != self.cluster.local.id]
        if not peers:
            return 0
        fetched = 0
        # Discover remote schema + per-shard holders first.
        holders: Dict[tuple, list] = {}  # (index, shard) -> [node, ...]
        for peer in peers:
            try:
                schema = self.client.schema(peer.uri)
            except ClientError:
                continue
            for idx_info in schema.get("indexes", []):
                iname = idx_info["name"]
                idx = self.holder.index(iname)
                if idx is None:
                    idx = self.holder.create_index(
                        iname, keys=idx_info["options"].get("keys", False),
                        track_existence=idx_info["options"].get(
                            "trackExistence", True))
                for f_info in idx_info.get("fields", []):
                    if idx.field(f_info["name"]) is None:
                        from pilosa_tpu.core.field import FieldOptions
                        o = f_info["options"]
                        idx.create_field(f_info["name"], FieldOptions(
                            type=o.get("type", "set"),
                            cache_type=o.get("cacheType", "ranked"),
                            cache_size=o.get("cacheSize", 50000),
                            min=o.get("min", 0), max=o.get("max", 0),
                            time_quantum=o.get("timeQuantum", ""),
                            keys=o.get("keys", False),
                            no_standard_view=o.get("noStandardView",
                                                   False),
                            max_columns=o.get("maxColumns", 0)))
                for shard in idx_info.get("shards", []):
                    holders.setdefault((iname, int(shard)), []).append(peer)
        # Pull each owned shard from the most AUTHORITATIVE holder:
        # pre-change owners first (they served every write of the ending
        # epoch; reference fragSources computes exactly these,
        # cluster.go:741-826), then current owners, then any holder.
        # Old non-owner copies can linger (cleanup is a separate step)
        # and may be epochs stale — pulling from "whoever lists the
        # shard" silently resurrects them.
        for (iname, shard), hold in holders.items():
            idx = self.holder.index(iname)
            if idx is None or not self.cluster.owns_shard(iname, shard):
                continue
            # A node REGAINING ownership may still hold a copy from an
            # older epoch that missed every write in between — it must
            # refresh (union-merge) from the authoritative holder, not
            # trust its own fragment. Previous-epoch owners served all
            # of the ending epoch's writes, so their copies are current
            # and need no refresh.
            local = self.cluster.local.id
            was_owner = any(
                n.id == local
                for n in self.cluster.shard_nodes(iname, shard,
                                                  previous=True))
            for peer in self._source_order(iname, shard, hold):
                got = self._maybe_pull(peer, idx, shard,
                                       refresh=not was_owner)
                fetched += got
                if got:
                    # Refreshed from the most authoritative holder;
                    # later candidates only fill views it lacked.
                    was_owner = True
        return fetched

    def _source_order(self, index: str, shard: int, holders: list) -> list:
        by_id = {n.id: n for n in holders}
        ordered = []
        for previous in (True, False):
            for n in self.cluster.shard_nodes(index, shard,
                                              previous=previous):
                if n.id in by_id:
                    ordered.append(by_id.pop(n.id))
        ordered.extend(by_id.values())
        return ordered

    def _maybe_pull(self, peer, idx, shard: int,
                    refresh: bool = False) -> int:
        """Pull shard fragments this node lacks from `peer`.
        refresh=True also union-merges fragments it already holds —
        used when ownership was just (re)gained and the local copy may
        be stale."""
        if not self.cluster.owns_shard(idx.name, shard):
            return 0
        # Fires per (peer, shard): an injected error propagates out of
        # pull_owned (it is NOT a ClientError, so the per-view fetch
        # handling below does not swallow it) and fails the resize
        # job's pull pass — the cluster stays safely RESIZING.
        _FP_RESIZE_PULL.fire(uri=peer.uri, index=idx.name, shard=shard)
        fetched = 0
        for fname, field in list(idx.fields.items()):
            try:
                views = self.client.views(peer.uri, idx.name, fname)
            except ClientError:
                continue
            for vname in views:
                view = field.view(vname)
                held = view is not None and view.fragment(shard) is not None
                if held and not refresh:
                    continue  # already hold it; anti-entropy reconciles
                try:
                    data = self.client.retrieve_shard(
                        peer.uri, idx.name, fname, vname, shard)
                except ClientError:
                    continue
                frag = field.create_view_if_not_exists(vname) \
                    .create_fragment_if_not_exists(shard)
                # REPLACE, don't union: a stale local copy must not
                # resurrect bits cleared while this node wasn't an
                # owner. (Narrow caveat, documented: a write that
                # reached ONLY this node during the resize window —
                # i.e. every other owner's leg failed — is dropped
                # here; the reference avoids this by rejecting writes
                # while RESIZING, api.go:76-99.)
                frag.replace_with_bytes(data)
                fetched += 1
                self._log("resize: pulled %s/%s/%s/shard %s from %s",
                          idx.name, fname, vname, shard, peer.id)
        return fetched

    def clean_unowned(self) -> int:
        """Drop fragments this node no longer owns (holderCleaner). Never
        runs while RESIZING: reads still route against the pre-change
        placement, so an old owner's copy is live data (the reference's
        holderCleaner likewise runs only after the cluster returns to
        NORMAL, holder.go:859)."""
        import os
        from pilosa_tpu.parallel.cluster import STATE_RESIZING
        if self.cluster.state == STATE_RESIZING:
            return 0
        removed = 0
        for iname, idx in list(self.holder.indexes.items()):
            for field in list(idx.fields.values()):
                for view in list(field.views.values()):
                    for shard in list(view.fragments):
                        if self.cluster.owns_shard(iname, shard):
                            continue
                        frag = view.fragments.pop(shard)
                        frag.close()
                        for p in (frag.path, frag.cache_path()):
                            if os.path.exists(p):
                                os.remove(p)
                        removed += 1
        return removed


class AntiEntropyLoop:
    """Periodic sync driver (reference monitorAntiEntropy,
    server.go:430)."""

    def __init__(self, syncer: HolderSyncer, interval: float):
        self.syncer = syncer
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self.interval <= 0:
            return

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.syncer.sync_holder()
                except Exception as e:  # keep the loop alive, but say why
                    self.syncer._log("anti-entropy pass failed: %s: %s",
                                     type(e).__name__, e)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
