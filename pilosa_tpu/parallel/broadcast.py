"""Queued, retried async cluster-message broadcast.

Reference: broadcast.go:30 SendAsync rides memberlist's
TransmitLimitedQueue (gossip/gossip.go:306-318) — a message to a
briefly-down node is retransmitted by the gossip layer rather than
lost. The rebuild's control plane is direct HTTP, so the equivalent is
explicit: per-peer FIFO queues drained by one worker thread; a failed
send backs that peer off (exponential, capped) and retries in order
until the message's TTL expires. Ordering per peer is preserved —
queued messages to a down peer never overtake each other — while a
down peer never blocks delivery to healthy ones.

Schema mutations stay on the synchronous broadcast path (the
reference's SendSync, server.go:582): their callers need create/delete
to be visible cluster-wide on return. This queue carries the
membership/cache messages where best-effort-with-retry is the point
(node-join/leave, resize-complete, shards-changed, translate pin).
"""

from __future__ import annotations

import threading
from pilosa_tpu.utils.locks import make_lock
import time
from collections import deque
from typing import Dict, Optional

from pilosa_tpu.parallel.client import InternalClient


class AsyncBroadcaster:
    RETRY_BASE_S = 1.0    # first retry delay after a failure
    RETRY_MAX_S = 15.0    # backoff cap

    def __init__(self, client: Optional[InternalClient] = None,
                 logger=None, ttl: float = 300.0):
        self._client = client or InternalClient(timeout=10.0)
        self._logger = logger
        self.ttl = ttl
        # peer uri -> deque of (deadline_unix, message dict)
        self._queues: Dict[str, deque] = {}
        # peer uri -> (next_attempt_unix, current_backoff_s)
        self._backoff: Dict[str, tuple] = {}
        self._lock = make_lock("AsyncBroadcaster._lock")
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._idle = threading.Event()  # set while every queue is empty
        self._idle.set()
        self.sent = 0      # delivered messages (observability/tests)
        self.expired = 0   # dropped past TTL
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="async-broadcast")
        self._thread.start()

    def _log(self, fmt, *args):
        if self._logger is not None:
            self._logger.printf(fmt, *args)

    def send(self, uri: str, message: dict,
             coalesce: bool = False) -> None:
        """Queue `message` for `uri`; returns immediately. Delivery is
        at-least-once within the TTL (receivers are idempotent — the
        same property the reference's gossip retransmit relies on).
        coalesce=True skips the enqueue when an identical message is
        already pending for this peer (pure cache-invalidation messages
        like shards-changed: N queued copies do what one does)."""
        with self._lock:
            q = self._queues.setdefault(uri, deque())
            if coalesce and any(m == message for _, m in q):
                return
            q.append((time.time() + self.ttl, message))
            self._idle.clear()
        self._wake.set()

    def has_pending(self, uri: str) -> bool:
        with self._lock:
            return bool(self._queues.get(uri))

    def send_now_or_queue(self, uri: str, message: dict,
                          coalesce: bool = False) -> bool:
        """Deliver synchronously when possible, queue otherwise —
        WITHOUT breaking per-peer ordering: if messages are already
        queued for this peer, this one lines up behind them (a sync
        send would overtake the queue and e.g. land resize-complete
        before the node-leave it completes). Topology-change callers
        use this so reachable peers learn the new membership BEFORE any
        follow-up direct RPC (the resize job's pull) reaches them, and
        cache-invalidation callers so an import ack means reachable
        peers already dropped their caches. Returns True when
        delivered now."""
        if not self.has_pending(uri):
            try:
                self._client.cluster_message(uri, message)
                with self._lock:
                    self.sent += 1
                return True
            except Exception:
                pass  # fall through to the queued/retried path
        self.send(uri, message, coalesce=coalesce)
        return False

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every queue is empty (tests); False on timeout."""
        return self._idle.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                pending = any(self._queues.values())
            # Timed wake only while retries are owed; fully idle blocks.
            self._wake.wait(timeout=0.5 if pending else None)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                now = time.time()
                with self._lock:
                    peers = [u for u, q in self._queues.items() if q]
                for uri in peers:
                    if self._stop.is_set():
                        return
                    with self._lock:
                        nxt, backoff = self._backoff.get(uri, (0.0, 0.0))
                    # graftlint: disable=GL015 — the backoff gate is
                    # advisory: a racing re-arm at worst drains one
                    # poll tick early, and _drain_peer re-reads the
                    # queue under the lock before every send.
                    if now < nxt:
                        continue
                    # graftlint: disable=GL015 — backoff is a retry
                    # hint, not state: _drain_peer resets it from the
                    # send outcome, re-checking the head under the
                    # lock before each pop.
                    self._drain_peer(uri, backoff)
                with self._lock:
                    if not any(self._queues.values()):
                        self._idle.set()
            except Exception as e:  # the worker must never die
                self._log("async-broadcast: worker error %r; continuing",
                          e)
                time.sleep(0.5)

    def _drain_peer(self, uri: str, backoff: float) -> None:
        """Send this peer's queue head-first until it empties or a send
        fails (which re-arms the peer's backoff)."""
        while not self._stop.is_set():
            with self._lock:
                q = self._queues.get(uri)
                if not q:
                    if q is not None:
                        # Drained empty: drop the peer's dict entry, so
                        # departed nodes don't leave a key behind for
                        # the life of the process (send() re-creates it
                        # on the next message).
                        del self._queues[uri]
                    return
                deadline, msg = q[0]
            if time.time() > deadline:
                with self._lock:
                    if q and q[0] == (deadline, msg):
                        q.popleft()
                self.expired += 1
                self._log("async-broadcast: message %r to %s expired "
                          "after %.0fs of retries", msg.get("type"), uri,
                          self.ttl)
                continue
            try:
                self._client.cluster_message(uri, msg)
            except Exception as e:
                # Broad on purpose: ANY delivery failure (transport, a
                # malformed 200 body raising in the codec, ...) must
                # back off and retry — an escaping exception would kill
                # the single worker thread and silently halt all async
                # control-plane delivery.
                nxt_backoff = min(self.RETRY_MAX_S,
                                  (backoff * 2) or self.RETRY_BASE_S)
                with self._lock:
                    self._backoff[uri] = (time.time() + nxt_backoff,
                                          nxt_backoff)
                self._log("async-broadcast: %s delivery failed (%s); "
                          "retrying in %.1fs", uri, e, nxt_backoff)
                return
            with self._lock:
                if q and q[0] == (deadline, msg):
                    q.popleft()
                self._backoff.pop(uri, None)
                self.sent += 1  # under the lock: callers also bump it
            backoff = 0.0
