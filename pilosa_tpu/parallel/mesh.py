"""Device mesh + shard placement.

Reference mapping:
- shard -> node placement: fnv64a(index,shard) mod 256 partitions ->
  jump-hash -> node (cluster.go:828-913). Here placement is *static block
  assignment onto a mesh axis*: device d owns shards where
  (shard_position mod n_devices) == d once the shard list is padded to a
  multiple of the mesh size. Elastic resize (cluster.go:1150's resize jobs
  streaming fragments node-to-node) becomes: change the mesh, re-put the
  banks — the durable store is the source of truth, so "resize" is a
  re-shard + recompile, not a data-migration protocol.
- mapReduce scatter-gather + reduce over HTTP (executor.go:2277-2415):
  the executor's single compiled program runs SPMD over the mesh; the
  shard-axis reduction (Count, TopN counts, BSI sums) lowers to psum/
  all-reduce on ICI within a slice and DCN across slices.
- replication (ReplicaN successor nodes, cluster.go:857): an optional
  leading `replica` mesh axis over which banks are *replicated*
  (PartitionSpec None on the shard axes), giving query failover the same
  way replicas served reads in the reference.

Multi-host: under `jax.distributed` initialization the same code spans
hosts — the mesh covers all global devices and XLA routes inter-host
collectives over DCN. No gossip/coordinator consensus is needed: the
single controller owns schema and placement (survey §7.6).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class ShardPlacement:
    """Static block placement of a shard list onto n devices."""

    def __init__(self, n_devices: int):
        self.n = n_devices

    def pad(self, shards: Sequence[int], floor: int = 0) -> List[int]:
        """Pad the shard list to a multiple of n with provably-absent shard
        ids (>= max(floor, max(shards)+1)); absent shards materialize as
        all-zero bank columns and contribute nothing to any reduction.
        `floor` must exceed every *existing* shard of the index, not just
        the requested subset — otherwise padding could alias real shards
        the caller excluded."""
        shards = list(shards)
        if not shards:
            shards = [0]
        rem = (-len(shards)) % self.n
        if rem:
            pad_base = max(floor, max(shards) + 1)
            shards = shards + [pad_base + i for i in range(rem)]
        return shards

    def device_of(self, shards: Sequence[int], shard: int) -> int:
        """Which device owns a shard (for diagnostics/routing)."""
        padded = self.pad(shards)
        return padded.index(shard) % self.n


class MeshContext:
    """Wraps a 1-or-2-axis mesh: optional 'replica' axis x 'shards' axis."""

    SHARD_AXIS = "shards"
    REPLICA_AXIS = "replica"

    def __init__(self, devices: Optional[Sequence] = None,
                 replicas: int = 1):
        import jax
        from jax.sharding import Mesh

        devices = list(devices if devices is not None else jax.devices())
        if replicas > 1:
            if len(devices) % replicas:
                raise ValueError(
                    f"{len(devices)} devices not divisible by "
                    f"{replicas} replicas")
            arr = np.array(devices).reshape(replicas, -1)
            self.mesh = Mesh(arr, (self.REPLICA_AXIS, self.SHARD_AXIS))
            self.n_shard_devices = arr.shape[1]
        else:
            self.mesh = Mesh(np.array(devices), (self.SHARD_AXIS,))
            self.n_shard_devices = len(devices)
        self.replicas = replicas
        self.placement = ShardPlacement(self.n_shard_devices)

    # -- shardings ----------------------------------------------------------

    def bank_sharding(self):
        """[rows, shards, words]: shard axis split across devices, rows and
        words replicated within a shard device."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(None, self.SHARD_AXIS, None))

    def row_sharding(self):
        """[shards, words] query-result rows."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(self.SHARD_AXIS, None))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def cache_key(self) -> str:
        dev_ids = tuple(d.id for d in self.mesh.devices.flat)
        return f"mesh{self.replicas}x{self.n_shard_devices}:{hash(dev_ids)}"

    def put_bank(self, host):
        import jax
        return jax.device_put(host, self.bank_sharding())

    def put_row(self, arr):
        """Commit a [shards, words] (or [k, shards, words]) array to the
        mesh with the shard axis split."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = (P(self.SHARD_AXIS, None) if arr.ndim == 2
                else P(None, self.SHARD_AXIS, None))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def pad_shards(self, shards: Sequence[int], floor: int = 0) -> List[int]:
        return self.placement.pad(shards, floor)
