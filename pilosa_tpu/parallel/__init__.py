"""Distribution layer: mesh placement, sharded execution, cluster state.

Replaces the reference's distribution stack (cluster.go jump-hash
placement, executor.go:2277 HTTP scatter-gather mapReduce, gossip
membership) with the single-controller JAX model: shards map onto a
`jax.sharding.Mesh` axis by static block placement, view banks are
device_put with a NamedSharding over that axis, and the executor's
compiled query programs auto-partition — XLA inserts the psum/all-gather
collectives over ICI that the reference performed as HTTP fan-out/reduce.
"""

from pilosa_tpu.parallel.mesh import MeshContext, ShardPlacement  # noqa: F401
