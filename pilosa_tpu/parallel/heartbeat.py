"""Failure detection + standing translate replication.

Reference: membership liveness comes from hashicorp/memberlist SWIM
gossip (/root/reference/gossip/gossip.go:43,246): probes mark nodes
dead, the cluster goes DEGRADED, and queries avoid dead members.
With a single-controller deployment a full SWIM protocol is
unnecessary; a direct heartbeat prober gives the same observable
behavior — peers marked down after N consecutive probe failures,
DEGRADED status, proactive query failover — without the gossip fabric
(divergence documented in parallel/cluster.py).

Translate replication: the reference runs a standing loop per replica
streaming the primary's translate log (monitorReplication/replicate,
/root/reference/translate.go:359-400). TranslateReplicationLoop is that
loop: incremental log pulls from the primary on an interval, so replicas
converge without waiting for anti-entropy or a read-path fallback.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.parallel.cluster import Cluster
from pilosa_tpu.utils.failpoints import (
    FAILPOINTS, FailpointDrop, FailpointError,
)

# One heartbeat probe about to be sent: `error`/`partition` count as a
# failed probe (drives mark_down after suspect_after rounds), `drop`
# silently loses the probe (no failure, no success — a lossy network),
# `delay` slows the prober. The receive side is the `api.status` site:
# arming error THERE makes a node look dead to every prober.
_FP_HB_PROBE = FAILPOINTS.register("heartbeat.probe")


class Heartbeater:
    """Rotating-subset failure detector: each round probes at most
    `probes_per_round` healthy peers from a shuffled ring (O(N) probe
    load cluster-wide, like SWIM's one-peer-per-round — the reference's
    memberlist config, gossip/gossip.go:246 — where an all-peers mesh
    would be O(N^2)), PLUS every currently-suspect peer (so detection
    still takes `suspect_after` consecutive rounds, not a full ring
    rotation) and one known-down peer (so recovery is noticed within a
    round). After `suspect_after` consecutive failures a peer is marked
    down (cluster DEGRADED, routing prefers live replicas); one
    successful probe marks it back up."""

    def __init__(self, cluster: Cluster, interval: float = 2.0,
                 suspect_after: int = 3, timeout: Optional[float] = None,
                 logger=None, probes_per_round: int = 2,
                 ssl_context=None):
        self.cluster = cluster
        self.interval = interval
        self.suspect_after = suspect_after
        self.probes_per_round = probes_per_round
        # Short probe timeout: a hung peer must not stall the prober.
        self.client = InternalClient(timeout=timeout or max(interval, 1.0),
                                     ssl_context=ssl_context)
        self.logger = logger
        self._fails: Dict[str, int] = {}
        self._ring: List[str] = []
        self._ring_pos = 0
        self._down_pos = 0
        self.last_round_probes = 0  # observability / tests
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _log(self, fmt, *args):
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    def _round_targets(self, peers):
        """Suspects + one rotating down peer + ring rotation filling up
        to probes_per_round."""
        by_id = {n.id: n for n in peers}
        if set(self._ring) != set(by_id):
            self._ring = list(by_id)
            random.shuffle(self._ring)
            self._ring_pos = 0
        down_ids = sorted(self.cluster.down_ids & set(by_id))
        targets: Dict[str, object] = {
            nid: by_id[nid] for nid in self._fails
            if nid in by_id and nid not in self.cluster.down_ids}
        if down_ids:
            pick = down_ids[self._down_pos % len(down_ids)]
            self._down_pos += 1
            targets.setdefault(pick, by_id[pick])
        budget = min(self.probes_per_round, len(peers))
        for _ in range(len(self._ring)):
            if len(targets) >= budget:
                break
            nid = self._ring[self._ring_pos % len(self._ring)]
            self._ring_pos += 1
            if nid in self.cluster.down_ids:
                continue  # down peers probe via the rotating slot above
            targets.setdefault(nid, by_id[nid])
        return list(targets.values())

    def probe_once(self) -> None:
        """One probe round (tests call this directly)."""
        peers = [n for n in self.cluster.nodes()
                 if n.id != self.cluster.local.id]
        if not peers:
            self.last_round_probes = 0
            return
        targets = self._round_targets(peers)
        self.last_round_probes = len(targets)
        for node in targets:
            try:
                try:
                    _FP_HB_PROBE.fire(uri=node.uri)
                except FailpointDrop:
                    continue  # probe lost in flight: no verdict either way
                self.client.status(node.uri)
            except (ClientError, FailpointError):
                n = self._fails.get(node.id, 0) + 1
                self._fails[node.id] = n
                if n >= self.suspect_after and \
                        self.cluster.mark_down(node.id):
                    self._log("heartbeat: node %s DOWN after %d failed "
                              "probes; cluster %s", node.id, n,
                              self.cluster.state)
            else:
                self._fails.pop(node.id, None)
                if self.cluster.mark_up(node.id):
                    self._log("heartbeat: node %s recovered; cluster %s",
                              node.id, self.cluster.state)

    def start(self) -> None:
        if self.interval <= 0:
            return

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.probe_once()
                except Exception as e:  # keep the detector alive
                    self._log("heartbeat round failed: %s: %s",
                              type(e).__name__, e)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


class TranslateReplicationLoop:
    """Standing replica-side loop pulling the primary's translate logs
    incrementally (reference replicate loop, translate.go:359-400; here
    pull-based from byte offsets instead of a held-open stream)."""

    def __init__(self, api, interval: float = 10.0):
        self.api = api
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def replicate_once(self) -> None:
        self.api._sync_translate_stores()

    def start(self) -> None:
        if self.interval <= 0:
            return

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.replicate_once()
                except Exception as e:
                    self.api.logger.printf(
                        "translate replication pass failed: %s: %s",
                        type(e).__name__, e)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
