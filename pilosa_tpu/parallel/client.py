"""Inter-node HTTP client.

Reference: /root/reference/http/client.go (InternalClient — query fan-out
:241, imports :439, fragment streaming :711, block sync :811-901) and the
interface /root/reference/client.go:32. Bodies and responses use the
binary wire codec (server/wire.py, the analog of the reference's protobuf
Serializer) with JSON fallback; roaring payloads stay raw bytes.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from pilosa_tpu.server import wire


class ClientError(RuntimeError):
    pass


class InternalClient:
    def __init__(self, timeout: float = 30.0, tracer=None):
        self.timeout = timeout
        self.tracer = tracer

    def _req(self, method: str, url: str, body: Optional[bytes] = None,
             raw: bool = False, obj=None):
        """One internal request. `obj` bodies and non-raw responses use the
        binary wire codec (server/wire.py — the rebuild's analog of the
        reference's protobuf Serializer, encoding/proto/proto.go:29);
        JSON stays the fallback for older peers."""
        if obj is not None:
            try:
                body = wire.dumps(obj)
                headers = {"Content-Type": wire.CONTENT_TYPE}
            except TypeError:  # e.g. >64-bit int — JSON handles it
                body = json.dumps(obj).encode("utf-8")
                headers = {"Content-Type": "application/json"}
        else:
            headers = {"Content-Type": "application/json"}
        if not raw:
            headers["Accept"] = f"{wire.CONTENT_TYPE}, application/json"
        if self.tracer is not None:
            self.tracer.inject(headers)
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
                if raw:
                    return payload
                if (resp.headers.get("Content-Type") or "").startswith(
                        wire.CONTENT_TYPE):
                    return wire.loads(payload)
                return json.loads(payload or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")[:500]
            raise ClientError(f"{method} {url}: {e.code}: {detail}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ClientError(f"{method} {url}: {e}") from e

    # -- query fan-out (reference QueryNode, http/client.go:241) -------------

    def query_node(self, uri: str, index: str, pql: str,
                   shards: List[int]) -> List[Any]:
        q = ",".join(str(s) for s in shards)
        res = self._req("POST", f"{uri}/index/{index}/query"
                                f"?shards={q}&remote=true",
                        pql.encode("utf-8"))
        return res["results"]

    # -- imports (reference importNode, http/client.go:439) ------------------

    def import_node(self, uri: str, index: str, field: str,
                    body: Dict[str, Any], clear: bool = False) -> None:
        suffix = "?clear=1&remote=true" if clear else "?remote=true"
        self._req("POST", f"{uri}/index/{index}/field/{field}/import{suffix}",
                  obj=body)

    def import_roaring_node(self, uri: str, index: str, field: str,
                            shard: int, data: bytes,
                            view: str = "standard") -> None:
        self._req("POST",
                  f"{uri}/index/{index}/field/{field}/import-roaring/{shard}"
                  f"?view={view}&remote=true", data)

    # -- fragment sync (reference :711-901) ----------------------------------

    def retrieve_shard(self, uri: str, index: str, field: str, view: str,
                       shard: int) -> bytes:
        return self._req(
            "GET", f"{uri}/internal/fragment/data?index={index}"
                   f"&field={field}&view={view}&shard={shard}", raw=True)

    def fragment_blocks(self, uri: str, index: str, field: str, view: str,
                        shard: int) -> List[dict]:
        res = self._req(
            "GET", f"{uri}/internal/fragment/blocks?index={index}"
                   f"&field={field}&view={view}&shard={shard}")
        return res["blocks"]

    def block_data(self, uri: str, index: str, field: str, view: str,
                   shard: int, block: int) -> dict:
        return self._req(
            "GET", f"{uri}/internal/fragment/block/data?index={index}"
                   f"&field={field}&view={view}&shard={shard}&block={block}")

    # -- attr sync (reference http/client.go:903-983 attr diff) ---------------

    def attr_blocks(self, uri: str, index: str,
                    field: Optional[str] = None) -> List[dict]:
        f = f"&field={field}" if field else ""
        return self._req(
            "GET", f"{uri}/internal/attr/blocks?index={index}{f}")["blocks"]

    def attr_block_data(self, uri: str, index: str, field: Optional[str],
                        block: int) -> Dict[str, Any]:
        f = f"&field={field}" if field else ""
        return self._req(
            "GET", f"{uri}/internal/attr/block/data?index={index}{f}"
                   f"&block={block}")["attrs"]

    def attr_merge(self, uri: str, index: str, field: Optional[str],
                   attrs: Dict[str, Any]) -> None:
        f = f"&field={field}" if field else ""
        self._req("POST", f"{uri}/internal/attr/merge?index={index}{f}",
                  obj={"attrs": attrs})

    # -- schema / membership --------------------------------------------------

    def schema(self, uri: str) -> dict:
        return self._req("GET", f"{uri}/schema")

    def status(self, uri: str) -> dict:
        return self._req("GET", f"{uri}/status")

    def local_shards(self, uri: str) -> Dict[str, List[int]]:
        return self._req("GET", f"{uri}/internal/local-shards")

    def views(self, uri: str, index: str, field: str) -> List[str]:
        return self._req(
            "GET", f"{uri}/internal/views?index={index}&field={field}"
        )["views"]

    def join(self, uri: str, node: dict) -> dict:
        return self._req("POST", f"{uri}/internal/join", obj=node)

    def resize_pull(self, uri: str, timeout: float = 600.0) -> dict:
        """Synchronous pull pass on a member during a resize job (the data
        motion of the reference's ResizeInstruction, cluster.go:1251).
        Long timeout: the node streams every fragment it now owns."""
        req = urllib.request.Request(f"{uri}/internal/resize/pull",
                                     data=b"", method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")[:500]
            raise ClientError(
                f"POST {uri}/internal/resize/pull: {e.code}: {detail}") \
                from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ClientError(f"POST {uri}/internal/resize/pull: {e}") from e

    def cluster_message(self, uri: str, message: dict) -> None:
        self._req("POST", f"{uri}/internal/cluster/message", obj=message)

    def create_index_node(self, uri: str, index: str, options: dict) -> None:
        try:
            self._req("POST", f"{uri}/index/{index}?remote=true",
                      obj={"options": options})
        except ClientError as e:
            if "409" not in str(e):
                raise

    def create_field_node(self, uri: str, index: str, field: str,
                          options: dict) -> None:
        try:
            self._req("POST", f"{uri}/index/{index}/field/{field}"
                              f"?remote=true",
                      obj={"options": options})
        except ClientError as e:
            if "409" not in str(e):
                raise
