"""Inter-node HTTP client.

Reference: /root/reference/http/client.go (InternalClient — query fan-out
:241, imports :439, fragment streaming :711, block sync :811-901) and the
interface /root/reference/client.go:32. Bodies and responses use the
binary wire codec (server/wire.py, the analog of the reference's protobuf
Serializer) with JSON fallback; roaring payloads stay raw bytes.
"""

from __future__ import annotations

import http.client
import json
from pilosa_tpu.utils.failpoints import (
    FAILPOINTS, FailpointDrop, FailpointError,
)
from pilosa_tpu.utils.locks import make_lock
from typing import Any, Dict, List, Optional
from urllib.parse import urlsplit

from pilosa_tpu.server import wire

# Fault-injection sites on the four ways an internal RPC actually fails
# in production (utils/failpoints.py catalog): connect refused /
# partitioned, mid-flight connection loss, a 5xx answer, and a torn
# response body (the one that parses into a NON-ClientError).
_FP_CONNECT = FAILPOINTS.register("client.connect")
_FP_READ = FAILPOINTS.register("client.read")
_FP_5XX = FAILPOINTS.register("client.5xx")
_FP_TORN = FAILPOINTS.register("client.torn_body")


class ClientError(RuntimeError):
    """status/body are set for HTTP >=400 responses (None for transport
    errors), so callers can match on the response rather than substring-
    scanning a string that also contains the request URL."""

    def __init__(self, msg: str, status: Optional[int] = None,
                 body: str = ""):
        super().__init__(msg)
        self.status = status
        self.body = body


class _ConnPool:
    """Keep-alive HTTP/1.1 connections per (scheme, host, port). The
    reference gets this from Go's default http.Transport pooling (TLS
    included); without it every scatter-gather leg pays a TCP — and for
    https a TLS — handshake."""

    MAX_IDLE_PER_HOST = 8

    def __init__(self, timeout: float, ssl_context=None):
        self.timeout = timeout
        self.ssl_context = ssl_context
        self._idle: Dict[tuple, list] = {}
        self._lock = make_lock("_ConnPool._lock")

    def _new_conn(self, scheme: str, host: str, port: int,
                  timeout: float) -> http.client.HTTPConnection:
        import socket as _socket
        if scheme == "https":
            ctx = self.ssl_context
            if ctx is None:
                # https peer with no configured context: strict default
                # verification (system CA bundle) — never silently
                # downgrade to unverified.
                import ssl
                ctx = ssl.create_default_context()
                self.ssl_context = ctx
            conn = http.client.HTTPSConnection(host, port, timeout=timeout,
                                               context=ctx)
        else:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        conn.connect()
        # Nagle + delayed-ACK on a reused connection turns every small
        # header+body request pair into a ~40 ms stall; disable it.
        raw = getattr(conn.sock, "socket", conn.sock)  # unwrap SSLSocket
        raw.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        return conn

    def get(self, scheme: str, host: str, port: int,
            timeout: Optional[float] = None):
        """-> (connection, reused): reused=True means it came from the
        idle pool and may have been closed server-side while idle.
        `timeout` overrides the socket timeout for THIS request only —
        the connection still pools (put() restores the default), so a
        per-request deadline no longer costs a TCP(+TLS) handshake the
        way the old dedicated-connection path did."""
        with self._lock:
            idle = self._idle.get((scheme, host, port))
            conn = idle.pop() if idle else None
        if conn is not None:
            if timeout is not None:
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
            return conn, True
        return self._new_conn(scheme, host, port,
                              self.timeout if timeout is None
                              else timeout), False

    def put(self, scheme: str, host: str, port: int,
            conn: http.client.HTTPConnection) -> None:
        if conn.timeout != self.timeout:
            # Restore the pool default before the conn serves another
            # request (a short health-probe timeout must not leak onto
            # the next 30 s query leg, nor vice versa).
            conn.timeout = self.timeout
            if conn.sock is not None:
                conn.sock.settimeout(self.timeout)
        with self._lock:
            idle = self._idle.setdefault((scheme, host, port), [])
            if len(idle) < self.MAX_IDLE_PER_HOST:
                idle.append(conn)
                return
        conn.close()

    def clear(self) -> None:
        with self._lock:
            conns = [c for idle in self._idle.values() for c in idle]
            self._idle.clear()
        for c in conns:
            c.close()


class InternalClient:
    # Class-level defaults for the three RPC classes (overridden per
    # instance by the [cluster] config keys — cli/main.py wiring). The
    # old scattered 5 s / 30 s / 600 s literals all resolve here now.
    DEFAULT_TIMEOUT = 30.0       # general RPC ([cluster] rpc_timeout_s)
    HEALTH_TIMEOUT = 5.0         # health/hotspots/timeline probes
    RESIZE_PULL_TIMEOUT = 600.0  # synchronous resize pull pass

    def __init__(self, timeout: float = DEFAULT_TIMEOUT, tracer=None,
                 ssl_context=None,
                 health_timeout: float = HEALTH_TIMEOUT,
                 resize_pull_timeout: float = RESIZE_PULL_TIMEOUT):
        """`ssl_context` verifies https peers (config.client_ssl_context
        builds it: CA bundle or skip-verify, reference
        server/server.go:244 InsecureSkipVerify). None + an https URI =
        strict system-CA verification."""
        self.timeout = timeout
        self.health_timeout = health_timeout
        self.resize_pull_timeout = resize_pull_timeout
        self.tracer = tracer
        self._pool = _ConnPool(timeout, ssl_context=ssl_context)

    def configure(self, timeout: Optional[float] = None,
                  health_timeout: Optional[float] = None,
                  resize_pull_timeout: Optional[float] = None) -> None:
        """[cluster] config wiring (cli/main.py): rpc_timeout_s /
        health_timeout_s / resize_pull_timeout_s."""
        if timeout is not None:
            self.timeout = float(timeout)
            self._pool.timeout = float(timeout)
        if health_timeout is not None:
            self.health_timeout = float(health_timeout)
        if resize_pull_timeout is not None:
            self.resize_pull_timeout = float(resize_pull_timeout)

    def drop_idle(self) -> None:
        """Close every idle pooled connection (test harnesses use this to
        sever keep-alive sockets when simulating a dead peer)."""
        self._pool.clear()

    def _req(self, method: str, url: str, body: Optional[bytes] = None,
             raw: bool = False, obj=None, timeout: Optional[float] = None):
        """One internal request over a pooled keep-alive connection.
        `obj` bodies and non-raw responses use the binary wire codec
        (server/wire.py — the rebuild's analog of the reference's
        protobuf Serializer, encoding/proto/proto.go:29); JSON stays the
        fallback for older peers."""
        if obj is not None:
            try:
                body = wire.dumps(obj)
                headers = {"Content-Type": wire.CONTENT_TYPE}
            except TypeError:  # e.g. >64-bit int — JSON handles it
                body = json.dumps(obj).encode("utf-8")
                headers = {"Content-Type": "application/json"}
        else:
            headers = {"Content-Type": "application/json"}
        if not raw:
            headers["Accept"] = f"{wire.CONTENT_TYPE}, application/json"
        if self.tracer is not None:
            self.tracer.inject(headers)
        try:
            _FP_5XX.fire(url=url)
        except FailpointError as e:
            raise ClientError(f"{method} {url}: 500: failpoint",
                              status=500, body="failpoint") from e
        parts = urlsplit(url)
        scheme = parts.scheme or "http"
        host = parts.hostname or "localhost"
        port = parts.port or (443 if scheme == "https" else 80)
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        try:
            _FP_CONNECT.fire(url=url)
            conn, reused = self._pool.get(scheme, host, port,
                                          timeout=timeout)
        except OSError as e:  # eager connect: refused/unreachable
            raise ClientError(f"{method} {url}: {e}") from e
        try:
            try:
                _FP_READ.fire(url=url)
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
            except (http.client.HTTPException, ConnectionError,
                    OSError) as e:
                # A REUSED connection may have gone stale (server closed
                # the idle socket); retry once on a fresh one — but never
                # after a timeout (a slow-but-alive peer must not be hit
                # twice) and never for fresh connections, matching Go's
                # transport semantics (retry only reused conns). The
                # narrow duplicate-POST race (server processed AND closed
                # before our read) is safe for every endpoint this path
                # carries: imports/cluster messages/attr merges are
                # idempotent, translate allocation is get-or-allocate,
                # and the schema create legs treat already-exists as
                # success (see create_index_node).
                conn.close()
                if not reused or isinstance(e, TimeoutError):
                    raise
                conn = self._pool._new_conn(scheme, host, port,
                                            timeout or self.timeout)
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
            payload = resp.read()
            try:
                _FP_TORN.fire(url=url)
            except FailpointDrop:
                payload = b""  # response lost after the server acted
            except FailpointError:
                # Torn body: the connection died mid-read. The parse
                # below then raises a NON-ClientError (JSONDecodeError /
                # WireError) — exactly the class the scatter-gather
                # accounting must survive.
                payload = payload[: len(payload) // 2]
            status = resp.status
            ctype = resp.headers.get("Content-Type") or ""
            reusable = not resp.will_close
            if reusable:
                self._pool.put(scheme, host, port, conn)
            else:
                conn.close()
            if status >= 400:
                body = payload.decode("utf-8", "replace")[:500]
                raise ClientError(f"{method} {url}: {status}: {body}",
                                  status=status, body=body)
            if raw:
                return payload
            if ctype.startswith(wire.CONTENT_TYPE):
                return wire.loads(payload)
            return json.loads(payload or b"{}")
        except ClientError:
            raise
        except (http.client.HTTPException, ConnectionError, OSError,
                TimeoutError) as e:
            conn.close()
            raise ClientError(f"{method} {url}: {e}") from e

    # -- query fan-out (reference QueryNode, http/client.go:241) -------------

    def query_node(self, uri: str, index: str, pql: str,
                   shards: List[int],
                   timeout: Optional[float] = None) -> List[Any]:
        return self.query_node_full(uri, index, pql, shards,
                                    timeout=timeout)["results"]

    def query_node_full(self, uri: str, index: str, pql: str,
                        shards: List[int], profile: bool = False,
                        timeout: Optional[float] = None
                        ) -> Dict[str, Any]:
        """query_node returning the FULL response dict. With
        profile=True the ?profile=true flag propagates to the remote
        node, whose response carries its own execution-profile fragment
        under "profile" — the coordinator merges these into one tree
        (cluster_executor._map_reduce -> QueryProfile.add_node_fragment).
        `timeout` is the scatter leg's share of the request's fan-out
        deadline budget (cluster_executor._map_reduce); None keeps the
        client default."""
        q = ",".join(str(s) for s in shards)
        p = "&profile=true" if profile else ""
        return self._req("POST", f"{uri}/index/{index}/query"
                                 f"?shards={q}&remote=true{p}",
                         pql.encode("utf-8"), timeout=timeout)

    # -- imports (reference importNode, http/client.go:439) ------------------

    def import_node(self, uri: str, index: str, field: str,
                    body: Dict[str, Any], clear: bool = False) -> None:
        suffix = "?clear=1&remote=true" if clear else "?remote=true"
        self._req("POST", f"{uri}/index/{index}/field/{field}/import{suffix}",
                  obj=body)

    def import_roaring_node(self, uri: str, index: str, field: str,
                            shard: int, data: bytes,
                            view: str = "standard") -> None:
        self._req("POST",
                  f"{uri}/index/{index}/field/{field}/import-roaring/{shard}"
                  f"?view={view}&remote=true", data)

    # -- fragment sync (reference :711-901) ----------------------------------

    def retrieve_shard(self, uri: str, index: str, field: str, view: str,
                       shard: int) -> bytes:
        return self._req(
            "GET", f"{uri}/internal/fragment/data?index={index}"
                   f"&field={field}&view={view}&shard={shard}", raw=True)

    def fragment_blocks(self, uri: str, index: str, field: str, view: str,
                        shard: int) -> List[dict]:
        res = self._req(
            "GET", f"{uri}/internal/fragment/blocks?index={index}"
                   f"&field={field}&view={view}&shard={shard}")
        return res["blocks"]

    def block_data(self, uri: str, index: str, field: str, view: str,
                   shard: int, block: int) -> dict:
        return self._req(
            "GET", f"{uri}/internal/fragment/block/data?index={index}"
                   f"&field={field}&view={view}&shard={shard}&block={block}")

    # -- attr sync (reference http/client.go:903-983 attr diff) ---------------

    def attr_blocks(self, uri: str, index: str,
                    field: Optional[str] = None) -> List[dict]:
        f = f"&field={field}" if field else ""
        return self._req(
            "GET", f"{uri}/internal/attr/blocks?index={index}{f}")["blocks"]

    def attr_block_data(self, uri: str, index: str, field: Optional[str],
                        block: int) -> Dict[str, Any]:
        f = f"&field={field}" if field else ""
        return self._req(
            "GET", f"{uri}/internal/attr/block/data?index={index}{f}"
                   f"&block={block}")["attrs"]

    def attr_merge(self, uri: str, index: str, field: Optional[str],
                   attrs: Dict[str, Any]) -> None:
        f = f"&field={field}" if field else ""
        self._req("POST", f"{uri}/internal/attr/merge?index={index}{f}",
                  obj={"attrs": attrs})

    # -- schema / membership --------------------------------------------------

    def schema(self, uri: str) -> dict:
        return self._req("GET", f"{uri}/schema")

    def status(self, uri: str) -> dict:
        return self._req("GET", f"{uri}/status")

    def node_health(self, uri: str,
                    timeout: Optional[float] = None) -> dict:
        """One node's health self-report (GET /internal/health) for the
        coordinator's /cluster/health merge. Short timeout (default
        `health_timeout`, [cluster] health_timeout_s): the health plane
        must report a wedged node as unhealthy, not hang the whole
        fleet document behind it."""
        return self._req("GET", f"{uri}/internal/health",
                         timeout=timeout or self.health_timeout)

    def node_hotspots(self, uri: str, timeout: Optional[float] = None,
                      top_k: Optional[int] = None) -> dict:
        """One node's workload snapshot (GET /debug/hotspots) for the
        /cluster/hotspots merge — same short-timeout rule as
        node_health: a wedged node is reported, not waited on. `top_k`
        forwards the coordinator's ?topk so every member's lists share
        one bound."""
        q = f"?topk={int(top_k)}" if top_k is not None else ""
        return self._req("GET", f"{uri}/debug/hotspots{q}",
                         timeout=timeout or self.health_timeout)

    def node_slo(self, uri: str,
                 timeout: Optional[float] = None) -> dict:
        """One node's SLO snapshot (GET /debug/slo) for the
        /cluster/slo merge — same short-timeout rule as node_health:
        a wedged node is reported, not waited on."""
        return self._req("GET", f"{uri}/debug/slo",
                         timeout=timeout or self.health_timeout)

    def node_timeline(self, uri: str, trace_id: str,
                      timeout: Optional[float] = None) -> dict:
        """One node's timeline slices for a trace id (GET
        /debug/timeline?trace=...) for the coordinator's
        /cluster/timeline assembly — same short-timeout rule as
        node_health: a wedged node is reported, not waited on."""
        from urllib.parse import quote
        return self._req("GET",
                         f"{uri}/debug/timeline?trace={quote(trace_id)}",
                         timeout=timeout or self.health_timeout)

    def local_shards(self, uri: str) -> Dict[str, List[int]]:
        return self._req("GET", f"{uri}/internal/local-shards")

    def views(self, uri: str, index: str, field: str) -> List[str]:
        return self._req(
            "GET", f"{uri}/internal/views?index={index}&field={field}"
        )["views"]

    def join(self, uri: str, node: dict) -> dict:
        return self._req("POST", f"{uri}/internal/join", obj=node)

    def resize_pull(self, uri: str,
                    timeout: Optional[float] = None) -> dict:
        """Synchronous pull pass on a member during a resize job (the data
        motion of the reference's ResizeInstruction, cluster.go:1251).
        Long timeout (default `resize_pull_timeout`, [cluster]
        resize_pull_timeout_s): the node streams every fragment it now
        owns."""
        return self._req("POST", f"{uri}/internal/resize/pull", body=b"",
                         timeout=timeout or self.resize_pull_timeout)

    def cluster_message(self, uri: str, message: dict) -> None:
        self._req("POST", f"{uri}/internal/cluster/message", obj=message)

    @staticmethod
    def _is_already_exists(e: ClientError) -> bool:
        # 409 alone is not enough: the API also answers 409 for "method
        # not allowed in state RESIZING" (server/api.py), which must NOT
        # read as success. Match the response BODY, never the whole
        # string — it contains the URL, and an index named "exists"
        # would alias.
        return e.status == 409 and "exists" in e.body

    def create_index_node(self, uri: str, index: str, options: dict) -> None:
        """Remote create leg. Already-exists reads as success: the
        stale-connection retry in _req can duplicate a POST when the
        peer processed the first request but closed the socket before
        the response was read (ADVICE r2)."""
        try:
            self._req("POST", f"{uri}/index/{index}?remote=true",
                      obj={"options": options})
        except ClientError as e:
            if not self._is_already_exists(e):
                raise

    def create_field_node(self, uri: str, index: str, field: str,
                          options: dict) -> None:
        try:
            self._req("POST", f"{uri}/index/{index}/field/{field}"
                              f"?remote=true",
                      obj={"options": options})
        except ClientError as e:
            if not self._is_already_exists(e):
                raise
