"""Shard placement hashing.

Reference: /root/reference/cluster.go:828-913 — shard -> partition via
fnv64a over (index name, shard) mod 256 partitions, then partition -> node
via Lamping-Veach jump consistent hashing (jmphasher.Hash, cluster.go:902),
with ReplicaN successive nodes around the ring (partitionNodes,
cluster.go:857-877). Reimplemented from the published algorithms.
"""

from __future__ import annotations

import struct
from typing import List

DEFAULT_PARTITION_N = 256

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3


def fnv64a(data: bytes) -> int:
    h = _FNV64_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def partition(index: str, shard: int, partition_n: int = DEFAULT_PARTITION_N
              ) -> int:
    """(reference cluster.partition, cluster.go:828-837: hashes the index
    name then the shard as 8 little-endian bytes)."""
    buf = struct.pack("<Q", shard)
    return fnv64a(index.encode("utf-8") + buf) % partition_n


def jump_hash(key: int, n_buckets: int) -> int:
    """Lamping-Veach jump consistent hash (reference jmphasher.Hash,
    cluster.go:902-913): minimal movement when n_buckets changes."""
    if n_buckets <= 0:
        return -1
    b, j = -1, 0
    key &= 0xFFFFFFFFFFFFFFFF
    while j < n_buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & 0xFFFFFFFFFFFFFFFF
        j = int((b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


def partition_nodes(partition_id: int, n_nodes: int, replica_n: int
                    ) -> List[int]:
    """Node indexes serving a partition: jump-hash owner + ReplicaN-1
    successors around the sorted ring (reference partitionNodes,
    cluster.go:857-877)."""
    if n_nodes == 0:
        return []
    replica_n = min(max(replica_n, 1), n_nodes)
    owner = jump_hash(partition_id, n_nodes)
    return [(owner + i) % n_nodes for i in range(replica_n)]


def shard_nodes(index: str, shard: int, n_nodes: int, replica_n: int = 1,
                partition_n: int = DEFAULT_PARTITION_N) -> List[int]:
    """(reference ShardNodes, cluster.go:840)."""
    return partition_nodes(partition(index, shard, partition_n), n_nodes,
                           replica_n)
