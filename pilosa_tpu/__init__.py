"""pilosa_tpu — a TPU-native distributed bitmap index.

A from-scratch rebuild of the capabilities of Pilosa (reference:
Crixalis2013/pilosa, a distributed in-memory roaring-bitmap index) designed
TPU-first:

- Shard-resident rows are packed uint32 bitsets in HBM (2^20 bits/shard).
- The reference's per-container Go kernels (roaring/roaring.go:2313-3607)
  collapse into fused XLA bitwise + popcount ops over dense words.
- Per-shard query evaluation is batched per chip (shards as a leading array
  axis) instead of goroutine-per-shard (executor.go:2377).
- Cross-shard reduction rides ICI collectives under jax.shard_map instead of
  HTTP scatter-gather (executor.go:2277).
- Sparse/run encodings (roaring containers) remain a host/storage concern:
  durability uses the reference's roaring file format (cookie 12348).
"""

from pilosa_tpu.ops.bitset import (  # noqa: F401
    SHARD_WIDTH,
    WORDS_PER_SHARD,
    WORD_BITS,
)

__version__ = "0.1.0"
