"""Dense packed-bitset kernels for TPU.

This is the L0 of the framework: where the reference implements nine families
of pairwise container kernels over three container encodings
(/root/reference/roaring/roaring.go:2313-3607 — intersect*/union*/difference*/
xor*/intersectionCount*/shift*/flip* for array/bitmap/run), we keep exactly one
device encoding — a dense packed bitset — and let every op be a fused XLA
elementwise + reduction over uint32 words.

Layout
------
A *shard row* is one row of one fragment restricted to a 2^20-column shard
(ShardWidth, /root/reference/fragment.go:50). On device it is a
`uint32[WORDS_PER_SHARD]` array (32768 words = 128 KiB). uint32 rather than
uint64 because the TPU VPU has 32-bit lanes; XLA legalizes u64 bitwise ops into
u32 pairs anyway, so we store u32 natively and avoid the round trip.

Bit p (0 <= p < 2^20) lives in word p >> 5, bit p & 31 — identical to the
little-endian uint64 layout viewed as pairs of uint32, so host numpy uint64
buffers convert with a zero-copy ``.view('<u4')``.

All ops are pure jnp functions over arrays whose *last* axis is words; any
leading axes (rows, shards) batch for free. Compositions are jitted at the
executor layer so XLA fuses e.g. Count(Intersect(a,b)) into a single
AND+popcount pass without materializing the intersection — the moral
equivalent of the reference's fused `intersectionCountBitmapBitmap`
(/root/reference/roaring/roaring.go:2438), generalized to every op pair.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.typing import ArrayLike

# Shard geometry. ShardWidth mirrors /root/reference/fragment.go:50-51
# (2^20 columns per shard); it must stay a power of two and a multiple of
# the container width 2^16 so host roaring containers tile it exactly.
SHARD_WIDTH_EXP = 20
SHARD_WIDTH = 1 << SHARD_WIDTH_EXP  # 1,048,576 columns per shard
WORD_BITS = 32
WORDS_PER_SHARD = SHARD_WIDTH // WORD_BITS  # 32,768 uint32 words (128 KiB)

WORD_DTYPE = jnp.uint32
NP_WORD_DTYPE = np.uint32

# ---------------------------------------------------------------------------
# Elementwise set algebra. Last axis = words; leading axes batch.
# ---------------------------------------------------------------------------


def b_and(a: ArrayLike, b: ArrayLike) -> jax.Array:
    """Intersect (reference: roaring.go:497 Intersect / :2630 bitmap∧bitmap)."""
    return jnp.bitwise_and(a, b)


def b_or(a: ArrayLike, b: ArrayLike) -> jax.Array:
    """Union (reference: roaring.go:522)."""
    return jnp.bitwise_or(a, b)


def b_xor(a: ArrayLike, b: ArrayLike) -> jax.Array:
    """Xor (reference: roaring.go:837)."""
    return jnp.bitwise_xor(a, b)


def b_andnot(a: ArrayLike, b: ArrayLike) -> jax.Array:
    """Difference a \\ b (reference: roaring.go:810)."""
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


def b_not(a: ArrayLike, existence: ArrayLike) -> jax.Array:
    """Not(a) relative to an existence row (reference executor computes Not as
    existence-difference, /root/reference/executor.go:1556-1587)."""
    return jnp.bitwise_and(existence, jnp.bitwise_not(a))


def union_many(stack: jax.Array, axis: int = 0) -> jax.Array:
    """N-way union over a stacked axis (reference UnionInPlace,
    roaring.go:536 — the bulk union used by time-range row reads)."""
    return jax.lax.reduce(
        stack,
        jnp.zeros((), dtype=stack.dtype),
        jnp.bitwise_or,
        (axis if axis >= 0 else stack.ndim + axis,),
    )


def intersect_many(stack: jax.Array, axis: int = 0) -> jax.Array:
    """N-way intersection over a stacked axis."""
    return jax.lax.reduce(
        stack,
        jnp.bitwise_not(jnp.zeros((), dtype=stack.dtype)),
        jnp.bitwise_and,
        (axis if axis >= 0 else stack.ndim + axis,),
    )


# ---------------------------------------------------------------------------
# Counting. popcount reduces the word axis; XLA fuses it into whatever
# elementwise op produced the words.
# ---------------------------------------------------------------------------


def popcount(a: ArrayLike,
             axis: Union[int, Tuple[int, ...]] = -1) -> jax.Array:
    """Total set bits, reduced over `axis` (reference Count, roaring.go:319).

    Returns uint32: one reduced axis covers at most one shard row
    (2^20 bits), and even a full 1024-shard stack is 2^30 < 2^32. Promote
    on the host when aggregating across many reductions."""
    return jnp.sum(jax.lax.population_count(a).astype(jnp.uint32), axis=axis,
                   dtype=jnp.uint32)


def count_and(a: ArrayLike, b: ArrayLike) -> jax.Array:
    """|a ∧ b| fused (reference IntersectionCount, roaring.go:472/2438)."""
    return popcount(jnp.bitwise_and(a, b))


def count_or(a: ArrayLike, b: ArrayLike) -> jax.Array:
    return popcount(jnp.bitwise_or(a, b))


def count_xor(a: ArrayLike, b: ArrayLike) -> jax.Array:
    return popcount(jnp.bitwise_xor(a, b))


def count_andnot(a: ArrayLike, b: ArrayLike) -> jax.Array:
    return popcount(jnp.bitwise_and(a, jnp.bitwise_not(b)))


# ---------------------------------------------------------------------------
# Shifts and masks.
# ---------------------------------------------------------------------------


def shift_bits(a: jax.Array, n: int = 1) -> jax.Array:
    """Shift every bit position up by n within the shard (reference
    roaring.Shift, roaring.go:865, used by executeShiftShard,
    executor.go:1591). Bits shifted past the top of the shard are dropped —
    matching the reference's per-rowSegment shift (row.go:180-197), which
    does not carry across shard boundaries either.
    """
    if n == 0:
        return a
    word_shift = n // WORD_BITS
    bit_shift = n % WORD_BITS
    # Move whole words by padding at the low end of the word axis.
    if word_shift:
        pad = [(0, 0)] * (a.ndim - 1) + [(word_shift, 0)]
        a = jnp.pad(a, pad)[..., : a.shape[-1]]
    if bit_shift:
        hi = jnp.left_shift(a, jnp.uint32(bit_shift))
        carry = jnp.right_shift(a, jnp.uint32(WORD_BITS - bit_shift))
        pad = [(0, 0)] * (a.ndim - 1) + [(1, 0)]
        carry = jnp.pad(carry, pad)[..., : a.shape[-1]]
        a = jnp.bitwise_or(hi, carry)
    return a


def range_mask_np(start: int, end: int, words: int = WORDS_PER_SHARD) -> np.ndarray:
    """Host-built uint32 mask with bits [start, end) set. Used for
    CountRange/OffsetRange-style column windows; built once per query on the
    host, so plain numpy."""
    mask = np.zeros(words, dtype=np.uint32)
    start = max(0, start)
    end = min(end, words * WORD_BITS)
    if end <= start:
        return mask
    w0, b0 = divmod(start, WORD_BITS)
    w1, b1 = divmod(end, WORD_BITS)
    if w0 == w1:
        mask[w0] = (np.uint64((1 << b1) - (1 << b0))).astype(np.uint32)
    else:
        mask[w0] = np.uint32(((1 << WORD_BITS) - (1 << b0)) & 0xFFFFFFFF)
        mask[w0 + 1 : w1] = np.uint32(0xFFFFFFFF)
        if b1:
            mask[w1] = np.uint32((1 << b1) - 1)
    return mask


# ---------------------------------------------------------------------------
# Host <-> device packing helpers (numpy; the storage layer owns durability).
# ---------------------------------------------------------------------------


def pack_positions(positions: Union[Sequence[int], np.ndarray],
                   width: int = SHARD_WIDTH) -> np.ndarray:
    """Pack sorted/unsorted bit positions (< width) into a uint32 word array."""
    words = np.zeros(width // WORD_BITS, dtype=np.uint32)
    if len(positions) == 0:
        return words
    pos = np.asarray(positions, dtype=np.uint64)
    # graftlint: disable=GL005 — w is a word-INDEX vector for
    # np.bitwise_or.at (numpy requires signed indices), not word data.
    w = (pos >> np.uint64(5)).astype(np.int64)
    b = (pos & np.uint64(31)).astype(np.uint32)
    np.bitwise_or.at(words, w, np.left_shift(np.uint32(1), b))
    return words


def unpack_positions(words: np.ndarray) -> np.ndarray:
    """Inverse of pack_positions: word array -> sorted uint64 bit positions."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    bytes_ = words.view(np.uint8)
    bits = np.unpackbits(bytes_, bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint64)


def u64_to_words(buf: np.ndarray) -> np.ndarray:
    """Zero-copy view of a little-endian uint64 bitmap buffer as u32 words."""
    return np.ascontiguousarray(buf).view("<u4")


def words_to_u64(words: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(words).view("<u8")


def transfer_nbytes(arrays) -> int:
    """Sum of .nbytes over an iterable of (device or host) arrays,
    skipping entries without the attribute. Shape metadata only — never
    touches array contents, so it is safe on unfetched device arrays
    (the profiler's H2D/D2H transfer-byte accounting)."""
    total = 0
    for a in arrays or ():
        n = getattr(a, "nbytes", None)
        if n is not None:
            total += int(n)
    return total
