"""Heterogeneous staged-query megakernel: N *different* compiled tree
programs in ONE device launch.

Same-signature fusion (executor/fusion.py) collapses structurally
identical queries into one vmapped program, but a realistic mixed burst
still pays one XLA launch per distinct query shape — and docs/perf.md
§5 shows the serving path is floor-bound by exactly that per-launch
host/tunnel cost. The fix here is the classic accelerator-offload move
(the FPGA bitmap-accelerator line of work, PAPERS.md arXiv 1803.11207):
make the query PLAN data instead of code. The bitmap op mix is tiny and
regular (AND/OR/XOR/ANDNOT over packed words + popcount reduce — the
Roaring survey's whole op table, arXiv 1709.07821), so every staged
eval lowers to a handful of register instructions, and one
opcode-interpreting program executes the concatenated instruction
streams of an arbitrary mixed batch in a single launch.

Execution model
---------------
* A *register* is one ``[S, W]`` uint32 word slab (S shards, W words).
* Registers ``0..n_slots-1`` are gathered operand rows: per distinct
  bank, ``bank[slots]`` fitted to the launch width and masked down to
  each owning entry's plan width (bit-identical to the unfused path's
  per-leaf ``_align_words``; zero-extension commutes with every opcode
  below, so pad words stay zero end to end).
* Registers ``n_slots..n_slots+n_xslots-1`` are *expand* registers
  (hybrid layout): rows of device-resident sparse banks
  (core/view.SparseBank — encoded set-bit positions instead of dense
  words), scatter-expanded to dense ``[S, W]`` rows before the
  instruction loop and importable into the dataflow ONLY through the
  ``OP_EXPAND`` opcode (verify_plan's expand typing rule).
* Registers above the gathered/expanded operands are scratch,
  allocated by the lowering.
* The plan buffer is an int32 ``[P, 4]`` array of ``(opcode, dst, a,
  b)`` rows; the interpreter fori-loops over it, ``lax.switch``-ing on
  the opcode. Instructions, slots, widths and output indices are all
  *data* — a new mixed-batch composition re-uses the compiled
  interpreter as long as the pow2-padded capacities match, so the
  compile cache holds O(log) variants, not one per composition.
* Outputs: ``counts[out_count] = popcount(reg)`` for count-mode
  entries (the fused AND+popcount the Tanimoto top-K workload is made
  of) and ``rows[out_row] = reg`` for row-mode entries, each entry
  slicing its lane (and its plan width) off the shared result.

BSI comparison predicates lower too: the executor/bsi.py scans are
pure AND/OR/ANDNOT folds whose per-bit branches depend only on the
*host-known* predicate value, so ``v > 300`` becomes ~2·depth plan
rows — value changes change plan bytes, never the compiled program.

The default interpreter is a jitted jnp program (one XLA launch — the
launch count is what the dispatch floor charges for); an opt-in Pallas
flavor of the instruction loop lives in ops/pallas_kernels.py under
the same PILOSA_TPU_PALLAS gate as the bank-sweep kernels.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

# A token-space register name while the Lowering accumulates: slot
# tokens are ("s", bank, k) until finish() resolves bank-grouped
# numbering, scratch tokens are plain ints counted from 0.
Token = Union[Tuple[str, int, int], int]

# Opcodes (plan-buffer rows are (opcode, dst, a, b); ZERO/COPY ignore b).
OP_AND = 0
OP_OR = 1
OP_XOR = 2
OP_ANDNOT = 3   # dst = a & ~b  (Difference, Not-via-existence)
OP_ZERO = 4     # dst = 0
OP_COPY = 5     # dst = a
# Sparse-expand: dst = the dense [S, W] expansion of expand register
# `a`. Expand registers (slab indices [n_slots, n_slots + n_xslots))
# hold rows of device-resident SPARSE banks (core/view.SparseBank:
# encoded bit positions, ~4 B/set bit) scatter-expanded by the
# interpreter before the instruction loop. They are the hybrid
# layout's typed boundary: only OP_EXPAND may read an expand register
# — a bitwise opcode addressing one directly is a type error
# (verify_plan), because the expansion (and its width mask) is what
# makes the register bit-identical to the dense bank row it replaces.
OP_EXPAND = 6   # dst = expanded(a); a must be an expand register
# Threshold accumulate: dst = dst | (a & b) — the thermometer step of
# the N-of-M counter (arXiv 1402.4466 §threshold queries). A K-of-N
# Threshold lowers to K accumulator registers t_1..t_K where t_j holds
# "columns with >= j of the operands seen so far"; folding operand x
# in is t_j |= t_{j-1} & x for j = K..2 plus t_1 |= x, so the whole
# query is O(K·N) plan rows of the SAME word-parallel ops as the rest
# of the table — no per-column counters, no widening. THRESH is the
# one opcode that READS its dst (verify_plan demands the accumulator
# is defined first: a missed t_j init would silently under-count).
OP_THRESH = 7

OP_NAMES = ("and", "or", "xor", "andnot", "zero", "copy", "expand",
            "thresh")

_FOLD_OPS = {"and": OP_AND, "or": OP_OR, "xor": OP_XOR, "diff": OP_ANDNOT}


def pow2_at_least(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the capacity buckets that
    keep the interpreter's compile cache O(log) in every axis."""
    return 1 << max(0, int(n) - 1).bit_length()


def expand_positions(pos: Any, starts: Any, slot: Any, n_shards: int,
                     width: int) -> Any:
    """Dense ``[n_shards, width]`` uint32 row from a sparse bank's
    encoded positions: ``pos`` carries ``(shard_idx << 16) | bitpos``
    per SET bit (sorted per row; bitpos < 2^16 because sparse banks
    only exist for trimmed widths within one container), ``starts`` is
    the per-row-slot i32 offset table, ``slot`` the traced row slot.
    The scatter uses add, which ORs because positions are unique per
    (row, shard) — the same carry-free argument as
    view._expand_sparse_chunk. Positions at/after ``width * 32`` (a
    write widened the view after the bank was built) and the pos
    buffer's pad tail both land on a scratch word past the row and add
    zero, so the result is always exactly the masked dense row."""
    import jax.numpy as jnp

    lo = starts[slot]
    hi = starts[slot + 1]
    idx = jnp.arange(pos.shape[0], dtype=jnp.int32)
    bitpos = (pos & jnp.uint32(0xFFFF)).astype(jnp.int32)
    shard = (pos >> 16).astype(jnp.int32)
    total = int(n_shards) * int(width)
    sel = (idx >= lo) & (idx < hi) & (bitpos < width * 32) \
        & (shard < n_shards)
    word = jnp.where(sel, shard * width + (bitpos >> 5), total)
    bit = jnp.where(sel,
                    jnp.left_shift(jnp.uint32(1),
                                   (pos & jnp.uint32(31))),
                    jnp.uint32(0))
    flat = jnp.zeros((total + 1,), jnp.uint32)
    flat = flat.at[word].add(bit, mode="drop", unique_indices=False)
    return flat[:total].reshape(n_shards, width)


class Lowering:
    """Accumulates one launch's plan across N staged evals.

    Slot registers are discovered in IR order but must land bank-grouped
    in the slab (the gather concatenates per-bank), so instructions are
    emitted in a token space and remapped by ``finish()`` once every
    bank's slot list is complete.
    """

    def __init__(self) -> None:
        # bank identity -> (dense index, ordered slot-value list)
        self.bank_order: List[Any] = []      # bank arrays, launch order
        self.bank_slots: List[List[int]] = []
        self.bank_widths: List[List[int]] = []
        self._bank_pos: Dict[int, int] = {}
        # (bank, slot, width) -> token: entries referencing the SAME
        # operand row share one slab register (slot registers are
        # read-only — folds write scratch), so the flagship
        # Count(Intersect(Row(fp=Q), Row(fp=c_i))) flood gathers the
        # shared query row Q once, not once per candidate.
        self._slot_pos: Dict[Tuple[int, int, int],
                             Tuple[str, int, int]] = {}
        # Sparse (hybrid-layout) operands: per sparse bank a
        # (pos, starts) device pair plus its ordered slot list; expand
        # registers are numbered after the dense slots in finish().
        self.xbank_order: List[Any] = []     # (pos, starts) pairs
        self.xbank_slots: List[List[int]] = []
        self.xbank_widths: List[List[int]] = []
        self._xbank_pos: Dict[int, int] = {}
        # (xbank, slot, width) -> the SCRATCH token holding its
        # OP_EXPAND result: entries sharing a sparse operand row share
        # one expansion, not one per reference.
        self._xslot_expanded: Dict[Tuple[int, int, int], int] = {}
        # token-space program; slot tokens are ("s", bank, k), scratch
        # tokens are plain ints counted from 0.
        self.instrs: List[Tuple[int, Token, Token, Token]] = []
        self.n_scratch = 0
        self.out_count: List[Token] = []  # token per count-mode entry
        self.out_row: List[Token] = []    # token per row-mode entry
        # Per-output-lane plan widths (real lanes only, lane order):
        # the verification plane's ground truth for the masking
        # invariant — every word of an output register at index >= the
        # entry's plan width must be provably zero (verify_plan).
        self.out_count_widths: List[int] = []
        self.out_row_widths: List[int] = []

    # ------------------------------------------------------------ building

    # GL008 disables below: a Lowering is a ONE-LAUNCH builder — it
    # lives from FusionCollector.flush to Plan construction and is
    # dropped with the flush, so its accumulators are bounded by the
    # batch being lowered, not by process lifetime.
    def _bank(self, array: Any) -> int:
        pos = self._bank_pos.get(id(array))
        if pos is None:
            pos = len(self.bank_order)
            # graftlint: disable=GL008 — per-launch builder state.
            self._bank_pos[id(array)] = pos
            # graftlint: disable=GL008 — per-launch builder state.
            self.bank_order.append(array)
            # graftlint: disable=GL008 — per-launch builder state.
            self.bank_slots.append([])
            # graftlint: disable=GL008 — per-launch builder state.
            self.bank_widths.append([])
        return pos

    def _slot(self, array: Any, slot: int, width: int) -> Tuple[str, int, int]:
        b = self._bank(array)
        key = (b, int(slot), int(width))
        token = self._slot_pos.get(key)
        if token is None:
            self.bank_slots[b].append(int(slot))
            self.bank_widths[b].append(int(width))
            token = ("s", b, len(self.bank_slots[b]) - 1)
            # graftlint: disable=GL008 — per-launch builder state.
            self._slot_pos[key] = token
        return token

    def _xbank(self, pair: Any) -> int:
        pos = self._xbank_pos.get(id(pair))
        if pos is None:
            pos = len(self.xbank_order)
            # graftlint: disable=GL008 — per-launch builder state.
            self._xbank_pos[id(pair)] = pos
            # graftlint: disable=GL008 — per-launch builder state.
            self.xbank_order.append(pair)
            # graftlint: disable=GL008 — per-launch builder state.
            self.xbank_slots.append([])
            # graftlint: disable=GL008 — per-launch builder state.
            self.xbank_widths.append([])
        return pos

    def _xslot(self, pair: Any, slot: int, width: int) -> int:
        """Sparse operand row: returns the scratch token holding its
        OP_EXPAND result (one expand register + one expansion per
        distinct (bank, slot, width), however many entries share it)."""
        b = self._xbank(pair)
        key = (b, int(slot), int(width))
        token = self._xslot_expanded.get(key)
        if token is None:
            self.xbank_slots[b].append(int(slot))
            self.xbank_widths[b].append(int(width))
            xtok = ("x", b, len(self.xbank_slots[b]) - 1)
            token = self._scratch()
            self._emit(OP_EXPAND, token, xtok, xtok)
            # graftlint: disable=GL008 — per-launch builder state.
            self._xslot_expanded[key] = token
        return token

    def _scratch(self) -> int:
        self.n_scratch += 1
        return self.n_scratch - 1

    def _emit(self, op: int, dst: Any, a: Any, b: Any) -> None:
        # graftlint: disable=GL008 — per-launch builder state.
        self.instrs.append((op, dst, a, b))

    def add_entry(self, ir: Sequence[tuple], bank_arrays: Sequence[Any],
                  idxs: Sequence[int], params: Sequence[int],
                  width: int, mode: str) -> int:
        """Lower one staged eval's postfix IR; returns the entry's lane
        in its mode's output array."""
        stack: List[Any] = []
        for node in ir:
            kind = node[0]
            if kind == "slot":
                _, pos, i = node
                stack.append(self._slot(bank_arrays[pos], idxs[i], width))
            elif kind == "xslot":
                # Hybrid-layout sparse leaf: bank_arrays[pos] is the
                # SparseBank's (pos, starts) device pair; the operand
                # value is the scratch holding its OP_EXPAND result.
                _, pos, i = node
                stack.append(self._xslot(bank_arrays[pos], idxs[i],
                                         width))
            elif kind == "zero":
                r = self._scratch()
                self._emit(OP_ZERO, r, r, r)
                stack.append(r)
            elif kind == "fold":
                _, opname, n = node
                ops = stack[-n:]
                del stack[-n:]
                acc = ops[0]
                if n > 1:
                    # Left fold into a scratch register: slot registers
                    # may be shared across entries (same bank slot), so
                    # they are read-only.
                    r = self._scratch()
                    self._emit(_FOLD_OPS[opname], r, acc, ops[1])
                    for operand in ops[2:]:
                        self._emit(_FOLD_OPS[opname], r, r, operand)
                    acc = r
                stack.append(acc)
            elif kind == "thresh":
                _, kval, n = node
                ops = stack[-n:]
                del stack[-n:]
                stack.append(self._lower_thresh(int(kval), ops))
            elif kind == "bsi":
                _, bkind, pos, i0, depth, j, k, allow_eq = node
                planes = [self._slot(bank_arrays[pos], idxs[i0 + d], width)
                          for d in range(depth + 1)]
                stack.append(self._lower_bsi(
                    bkind, planes, depth, params, j, k, allow_eq))
            else:  # pragma: no cover - planner and lowering move together
                raise ValueError(f"unknown megakernel IR node {node!r}")
        if len(stack) != 1:  # pragma: no cover - structural invariant
            raise ValueError(f"unbalanced megakernel IR ({len(stack)})")
        root = stack[0]
        if mode == "count":
            # graftlint: disable=GL008 — per-launch builder state.
            self.out_count.append(root)
            # graftlint: disable=GL008 — per-launch builder state.
            self.out_count_widths.append(int(width))
            return len(self.out_count) - 1
        # graftlint: disable=GL008 — per-launch builder state.
        self.out_row.append(root)
        # graftlint: disable=GL008 — per-launch builder state.
        self.out_row_widths.append(int(width))
        return len(self.out_row) - 1

    def _lower_thresh(self, k: int, ops: List[Any]) -> Any:
        """Thermometer N-of-M counter: after folding every operand,
        ``t_j`` holds the columns where at least ``j`` operands are
        set; the query's answer is ``t_k``. The executor maps the
        degenerate edges (k <= 1 -> OR fold, k == n -> AND fold)
        before lowering, but the expansion is correct for any
        1 <= k <= n; k > n (more votes than operands) is the empty
        row — a zeroed register, with the already-staged operands
        consumed from the stack. Descending ``j`` order is
        load-bearing: each step must read the PREVIOUS operand's
        t_{j-1}."""
        n = len(ops)
        if k < 1:
            raise ValueError(f"thresh k={k} must be >= 1")
        if k > n:
            r = self._scratch()
            self._emit(OP_ZERO, r, r, r)
            return r
        regs = []
        for _ in range(k):
            r = self._scratch()
            self._emit(OP_ZERO, r, r, r)
            regs.append(r)
        for x in ops:
            for j in range(k - 1, 0, -1):
                self._emit(OP_THRESH, regs[j], regs[j - 1], x)
            self._emit(OP_OR, regs[0], regs[0], x)
        return regs[k - 1]

    # ------------------------------------------------------ BSI expansion

    @staticmethod
    def _value(params: Sequence[int], j: int) -> int:
        """Reassemble the two u32 limbs executor params carry."""
        return int(params[j]) | (int(params[j + 1]) << 32)

    def _lower_bsi(self, kind: str, planes: List[Any], depth: int,
                   params: Sequence[int], j: int, k: int,
                   allow_eq: bool) -> Any:
        """Expand one comparison into the exact bit-plane scan
        executor/bsi.py traces, with the per-bit branch taken on the
        host value instead of a traced select — bit-identical because
        ``jnp.where(vb, x, y)`` with a concrete vb IS x or y."""
        nn = planes[depth]  # not-null plane
        if kind == "notnull":
            return nn
        if kind == "eq" or kind == "neq":
            value = self._value(params, j)
            m = self._scratch()
            self._emit(OP_COPY, m, nn, nn)
            for i in range(depth):
                op = OP_AND if (value >> i) & 1 else OP_ANDNOT
                self._emit(op, m, m, planes[i])
            if kind == "eq":
                return m
            r = self._scratch()
            self._emit(OP_ANDNOT, r, nn, m)
            return r
        if kind == "between":
            lo = self._lower_scan(planes, depth, self._value(params, j),
                                  "gt", True)
            hi = self._lower_scan(planes, depth, self._value(params, k),
                                  "lt", True)
            self._emit(OP_AND, lo, lo, hi)
            return lo
        return self._lower_scan(planes, depth, self._value(params, j),
                                kind, allow_eq)

    def _lower_scan(self, planes: List[Any], depth: int, value: int,
                    kind: str, allow_eq: bool) -> Any:
        """The MSB-first lt/gt scan (executor/bsi.py lt/gt): `matched`
        accumulates, `eq_prefix` narrows, strictly in source order."""
        matched = self._scratch()
        self._emit(OP_ZERO, matched, matched, matched)
        eqp = self._scratch()
        self._emit(OP_COPY, eqp, planes[depth], planes[depth])
        tmp = self._scratch()
        for i in reversed(range(depth)):
            vb = (value >> i) & 1
            grows = vb if kind == "lt" else (1 - vb)
            if grows:
                # lt: values with 0 under a predicate 1-bit are smaller;
                # gt: values with 1 under a predicate 0-bit are larger.
                op = OP_ANDNOT if kind == "lt" else OP_AND
                self._emit(op, tmp, eqp, planes[i])
                self._emit(OP_OR, matched, matched, tmp)
            self._emit(OP_AND if vb else OP_ANDNOT, eqp, eqp, planes[i])
        if allow_eq:
            self._emit(OP_OR, matched, matched, eqp)
        return matched

    # ------------------------------------------------------------ finish

    def finish(self) -> "Plan":
        """Resolve tokens to bank-grouped register numbers and pad every
        axis to its pow2 capacity bucket. Slab layout: dense slot
        registers, then expand registers (sparse operands), then
        scratch, then the pow2 pad with its spare register on top."""
        offsets: List[int] = []
        total = 0
        for slots in self.bank_slots:
            offsets.append(total)
            total += len(slots)
        n_slots = total
        xoffsets: List[int] = []
        xtotal = 0
        for slots in self.xbank_slots:
            xoffsets.append(xtotal)
            xtotal += len(slots)
        n_xslots = xtotal

        def reg(token: Any) -> int:
            if isinstance(token, tuple):
                kind, b, kth = token
                if kind == "x":
                    return n_slots + xoffsets[b] + kth
                return offsets[b] + kth
            return n_slots + n_xslots + int(token)

        n_regs = n_slots + n_xslots + self.n_scratch
        # +1 spare register: pad instructions and pad output lanes need
        # a dead destination that no real lane reads.
        t_pad = pow2_at_least(n_regs + 1)
        spare = t_pad - 1
        instrs = [(op, reg(d), reg(a), reg(b))
                  for op, d, a, b in self.instrs]
        p_pad = pow2_at_least(len(instrs))
        n_instrs = len(instrs)
        instrs += [(OP_ZERO, spare, spare, spare)] * (p_pad - n_instrs)
        widths = [w for ws in self.bank_widths for w in ws]
        widths += [w for ws in self.xbank_widths for w in ws]
        out_count = [reg(t) for t in self.out_count]
        out_row = [reg(t) for t in self.out_row]
        nc, nr = len(out_count), len(out_row)
        out_count += [spare] * (pow2_at_least(nc) - nc)
        out_row += [spare] * (pow2_at_least(nr) - nr)
        return Plan(
            banks=tuple(self.bank_order),
            slots=tuple(np.asarray(s, np.int32) for s in self.bank_slots),
            widths=np.asarray(
                widths + [0] * (t_pad - n_slots - n_xslots), np.int32),
            instrs=np.asarray(instrs, np.int32).reshape(p_pad, 4),
            out_count=np.asarray(out_count, np.int32),
            out_row=np.asarray(out_row, np.int32),
            n_slots=n_slots, n_regs=t_pad, n_instrs=n_instrs,
            lane_count_widths=tuple(self.out_count_widths),
            lane_row_widths=tuple(self.out_row_widths),
            xbanks=tuple(self.xbank_order),
            xslots=tuple(np.asarray(s, np.int32)
                         for s in self.xbank_slots),
            n_xslots=n_xslots)


class Plan:
    """One launch's finished plan buffers (host numpy; the executor
    uploads them and counts the bytes as plan-buffer H2D)."""

    __slots__ = ("banks", "slots", "widths", "instrs", "out_count",
                 "out_row", "n_slots", "n_regs", "n_instrs",
                 "lane_count_widths", "lane_row_widths",
                 "xbanks", "xslots", "n_xslots", "opt_stats")

    def __init__(self, banks: Tuple[Any, ...],
                 slots: Tuple[np.ndarray, ...], widths: np.ndarray,
                 instrs: np.ndarray, out_count: np.ndarray,
                 out_row: np.ndarray, n_slots: int, n_regs: int,
                 n_instrs: int,
                 lane_count_widths: Tuple[int, ...] = (),
                 lane_row_widths: Tuple[int, ...] = (),
                 xbanks: Tuple[Any, ...] = (),
                 xslots: Tuple[np.ndarray, ...] = (),
                 n_xslots: int = 0) -> None:
        self.banks = banks
        self.slots = slots
        self.widths = widths
        self.instrs = instrs
        self.out_count = out_count
        self.out_row = out_row
        self.n_slots = n_slots
        self.n_regs = n_regs
        self.n_instrs = n_instrs
        # Real (unpadded) output-lane plan widths, lane order — the
        # verifier's masking-invariant targets; their lengths are the
        # real lane counts (out_count/out_row are pow2-padded).
        self.lane_count_widths = lane_count_widths
        self.lane_row_widths = lane_row_widths
        # Sparse (hybrid-layout) operands: per sparse bank a
        # (pos, starts) device pair + its slot list; the expand
        # registers live at slab indices [n_slots, n_slots + n_xslots)
        # and are readable only through OP_EXPAND (verify_plan).
        self.xbanks = xbanks
        self.xslots = xslots
        self.n_xslots = n_xslots
        # Filled by ops/plan_opt.optimize_plan when the optimizer ran
        # over this plan (None otherwise): the before/after entry and
        # byte accounting the executor's opt telemetry reports.
        self.opt_stats = None

    @property
    def plan_nbytes(self) -> int:
        """Bytes of plan data uploaded per launch (the telemetry
        number: how much H2D one mixed batch costs instead of N
        launches)."""
        return int(self.instrs.nbytes + self.widths.nbytes
                   + self.out_count.nbytes + self.out_row.nbytes
                   + sum(int(s.nbytes) for s in self.slots)
                   + sum(int(s.nbytes) for s in self.xslots))

    def sig(self, n_shards: int, w_mega: int) -> str:
        """Compile-cache key: capacities + operand bank shapes + the
        per-bank slot-list lengths — every axis the traced program
        specializes on, nothing else (instruction CONTENT is data)."""
        bshapes = [(tuple(getattr(a, "shape", ())), len(s))
                   for a, s in zip(self.banks, self.slots)]
        xshapes = [(tuple(getattr(p, "shape", ()) for p in pair),
                    len(s))
                   for pair, s in zip(self.xbanks, self.xslots)]
        return (f"mega|S{n_shards}|W{w_mega}|T{self.n_regs}"
                f"|P{self.instrs.shape[0]}|C{len(self.out_count)}"
                f"|R{len(self.out_row)}|B{bshapes}|X{xshapes}")


def slab_nbytes(n_regs: int, n_shards: int, w_mega: int) -> int:
    """HBM footprint of the launch's register slab."""
    return int(n_regs) * int(n_shards) * int(w_mega) * 4


# ------------------------------------------------------- mesh epilogue
#
# A mesh launch runs the SAME [P, 4] plan buffer on every device slice
# of the shard axis (banks land sharded via MeshContext.put_bank, the
# plan buffers replicated), so the instruction loop needs no changes —
# registers are [S, W] slabs whose S axis is simply split across chips.
# What changes is the OUTPUT stage: the single-device program returns
# per-shard count vectors for the host to sum, which on a mesh would
# ship S partials per lane over PCIe. The epilogue finishes the
# reduction in-kernel instead: count lanes collapse the shard axis on
# device (under GSPMD the sum over the mesh-sharded axis lowers to an
# XLA all-reduce — a psum over the shard axis), and row lanes are
# all-gathered to every device by the launch's replicated out_shardings
# so the coordinator reads whole rows, not per-device slices. Like the
# instruction stream, the epilogue is typed DATA: one collective opcode
# per real output lane, verified pre-launch (verify_plan's mesh rules)
# so a mis-built mesh plan fails loudly instead of double-counting.

EPI_NONE = 0
# Count lane: collapse the shard axis in-kernel. Over mesh-sharded
# banks this is the cross-chip all-reduce; uint32 is safe because one
# reduced lane covers at most the full shard stack's set bits
# (popcount's 2^30 < 2^32 bound, ops/bitset.py).
EPI_PSUM = 1
# Row lane: replicate the [S, W] result words to every device (the
# launch's replicated out_shardings inserts the all-gather); device
# top-k over row lanes reads the gathered words without a host hop.
EPI_ALL_GATHER = 2

EPI_NAMES = ("none", "psum", "all_gather")


class Epilogue:
    """Typed collective plan for one mesh launch: which named mesh axes
    the epilogue reduces over, and one collective opcode per REAL
    output lane (count lanes and row lanes separately — pad lanes never
    reach a collective, the masking invariant keeps them zero). Pure
    host data, same contract as the instruction buffer: verified before
    launch, hashed into the jit-cache key."""

    __slots__ = ("axes", "count_ops", "row_ops")

    def __init__(self, axes: Sequence[str], count_ops: Sequence[int],
                 row_ops: Sequence[int]):
        self.axes = tuple(str(a) for a in axes)
        self.count_ops = np.asarray(list(count_ops), dtype=np.int32)
        self.row_ops = np.asarray(list(row_ops), dtype=np.int32)


class MeshSpec:
    """Host-side description of the device mesh a plan is verified
    against — axis names, device counts and the collective epilogue.
    Deliberately NOT parallel.mesh.MeshContext: verify_plan/plan_cost
    stay pure host numpy (no jax import, no device handles), so the
    planverify/plan_fuzz sweeps can type-check mesh plans on a machine
    with zero accelerators."""

    __slots__ = ("shard_axis", "replica_axis", "n_devices", "replicas",
                 "epilogue")

    def __init__(self, shard_axis: str, replica_axis: str,
                 n_devices: int, replicas: int = 1,
                 epilogue: Optional[Epilogue] = None):
        self.shard_axis = str(shard_axis)
        self.replica_axis = str(replica_axis)
        self.n_devices = int(n_devices)
        self.replicas = int(replicas)
        self.epilogue = epilogue


def mesh_epilogue(plan: Plan, shard_axis: str = "shards") -> Epilogue:
    """The canonical epilogue for a finished plan: every real count
    lane reduces with a shard-axis psum, every real row lane
    all-gathers. Built from the plan's REAL lane counts (pad lanes are
    excluded by construction — exactly the lanes the masking invariant
    proves are result-invisible)."""
    nc = len(plan.lane_count_widths)
    nr = len(plan.lane_row_widths)
    return Epilogue((shard_axis,), [EPI_PSUM] * nc,
                    [EPI_ALL_GATHER] * nr)


# --------------------------------------------------------- verification
#
# The plan buffer is DATA handed to one compiled interpreter, so a
# lowering bug produces wrong bits silently: the fori/switch machine
# happily reads a register nothing wrote (zeros), clobbers a shared
# operand row another entry still needs, or popcounts words past an
# entry's plan width. verify_plan() is the pre-launch type checker for
# that machine — every invariant below is one the shipped lowering
# maintains by construction and a mutated or mis-lowered plan breaks.
# It is pure host numpy (no jax import, no device touch) so the
# planverify/plan_fuzz tools can sweep thousands of plans cheaply and
# the production gate costs microseconds per launch.


class PlanVerifyError(ValueError):
    """A plan buffer failed pre-launch verification. Raised BEFORE the
    interpreter dispatches; the message names the instruction/lane and
    the invariant it broke."""


_READS_A = (OP_AND, OP_OR, OP_XOR, OP_ANDNOT, OP_COPY, OP_THRESH)
_READS_B = (OP_AND, OP_OR, OP_XOR, OP_ANDNOT, OP_THRESH)
# THRESH is the accumulate opcode: dst = dst | (a & b), so dst is a
# READ operand too and must be defined before the instruction runs.
_READS_DST = (OP_THRESH,)


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def verify_plan(plan: Plan, n_shards: int, w_mega: int,
                mesh: Optional[MeshSpec] = None) -> None:
    """Validate one launch's plan buffers against the interpreter's
    execution model; raise :class:`PlanVerifyError` on the first
    violation, return ``None`` when every invariant holds.

    Checked invariants (the megakernel IR type system):

    * **Structural** — ``instrs`` is int32 ``[P, 4]`` with ``P`` a pow2
      capacity >= ``n_instrs``; ``n_regs`` pow2 with room for the slab
      spare register above ``n_slots``; output-lane arrays pow2-padded;
      per-bank slot lists consistent with ``n_slots``.
    * **Gather bounds** — every slot index addresses a real row of its
      bank ([0, rows)), and 3-d banks carry exactly ``n_shards``
      shards, so ``bank[slots]`` can never gather out of bounds.
    * **Width masks** — slot registers carry a plan width in
      ``[1, w_mega]``; pad registers carry width 0 (their mask rows are
      never read).
    * **Opcodes** — every executed instruction's opcode is in the
      table; a byte flip into lax.switch's clamp region would silently
      execute the wrong branch.
    * **Register bounds + slot protection** — dst/a/b address real
      registers, and no instruction writes a slot OR expand register:
      gathered operand rows are SHARED across entries (the Tanimoto
      query row), so they are read-only by contract.
    * **Expand typing (hybrid layout)** — expand registers (slab
      indices ``[n_slots, n_slots + n_xslots)``) hold scatter-expanded
      sparse-bank rows. ONLY ``OP_EXPAND`` may read one (a bitwise
      opcode addressing one directly would bypass the expansion
      contract), ``OP_EXPAND``'s ``a`` operand must BE one (expanding
      a dense slot or scratch register is meaningless), its ``dst``
      must be scratch, and the result's abstract span is the expand
      register's declared width — sparse expansion enters the masking
      lattice exactly where the dense row it replaces would. Sparse
      slot indices must address real rows of their (pos, starts) pair
      (``starts`` has rows + 1 entries).
    * **Def-before-use** — an operand a real instruction actually
      reads (per-opcode: ZERO reads nothing, COPY reads ``a``) is
      either a gathered slot or a scratch register some earlier
      instruction wrote. The interpreter zero-fills scratch, so a RAW
      violation doesn't crash — it silently computes on zeros, the
      exact hazard class that sank the grid-per-entry Pallas
      formulation.
    * **Pad-tail no-ops** — instructions past ``n_instrs`` must be
      ``ZERO`` into a non-slot register that no real output lane
      reads: provably invisible to every result.
    * **Masking invariant (abstract interpretation)** — each register
      is abstracted to the least upper bound on its nonzero word span
      (words at index >= z are provably zero). Slot registers enter at
      their masked plan width; AND takes ``min``, OR/XOR ``max``,
      ANDNOT keeps the left span, COPY propagates, ZERO resets — i.e.
      zero-extension commutes with every opcode. Each real output
      lane's register must prove ``z <= lane plan width``, which is
      exactly what makes per-entry slices (and full-width popcounts)
      bit-identical to the unfused per-plan programs. ``THRESH``
      (``dst = dst | (a & b)``) additionally READS its dst: the
      accumulator must be defined (a missed thermometer init would
      silently under-count) and its span joins ``min(za, zb)``.
    * **Mesh collectives (``mesh`` is not None)** — the launch's shard
      axis must split evenly across the mesh's shard devices
      (shard-axis agreement: a ragged split would give devices
      different local S and the shared plan buffer different register
      shapes per chip); the epilogue must reduce over EXACTLY the
      shard axis — never the replica axis (a psum over a replicated
      axis multiplies every count by R: the replica-axis no-op proof);
      and every REAL output lane carries a typed collective — count
      lanes ``psum``, row lanes ``all_gather`` — so no lane can leak
      per-device partials to the host merge path.
    """
    instrs = plan.instrs
    if instrs.ndim != 2 or instrs.shape[1] != 4:
        raise PlanVerifyError(
            f"instrs must be [P, 4], got shape {instrs.shape}")
    if instrs.dtype != np.int32:
        raise PlanVerifyError(
            f"instrs must be int32, got {instrs.dtype}")
    T = int(plan.n_regs)
    P = int(instrs.shape[0])
    n_slots = int(plan.n_slots)
    n_xslots = int(getattr(plan, "n_xslots", 0))
    n_gathered = n_slots + n_xslots
    n_instrs = int(plan.n_instrs)
    if not _is_pow2(T) or T <= n_gathered:
        raise PlanVerifyError(
            f"n_regs={T} must be a pow2 capacity > n_slots={n_slots} "
            f"+ n_xslots={n_xslots} (the pad/spare register lives "
            f"above the gathered/expanded operands)")
    if not _is_pow2(P) or not 0 <= n_instrs <= P:
        raise PlanVerifyError(
            f"instr capacity P={P} must be pow2 >= n_instrs={n_instrs}")
    if len(plan.banks) != len(plan.slots):
        raise PlanVerifyError(
            f"{len(plan.banks)} banks but {len(plan.slots)} slot lists")
    if sum(len(s) for s in plan.slots) != n_slots:
        raise PlanVerifyError(
            f"per-bank slot lists sum to "
            f"{sum(len(s) for s in plan.slots)} != n_slots={n_slots}")
    if len(plan.xbanks) != len(plan.xslots):
        raise PlanVerifyError(
            f"{len(plan.xbanks)} sparse banks but {len(plan.xslots)} "
            f"sparse slot lists")
    if sum(len(s) for s in plan.xslots) != n_xslots:
        raise PlanVerifyError(
            f"per-sparse-bank slot lists sum to "
            f"{sum(len(s) for s in plan.xslots)} != "
            f"n_xslots={n_xslots}")
    if plan.widths.shape != (T,):
        raise PlanVerifyError(
            f"widths must be [n_regs]={T}, got {plan.widths.shape}")
    nc = len(plan.lane_count_widths)
    nr = len(plan.lane_row_widths)
    if len(plan.out_count) != pow2_at_least(nc) or nc > len(plan.out_count):
        raise PlanVerifyError(
            f"out_count holds {len(plan.out_count)} lanes for {nc} "
            f"real count entries (pow2 pad expected)")
    if len(plan.out_row) != pow2_at_least(nr) or nr > len(plan.out_row):
        raise PlanVerifyError(
            f"out_row holds {len(plan.out_row)} lanes for {nr} "
            f"real row entries (pow2 pad expected)")

    # Gather bounds: slot indices inside each bank, shard axis aligned.
    for b, (bank, slots) in enumerate(zip(plan.banks, plan.slots)):
        shape = getattr(bank, "shape", None)
        if not isinstance(shape, tuple) or not shape:
            continue  # opaque bank (tests stub them); widths still check
        rows = int(shape[0])
        for j, s in enumerate(np.asarray(slots).tolist()):
            if not 0 <= int(s) < rows:
                raise PlanVerifyError(
                    f"bank {b} slot[{j}]={int(s)} outside its "
                    f"{rows}-row bank")
        if len(shape) == 3 and int(shape[1]) != int(n_shards):
            raise PlanVerifyError(
                f"bank {b} carries {int(shape[1])} shards, launch "
                f"expects {int(n_shards)}")

    # Sparse gather bounds: each sparse slot addresses a real row of
    # its (pos, starts) pair (starts carries rows + 1 offsets).
    for b, (pair, slots) in enumerate(zip(plan.xbanks, plan.xslots)):
        starts = pair[1] if isinstance(pair, (tuple, list)) \
            and len(pair) == 2 else None
        sshape = getattr(starts, "shape", None)
        if not isinstance(sshape, tuple) or not sshape:
            continue  # opaque pair (tests stub them)
        rows = int(sshape[0]) - 1
        for j, s in enumerate(np.asarray(slots).tolist()):
            if not 0 <= int(s) < rows:
                raise PlanVerifyError(
                    f"sparse bank {b} slot[{j}]={int(s)} outside its "
                    f"{rows}-row starts table")

    # Width masks: slot AND expand registers in [1, w_mega], pad
    # registers 0.
    # graftlint: disable=GL003 — plan buffers are HOST numpy (built by
    # Lowering.finish, uploaded later); no device sync happens here.
    widths = plan.widths.tolist()
    for k in range(n_gathered):
        if not 1 <= int(widths[k]) <= int(w_mega):
            kind = "slot" if k < n_slots else "expand"
            raise PlanVerifyError(
                f"{kind} register {k} width {int(widths[k])} outside "
                f"[1, w_mega={int(w_mega)}]")
    for k in range(n_gathered, T):
        if int(widths[k]) != 0:
            raise PlanVerifyError(
                f"pad register {k} carries width {int(widths[k])} "
                f"(must be 0: its mask row is never gathered)")

    # Real instructions: opcode table, register bounds, slot
    # protection, def-before-use, and the abstract width lattice.
    # span[r] = least upper bound on r's nonzero word span; None =
    # never written (reads of it are RAW violations even though the
    # machine would silently read zeros).
    span: List[Optional[int]] = [int(widths[k])
                                 for k in range(n_gathered)]
    span += [None] * (T - n_gathered)
    # graftlint: disable=GL003 — host numpy plan buffer, as above.
    rows_list = instrs.tolist()
    for i in range(n_instrs):
        op, dst, a, b = (int(x) for x in rows_list[i])
        if not 0 <= op < len(OP_NAMES):
            raise PlanVerifyError(
                f"instr {i}: opcode {op} not in the table "
                f"(0..{len(OP_NAMES) - 1})")
        for nm, r in (("dst", dst), ("a", a), ("b", b)):
            if not 0 <= r < T:
                raise PlanVerifyError(
                    f"instr {i} ({OP_NAMES[op]}): {nm}={r} outside "
                    f"the {T}-register slab")
        if dst < n_gathered:
            kind = ("slot" if dst < n_slots else "expand")
            raise PlanVerifyError(
                f"instr {i} ({OP_NAMES[op]}): writes {kind} register "
                f"{dst} — gathered/expanded operand rows are shared "
                f"across entries and read-only")
        if op == OP_EXPAND:
            # Expand typing: `a` must BE an expand register; the
            # result enters the width lattice at that register's
            # declared (masked) width.
            if not n_slots <= a < n_gathered:
                raise PlanVerifyError(
                    f"instr {i} (expand): a={a} is not an expand "
                    f"register (expected [{n_slots}, {n_gathered}))")
            span[dst] = int(widths[a])
            continue
        reads = []
        if op in _READS_A:
            reads.append(("a", a))
        if op in _READS_B:
            reads.append(("b", b))
        if op in _READS_DST:
            # THRESH accumulates (dst = dst | (a & b)): an undefined
            # accumulator means a missed thermometer init — the
            # machine would OR into zeros and silently under-count.
            reads.append(("dst", dst))
        for nm, r in reads:
            if n_slots <= r < n_gathered:
                raise PlanVerifyError(
                    f"instr {i} ({OP_NAMES[op]}): reads expand "
                    f"register {r} ({nm}) directly — sparse operands "
                    f"are readable only through OP_EXPAND")
            if r >= n_gathered and span[r] is None:
                raise PlanVerifyError(
                    f"instr {i} ({OP_NAMES[op]}): reads scratch "
                    f"register {r} ({nm}) before any instruction "
                    f"defines it (RAW chain broken — the machine "
                    f"would silently read zeros)")
        # Zero-extension transfer function per opcode. Read operands
        # were just proven defined, so their spans are concrete ints.
        za = span[a] if op in _READS_A else 0
        zb = span[b] if op in _READS_B else 0
        za = 0 if za is None else int(za)
        zb = 0 if zb is None else int(zb)
        if op == OP_ZERO:
            span[dst] = 0
        elif op in (OP_COPY, OP_ANDNOT):
            span[dst] = za
        elif op == OP_AND:
            span[dst] = min(za, zb)
        elif op == OP_THRESH:
            # dst | (a & b): the old accumulator span joins the AND of
            # the operand spans — dst was just proven defined above.
            zd = span[dst]
            zd = 0 if zd is None else int(zd)
            span[dst] = max(zd, min(za, zb))
        else:  # OR / XOR
            span[dst] = max(za, zb)

    # Real output lanes: in-bounds, defined, and width-masked.
    # graftlint: disable=GL003 — host numpy plan buffer, as above.
    out_count = plan.out_count.tolist()
    # graftlint: disable=GL003 — host numpy plan buffer, as above.
    out_row = plan.out_row.tolist()
    for mode, lanes, lane_widths in (
            ("count", out_count, plan.lane_count_widths),
            ("row", out_row, plan.lane_row_widths)):
        for j, r in enumerate(lanes):
            if not 0 <= int(r) < T:
                raise PlanVerifyError(
                    f"{mode} lane {j}: register {int(r)} outside the "
                    f"{T}-register slab")
        for j, w in enumerate(lane_widths):
            r = int(lanes[j])
            if n_slots <= r < n_gathered:
                raise PlanVerifyError(
                    f"{mode} lane {j}: reads expand register {r} "
                    f"directly — sparse operands are readable only "
                    f"through OP_EXPAND")
            sv = span[r]
            if sv is None:
                raise PlanVerifyError(
                    f"{mode} lane {j}: reads register {r} that no "
                    f"instruction defines")
            z = int(sv)
            if not 1 <= int(w) <= int(w_mega):
                raise PlanVerifyError(
                    f"{mode} lane {j}: plan width {int(w)} outside "
                    f"[1, w_mega={int(w_mega)}]")
            if z > int(w):
                raise PlanVerifyError(
                    f"{mode} lane {j}: register {r} may carry "
                    f"nonzero words up to {z}, past the entry's plan "
                    f"width {int(w)} — the masking invariant "
                    f"(zero-extension commutes with every opcode) "
                    f"does not hold")

    # Pad tail: provably no-ops. Writes happen after every real read,
    # so a pad instruction is invisible exactly when it is a ZERO into
    # a non-slot register no real output lane references.
    real_out = {int(out_count[j]) for j in range(nc)}
    real_out |= {int(out_row[j]) for j in range(nr)}
    for i in range(n_instrs, P):
        op, dst, a, b = (int(x) for x in rows_list[i])
        if op != OP_ZERO:
            name = OP_NAMES[op] if 0 <= op < len(OP_NAMES) else op
            raise PlanVerifyError(
                f"pad instr {i}: opcode {name} — pad-tail "
                f"instructions must be ZERO")
        for nm, r in (("dst", dst), ("a", a), ("b", b)):
            if not 0 <= r < T:
                raise PlanVerifyError(
                    f"pad instr {i}: {nm}={r} outside the "
                    f"{T}-register slab")
        if dst < n_gathered:
            raise PlanVerifyError(
                f"pad instr {i}: zeroes slot/expand register {dst} — "
                f"pads must write a dead register")
        if dst in real_out:
            raise PlanVerifyError(
                f"pad instr {i}: zeroes register {dst} that a real "
                f"output lane reads — the pad tail would corrupt a "
                f"result")

    if mesh is not None:
        _verify_mesh(mesh, n_shards, nc, nr)


def _verify_mesh(mesh: MeshSpec, n_shards: int, nc: int,
                 nr: int) -> None:
    """The mesh rules of verify_plan: shard-axis agreement, the
    replica-axis no-op proof, and per-lane collective typing."""
    D = int(mesh.n_devices)
    if D < 1:
        raise PlanVerifyError(f"mesh: n_devices={D} must be >= 1")
    if int(n_shards) % D != 0:
        raise PlanVerifyError(
            f"mesh: n_shards={int(n_shards)} does not split evenly "
            f"over {D} shard devices — shard-axis agreement requires "
            f"identical local register shapes on every chip")
    if not mesh.shard_axis or mesh.shard_axis == mesh.replica_axis:
        raise PlanVerifyError(
            f"mesh: shard axis {mesh.shard_axis!r} must be a named "
            f"axis distinct from replica axis {mesh.replica_axis!r}")
    epi = mesh.epilogue
    if epi is None:
        raise PlanVerifyError(
            "mesh: launch has no collective epilogue — a mesh plan "
            "without typed collectives would return per-device "
            "partials")
    if epi.axes != (mesh.shard_axis,):
        raise PlanVerifyError(
            f"mesh: epilogue reduces over axes {epi.axes}, expected "
            f"exactly ({mesh.shard_axis!r},)")
    if mesh.replica_axis in epi.axes:
        raise PlanVerifyError(
            f"mesh: epilogue reduces over the replica axis "
            f"{mesh.replica_axis!r} — replicated operands would be "
            f"counted {int(mesh.replicas)}x (the replica-axis no-op "
            f"proof fails)")
    if len(epi.count_ops) != nc or len(epi.row_ops) != nr:
        raise PlanVerifyError(
            f"mesh: epilogue types {len(epi.count_ops)} count / "
            f"{len(epi.row_ops)} row lanes, plan has {nc} / {nr} real "
            f"lanes")
    # graftlint: disable=GL003 — epilogue ops are host numpy by
    # construction (Epilogue.__init__), never device buffers.
    for j, op in enumerate(epi.count_ops.tolist()):
        if op != EPI_PSUM:
            name = EPI_NAMES[op] if 0 <= op < len(EPI_NAMES) else op
            raise PlanVerifyError(
                f"mesh: count lane {j} typed {name!r}, must be "
                f"'psum' — anything else ships per-shard partials "
                f"to the host")
    # graftlint: disable=GL003 — host-numpy epilogue ops, as above.
    for j, op in enumerate(epi.row_ops.tolist()):
        if op != EPI_ALL_GATHER:
            name = EPI_NAMES[op] if 0 <= op < len(EPI_NAMES) else op
            raise PlanVerifyError(
                f"mesh: row lane {j} typed {name!r}, must be "
                f"'all_gather' — the coordinator reads whole rows, "
                f"not per-device slices")


# ------------------------------------------------------ cost attribution
#
# plan_cost() is the measured half of the calibration loop: the same
# [P, 4] IR the verifier types is also a complete statement of the
# launch's HBM traffic, so the executor can attribute bytes to every
# launch for free (host numpy, microseconds) and the profiler's sampled
# device fences turn them into achieved GB/s. Like verify_plan it is
# pure host code — no jax import, no fences, GL003 clean by
# construction.


def _buf_nbytes(a: Any) -> int:
    """Byte size of a (possibly device-resident) buffer WITHOUT
    materializing it: `.nbytes`/`.shape` are host metadata on both
    numpy and jax arrays; opaque stubs fall back to 0."""
    n = getattr(a, "nbytes", None)
    if n is not None:
        return int(n)
    shape = getattr(a, "shape", None)
    if isinstance(shape, tuple) and shape:
        item = getattr(getattr(a, "dtype", None), "itemsize", 4) or 4
        return int(np.prod(shape)) * int(item)
    return 0


def plan_cost(plan: Plan, n_shards: int, w_mega: int,
              mesh: Optional[MeshSpec] = None) -> Dict[str, Any]:
    """Per-launch HBM traffic model over one finished plan, split by
    kind, plus the per-opcode instruction histogram.

    The model (``row`` = one padded ``[S, W]`` register row =
    ``n_shards * w_mega * 4`` bytes; ``live(r)`` = the masked words =
    ``n_shards * widths[r] * 4``):

    * ``gatherBytes`` — per dense slot: ``live(r)`` read from the bank
      plus one ``row`` written into the slab.
    * ``expandBytes`` — per expand register: its sparse bank's full
      ``(pos, starts)`` buffers read (the interpreter's pre-loop
      scatter sweeps the whole pos table per slot) plus one ``row``
      scatter-written; per ``OP_EXPAND`` instruction: one ``row`` read
      + one ``row`` written.
    * ``computeBytes`` — per real non-EXPAND instruction: one ``row``
      per register read (exactly the verifier's read sets — _READS_A /
      _READS_B, THRESH's dst read via _READS_DST; ZERO reads nothing)
      plus one ``row`` written; plus the output stage: each real count
      lane popcount-reads one ``row`` and writes ``S * 4`` bytes, each
      real row lane moves ``2 * row``.
    * ``padBytes`` — the pow2 capacity waste as a first-class split,
      mirroring the memledger live-vs-padded convention: unreferenced
      slab registers above the high-water mark (incl. the spare), pad
      OP_ZERO instruction writes, and pad output lanes.

    ``totalBytes`` is the sum of the four splits. ``slabBytes`` /
    ``liveSlabBytes`` / ``planBytes`` restate the ledger's numbers so
    a reader can assert ``padded_bytes == (slabBytes - liveSlabBytes)
    + planBytes`` against the ``fusion_pad`` entry of the same launch.
    ``opcodeHist`` counts REAL instructions only, keyed by OP_NAMES,
    zero-count opcodes omitted.

    With ``mesh`` set, three more keys attribute the multi-chip
    launch: ``meshDevices``, ``deviceBytes`` (every split above scales
    with the shard axis, so one chip's HBM share is the ceiling of
    ``totalBytes / D``), and ``collectiveBytes`` = ``psumBytes`` (ring
    all-reduce of the real count lanes' uint32 partial vector:
    ``2 * (D-1) * nc * 4``) + ``allGatherBytes`` (each real row lane's
    ``[S, W]`` words replicated to the other ``D-1`` devices:
    ``(D-1) * nr * row``) — ICI wire bytes, disjoint from the HBM
    splits.
    """
    S, W = int(n_shards), int(w_mega)
    row = S * W * 4
    n_slots = int(plan.n_slots)
    n_xslots = int(getattr(plan, "n_xslots", 0))
    n_gathered = n_slots + n_xslots
    n_instrs = int(plan.n_instrs)
    P = int(plan.instrs.shape[0])
    # Plan buffers are host numpy by construction (Lowering.finish);
    # .tolist() is a host copy, never a device sync.
    # graftlint: disable=GL003 — host-numpy plan buffer read.
    widths = [int(w) for w in plan.widths.tolist()]

    gather = sum(S * widths[r] * 4 + row for r in range(n_slots))

    hist: Dict[str, int] = {}
    compute = 0
    n_expand_instrs = 0
    used_high = n_gathered  # slab high-water mark (exclusive)
    rows_list = plan.instrs[:n_instrs].tolist()
    for op, dst, a, b in rows_list:
        op, dst, a, b = int(op), int(dst), int(a), int(b)
        name = OP_NAMES[op] if 0 <= op < len(OP_NAMES) else str(op)
        hist[name] = hist.get(name, 0) + 1
        used_high = max(used_high, dst + 1)
        if op == OP_EXPAND:
            n_expand_instrs += 1
            used_high = max(used_high, a + 1)
            continue
        reads = 0
        if op in _READS_A:
            reads += 1
            used_high = max(used_high, a + 1)
        if op in _READS_B:
            reads += 1
            used_high = max(used_high, b + 1)
        if op in _READS_DST:
            reads += 1
        compute += (reads + 1) * row

    expand = n_expand_instrs * 2 * row
    for pair, slots in zip(plan.xbanks, plan.xslots):
        pair_bytes = 0
        if isinstance(pair, (tuple, list)) and len(pair) == 2:
            pair_bytes = _buf_nbytes(pair[0]) + _buf_nbytes(pair[1])
        expand += len(slots) * (pair_bytes + row)

    nc = len(plan.lane_count_widths)
    nr = len(plan.lane_row_widths)
    for j in range(nc):
        used_high = max(used_high, int(plan.out_count[j]) + 1)
    for j in range(nr):
        used_high = max(used_high, int(plan.out_row[j]) + 1)
    compute += nc * (row + S * 4) + nr * 2 * row

    n_regs = int(plan.n_regs)
    pad = ((n_regs - used_high) * row
           + (P - n_instrs) * row
           + (len(plan.out_count) - nc) * (row + S * 4)
           + (len(plan.out_row) - nr) * 2 * row)

    total = gather + compute + expand + pad
    out = {
        "gatherBytes": int(gather),
        "computeBytes": int(compute),
        "expandBytes": int(expand),
        "padBytes": int(pad),
        "totalBytes": int(total),
        "slabBytes": slab_nbytes(n_regs, S, W),
        "liveSlabBytes": slab_nbytes(n_gathered, S, W),
        "planBytes": int(plan.plan_nbytes),
        "opcodeHist": hist,
        "nInstrs": n_instrs,
    }
    if mesh is not None:
        D = max(1, int(mesh.n_devices))
        psum = 2 * (D - 1) * nc * 4
        ag = (D - 1) * nr * row
        out["meshDevices"] = D
        out["deviceBytes"] = int(-(-total // D))
        out["psumBytes"] = int(psum)
        out["allGatherBytes"] = int(ag)
        out["collectiveBytes"] = int(psum + ag)
    return out


def build_program(n_shards: int, w_mega: int, t_pad: int,
                  use_pallas: bool = False,
                  epilogue: Optional[Epilogue] = None
                  ) -> Callable[..., Any]:
    """The traceable interpreter body for one capacity bucket. The
    caller jits it (through the executor's LRU compile cache, so the
    retrace counter sees every real signature miss).

    With ``epilogue`` set (mesh launch) the count output stage
    collapses the shard axis in-kernel: under GSPMD the sum over the
    mesh-sharded axis lowers to an XLA all-reduce (the psum the
    epilogue's count lanes are typed with), so the launch returns
    final ``[Nc]`` answers instead of ``[Nc, S]`` partials. Row lanes
    keep their ``[Nr, S, W]`` shape — the caller's replicated
    out_shardings inserts the all_gather the row lanes are typed
    with. uint32 stays safe: one reduced lane covers at most the full
    shard stack (popcount's 2^30 < 2^32 bound)."""
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.ops.bitset import popcount

    def _fit(rows: Any) -> Any:
        """Slice or zero-pad the word axis to the launch width — the
        launch-level _align_words."""
        w = rows.shape[-1]
        if w > w_mega:
            return rows[..., :w_mega]
        if w < w_mega:
            return jnp.pad(rows, [(0, 0)] * (rows.ndim - 1)
                           + [(0, w_mega - w)])
        return rows

    def run(banks: Tuple[Any, ...], slots: Tuple[Any, ...], widths: Any,
            instrs: Any, out_count: Any, out_row: Any,
            xbanks: Tuple[Any, ...] = (),
            xslots: Tuple[Any, ...] = ()) -> Tuple[Any, Any]:
        parts = [_fit(bank[sl]) for bank, sl in zip(banks, slots)]
        # Expand registers: each sparse bank's referenced rows
        # scatter-expand to dense [S, w_mega] rows (one vmapped
        # expansion per bank), stacked into the slab right after the
        # dense slots — OP_EXPAND instructions then import them into
        # the dataflow at their masked widths.
        for pair, sl in zip(xbanks, xslots):
            pos, starts = pair
            parts.append(jax.vmap(
                lambda r, _p=pos, _s=starts: expand_positions(
                    _p, _s, r, n_shards, w_mega))(sl))
        if parts:
            slab = jnp.concatenate(parts, axis=0)
        else:
            slab = jnp.zeros((0, n_shards, w_mega), jnp.uint32)
        n_gathered = slab.shape[0]
        # Mask every gathered/expanded row down to its entry's plan
        # width: ops below keep zero-extended words zero, so per-entry
        # outputs sliced back to plan width are bit-identical to the
        # unfused per-plan programs.
        wmask = (jnp.arange(w_mega, dtype=jnp.int32)[None, :]
                 < widths[:n_gathered, None])
        slab = jnp.where(wmask[:, None, :], slab, jnp.uint32(0))
        slab = jnp.concatenate(
            [slab, jnp.zeros((t_pad - n_gathered, n_shards, w_mega),
                             jnp.uint32)], axis=0)

        if use_pallas:
            from pilosa_tpu.ops import pallas_kernels
            slab = pallas_kernels.mega_interpret(slab, instrs)
        else:
            # Branches take (d, a, b): d is the CURRENT dst value, read
            # for the THRESH accumulate and ignored by every other
            # opcode (XLA drops the dead gather per branch).
            branches = (
                lambda d, a, b: jnp.bitwise_and(a, b),
                lambda d, a, b: jnp.bitwise_or(a, b),
                lambda d, a, b: jnp.bitwise_xor(a, b),
                lambda d, a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
                lambda d, a, b: jnp.zeros_like(a),
                lambda d, a, b: a,
                # OP_EXPAND: the expand register was materialized (and
                # width-masked) above, so importing it is the identity
                # on its value — the opcode's job is the TYPED
                # boundary, enforced pre-launch by verify_plan.
                lambda d, a, b: a,
                # OP_THRESH: thermometer accumulate (N-of-M counting).
                lambda d, a, b: jnp.bitwise_or(
                    d, jnp.bitwise_and(a, b)),
            )

            def body(i: Any, sl: Any) -> Any:
                op = instrs[i, 0]
                vd = sl[instrs[i, 1]]
                va = sl[instrs[i, 2]]
                vb = sl[instrs[i, 3]]
                res = jax.lax.switch(op, branches, vd, va, vb)
                return sl.at[instrs[i, 1]].set(res)

            slab = jax.lax.fori_loop(0, instrs.shape[0], body, slab)
        counts = popcount(slab[out_count], axis=-1)   # [Nc, S] uint32
        rows = slab[out_row]                          # [Nr, S, W]
        if epilogue is not None:
            # EPI_PSUM over every count lane: the shard axis is the
            # mesh-sharded one, so this sum IS the cross-chip
            # all-reduce — [Nc] final answers, zero host partials.
            counts = jnp.sum(counts, axis=-1, dtype=jnp.uint32)
        return counts, rows

    return run
