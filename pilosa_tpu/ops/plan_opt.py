"""Cost-based optimizer for finished megakernel plans.

PR 11 made query plans *data* (ops/megakernel.py) and PR 12 made them
*checkable* (verify_plan + the planverify/plan_fuzz tooling); this
module makes them *cheaper* before they launch. The passes are the
classic bitmap-index playbook (the Roaring cost model, arXiv
1709.07821; threshold algebra, arXiv 1402.4466) applied at the IR
level, where every rewrite is provably safe because the optimized
plan still has to pass the same pre-launch verifier and stay
bit-exact under the three-way differential fuzzer:

1. **Density-ordered fold reordering** — commutative AND/OR/XOR
   operand chains sort cheapest-first using the per-bank
   ``liveDensity`` the HBM ledger samples at bank build (core/view
   ``_ledger_bank``), so intersections shrink the working register
   early; ANDNOT tails subtract densest-first. Order only ever
   affects *cost*: every reordered chain computes the identical
   value, and the canonical order is what lets the CSE pass match
   structurally equal subtrees that merely arrived in different
   operand order.
2. **Cross-request common-subexpression elimination** — value
   numbering over the whole mixed batch: subtrees canonicalize by
   (opcode, sorted-commutative-operands) fingerprint, COPYs
   propagate, and algebraic identities fold (``x AND 0 = 0``,
   ``x OR 0 = x``, ``x ANDNOT x = 0``, a THRESH step over a
   still-zero accumulator is the plain AND...). This generalizes the
   Lowering's shared-slot dedup (one gather per distinct operand
   row) from single rows to whole subtrees across *different*
   requests — 64 concurrent ``Intersect(hot_row, X_i)`` gather AND
   compute ``hot_row``'s sub-expressions once.
3. **Dead-register elimination + linear-scan re-allocation** — only
   value numbers a real output lane transitively reads are
   re-emitted, scratch registers are re-assigned lowest-free-first
   and freed at their last read, so the rebuilt slab drops whole
   pow2 capacity buckets (slab bytes are the HBM number the
   megakernel budget gate charges).
4. **Width narrowing** — per-output-lane plan widths tighten to the
   abstract interpreter's proven nonzero spans (the PR 12
   zero-extension lattice), hardening the verifier's masking
   contract. Gathered slot/expand width *masks* are never touched:
   they define the data, lane widths only bound it.

Everything here is host numpy/python on the already-finished Plan —
no jax import, no device touch — and the executor wiring
(executor/megakernel._build, PILOSA_TPU_PLAN_OPT) treats the whole
pipeline as best-effort: any surprise falls back to the unoptimized
plan, never to a wrong answer.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pilosa_tpu.ops import megakernel as mk
from pilosa_tpu.utils.locks import make_lock

# --------------------------------------------------------- density feed
#
# core/view.py reports each device bank's popcount-sampled live-bit
# density when it registers the bank with the HBM ledger; the optimizer
# only needs a *relative* ordering, so a bounded id()->density map is
# enough. Staleness (or an id() reused after GC) can only produce a
# suboptimal ORDER, never wrong bits — reordering is value-preserving
# by construction.

_DENSITY_CAP = 4096
_density_lock = make_lock("plan_opt.density")
_density: "OrderedDict[int, float]" = OrderedDict()

# Sparse (hybrid-layout) banks only exist for rows far below the dense
# break-even, so their expanded operands sort as very cheap.
SPARSE_DENSITY = 0.02
# Unknown dense operands sort between sparse rows and computed
# intermediates (scratch), which are assumed dense.
DEFAULT_DENSITY = 0.5
SCRATCH_DENSITY = 1.0


def note_bank_density(array: Any, density: Optional[float]) -> None:
    """Record a device bank's sampled live density (called from the
    bank-build ledger path; best-effort, bounded)."""
    if density is None or array is None:
        return
    with _density_lock:
        _density[id(array)] = float(density)
        _density.move_to_end(id(array))
        while len(_density) > _DENSITY_CAP:
            _density.popitem(last=False)


def bank_density(array: Any) -> float:
    with _density_lock:
        return _density.get(id(array), DEFAULT_DENSITY)


# ------------------------------------------------------------ statistics


class OptStats:
    """One plan's before/after accounting (executor telemetry feed)."""

    __slots__ = ("entries_before", "entries_after", "cse_hits",
                 "folds_reordered", "regs_before", "regs_after",
                 "slab_bytes_before", "slab_bytes_after",
                 "plan_bytes_before", "plan_bytes_after",
                 "narrowed_lanes", "predicted_bytes",
                 "fold_order_densities")

    def __init__(self) -> None:
        self.entries_before = 0
        self.entries_after = 0
        self.cse_hits = 0
        self.folds_reordered = 0
        self.regs_before = 0
        self.regs_after = 0
        self.slab_bytes_before = 0
        self.slab_bytes_after = 0
        self.plan_bytes_before = 0
        self.plan_bytes_after = 0
        self.narrowed_lanes = 0
        # Calibration feed (utils/roofline.py): the density-weighted
        # traffic this model PREDICTS for the plan it emitted, recorded
        # beside the measured per-launch cost so the drift detector can
        # flag cohorts where the heuristic mis-ranks work.
        self.predicted_bytes = 0
        # The density-ordered operand weights of the first reordered
        # fold chains (bounded): the concrete "predicted fold order"
        # a /debug/roofline reader compares against measured drift.
        self.fold_order_densities: List[Tuple[float, ...]] = []

    @property
    def entries_eliminated(self) -> int:
        return max(0, self.entries_before - self.entries_after)

    @property
    def bytes_saved(self) -> int:
        """Slab + plan-buffer bytes the rewrite dropped (the HBM and
        H2D numbers the launch actually pays)."""
        return max(0, (self.slab_bytes_before - self.slab_bytes_after)
                   + (self.plan_bytes_before - self.plan_bytes_after))

    def as_dict(self) -> Dict[str, int]:
        return {
            "entriesBefore": self.entries_before,
            "entriesAfter": self.entries_after,
            "entriesEliminated": self.entries_eliminated,
            "cseHits": self.cse_hits,
            "foldsReordered": self.folds_reordered,
            "regsBefore": self.regs_before,
            "regsAfter": self.regs_after,
            "slabBytesBefore": self.slab_bytes_before,
            "slabBytesAfter": self.slab_bytes_after,
            "bytesSaved": self.bytes_saved,
            "narrowedLanes": self.narrowed_lanes,
            "predictedBytes": self.predicted_bytes,
        }


class _Bail(Exception):
    """Internal: the plan has a shape this optimizer does not model
    (defensively detected); the caller keeps the original plan."""


# ------------------------------------------------------- fold reordering

_COMMUTATIVE = (mk.OP_AND, mk.OP_OR, mk.OP_XOR)


def _register_densities(plan: mk.Plan,
                        rows: List[List[int]]) -> Dict[int, float]:
    """Per-register sort weight: dense slots carry their bank's sampled
    live density, OP_EXPAND results their sparse discount, everything
    else the dense-intermediate default."""
    dens: Dict[int, float] = {}
    r = 0
    for bank, slots in zip(plan.banks, plan.slots):
        d = bank_density(bank)
        for _ in range(len(slots)):
            dens[r] = d
            r += 1
    for op, dst, _a, _b in rows:
        if op == mk.OP_EXPAND:
            dens[dst] = SPARSE_DENSITY
    return dens


def _reorder_folds(rows: List[List[int]], dens: Dict[int, float],
                   stats: OptStats) -> None:
    """Sort the operand chains the Lowering's left folds emit.

    A chain is the consecutive run ``(op, r, x0, x1), (op, r, r, x2),
    ... (op, r, r, xm)`` writing one scratch accumulator ``r``; only
    ``r`` is written inside the run, so its operands are all defined
    before it starts and any permutation of the commutative ones
    computes the same value. AND/OR/XOR chains sort ascending by
    density (cheapest operand first — intersections shrink the
    working register early, and the canonical order feeds the CSE
    fingerprints); ANDNOT keeps its pinned left operand and subtracts
    the densest negatives first."""
    def weight(r: int) -> float:
        return dens.get(r, SCRATCH_DENSITY)

    i, n = 0, len(rows)
    while i < n:
        op, r, x0, x1 = rows[i]
        if (op not in _COMMUTATIVE and op != mk.OP_ANDNOT) \
                or x0 == r or x1 == r:
            i += 1
            continue
        j = i + 1
        operands = [x0, x1]
        while j < n:
            op2, r2, a2, b2 = rows[j]
            if op2 != op or r2 != r or a2 != r or b2 == r:
                break
            operands.append(b2)
            j += 1
        if op in _COMMUTATIVE:
            ordered = [x for _, x in sorted(
                enumerate(operands),
                key=lambda t: (weight(t[1]), t[0]))]
        else:
            head, tail = operands[0], operands[1:]
            ordered = [head] + [x for _, x in sorted(
                enumerate(tail),
                key=lambda t: (-weight(t[1]), t[0]))]
        if len(stats.fold_order_densities) < 8:
            # The predicted order itself, as sort weights — what the
            # roofline plane's drift detector calibrates against.
            stats.fold_order_densities.append(
                tuple(round(weight(x), 4) for x in ordered))
        if ordered != operands:
            stats.folds_reordered += 1
            rows[i] = [op, r, ordered[0], ordered[1]]
            for m, x in enumerate(ordered[2:]):
                rows[i + 1 + m] = [op, r, r, x]
        i = j


def predict_cost_bytes(rows: List[List[int]], dens: Dict[int, float],
                       n_shards: int, w_mega: int) -> int:
    """The optimizer's own density-weighted traffic prediction for a
    plan body, in bytes: each instruction reads its operands at their
    sampled live density and writes one dense row — the same weights
    _reorder_folds sorts by, priced in the megakernel's row unit so
    the roofline plane can compare it against plan_cost()'s measured
    model (and the fenced device time) per cohort."""
    row = int(n_shards) * int(w_mega) * 4

    def weight(r: int) -> float:
        return dens.get(r, SCRATCH_DENSITY)

    total = 0.0
    for op, dst, a, b in rows:
        reads = 0.0
        if op == mk.OP_EXPAND:
            reads = SPARSE_DENSITY
        else:
            if op in mk._READS_A:
                reads += weight(a)
            if op in mk._READS_B:
                reads += weight(b)
            if op in mk._READS_DST:
                reads += weight(dst)
        total += (reads + 1.0) * row
    return int(total)

# ------------------------------------------------- value numbering / CSE
#
# Node forms: ("zero",) | ("in", reg) | ("expand", xreg)
#           | ("bin", op, va, vb) | ("thresh", vd, va, vb)
# Operand vns are always created before their consumers, so node index
# order IS a valid emission order.

_ZERO_VN = 0


def _value_number(plan: mk.Plan, rows: List[List[int]],
                  n_slots: int, n_gathered: int, widths: List[int],
                  stats: OptStats
                  ) -> Tuple[List[tuple], List[int], Dict[int, int]]:
    nodes: List[tuple] = [("zero",)]
    spans: List[int] = [0]
    key2vn: Dict[tuple, int] = {("zero",): _ZERO_VN}
    reg_vn: Dict[int, int] = {}

    def new_node(node: tuple, span: int, key: Optional[tuple]) -> int:
        vn = len(nodes)
        nodes.append(node)
        spans.append(int(span))
        if key is not None:
            key2vn[key] = vn
        return vn

    def read(r: int) -> int:
        if r < n_gathered:
            if r >= n_slots:
                # Direct expand-register read: ill-typed by the
                # verifier's contract; never emitted by the Lowering.
                raise _Bail(f"direct expand read r={r}")
            key = ("in", r)
            vn = key2vn.get(key)
            if vn is None:
                vn = new_node(key, widths[r], key)
            return vn
        vn = reg_vn.get(r)
        if vn is None:
            raise _Bail(f"read of undefined scratch r={r}")
        return vn

    for op, dst, a, b in rows:
        if op == mk.OP_ZERO:
            reg_vn[dst] = _ZERO_VN
        elif op == mk.OP_COPY:
            reg_vn[dst] = read(a)
        elif op == mk.OP_EXPAND:
            key = ("expand", a)
            vn = key2vn.get(key)
            if vn is None:
                vn = new_node(key, widths[a], key)
            else:
                stats.cse_hits += 1
            reg_vn[dst] = vn
        elif op == mk.OP_THRESH:
            vd = reg_vn.get(dst)
            if vd is None and dst < n_gathered:
                raise _Bail("thresh into gathered register")
            if vd is None:
                raise _Bail("thresh over undefined accumulator")
            va, vb = read(a), read(b)
            if va == _ZERO_VN or vb == _ZERO_VN:
                reg_vn[dst] = vd        # dst | (x & 0) == dst
                continue
            if vd == _ZERO_VN:
                # 0 | (a & b) == a & b: the first thermometer step is
                # the plain intersection — key it as one so it CSEs
                # with real ANDs.
                reg_vn[dst] = _bin(mk.OP_AND, va, vb, nodes, spans,
                                   key2vn, stats)
                continue
            lo, hi = (va, vb) if va <= vb else (vb, va)
            key = ("thresh", vd, lo, hi)
            vn = key2vn.get(key)
            if vn is None:
                vn = new_node(("thresh", vd, lo, hi),
                              max(spans[vd], min(spans[va], spans[vb])),
                              key)
            else:
                stats.cse_hits += 1
            reg_vn[dst] = vn
        else:
            va, vb = read(a), read(b)
            reg_vn[dst] = _bin(op, va, vb, nodes, spans, key2vn, stats)

    return nodes, spans, reg_vn


def _bin(op: int, va: int, vb: int, nodes: List[tuple],
         spans: List[int], key2vn: Dict[tuple, int],
         stats: OptStats) -> int:
    """Algebraic simplification + hash-consing for the two-operand
    bitwise opcodes."""
    if op == mk.OP_AND:
        if va == _ZERO_VN or vb == _ZERO_VN:
            return _ZERO_VN
        if va == vb:
            return va
    elif op == mk.OP_OR:
        if va == _ZERO_VN:
            return vb
        if vb == _ZERO_VN or va == vb:
            return va
    elif op == mk.OP_XOR:
        if va == vb:
            return _ZERO_VN
        if va == _ZERO_VN:
            return vb
        if vb == _ZERO_VN:
            return va
    elif op == mk.OP_ANDNOT:
        if va == _ZERO_VN or va == vb:
            return _ZERO_VN
        if vb == _ZERO_VN:
            return va
    else:
        raise _Bail(f"unmodeled opcode {op}")
    if op in _COMMUTATIVE and vb < va:
        va, vb = vb, va
    key = ("bin", op, va, vb)
    vn = key2vn.get(key)
    if vn is not None:
        stats.cse_hits += 1
        return vn
    if op == mk.OP_AND:
        span = min(spans[va], spans[vb])
    elif op == mk.OP_ANDNOT:
        span = spans[va]
    else:
        span = max(spans[va], spans[vb])
    vn = len(nodes)
    nodes.append(key)
    spans.append(int(span))
    key2vn[key] = vn
    return vn


# ------------------------------------------- DCE + linear-scan emission


def _operands(node: tuple) -> Tuple[int, ...]:
    if node[0] == "bin":
        return (node[2], node[3])
    if node[0] == "thresh":
        return (node[1], node[2], node[3])
    return ()


def _emit(nodes: List[tuple], out_vns: List[int], n_gathered: int
          ) -> Tuple[List[List[int]], Dict[int, int], int]:
    """Re-emit the live value-number graph as an instruction list with
    linear-scan scratch allocation (lowest free register first, freed
    at last read). Returns (rows, vn->register, scratch high water)."""
    live = set(out_vns)
    worklist = list(live)
    while worklist:
        for o in _operands(nodes[worklist.pop()]):
            if o not in live:
                live.add(o)
                worklist.append(o)

    last_use: Dict[int, int] = {vn: len(nodes) + 1 for vn in out_vns}
    for vn in sorted(live):
        for o in _operands(nodes[vn]):
            last_use[o] = max(last_use.get(o, -1), vn)

    rows: List[List[int]] = []
    loc: Dict[int, int] = {}
    free: List[int] = []
    high = n_gathered

    def alloc() -> int:
        nonlocal high
        if free:
            free.sort()
            return free.pop(0)
        high += 1
        return high - 1

    def release(vn: int, at: int) -> None:
        r = loc[vn]
        if r >= n_gathered and last_use.get(vn, -1) <= at \
                and r not in free:
            free.append(r)

    for vn in sorted(live):
        node = nodes[vn]
        kind = node[0]
        if kind == "in":
            loc[vn] = node[1]
            continue
        if kind == "zero":
            r = alloc()
            rows.append([mk.OP_ZERO, r, r, r])
            loc[vn] = r
            continue
        if kind == "expand":
            r = alloc()
            rows.append([mk.OP_EXPAND, r, node[1], node[1]])
            loc[vn] = r
            continue
        if kind == "thresh":
            vd, va, vb = node[1], node[2], node[3]
            rd, ra, rb = loc[vd], loc[va], loc[vb]
            # Accumulate in place when this step is the accumulator's
            # last reader (the thermometer chain's common case — each
            # t_j version is consumed exactly once, by the next step);
            # otherwise the accumulator is still live and the new
            # version needs its own register seeded by a COPY.
            in_place = (rd >= n_gathered and last_use.get(vd, -1) <= vn)
            if in_place:
                release(va, vn)
                release(vb, vn)
                r = rd
            else:
                # Allocate BEFORE releasing: the seeding COPY writes r
                # ahead of the THRESH read, so r must not alias a
                # still-needed operand register.
                r = alloc()
                rows.append([mk.OP_COPY, r, rd, rd])
                release(vd, vn)
                release(va, vn)
                release(vb, vn)
            rows.append([mk.OP_THRESH, r, ra, rb])
            loc[vn] = r
            continue
        # ("bin", op, va, vb)
        op, va, vb = node[1], node[2], node[3]
        ra, rb = loc[va], loc[vb]
        release(va, vn)
        release(vb, vn)
        r = alloc()
        rows.append([op, r, ra, rb])
        loc[vn] = r
    return rows, loc, high


# --------------------------------------------------------------- driver


# graftlint: materialize — the optimizer is host-only by design: Plan
# metadata (widths/instrs) is numpy, never a device array, and the
# pass runs before any launch so there is no device work to block on.
def optimize_plan(plan: mk.Plan, n_shards: int,
                  w_mega: int) -> Tuple[mk.Plan, OptStats]:
    """Run the full pass pipeline over one finished plan. Returns the
    optimized plan (or the original, untouched, when the rewrite
    cannot help or the plan has an unmodeled shape) plus the
    before/after accounting. Value-preserving by construction; the
    executor still runs the optimized plan through ``verify_plan``
    under the usual PILOSA_TPU_PLAN_VERIFY gate."""
    stats = OptStats()
    n_slots = int(plan.n_slots)
    n_gathered = n_slots + int(plan.n_xslots)
    n_instrs = int(plan.n_instrs)
    stats.entries_before = n_instrs
    stats.entries_after = n_instrs
    stats.regs_before = int(plan.n_regs)
    stats.regs_after = int(plan.n_regs)
    stats.slab_bytes_before = mk.slab_nbytes(plan.n_regs, n_shards,
                                             w_mega)
    stats.slab_bytes_after = stats.slab_bytes_before
    stats.plan_bytes_before = plan.plan_nbytes
    stats.plan_bytes_after = stats.plan_bytes_before

    widths = [int(w) for w in plan.widths.tolist()]
    rows = [[int(x) for x in r]
            for r in plan.instrs[:n_instrs].tolist()]
    try:
        dens = _register_densities(plan, rows)
        _reorder_folds(rows, dens, stats)
        # Predicted cost of the (reordered) plan body — recorded even
        # when a later pass bails, so the calibration loop always has
        # the heuristic's number beside the measured one.
        stats.predicted_bytes = predict_cost_bytes(
            rows, dens, n_shards, w_mega)
        nodes, spans, reg_vn = _value_number(
            plan, rows, n_slots, n_gathered, widths, stats)

        nc = len(plan.lane_count_widths)
        nr = len(plan.lane_row_widths)
        out_vns: List[int] = []
        for r in plan.out_count[:nc].tolist():
            out_vns.append(_lane_vn(int(r), reg_vn, n_slots, n_gathered))
        for r in plan.out_row[:nr].tolist():
            out_vns.append(_lane_vn(int(r), reg_vn, n_slots, n_gathered))

        # Lanes reading a gathered slot directly need its input vn to
        # exist even when no instruction read it.
        in_vns: Dict[int, int] = {}
        for i, node in enumerate(nodes):
            if node[0] == "in":
                in_vns[node[1]] = i
        for j, vn in enumerate(out_vns):
            if vn < 0:
                r = -vn - 1
                got = in_vns.get(r)
                if got is None:
                    got = len(nodes)
                    nodes.append(("in", r))
                    spans.append(widths[r])
                    in_vns[r] = got
                out_vns[j] = got

        new_rows, loc, high = _emit(nodes, out_vns, n_gathered)
    except _Bail:
        return plan, stats

    if len(new_rows) > n_instrs:
        # The THRESH copy-seeding can in principle outgrow the input;
        # a rewrite that got bigger is not an optimization.
        return plan, stats

    n_scratch = high - n_gathered
    t_pad = mk.pow2_at_least(n_gathered + n_scratch + 1)
    spare = t_pad - 1
    p_pad = mk.pow2_at_least(len(new_rows))
    instrs = list(new_rows) + [[mk.OP_ZERO, spare, spare, spare]] \
        * (p_pad - len(new_rows))

    out_count = [loc[vn] for vn in out_vns[:nc]]
    out_row = [loc[vn] for vn in out_vns[nc:]]
    out_count += [spare] * (mk.pow2_at_least(nc) - nc)
    out_row += [spare] * (mk.pow2_at_least(nr) - nr)

    lane_count_widths = []
    for w, vn in zip(plan.lane_count_widths, out_vns[:nc]):
        nw = min(int(w), max(1, int(spans[vn])))
        if nw < int(w):
            stats.narrowed_lanes += 1
        lane_count_widths.append(nw)
    lane_row_widths = []
    for w, vn in zip(plan.lane_row_widths, out_vns[nc:]):
        nw = min(int(w), max(1, int(spans[vn])))
        if nw < int(w):
            stats.narrowed_lanes += 1
        lane_row_widths.append(nw)

    new_plan = mk.Plan(
        banks=plan.banks,
        slots=plan.slots,
        widths=np.asarray(widths[:n_gathered]
                          + [0] * (t_pad - n_gathered), np.int32),
        instrs=np.asarray(instrs, np.int32).reshape(p_pad, 4),
        out_count=np.asarray(out_count, np.int32),
        out_row=np.asarray(out_row, np.int32),
        n_slots=n_slots, n_regs=t_pad, n_instrs=len(new_rows),
        lane_count_widths=tuple(lane_count_widths),
        lane_row_widths=tuple(lane_row_widths),
        xbanks=plan.xbanks, xslots=plan.xslots,
        n_xslots=int(plan.n_xslots))
    stats.entries_after = len(new_rows)
    stats.regs_after = t_pad
    stats.slab_bytes_after = mk.slab_nbytes(t_pad, n_shards, w_mega)
    stats.plan_bytes_after = new_plan.plan_nbytes
    # The plan that will actually launch is the rewritten one — its
    # predicted cost is what the measured per-launch bytes/time must
    # be compared against (slot registers keep their numbering, so the
    # density map still applies; rebuilt scratch carries the default).
    stats.predicted_bytes = predict_cost_bytes(
        new_rows, dens, n_shards, w_mega)
    new_plan.opt_stats = stats
    return new_plan, stats


def _lane_vn(r: int, reg_vn: Dict[int, int], n_slots: int,
             n_gathered: int) -> int:
    """Output lane register -> value number; gathered-slot lanes that
    no instruction read are flagged negative for the caller to
    materialize an input vn."""
    if r < n_gathered:
        if r >= n_slots:
            raise _Bail(f"output lane reads expand register {r}")
        return -r - 1
    vn = reg_vn.get(r)
    if vn is None:
        raise _Bail(f"output lane reads undefined register {r}")
    return vn
