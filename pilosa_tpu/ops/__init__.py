"""Bitset kernel layer — the TPU replacement for the reference's roaring
container kernels (/root/reference/roaring/roaring.go:2313-3607)."""
