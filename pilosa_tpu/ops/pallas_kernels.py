"""Pallas TPU kernels for the bitmap hot loops.

The reference's hottest code is the per-container popcount/AND loops
(/root/reference/roaring/roaring.go:2438 intersectionCountBitmapBitmap,
:2630 intersectBitmapBitmap) driven by the TopN scan
(/root/reference/fragment.go:1067-1188). Here the equivalent unit of work is
a *bank sweep*: popcount every row of a [rows, shards, words] HBM-resident
view bank, optionally AND-ed with a broadcast filter row — one pass that is
purely HBM-bandwidth-bound.

XLA already compiles `sum(popcount(bank & filt))` well; the Pallas kernels
below exist to (a) pin the tiling — one (row, shard) block of 128 KiB per
grid step, double-buffered HBM→VMEM by the pipeline — and (b) fuse the
masked and unmasked counts into a single data pass: TopN-with-filter needs
BOTH |row ∧ filter| and |row| (for the tanimoto denominator,
/root/reference/fragment.go:1087-1093), which the stock XLA path reads the
bank twice for.

Mosaic requires output blocks to be lane-shaped (…, 8k, 128), so each
kernel accumulates an (8, 128)-shaped partial per row across the shard grid
axis (the shard axis is the minor, sequential grid dimension) and a tiny
fused jnp reduction collapses it afterwards.

All kernels degrade gracefully: `available()` is False off-TPU, and the
executor falls back to the fused-jnp path. Tests run the kernels in
interpret mode on CPU against the jnp reference.

Measured (single tunneled TPU chip, 1 GiB bank, 4 masked sweeps chained in
one jit to amortize the ~68 ms host↔device round-trip): XLA-fused jnp
31.3 GB/s effective vs Pallas 25-27 GB/s — XLA's own fusion of
popcount(b∧f)+popcount(b) already reads the bank once, so the hand tiling
buys nothing on this part. The executor therefore defaults to the jnp path
and uses these kernels only when PILOSA_TPU_PALLAS=1 (`enabled()`); they
are kept correct and benchmarked so the tradeoff can be re-measured on
other TPU generations.
"""
# graftlint: disable-file=GL006 — module-level jitted entry points,
# compiled once per static shape bucket; executor call sites reach
# them only from inside _note_jit_compile-tracked programs
# (_counts_fn), so the retrace counter still sees every real
# signature miss.


from __future__ import annotations

import functools
import os
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from pilosa_tpu.ops.bitset import WORDS_PER_SHARD

# Words per (row, shard) block reshaped to VPU-friendly (sublane, lane) tiles:
# 32768 u32 words = 256 sublanes x 128 lanes = 128 KiB VMEM per block.
_LANES = 128
_SUBLANES = WORDS_PER_SHARD // _LANES
# Partial-sum tile kept per row: the minimal 32-bit VMEM tile (8, 128).
_ACC_SUB = 8
_ACC_GROUPS = _SUBLANES // _ACC_SUB


def available() -> bool:
    """True when a TPU backend is attached and Pallas is not disabled."""
    if os.environ.get("PILOSA_TPU_NO_PALLAS"):
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def enabled() -> bool:
    """True when the executor should route sweeps through Pallas: opt-in
    via PILOSA_TPU_PALLAS=1 (XLA's fused path measured faster on current
    hardware — see module docstring)."""
    flag = os.environ.get("PILOSA_TPU_PALLAS", "").strip().lower()
    return flag in ("1", "true", "yes", "on") and available()


def _popcount32(x: jax.Array) -> jax.Array:
    """SWAR popcount over uint32 lanes (kept to VPU-native shift/and/add/mul
    so it lowers on every Mosaic version; equivalent to
    jax.lax.population_count)."""
    x = x - ((x >> jnp.uint32(1)) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> jnp.uint32(2))
                                        & jnp.uint32(0x33333333))
    x = (x + (x >> jnp.uint32(4))) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> jnp.uint32(24)


def _block_partial(blk: jax.Array) -> jax.Array:
    """[SUBLANES, LANES] uint32 words -> (ACC_SUB, LANES) popcount partial.

    Accumulates in int32 (Mosaic has no unsigned reductions); per-lane
    partials stay far below 2^31 — ≤32 bits/word × 32 groups × shards."""
    return jnp.sum(
        _popcount32(blk).astype(jnp.int32).reshape(
            _ACC_GROUPS, _ACC_SUB, _LANES),
        axis=0, dtype=jnp.int32)


def _counts_kernel(bank_ref: Any, out_ref: Any) -> None:
    """Grid step (r, s): accumulate one block's popcount into out[r]."""
    from jax.experimental import pallas as pl

    partial = _block_partial(bank_ref[0, 0])
    first = pl.program_id(1) == 0

    @pl.when(first)
    def _init() -> None:
        out_ref[0] = partial

    @pl.when(jnp.logical_not(first))
    def _acc() -> None:
        out_ref[0] += partial


def _masked_counts_kernel(bank_ref: Any, filt_ref: Any,
                          inter_ref: Any, raw_ref: Any) -> None:
    """Grid step (r, s): one data pass accumulates BOTH |row ∧ filt| and
    |row| partials."""
    from jax.experimental import pallas as pl

    blk = bank_ref[0, 0]
    p_inter = _block_partial(blk & filt_ref[0])
    p_raw = _block_partial(blk)
    first = pl.program_id(1) == 0

    @pl.when(first)
    def _init() -> None:
        inter_ref[0] = p_inter
        raw_ref[0] = p_raw

    @pl.when(jnp.logical_not(first))
    def _acc() -> None:
        inter_ref[0] += p_inter
        raw_ref[0] += p_raw


@functools.partial(jax.jit, static_argnames=("interpret",))
def bank_row_counts(bank: jax.Array, *,
                    interpret: bool = False) -> jax.Array:
    """Per-row popcounts of a [R, S, W] uint32 bank -> uint32[R].

    The TopN sweep (reference fragment.top, fragment.go:1067 — there a
    heap scan over cached counts; here an exact full sweep).
    """
    from jax.experimental import pallas as pl

    R, S, W = bank.shape
    assert W == WORDS_PER_SHARD, bank.shape
    tiled = bank.reshape(R, S, _SUBLANES, _LANES)
    partials = pl.pallas_call(
        _counts_kernel,
        grid=(R, S),
        in_specs=[pl.BlockSpec((1, 1, _SUBLANES, _LANES),
                               lambda r, s: (r, s, 0, 0))],
        out_specs=pl.BlockSpec((1, _ACC_SUB, _LANES), lambda r, s: (r, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((R, _ACC_SUB, _LANES), jnp.int32),
        interpret=interpret,
    )(tiled)
    return jnp.sum(partials, axis=(1, 2), dtype=jnp.int32).astype(jnp.uint32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def bank_row_counts_masked(
        bank: jax.Array, filt: jax.Array, *,
        interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """([R,S,W] bank, [S,W] filter) -> (|row ∧ filt| uint32[R], |row|
    uint32[R]) in ONE pass over the bank (tanimoto needs both,
    fragment.go:1087-1093)."""
    from jax.experimental import pallas as pl

    R, S, W = bank.shape
    assert W == WORDS_PER_SHARD, bank.shape
    assert filt.shape == (S, W), (filt.shape, bank.shape)
    tiled = bank.reshape(R, S, _SUBLANES, _LANES)
    filt_t = filt.reshape(S, _SUBLANES, _LANES)
    inter, raw = pl.pallas_call(
        _masked_counts_kernel,
        grid=(R, S),
        in_specs=[
            pl.BlockSpec((1, 1, _SUBLANES, _LANES),
                         lambda r, s: (r, s, 0, 0)),
            pl.BlockSpec((1, _SUBLANES, _LANES), lambda r, s: (s, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _ACC_SUB, _LANES), lambda r, s: (r, 0, 0)),
            pl.BlockSpec((1, _ACC_SUB, _LANES), lambda r, s: (r, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, _ACC_SUB, _LANES), jnp.int32),
            jax.ShapeDtypeStruct((R, _ACC_SUB, _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(tiled, filt_t)
    return (jnp.sum(inter, axis=(1, 2), dtype=jnp.int32).astype(jnp.uint32),
            jnp.sum(raw, axis=(1, 2), dtype=jnp.int32).astype(jnp.uint32))


# ---------------------------------------------------------------------------
# Positions-bank membership (probe stage — VERDICT r5 #2)
#
# The tanimoto flagship's warm floor is the sparse-filter membership in
# the fixed-layout pbank kernel: |row ∧ filter| over [R, L] u16 position
# rows vs ~48 query positions, measured ~1 ns/position as an XLA
# [P]x[QCAP] compare fan-out. This kernel fuses compare+rowsum with the
# query positions VMEM-resident, accumulating through a fori loop so no
# [P, QCAP] intermediate ever materializes. Layout: u16 positions
# bitcast to u32 pairs and GROUPED 16 rows per block-row so every Mosaic
# tile is lane-aligned: in [GB, 16*L2] u32 (L2 = L/2), out (8, 128) i32
# = 1024 row counts per grid step.
#
# Status: correctness-tested in interpret mode; measured on hardware by
# benches/pbank_membership_probe.py before any production wiring (the
# r4 bank-sweep Pallas kernels measured SLOWER than XLA fusion, so this
# ships opt-in until the probe says otherwise).

_MEM_ROWS_BLOCK = 1024  # rows per grid step (= 8*128 out tile)
_MEM_GROUP = 16         # bank rows packed per block-row


def _membership_kernel(qk: int) -> Callable[..., None]:
    def kernel(pos_ref: Any, qtop_ref: Any, out_ref: Any) -> None:
        blk = pos_ref[...]                    # [GB, 16*L2] u32
        qvals = qtop_ref[...]                 # (8, 128) i32, qk real
        gb, gl2 = blk.shape
        l2 = gl2 // _MEM_GROUP
        pairs = blk.reshape(gb * _MEM_GROUP, l2)
        lo = (pairs & jnp.uint32(0xFFFF)).astype(jnp.int32)
        hi = (pairs >> jnp.uint32(16)).astype(jnp.int32)
        # Static unroll over the query positions: each step is one
        # VPU-wide compare+or against a scalar held in VMEM — no
        # [P, QCAP] intermediate, no dynamic indexing.
        mlo = jnp.zeros(lo.shape, dtype=jnp.bool_)
        mhi = jnp.zeros(hi.shape, dtype=jnp.bool_)
        for j in range(qk):
            q = qvals[j // 128, j % 128]
            mlo |= lo == q
            mhi |= hi == q
        counts = (mlo.astype(jnp.int32) + mhi.astype(jnp.int32)
                  ).sum(axis=1, dtype=jnp.int32)
        out_ref[0] = counts.reshape(8, 128)
    return kernel


@functools.partial(jax.jit, static_argnames=("qk", "interpret"))
def pbank_membership_counts(pos_grouped: jax.Array, qtop_pad: jax.Array,
                            *, qk: int,
                            interpret: bool = False) -> jax.Array:
    """([R/16, 16*L2] u32 grouped position pairs, (8,128) i32 padded
    query positions, qk = real query count) -> |row ∧ query| i32[R].

    R must be a multiple of 1024 (the fixed layout pads rows anyway);
    0xFFFF pads match nothing as long as no real position is 0xFFFF
    (fingerprint positions are < 4096)."""
    from jax.experimental import pallas as pl

    rg, gl2 = pos_grouped.shape
    R = rg * _MEM_GROUP
    assert R % _MEM_ROWS_BLOCK == 0, R
    gb = _MEM_ROWS_BLOCK // _MEM_GROUP
    out = pl.pallas_call(
        _membership_kernel(qk),
        grid=(R // _MEM_ROWS_BLOCK,),
        in_specs=[
            pl.BlockSpec((gb, gl2), lambda r: (r, 0)),
            pl.BlockSpec((8, 128), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, 128), lambda r: (r, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((R // _MEM_ROWS_BLOCK, 8, 128),
                                       jnp.int32),
        interpret=interpret,
    )(pos_grouped, qtop_pad)
    return out.reshape(R)


# ---------------------------------------------------------------------------
# Heterogeneous staged-query megakernel — the instruction-interpreter
# loop of ops/megakernel.py as ONE Pallas kernel: the [P, 4] plan
# buffer (opcode, dst, a, b) sits in SMEM, the register slab in VMEM,
# and a fori loop inside the kernel body walks the plan, dynamically
# loading the two operand registers each entry names, dispatching on
# its opcode, and storing the destination register in place. The
# read-after-write chain between plan entries (entry k reads what
# entry k-1 wrote) lives INSIDE one kernel invocation, so it is
# sequential by construction — a grid-per-entry formulation with
# aliased outputs reads stale operand blocks and is wrong.
#
# Status: correctness-pinned in interpret mode (tests/
# test_pallas_kernels.py) like the bank-sweep kernels above, and
# reached only under the same PILOSA_TPU_PALLAS=1 opt-in
# (executor/megakernel.py builds the jnp fori/switch interpreter
# otherwise, which XLA compiles to the same single launch). The whole
# slab must fit VMEM in this formulation — the flood-workload slabs
# (a few hundred trimmed registers) do; validate on hardware via the
# bench probe before flipping the default, as with every kernel here.


def _mega_loop_kernel(n_instrs: int) -> Callable[..., None]:
    def kernel(instr_ref: Any, slab_ref: Any, out_ref: Any) -> None:
        from jax.experimental import pallas as pl

        out_ref[...] = slab_ref[...]

        def body(i: Any, carry: Any) -> Any:
            op = instr_ref[i, 0]
            vd = pl.load(out_ref, (pl.ds(instr_ref[i, 1], 1),))
            va = pl.load(out_ref, (pl.ds(instr_ref[i, 2], 1),))
            vb = pl.load(out_ref, (pl.ds(instr_ref[i, 3], 1),))
            zero = jnp.zeros_like(va)
            # OP_THRESH (7) reads the CURRENT dst: thermometer
            # accumulate dst | (a & b) — see ops/megakernel.OP_THRESH.
            res = jnp.where(
                op == 0, jnp.bitwise_and(va, vb),
                jnp.where(op == 1, jnp.bitwise_or(va, vb),
                          jnp.where(op == 2, jnp.bitwise_xor(va, vb),
                                    jnp.where(op == 3,
                                              jnp.bitwise_and(
                                                  va,
                                                  jnp.bitwise_not(vb)),
                                              jnp.where(op == 4, zero,
                                                        va)))))
            res = jnp.where(
                op == 7, jnp.bitwise_or(vd, jnp.bitwise_and(va, vb)),
                res)
            pl.store(out_ref, (pl.ds(instr_ref[i, 1], 1),), res)
            return carry

        jax.lax.fori_loop(0, n_instrs, body, 0)
    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def mega_interpret(slab: jax.Array, instrs: jax.Array, *,
                   interpret: bool = False) -> jax.Array:
    """Run a [P, 4] int32 plan buffer (opcode, dst, a, b) over a
    [T, S, W] uint32 register slab; returns the final slab.

    This flavor interprets the SAME IR as the jnp fori/switch program
    (ops/megakernel.build_program) and inherits the same pre-launch
    contract: the executor runs ops/megakernel.verify_plan over every
    plan before either interpreter sees it (PILOSA_TPU_PLAN_VERIFY),
    so opcode/register/width invariants are already proven host-side.
    Only the structural shape of the buffers is re-asserted here —
    trace-time, zero device cost — because a malformed buffer handed
    directly to pallas_call would fail far less legibly in Mosaic."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, S, W = slab.shape
    assert instrs.ndim == 2 and instrs.shape[1] == 4, (
        f"plan buffer must be [P, 4], got {instrs.shape}")
    assert instrs.dtype == jnp.int32, (
        f"plan buffer must be int32, got {instrs.dtype}")
    assert slab.dtype == jnp.uint32, (
        f"register slab must be uint32, got {slab.dtype}")
    P = instrs.shape[0]
    return pl.pallas_call(
        _mega_loop_kernel(P),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((T, S, W), lambda: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((T, S, W), lambda: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((T, S, W), slab.dtype),
        interpret=interpret,
    )(instrs, slab)


@functools.partial(jax.jit, static_argnames=("interpret",))
def bsi_plane_counts(planes: jax.Array, mask: jax.Array, *,
                     interpret: bool = False) -> jax.Array:
    """([D, S, W] bit-planes, [S, W] column mask) -> uint32[D] masked
    popcounts per plane — the O(bitDepth) loop of BSI Sum/Range
    (reference fragment.sum, fragment.go:767: per-bit IntersectionCount).
    The caller weights plane d by 2^d and handles sign/offset. Identical
    sweep shape to bank_row_counts_masked with planes as rows."""
    inter, _ = bank_row_counts_masked(planes, mask, interpret=interpret)
    return inter
