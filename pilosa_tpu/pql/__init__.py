"""PQL — the Pilosa Query Language.

Reference grammar: /root/reference/pql/pql.peg (PEG, compiled to a generated
Go parser). Here: a hand-written recursive-descent parser producing the same
Call/Condition AST shapes (/root/reference/pql/ast.go:27,247,466).
"""

from pilosa_tpu.pql.ast import Call, Condition, Query  # noqa: F401
from pilosa_tpu.pql.parser import (parse_string,  # noqa: F401
                                   parse_string_cached, ParseError)
