"""Recursive-descent PQL parser.

Follows the PEG grammar /root/reference/pql/pql.peg rule for rule:
Calls / Call (special forms Set, SetRowAttrs, SetColumnAttrs, Clear,
ClearRow, Store, TopN, Rows, plus the generic IDENT form) / allargs / args /
arg / COND / conditional / value / item. Semantics verified against the
grammar actions (startCall/addPosNum/addCond/endConditional in
/root/reference/pql/ast.go).
"""

from __future__ import annotations

import re
from pilosa_tpu.utils.locks import make_lock
from typing import Any, List, Optional

from pilosa_tpu.pql.ast import (
    BETWEEN, Call, Condition, EQ, GT, GTE, LT, LTE, NEQ, Query,
)

_IDENT_RE = re.compile(r"[A-Za-z][A-Za-z0-9]*")
_FIELD_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_RESERVED = ("_row", "_col", "_start", "_end", "_timestamp", "_field")
_NUMBER_RE = re.compile(r"-?(\d+(\.\d*)?|\.\d+)")
_UINT_RE = re.compile(r"[1-9]\d*|0")
_CONDINT_RE = re.compile(r"-?[1-9]\d*|0")
# token form of bare strings: letters/digits/dash/underscore/colon
_TOKEN_RE = re.compile(r"[A-Za-z0-9\-_:]+")
_TIMESTAMP_RE = re.compile(
    r"\d{4}-[01]\d-[0-3]\d(T[0-2]\d:[0-6]\d(:[0-6]\d)?| [0-2]\d:[0-6]\d)?")


class ParseError(ValueError):
    def __init__(self, msg: str, pos: int = -1):
        super().__init__(msg)
        self.pos = pos


class _Parser:
    def __init__(self, src: str):
        self.src = src
        self.pos = 0

    # -- low-level ----------------------------------------------------------

    def error(self, msg: str):
        raise ParseError(f"{msg} at offset {self.pos}: "
                         f"{self.src[self.pos:self.pos + 20]!r}", self.pos)

    def sp(self) -> None:
        while self.pos < len(self.src) and self.src[self.pos] in " \t\n\r":
            self.pos += 1

    def eof(self) -> bool:
        return self.pos >= len(self.src)

    def peek(self, s: str) -> bool:
        return self.src.startswith(s, self.pos)

    def lit(self, s: str) -> bool:
        if self.src.startswith(s, self.pos):
            self.pos += len(s)
            return True
        return False

    def expect(self, s: str) -> None:
        if not self.lit(s):
            self.error(f"expected {s!r}")

    def match(self, regex) -> Optional[str]:
        m = regex.match(self.src, self.pos)
        if m is None:
            return None
        self.pos = m.end()
        return m.group(0)

    def open(self) -> None:
        self.expect("(")
        self.sp()

    def close(self) -> None:
        self.expect(")")
        self.sp()

    def comma(self) -> bool:
        save = self.pos
        self.sp()
        if self.lit(","):
            self.sp()
            return True
        self.pos = save
        return False

    # -- grammar ------------------------------------------------------------

    def parse_query(self) -> Query:
        q = Query()
        self.sp()
        while not self.eof():
            q.calls.append(self.parse_call())
            self.sp()
        return q

    def parse_call(self) -> Call:
        save = self.pos
        name = self.match(_IDENT_RE)
        if name is None:
            self.error("expected call name")
        if not self.peek("("):
            self.pos = save
            self.error("expected '(' after call name")
        handler = getattr(self, f"_call_{name}", None)
        if handler is not None:
            after_name = self.pos
            try:
                return handler()
            except ParseError as special_err:
                # PEG ordered choice (pql.peg Call): a failed special
                # form falls back to the generic IDENT alternative —
                # this is how Rows()/TopN() with no posfield parse in
                # the reference. When BOTH alternatives fail, report
                # whichever error got furthest into the input: the
                # generic attempt usually dies at the first positional
                # token, which would mask the special form's precise
                # diagnosis (e.g. an invalid escape deep in an arg).
                self.pos = after_name
                try:
                    return self._call_generic(name)
                except ParseError as generic_err:
                    raise (special_err
                           if special_err.pos > generic_err.pos
                           else generic_err) from None
        return self._call_generic(name)

    # Special forms. Each mirrors one branch of pql.peg `Call`.

    def _call_Set(self) -> Call:
        call = Call("Set")
        self.open()
        self._pos_col(call)
        self._req_comma()
        self._args(call)
        if self.comma():
            call.args["_timestamp"] = self._timestamp()
        self.close()
        return call

    def _call_SetRowAttrs(self) -> Call:
        call = Call("SetRowAttrs")
        self.open()
        self._posfield(call)
        self._req_comma()
        self._pos_row(call)
        self._req_comma()
        self._args(call)
        self.close()
        return call

    def _call_SetColumnAttrs(self) -> Call:
        call = Call("SetColumnAttrs")
        self.open()
        self._pos_col(call)
        self._req_comma()
        self._args(call)
        self.close()
        return call

    def _call_Clear(self) -> Call:
        call = Call("Clear")
        self.open()
        self._pos_col(call)
        self._req_comma()
        self._args(call)
        self.close()
        return call

    def _call_ClearRow(self) -> Call:
        call = Call("ClearRow")
        self.open()
        self._arg(call)
        self.close()
        return call

    def _call_Store(self) -> Call:
        call = Call("Store")
        self.open()
        call.children.append(self.parse_call())
        self.sp()
        self._req_comma()
        self._arg(call)
        self.close()
        return call

    def _call_TopN(self) -> Call:
        return self._posfield_form("TopN")

    def _call_Rows(self) -> Call:
        return self._posfield_form("Rows")

    def _posfield_form(self, name: str) -> Call:
        call = Call(name)
        self.open()
        self._posfield(call)
        if self.comma():
            self._allargs(call)
        self.close()
        return call

    def _call_generic(self, name: str) -> Call:
        call = Call(name)
        self.open()
        self._allargs(call)
        self.comma()  # trailing comma allowed
        self.close()
        return call

    # -- arg forms ----------------------------------------------------------

    def _req_comma(self) -> None:
        if not self.comma():
            self.error("expected ','")

    def _allargs(self, call: Call) -> None:
        """allargs <- Call (comma Call)* (comma args)? / args / sp"""
        self.sp()
        if self._at_call():
            call.children.append(self.parse_call())
            self.sp()
            while self.comma():
                if self._at_call():
                    call.children.append(self.parse_call())
                    self.sp()
                else:
                    self._args(call)
                    return
            return
        if self.peek(")"):
            return
        self._args(call)

    def _at_call(self) -> bool:
        m = _IDENT_RE.match(self.src, self.pos)
        return m is not None and self.src.startswith("(", m.end())

    def _args(self, call: Call) -> None:
        """args <- arg (comma args)? sp  — PEG ordered choice: if the text
        after a comma isn't an arg (e.g. Set's trailing timestamp), rewind
        the comma and stop."""
        self._arg(call)
        while True:
            save = self.pos
            if not self.comma():
                break
            if self.peek(")"):
                self.pos = save
                break
            try:
                self._arg(call)
            except ParseError:
                self.pos = save
                break
        self.sp()

    def _arg(self, call: Call) -> None:
        """arg <- field '=' value / field COND value / conditional"""
        save = self.pos
        # conditional: int < field < int
        cond = self._try_conditional(call)
        if cond:
            return
        self.pos = save
        name = self._field_name()
        self.sp()
        if self.peek("=") and not self.peek("=="):
            self.lit("=")
            self.sp()
            call.args[name] = self._value()
            return
        op = self._cond_op()
        if op is None:
            self.error("expected '=' or comparison operator")
        self.sp()
        call.args[name] = Condition(op, self._value())

    def _cond_op(self) -> Optional[str]:
        for op in (BETWEEN, LTE, GTE, EQ, NEQ, LT, GT):
            if self.lit(op):
                return op
        return None

    def _try_conditional(self, call: Call) -> bool:
        """conditional <- condint condLT condfield condLT condint
        Normalized to an inclusive BETWEEN (reference endConditional,
        pql/ast.go:82-101: '<' bumps the bound inward)."""
        save = self.pos
        low_s = self.match(_CONDINT_RE)
        if low_s is None:
            return False
        self.sp()
        op1 = "<=" if self.lit("<=") else ("<" if self.lit("<") else None)
        if op1 is None:
            self.pos = save
            return False
        self.sp()
        field = self.match(_FIELD_RE)
        if field is None:
            self.pos = save
            return False
        self.sp()
        op2 = "<=" if self.lit("<=") else ("<" if self.lit("<") else None)
        if op2 is None:
            self.pos = save
            return False
        self.sp()
        high_s = self.match(_CONDINT_RE)
        if high_s is None:
            self.pos = save
            return False
        self.sp()
        low, high = int(low_s), int(high_s)
        if op1 == "<":
            low += 1
        if op2 == "<":
            high -= 1
        call.args[field] = Condition(BETWEEN, [low, high])
        return True

    def _field_name(self) -> str:
        for r in _RESERVED:
            if self.peek(r):
                self.pos += len(r)
                return r
        name = self.match(_FIELD_RE)
        if name is None:
            self.error("expected field name")
        return name

    def _posfield(self, call: Call) -> None:
        name = self.match(_FIELD_RE)
        if name is None:
            self.error("expected field name")
        call.args["_field"] = name

    def _pos_col(self, call: Call) -> None:
        call.args["_col"] = self._pos_id()

    def _pos_row(self, call: Call) -> None:
        call.args["_row"] = self._pos_id()

    def _pos_id(self) -> Any:
        u = self.match(_UINT_RE)
        if u is not None:
            return int(u)
        if self.lit("'"):
            return self._quoted("'")
        if self.lit('"'):
            return self._quoted('"')
        self.error("expected id or quoted key")

    def _timestamp(self) -> str:
        ts = self.match(_TIMESTAMP_RE)
        if ts is not None:
            return ts
        if self.lit("'"):
            return self._quoted("'")
        if self.lit('"'):
            return self._quoted('"')
        self.error("expected timestamp")

    # Go strconv.Unquote escapes for double-quoted strings (pql.peg:50
    # runs Unquote on the captured token). \' is deliberately absent:
    # Go rejects it inside double quotes.
    _DQ_ESCAPES = {'"': '"', "\\": "\\", "n": "\n", "t": "\t",
                   "r": "\r", "a": "\a", "b": "\b", "f": "\f", "v": "\v"}

    def _quoted(self, q: str) -> str:
        """Quoted string body (cursor past the opening quote).

        Double quotes interpret Go escape sequences, matching the
        reference's strconv.Unquote (pql.peg:50) — except that an
        INVALID escape raises a parse error here, where the reference
        ignores the Unquote error and silently yields "" (documented
        divergence: an error beats silently dropping user data).
        Single quotes unescape only \\' and \\\\ — a divergence from
        the reference, which captures the raw text backslashes and
        all (pql.peg:51); the unescaped form round-trips through
        Call.to_pql, the raw form cannot."""
        out = []
        while self.pos < len(self.src):
            ch = self.src[self.pos]
            if ch == "\\" and self.pos + 1 < len(self.src):
                nxt = self.src[self.pos + 1]
                if q == "'":
                    if nxt in ("'", "\\"):
                        out.append(nxt)
                        self.pos += 2
                        continue
                elif nxt in self._DQ_ESCAPES:
                    out.append(self._DQ_ESCAPES[nxt])
                    self.pos += 2
                    continue
                elif nxt in "xuU01234567":
                    out.append(self._numeric_escape(nxt))
                    continue
                else:
                    self.error(f"invalid escape \\{nxt}")
            if ch == q:
                self.pos += 1
                return "".join(out)
            out.append(ch)
            self.pos += 1
        self.error(f"unterminated {q} string")

    _OCTAL = frozenset("01234567")
    _HEX = frozenset("0123456789abcdefABCDEF")

    def _numeric_escape(self, kind: str) -> str:
        """\\xNN, \\uNNNN, \\UNNNNNNNN, \\NNN (octal) — cursor on the
        backslash; consumes the whole escape. Matches Go strconv
        bounds: octal <= 255, no lone surrogates, <= U+10FFFF; digits
        are validated per character (int() would accept '_')."""
        start = self.pos
        self.pos += 2  # backslash + kind char
        if kind in self._OCTAL:
            want, digits = 3, self.src[start + 1:start + 4]
            base, allowed, self.pos = 8, self._OCTAL, start + 4
        else:
            want = {"x": 2, "u": 4, "U": 8}[kind]
            digits = self.src[self.pos:self.pos + want]
            base, allowed = 16, self._HEX
            self.pos += want
        if len(digits) != want or any(d not in allowed for d in digits):
            self.pos = start
            self.error("invalid numeric escape")
        code = int(digits, base)
        if base == 8 and code > 255:
            self.pos = start
            self.error("octal escape value > 255")
        if code > 0x10FFFF or 0xD800 <= code <= 0xDFFF:
            self.pos = start
            self.error("invalid unicode code point in escape")
        return chr(code)

    # -- values -------------------------------------------------------------

    def _value(self) -> Any:
        if self.lit("["):
            self.sp()
            items: List[Any] = []
            if not self.peek("]"):
                items.append(self._item())
                while self.comma():
                    items.append(self._item())
            self.sp()
            self.expect("]")
            self.sp()
            return items
        return self._item()

    def _terminates_item(self, at: int) -> bool:
        """item literals must be followed by comma/close/bracket (pql.peg
        `&(comma / sp close)`)."""
        i = at
        while i < len(self.src) and self.src[i] in " \t\n\r":
            i += 1
        return i >= len(self.src) or self.src[i] in ",)]"

    def _item(self) -> Any:
        for word, val in (("null", None), ("true", True), ("false", False)):
            if self.peek(word) and self._terminates_item(self.pos + len(word)):
                self.pos += len(word)
                return val
        ts = self.match(_TIMESTAMP_RE)
        if ts is not None and self._terminates_item(self.pos):
            return ts
        elif ts is not None:
            self.pos -= len(ts)
        num = self.match(_NUMBER_RE)
        if num is not None and self._terminates_item(self.pos):
            return float(num) if ("." in num) else int(num)
        elif num is not None:
            self.pos -= len(num)
        if self._at_call():
            return self.parse_call()
        if self.lit('"'):
            return self._quoted('"')
        if self.lit("'"):
            return self._quoted("'")
        tok = self.match(_TOKEN_RE)
        if tok is not None:
            return tok
        self.error("expected value")


def parse_string(src: str) -> Query:
    """Parse a PQL string into a Query (reference ParseString,
    pql/parser.go)."""
    return _Parser(src).parse_query()


_PARSE_CACHE: "dict[str, Query]" = {}
_PARSE_LOCK = make_lock("pql._PARSE_LOCK")
_PARSE_CACHE_MAX = 512


def parse_string_cached(src: str) -> Query:
    """parse_string through a small LRU keyed by the source text,
    returning a CLONE of the cached tree (the executor's key
    translation writes resolved ids into call.args, so the pristine
    parse must never escape). Serving workloads re-issue identical
    query strings; the ~0.2 ms parse is pure overhead on a warm
    small-query path."""
    with _PARSE_LOCK:
        hit = _PARSE_CACHE.pop(src, None)
        if hit is not None:
            _PARSE_CACHE[src] = hit  # re-insert: LRU by dict order
    if hit is not None:
        # Clone OUTSIDE the lock: the warm path runs on every request
        # thread, and a big filter tree's clone under a global lock
        # would serialize them.
        return hit.clone()
    parsed = parse_string(src)
    with _PARSE_LOCK:
        while len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            _PARSE_CACHE.pop(next(iter(_PARSE_CACHE)))
        _PARSE_CACHE[src] = parsed
    return parsed.clone()
