"""PQL AST: Query -> Call tree with args and conditions.

Mirrors /root/reference/pql/ast.go: `Call` (:247) holds a name, an argument
map, and child calls; `Condition` (:466) is a comparison operator + operand
used as an argument value (`Row(x > 5)`); positional tokens are stored under
reserved arg keys `_field`, `_col`, `_row`, `_start`, `_end`, `_timestamp`
(pql.peg `reserved`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# Condition operators (reference token names, pql/token.go).
EQ = "=="
NEQ = "!="
LT = "<"
LTE = "<="
GT = ">"
GTE = ">="
BETWEEN = "><"


@dataclass
class Condition:
    op: str
    value: Any  # int/float, or [low, high] for BETWEEN (inclusive bounds)

    def int_slice(self) -> List[int]:
        if not isinstance(self.value, list):
            raise ValueError(f"expected list value, got {self.value!r}")
        return [int(v) for v in self.value]

    def __str__(self) -> str:
        return f"{self.op} {self.value}"


@dataclass
class Call:
    name: str
    args: Dict[str, Any] = field(default_factory=dict)
    children: List["Call"] = field(default_factory=list)

    def arg(self, key: str, default: Any = None) -> Any:
        return self.args.get(key, default)

    def clone(self) -> "Call":
        """Fresh Call tree with its OWN args dicts, children lists,
        Call-valued args (GroupBy filter), list-valued args (previous,
        ids), and Conditions — every structure the executor's key
        translation can write resolved ids into (executor.py
        _translate_call mutates args in place, including nested filter
        trees and `previous` lists). Scalars are shared (immutable)."""
        args: Dict[str, Any] = {}
        for k, v in self.args.items():
            if isinstance(v, Call):
                v = v.clone()
            elif isinstance(v, Condition):
                v = Condition(v.op, list(v.value)
                              if isinstance(v.value, list) else v.value)
            elif isinstance(v, list):
                v = list(v)
            args[k] = v
        return Call(self.name, args, [c.clone() for c in self.children])

    def uint_arg(self, key: str) -> Optional[int]:
        v = self.args.get(key)
        if v is None:
            return None
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(f"arg {key!r} must be numeric, got {v!r}")
        return int(v)

    def has_condition_arg(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def condition_field(self) -> Optional[str]:
        for k, v in self.args.items():
            if isinstance(v, Condition):
                return k
        return None

    def writes(self) -> bool:
        return self.name in ("Set", "Clear", "ClearRow", "Store",
                             "SetRowAttrs", "SetColumnAttrs")

    def to_pql(self) -> str:
        """Serialize back to parseable PQL (the reference serializes Calls
        with String() for remote re-execution, pql/ast.go:418)."""
        def val(v) -> str:
            if v is None:
                return "null"
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, str):
                escaped = v.replace("\\", "\\\\").replace("'", "\\'")
                return f"'{escaped}'"
            if isinstance(v, list):
                return "[" + ", ".join(val(x) for x in v) + "]"
            if isinstance(v, Call):
                return v.to_pql()
            return repr(v)

        def plain_args(skip=()):
            parts = []
            for k in self.args:
                if k.startswith("_") or k in skip:
                    continue
                v = self.args[k]
                if isinstance(v, Condition):
                    parts.append(f"{k} {v.op} {val(v.value)}")
                else:
                    parts.append(f"{k}={val(v)}")
            return parts

        name = self.name
        if name in ("Set", "Clear"):
            parts = [val(self.args["_col"])] + plain_args()
            if name == "Set" and self.args.get("_timestamp"):
                parts.append(val(self.args["_timestamp"]))
            return f"{name}({', '.join(parts)})"
        if name == "SetColumnAttrs":
            parts = [val(self.args["_col"])] + plain_args()
            return f"{name}({', '.join(parts)})"
        if name == "SetRowAttrs":
            parts = [self.args["_field"], val(self.args["_row"])] \
                + plain_args()
            return f"{name}({', '.join(parts)})"
        if name == "Store":
            parts = [self.children[0].to_pql()] + plain_args()
            return f"{name}({', '.join(parts)})"
        if name in ("TopN", "Rows"):
            parts = [self.args["_field"]] \
                + [c.to_pql() for c in self.children] + plain_args()
            return f"{name}({', '.join(parts)})"
        parts = [c.to_pql() for c in self.children] + plain_args()
        return f"{name}({', '.join(parts)})"

    def __str__(self) -> str:
        parts = [str(c) for c in self.children]
        for k in sorted(self.args):
            v = self.args[k]
            if isinstance(v, Condition):
                parts.append(f"{k} {v}")
            else:
                parts.append(f"{k}={v!r}")
        return f"{self.name}({', '.join(parts)})"


@dataclass
class Query:
    calls: List[Call] = field(default_factory=list)

    def write_calls(self) -> List[Call]:
        return [c for c in self.calls if c.writes()]

    def clone(self) -> "Query":
        return Query([c.clone() for c in self.calls])

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)
