"""ctypes bindings for the native C++ host-runtime library.

The library (native/pilosa_native.cpp) implements the host storage hot
path — roaring file parse/serialize (reference roaring.go:963-1126) with
ops-log replay (roaring.go:3628-3691), and packed-word popcount kernels
(the host analog of roaring.go:2438's intersection-count loop).

The Python implementations in storage/roaring.py remain the reference
semantics and the fallback: if the shared library is missing it is built
on first import with `make` (g++ is in the image); if that fails, callers
get None from load() and use the numpy paths. Set PILOSA_TPU_NO_NATIVE=1
to force the fallback (used by tests to cross-check both paths).

Sanitizer variants (the native correctness plane, docs/development.md):
PILOSA_TPU_NATIVE_SAN=asan|ubsan|tsan selects a
libpilosa_native.{san}.so built with `make SAN=...`
(-fsanitize=... -fno-omit-frame-pointer -g). Availability-gated like
the default build: if the variant cannot be built/loaded, load()
returns None and callers take the Python paths (an unrecognized value
also yields None — silently loading the uninstrumented library would
defeat the point of asking for a sanitizer). ASan/TSan runtimes must be
preloaded into the python process (tools/check.sh --san does this);
under a sanitizer, untrusted input bytes are staged in exact-size libc
malloc buffers so one-byte over-reads land in a redzone instead of
slack inside a Python object.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import subprocess
from pilosa_tpu.utils.locks import make_lock
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

_SAN_VARIANTS = ("asan", "ubsan", "tsan")

_lock = make_lock("native._lock")
# Load results keyed by requested sanitizer variant ('' = plain build):
# a PILOSA_TPU_NATIVE_SAN set AFTER the plain library was first loaded
# must get the instrumented .so, not the cached uninstrumented one (and
# a failed sanitizer load must not poison a later plain request). The
# key space is closed: '' plus _SAN_VARIANTS.
_libs: Dict[str, Optional[ctypes.CDLL]] = {}
_libc: Optional[ctypes.CDLL] = None
_force_python = 0

CONTAINER_WORDS = 1024


def active_san() -> str:
    """The requested sanitizer variant ('' = the plain build). Values
    outside the matrix read as a bogus request: load() then returns
    None rather than silently serving the uninstrumented library."""
    return os.environ.get("PILOSA_TPU_NATIVE_SAN", "").strip().lower()


def _so_path(san: str) -> str:
    name = f"libpilosa_native.{san}.so" if san else "libpilosa_native.so"
    return os.path.join(_NATIVE_DIR, name)


def _build(san: str) -> bool:
    if not os.path.isdir(_NATIVE_DIR):
        return False
    cmd = ["make", "-C", _NATIVE_DIR]
    if san:
        cmd.append(f"SAN={san}")
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return os.path.exists(_so_path(san))
    except (OSError, subprocess.SubprocessError):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64 = ctypes.c_uint64
    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    p_u64 = ctypes.POINTER(u64)
    p_u16 = ctypes.POINTER(ctypes.c_uint16)
    lib.rb_load.argtypes = [p_u8, u64]
    lib.rb_load.restype = ctypes.c_void_p
    lib.rb_error.argtypes = [ctypes.c_void_p]
    lib.rb_error.restype = ctypes.c_char_p
    lib.rb_container_count.argtypes = [ctypes.c_void_p]
    lib.rb_container_count.restype = u64
    lib.rb_op_count.argtypes = [ctypes.c_void_p]
    lib.rb_op_count.restype = u64
    lib.rb_op_small_count.argtypes = [ctypes.c_void_p]
    lib.rb_op_small_count.restype = u64
    lib.rb_ops_bytes.argtypes = [ctypes.c_void_p]
    lib.rb_ops_bytes.restype = u64
    lib.rb_snapshot_bytes.argtypes = [ctypes.c_void_p]
    lib.rb_snapshot_bytes.restype = u64
    lib.rb_tail_dropped.argtypes = [ctypes.c_void_p]
    lib.rb_tail_dropped.restype = u64
    lib.rb_copy_out.argtypes = [ctypes.c_void_p, p_u64, p_u64]
    lib.rb_copy_out.restype = None
    lib.rb_keys.argtypes = [ctypes.c_void_p, p_u64]
    lib.rb_keys.restype = None
    lib.rb_counts.argtypes = [ctypes.c_void_p, p_u64]
    lib.rb_counts.restype = None
    lib.rb_export_split.argtypes = [ctypes.c_void_p, u64, p_u16, p_u64]
    lib.rb_export_split.restype = None
    lib.rb_free.argtypes = [ctypes.c_void_p]
    lib.rb_free.restype = None
    lib.rb_serialize_cap.argtypes = [u64]
    lib.rb_serialize_cap.restype = u64
    lib.rb_serialize.argtypes = [p_u64, p_u64, u64, p_u8]
    lib.rb_serialize.restype = u64
    lib.rb_serialize_ptrs.argtypes = [p_u64, p_u64, u64, p_u8]
    lib.rb_serialize_ptrs.restype = u64
    lib.pn_crc32.argtypes = [p_u8, u64, ctypes.c_uint32]
    lib.pn_crc32.restype = ctypes.c_uint32
    lib.pn_import_build.argtypes = [p_u64, p_u64, u64, ctypes.c_uint32]
    lib.pn_import_build.restype = ctypes.c_void_p
    lib.ib_error.argtypes = [ctypes.c_void_p]
    lib.ib_error.restype = ctypes.c_char_p
    lib.ib_count.argtypes = [ctypes.c_void_p]
    lib.ib_count.restype = u64
    lib.ib_nbits.argtypes = [ctypes.c_void_p]
    lib.ib_nbits.restype = u64
    lib.ib_payload_size.argtypes = [ctypes.c_void_p]
    lib.ib_payload_size.restype = u64
    lib.ib_keys_counts.argtypes = [ctypes.c_void_p, p_u64, p_u64]
    lib.ib_keys_counts.restype = None
    lib.ib_words.argtypes = [ctypes.c_void_p, p_u64]
    lib.ib_words.restype = None
    lib.ib_payload.argtypes = [ctypes.c_void_p, p_u8]
    lib.ib_payload.restype = None
    lib.ib_free.argtypes = [ctypes.c_void_p]
    lib.ib_free.restype = None
    lib.pn_serialize_groups_cap.argtypes = [u64, u64]
    lib.pn_serialize_groups_cap.restype = u64
    lib.pn_serialize_groups.argtypes = [p_u64, p_u16, p_u64, u64, p_u8]
    lib.pn_serialize_groups.restype = u64
    lib.pn_fnv1a32.argtypes = [p_u8, u64, ctypes.c_uint32]
    lib.pn_fnv1a32.restype = ctypes.c_uint32
    lib.pn_popcount.argtypes = [p_u64, u64]
    lib.pn_popcount.restype = u64
    lib.pn_intersection_count.argtypes = [p_u64, p_u64, u64]
    lib.pn_intersection_count.restype = u64
    lib.pn_row_popcounts.argtypes = [p_u64, u64, u64, p_u64]
    lib.pn_row_popcounts.restype = None
    lib.pn_build_masks.argtypes = [p_u64, u64, u64, p_u64, p_u64]
    lib.pn_build_masks.restype = u64
    lib.pn_scatter_rows.argtypes = [p_u16, p_u64, u64, p_u64, u64, p_u64]
    lib.pn_scatter_rows.restype = None
    # The chunk-pointer arrays ride as uint64 address arrays (same ABI as
    # const uint64_t* const* and ~100x cheaper than building per-element
    # ctypes pointer objects).
    lib.pn_popcount_ptrs.argtypes = [p_u64, u64, u64]
    lib.pn_popcount_ptrs.restype = u64
    lib.pn_dense_positions_ptrs.argtypes = [p_u64, u64, u64, p_u64, p_u64]
    lib.pn_dense_positions_ptrs.restype = u64
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Return the bound native library, building it if needed; None if
    unavailable (missing toolchain), disabled via PILOSA_TPU_NO_NATIVE,
    or an unbuildable/unknown PILOSA_TPU_NATIVE_SAN variant was
    requested."""
    if os.environ.get("PILOSA_TPU_NO_NATIVE"):
        return None
    san = active_san()
    if san and san not in _SAN_VARIANTS:
        return None
    with _lock:
        if san in _libs:
            return _libs[san]
        so_path = _so_path(san)
        lib: Optional[ctypes.CDLL] = None
        # Always run make: it is mtime-based (a no-op when fresh) and
        # rebuilds a stale .so whose symbols predate these bindings.
        # graftlint: disable=GL009 — build-once critical section: the
        # lock EXISTS to make every caller wait for the single
        # first-touch make; there is nothing useful to do before the
        # library is bound, so blocking under it is the point.
        if _build(san) or os.path.exists(so_path):
            try:
                lib = _bind(ctypes.CDLL(so_path))
            except (OSError, AttributeError):
                # AttributeError = missing symbol in a stale library
                # that make could not refresh; OSError also covers a
                # sanitizer runtime that is not preloaded into this
                # process. Fall back to the Python paths either way.
                lib = None
        # graftlint: disable=GL008 — closed key space ('' + 3 variants)
        _libs[san] = lib
        return lib


def available() -> bool:
    return _force_python == 0 and load() is not None


@contextlib.contextmanager
def force_python() -> Iterator[None]:
    """Make available() report False inside the block, routing every
    caller that gates on it (storage/roaring.py) onto the pure-Python
    paths. Direct entry points (roaring_load_ex etc.) keep working: the
    differential oracle parses natively while forcing the Python
    reader. Reentrant; used by the fuzzer and the differential tests."""
    global _force_python
    _force_python += 1
    try:
        yield
    finally:
        _force_python -= 1


def _as_u64_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


class _StagedBytes:
    """Untrusted input bytes staged for a native call.

    Plain build: a ctypes copy of the data (the pre-existing path).
    Sanitizer build: an EXACT-size libc malloc block instead — ASan
    intercepts malloc and places redzones at the precise boundary, so a
    one-past-the-end read in the parser faults immediately. A ctypes
    array cannot give that: its bytes sit inline in the Python object
    (or inside a pymalloc arena), where an over-read lands in
    uninstrumented slack and is silent.
    """

    def __init__(self, data: bytes):
        self._raw = None
        self._libc = None
        if active_san():
            global _libc
            if _libc is None:
                libc = ctypes.CDLL(None)
                libc.malloc.argtypes = [ctypes.c_size_t]
                libc.malloc.restype = ctypes.c_void_p
                libc.free.argtypes = [ctypes.c_void_p]
                libc.free.restype = None
                _libc = libc
            raw = _libc.malloc(max(len(data), 1))
            if raw:
                self._raw = raw
                self._libc = _libc
                ctypes.memmove(raw, data, len(data))
                self.ptr = ctypes.cast(
                    raw, ctypes.POINTER(ctypes.c_uint8))
                return
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        self._buf = buf  # keepalive
        self.ptr = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))

    def __enter__(self) -> "ctypes.POINTER(ctypes.c_uint8)":
        return self.ptr

    def __exit__(self, *exc) -> None:
        if self._raw is not None:
            self._libc.free(self._raw)
            self._raw = None


def _as_u8_ptr(buf) -> "ctypes.POINTER(ctypes.c_uint8)":
    return ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))


class NativeParseError(ValueError):
    pass


def roaring_load(data: bytes
                 ) -> Optional[Tuple[List[int], np.ndarray, int, int]]:
    """Parse a roaring file (snapshot + ops log) natively.

    Returns (sorted container keys, dense words [n, 1024] uint64, op count,
    torn-tail bytes dropped), or None when the native library is
    unavailable. Raises NativeParseError on malformed input (same
    conditions as the Python reader; a truncated FINAL op is tolerated
    and reported via the last tuple element instead)."""
    ex = roaring_load_ex(data)
    if ex is None:
        return None
    return ex["keys"], ex["words"], ex["op_n"], ex["tail_dropped"]


def roaring_load_ex(data: bytes,
                    split_max_card: Optional[int] = None
                    ) -> Optional[dict]:
    """roaring_load plus the op-log accounting the snapshot policy needs:
    {keys, op_n, op_n_small, ops_bytes, snapshot_bytes, tail_dropped}
    and the container payload. None when unavailable.

    Default payload: "words" — every container dense [n, 1024]. With
    split_max_card set, the payload is encoding-split instead: "counts"
    (u64[n]), "lows" (u16 positions of all containers whose cardinality
    is <= split_max_card, concatenated in key order) and "dense"
    ([n_dense, 1024] for the rest) — a sparse 16k-container fragment
    then loads ~2 MB instead of materializing 128 MB dense and
    re-optimizing."""
    lib = load()
    if lib is None:
        return None
    # The staged buffer must outlive rb_free: compact-mode handles keep
    # refs into the input bytes across the accessor calls below.
    with _StagedBytes(data) as buf:
        h = lib.rb_load(buf, len(data))
        if not h:
            raise MemoryError("rb_load allocation failed")
        try:
            err = lib.rb_error(h)
            if err:
                raise NativeParseError(err.decode())
            n = lib.rb_container_count(h)
            keys = np.empty(n, dtype=np.uint64)
            out = {
                "op_n": int(lib.rb_op_count(h)),
                "op_n_small": int(lib.rb_op_small_count(h)),
                "ops_bytes": int(lib.rb_ops_bytes(h)),
                "snapshot_bytes": int(lib.rb_snapshot_bytes(h)),
                "tail_dropped": int(lib.rb_tail_dropped(h)),
            }
            if split_max_card is None:
                words = np.empty((n, CONTAINER_WORDS), dtype=np.uint64)
                if n:
                    lib.rb_copy_out(h, _as_u64_ptr(keys),
                                    _as_u64_ptr(words))
                out["keys"] = [int(k) for k in keys]
                out["words"] = words
                return out
            counts = np.empty(n, dtype=np.uint64)
            if n:
                lib.rb_keys(h, _as_u64_ptr(keys))
                lib.rb_counts(h, _as_u64_ptr(counts))
            arr_mask = counts <= split_max_card
            lows = np.empty(int(counts[arr_mask].sum()), dtype=np.uint16)
            dense = np.empty((int((~arr_mask).sum()), CONTAINER_WORDS),
                             dtype=np.uint64)
            if n:
                lib.rb_export_split(
                    h, split_max_card,
                    lows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                    _as_u64_ptr(dense))
            out["keys"] = [int(k) for k in keys]
            out["counts"] = counts
            out["lows"] = lows
            out["dense"] = dense
            return out
        finally:
            lib.rb_free(h)


def roaring_serialize(keys: np.ndarray, words: np.ndarray) -> Optional[bytes]:
    """Serialize sorted non-empty dense containers to the file format.
    keys: uint64[n]; words: uint64[n, 1024]. None when unavailable."""
    lib = load()
    if lib is None:
        return None
    n = len(keys)
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    words = np.ascontiguousarray(words, dtype=np.uint64)
    # numpy buffer + one slicing copy out — (ctypes array; bytearray(out))
    # copied the full worst-case capacity twice and dominated snapshot
    # time for large fragments.
    out = np.empty(int(lib.rb_serialize_cap(n)), dtype=np.uint8)
    size = lib.rb_serialize(_as_u64_ptr(keys), _as_u64_ptr(words), n,
                            out.ctypes.data_as(
                                ctypes.POINTER(ctypes.c_uint8)))
    if size == 0 and n > 0:
        raise ValueError("rb_serialize: empty container passed")
    return out[:size].tobytes()


def roaring_serialize_ptrs(keys: np.ndarray, containers) -> Optional[bytes]:
    """Like roaring_serialize but over independently-allocated dense
    containers (a list of uint64[1024] arrays) — no stacking copy."""
    lib = load()
    if lib is None:
        return None
    n = len(keys)
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    addrs = np.fromiter(
        (c.__array_interface__["data"][0] for c in containers),
        dtype=np.uint64, count=n)
    out = np.empty(int(lib.rb_serialize_cap(n)), dtype=np.uint8)
    size = lib.rb_serialize_ptrs(_as_u64_ptr(keys), _as_u64_ptr(addrs), n,
                                 out.ctypes.data_as(
                                     ctypes.POINTER(ctypes.c_uint8)))
    if size == 0 and n > 0:
        raise ValueError("rb_serialize_ptrs: empty container passed")
    return out[:size].tobytes()


def import_build(row_ids: np.ndarray, col_ids: np.ndarray,
                 swidth_exp: int):
    """Fused bulk import build: positions = row*2^swidth_exp +
    (col mod 2^swidth_exp) scattered into dense container masks (no
    sort), popcounted, and pre-serialized as an OP_ADD_ROARING payload
    — one native call. Returns (keys uint64[m] sorted,
    words uint64[m, 1024], counts uint64[m], payload bytes, n_bits) or
    None when unavailable / the batch's row range is unsuited to dense
    scatter (caller falls back to the grouped numpy path)."""
    lib = load()
    if lib is None:
        return None
    row_ids = np.ascontiguousarray(row_ids, dtype=np.uint64)
    col_ids = np.ascontiguousarray(col_ids, dtype=np.uint64)
    h = lib.pn_import_build(_as_u64_ptr(row_ids), _as_u64_ptr(col_ids),
                            len(row_ids), swidth_exp)
    if not h:
        raise MemoryError("pn_import_build allocation failed")
    try:
        if lib.ib_error(h):
            return None
        m = int(lib.ib_count(h))
        keys = np.empty(m, dtype=np.uint64)
        counts = np.empty(m, dtype=np.uint64)
        words = np.empty((m, CONTAINER_WORDS), dtype=np.uint64)
        payload = np.empty(int(lib.ib_payload_size(h)), dtype=np.uint8)
        if m:
            lib.ib_keys_counts(h, _as_u64_ptr(keys), _as_u64_ptr(counts))
            lib.ib_words(h, _as_u64_ptr(words))
            lib.ib_payload(h, payload.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)))
        return keys, words, counts, payload.tobytes(), int(lib.ib_nbits(h))
    finally:
        lib.ib_free(h)


def serialize_groups(keys: np.ndarray, lows: np.ndarray,
                     bounds: np.ndarray) -> Optional[bytes]:
    """Roaring snapshot payload from pre-grouped sorted-unique
    positions: keys uint64[m] ascending, lows uint16[n] (all groups
    back to back), bounds uint64[m+1] offsets. None when unavailable."""
    lib = load()
    if lib is None:
        return None
    m = len(keys)
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    lows = np.ascontiguousarray(lows, dtype=np.uint16)
    bounds = np.ascontiguousarray(bounds, dtype=np.uint64)
    out = np.empty(int(lib.pn_serialize_groups_cap(m, len(lows))),
                   dtype=np.uint8)
    size = lib.pn_serialize_groups(
        _as_u64_ptr(keys),
        lows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        _as_u64_ptr(bounds), m,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if size == (1 << 64) - 1:
        # Native execution failure (OOM/thread spawn) — distinct from
        # bad bounds; None routes callers to the Python serializer.
        return None
    if size == 0 and m > 0:
        raise ValueError("pn_serialize_groups: bad group bounds")
    return out[:size].tobytes()


def fnv1a32(chunks, seed: int = 0x811C9DC5) -> Optional[int]:
    """Chained fnv1a32 over byte chunks; None when unavailable."""
    lib = load()
    if lib is None:
        return None
    h = seed
    for c in chunks:
        # Zero-copy: bytes objects pin their buffer; cast the address
        # directly instead of copying multi-MB batch payloads.
        c = bytes(c) if not isinstance(c, bytes) else c
        buf = ctypes.cast(ctypes.c_char_p(c),
                          ctypes.POINTER(ctypes.c_uint8))
        h = lib.pn_fnv1a32(buf, len(c), h)
    return h


def popcount(words: np.ndarray) -> Optional[int]:
    lib = load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib.pn_popcount(_as_u64_ptr(words), words.size))


def intersection_count(a: np.ndarray, b: np.ndarray) -> Optional[int]:
    lib = load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, dtype=np.uint64)
    b = np.ascontiguousarray(b, dtype=np.uint64)
    assert a.size == b.size
    return int(lib.pn_intersection_count(_as_u64_ptr(a), _as_u64_ptr(b),
                                         a.size))


def row_popcounts(words: np.ndarray) -> Optional[np.ndarray]:
    """words: uint64[rows, words_per_row] → uint64[rows] popcounts."""
    lib = load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.uint64)
    rows, wpr = words.shape
    out = np.empty(rows, dtype=np.uint64)
    lib.pn_row_popcounts(_as_u64_ptr(words), rows, wpr, _as_u64_ptr(out))
    return out


def build_masks(positions: np.ndarray, m: int):
    """Dense container masks for sorted positions grouped by pos>>16.
    Returns (keys uint64[m], words uint64[m, 1024]) or None when the
    native library is unavailable. `m` = distinct key count (callers have
    it from np.unique)."""
    lib = load()
    if lib is None:
        return None
    positions = np.ascontiguousarray(positions, dtype=np.uint64)
    keys = np.empty(m, dtype=np.uint64)
    words = np.zeros((m, CONTAINER_WORDS), dtype=np.uint64)
    got = lib.pn_build_masks(_as_u64_ptr(positions), len(positions), m,
                             _as_u64_ptr(keys), _as_u64_ptr(words))
    if got != m:
        raise ValueError(f"pn_build_masks: {got} groups, expected {m}")
    return keys, words


def dense_positions_of(containers, bases: np.ndarray
                       ) -> Optional[np.ndarray]:
    """Like dense_positions but over a list of independently-allocated
    dense containers (uint64, C-contiguous, equal length) — avoids
    stacking them into one copy. None when unavailable."""
    lib = load()
    if lib is None or not containers:
        return None if lib is None else np.empty(0, dtype=np.uint64)
    wpc = containers[0].size
    # __array_interface__ hands back the raw address without building a
    # ctypes pointer object per container (the hot-loop cost at ~10k
    # containers per call).
    addrs = np.fromiter(
        (c.__array_interface__["data"][0] for c in containers),
        dtype=np.uint64, count=len(containers))
    ptrs = _as_u64_ptr(addrs)
    bases = np.ascontiguousarray(bases, dtype=np.uint64)
    n = int(lib.pn_popcount_ptrs(ptrs, len(containers), wpc))
    out = np.empty(n, dtype=np.uint64)
    got = lib.pn_dense_positions_ptrs(ptrs, len(containers), wpc,
                                      _as_u64_ptr(bases), _as_u64_ptr(out))
    if got != n:
        raise ValueError(f"pn_dense_positions_ptrs wrote {got}, "
                         f"expected {n}")
    return out


def scatter_rows(pos: np.ndarray, lens: np.ndarray, row_index: np.ndarray,
                 words64: int, out: np.ndarray) -> bool:
    """Scatter concatenated per-row u16 positions into `out` (u64,
    row-major, width words64). Returns False when unavailable."""
    lib = load()
    if lib is None:
        return False
    pos = np.ascontiguousarray(pos, dtype=np.uint16)
    lens = np.ascontiguousarray(lens, dtype=np.uint64)
    row_index = np.ascontiguousarray(row_index, dtype=np.uint64)
    lib.pn_scatter_rows(
        pos.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        _as_u64_ptr(lens), len(lens), _as_u64_ptr(row_index),
        words64, _as_u64_ptr(out))
    return True
