"""TopN row-count caches.

Mirrors the reference cache layer (/root/reference/cache.go:35 `cache`
interface; rankCache :136, lruCache :58, nopCache). On TPU a full popcount
sweep over a fragment's row bank is one fused kernel, so the cache is a
latency optimization (skip the sweep for hot fragments), not a correctness
requirement as in the reference — `TopN` falls back to exact device
recounts whenever the cache is cold or invalidated.

Persistence: `.cache` sidecar file of little-endian (uint64 id, uint64
count) pairs (the reference persists protobuf Pairs, fragment.go:1858;
the on-disk encoding here is our own).
"""

from __future__ import annotations

import os
import struct
from collections import OrderedDict
from typing import Dict, Iterable, List, Tuple

THRESHOLD_FACTOR = 1.1

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"
DEFAULT_CACHE_SIZE = 50000


class RankedCache:
    """Keeps the top `size` rows by count; entries below the current
    threshold are rejected once the cache is full (reference rankCache
    recalculation, cache.go:245).

    Saturation: on this framework the ranked cache serves reads ONLY
    while it provably holds every present row (TopN's warm shortcut,
    executor._topn_cached_counts) — unlike the reference, whose TopN
    approximates from a partial cache (fragment.go:1067). So the moment
    cardinality exceeds the bound (an eviction or threshold rejection
    happens), the cache can never serve a read again until invalidated,
    and maintaining it further is pure write-path cost: `saturated`
    latches, add() becomes O(1), and Fragment skips the row recounts
    that fed it (the resolution of VERDICT r2 weak #7)."""

    def __init__(self, size: int = DEFAULT_CACHE_SIZE):
        self.size = size
        self.counts: Dict[int, int] = {}
        self._threshold = 0
        self.saturated = False

    def add(self, row_id: int, count: int) -> None:
        if self.saturated:
            return
        if count == 0:
            self.counts.pop(row_id, None)
            return
        if (len(self.counts) >= self.size * THRESHOLD_FACTOR
                and count < self._threshold and row_id not in self.counts):
            self.saturated = True
            return
        self.counts[row_id] = count
        if len(self.counts) > self.size * THRESHOLD_FACTOR:
            self._recalculate()
            self.saturated = True

    bulk_add = add

    def get(self, row_id: int) -> int:
        return self.counts.get(row_id, 0)

    def ids(self) -> List[int]:
        return sorted(self.counts)

    def top(self) -> List[Tuple[int, int]]:
        """(row_id, count) sorted by count desc, id asc, trimmed to size."""
        pairs = sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return pairs[: self.size]

    def _recalculate(self) -> None:
        pairs = self.top()
        self.counts = dict(pairs)
        self._threshold = pairs[-1][1] if len(pairs) >= self.size else 0

    def invalidate(self) -> None:
        self.counts.clear()
        self._threshold = 0
        self.saturated = False

    def __len__(self) -> int:
        return len(self.counts)


class LRUCache:
    """LRU variant (reference lruCache, cache.go:58 / lru/lru.go)."""

    def __init__(self, size: int = DEFAULT_CACHE_SIZE):
        self.size = size
        self.counts: "OrderedDict[int, int]" = OrderedDict()

    def add(self, row_id: int, count: int) -> None:
        if row_id in self.counts:
            self.counts.move_to_end(row_id)
        self.counts[row_id] = count
        while len(self.counts) > self.size:
            self.counts.popitem(last=False)

    bulk_add = add

    def get(self, row_id: int) -> int:
        if row_id in self.counts:
            self.counts.move_to_end(row_id)
            return self.counts[row_id]
        return 0

    def ids(self) -> List[int]:
        return sorted(self.counts)

    def top(self) -> List[Tuple[int, int]]:
        return sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))

    def invalidate(self) -> None:
        self.counts.clear()

    def __len__(self) -> int:
        return len(self.counts)


class NopCache:
    size = 0

    def add(self, row_id: int, count: int) -> None:
        pass

    bulk_add = add

    def get(self, row_id: int) -> int:
        return 0

    def ids(self) -> List[int]:
        return []

    def top(self) -> List[Tuple[int, int]]:
        return []

    def invalidate(self) -> None:
        pass

    def __len__(self) -> int:
        return 0


def new_cache(cache_type: str, size: int):
    if cache_type == CACHE_TYPE_RANKED:
        return RankedCache(size)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(size)
    if cache_type == CACHE_TYPE_NONE:
        return NopCache()
    raise ValueError(f"invalid cache type: {cache_type}")


# Sidecar header magic; bumped when the format changes. v2 adds a stamp
# binding the sidecar to the exact storage bytes it was computed from, so
# a cache written before ops that reached disk without a clean close can
# never be mistaken for complete (TopN's warm-cache shortcut relies on
# completeness implying exactness).
CACHE_MAGIC = 0x70635632  # "pcV2"


def save_cache(cache, path: str, stamp: bytes = b"") -> None:
    # A saturated ranked cache stopped tracking writes: its counts may
    # be stale and it can never serve a read, so persist it empty (a
    # cold reload) rather than as plausible-looking numbers.
    pairs = [] if getattr(cache, "saturated", False) else cache.top()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<IH", CACHE_MAGIC, len(stamp)))
        f.write(stamp)
        f.write(struct.pack("<Q", len(pairs)))
        for row_id, count in pairs:
            f.write(struct.pack("<QQ", row_id, count))
    os.replace(tmp, path)


def load_cache(cache, path: str, stamp: bytes = b"") -> bool:
    """Load the sidecar into `cache`. Returns False (loading nothing) when
    the file is absent, pre-v2, or its stamp does not match `stamp` —
    i.e. the storage bytes changed since the cache was saved."""
    if not os.path.exists(path):
        return False
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 6:
        return False
    magic, stamp_len = struct.unpack_from("<IH", data, 0)
    if magic != CACHE_MAGIC:
        return False  # legacy/foreign sidecar: treat as cold
    off = 6 + stamp_len
    if data[6:off] != stamp:
        return False
    (n,) = struct.unpack_from("<Q", data, off)
    off += 8
    for i in range(n):
        row_id, count = struct.unpack_from("<QQ", data, off + 16 * i)
        cache.add(row_id, count)
    return True


class Pairs:
    """Merge helper for reducing TopN results across shards (reference
    Pairs.Add, cache.go:356)."""

    @staticmethod
    def merge(*pair_lists: Iterable[Tuple[int, int]]) -> List[Tuple[int, int]]:
        acc: Dict[int, int] = {}
        for pairs in pair_lists:
            for row_id, count in pairs:
                acc[row_id] = acc.get(row_id, 0) + count
        return sorted(acc.items(), key=lambda kv: (-kv[1], kv[0]))
