"""TopN row-count caches.

Mirrors the reference cache layer (/root/reference/cache.go:35 `cache`
interface; rankCache :136, lruCache :58, nopCache). On TPU a full popcount
sweep over a fragment's row bank is one fused kernel, so the cache is a
latency optimization (skip the sweep for hot fragments), not a correctness
requirement as in the reference — `TopN` falls back to exact device
recounts whenever the cache is cold or invalidated.

Persistence: `.cache` sidecar file of little-endian (uint64 id, uint64
count) pairs (the reference persists protobuf Pairs, fragment.go:1858;
the on-disk encoding here is our own).
"""

from __future__ import annotations

import heapq
import os
import struct
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from pilosa_tpu.utils.locks import make_lock

THRESHOLD_FACTOR = 1.1

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"
DEFAULT_CACHE_SIZE = 50000


class RankedCache:
    """Keeps the top `size` rows by count; entries below the current
    threshold are rejected once the cache is full (reference rankCache
    recalculation, cache.go:245).

    Saturation: on this framework the ranked cache serves reads ONLY
    while it provably holds every present row (TopN's warm shortcut,
    executor._topn_cached_counts) — unlike the reference, whose TopN
    approximates from a partial cache (fragment.go:1067). So the moment
    cardinality exceeds the bound (an eviction or threshold rejection
    happens), the cache can never serve a read again until invalidated,
    and maintaining it further is pure write-path cost: `saturated`
    latches, add() becomes O(1), and Fragment skips the row recounts
    that fed it (the resolution of VERDICT r2 weak #7)."""

    def __init__(self, size: int = DEFAULT_CACHE_SIZE):
        self.size = size
        self.counts: Dict[int, int] = {}
        self._threshold = 0
        self.saturated = False
        # One executor serves every request thread, and the sampled
        # warm-cache self-check repairs caches from a request thread
        # while write paths keep refreshing them — mutations must not
        # interleave. Reads of `counts` stay lock-free by design
        # (invalidate/recalculate REBIND the dict instead of mutating
        # it in place, so a concurrent reader sees one consistent
        # snapshot).
        self._lock = make_lock("RankedCache._lock")

    def add(self, row_id: int, count: int) -> None:
        with self._lock:
            if self.saturated:
                return
            if count == 0:
                self.counts.pop(row_id, None)
                return
            if (len(self.counts) >= self.size * THRESHOLD_FACTOR
                    and count < self._threshold
                    and row_id not in self.counts):
                self.saturated = True
                return
            self.counts[row_id] = count
            if len(self.counts) > self.size * THRESHOLD_FACTOR:
                self._recalculate()
                self.saturated = True

    bulk_add = add

    def get(self, row_id: int) -> int:
        return self.counts.get(row_id, 0)

    def ids(self) -> List[int]:
        return sorted(self.counts)

    def top(self) -> List[Tuple[int, int]]:
        """(row_id, count) sorted by count desc, id asc, trimmed to size."""
        pairs = sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return pairs[: self.size]

    def _recalculate(self) -> None:
        """Batch top-`size` selection (lock held): heapq.nlargest is
        O(n log size) against the former full sort's O(n log n), and
        the survivors land in a FRESH dict (rebind, not in-place) so
        lock-free readers never observe a half-pruned map."""
        pairs = heapq.nlargest(self.size, self.counts.items(),
                               key=lambda kv: (kv[1], -kv[0]))
        self.counts = dict(pairs)
        self._threshold = pairs[-1][1] if len(pairs) >= self.size else 0

    def invalidate(self) -> None:
        # O(1): rebind instead of clear() — clear() walks every slot
        # under the lock AND yanks the dict out from under lock-free
        # readers mid-iteration.
        with self._lock:
            self.counts = {}
            self._threshold = 0
            self.saturated = False

    def __len__(self) -> int:
        return len(self.counts)


class LRUCache:
    """LRU variant (reference lruCache, cache.go:58 / lru/lru.go).
    Mutations are lock-guarded like RankedCache; get() recency-touches
    and therefore locks too."""

    def __init__(self, size: int = DEFAULT_CACHE_SIZE):
        self.size = size
        self.counts: "OrderedDict[int, int]" = OrderedDict()
        self._lock = make_lock("LRUCache._lock")

    def add(self, row_id: int, count: int) -> None:
        with self._lock:
            if row_id in self.counts:
                self.counts.move_to_end(row_id)
            self.counts[row_id] = count
            while len(self.counts) > self.size:
                self.counts.popitem(last=False)

    bulk_add = add

    def get(self, row_id: int) -> int:
        with self._lock:
            if row_id in self.counts:
                self.counts.move_to_end(row_id)
                return self.counts[row_id]
            return 0

    def ids(self) -> List[int]:
        return sorted(self.counts)

    def top(self) -> List[Tuple[int, int]]:
        return sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))

    def invalidate(self) -> None:
        with self._lock:
            self.counts = OrderedDict()

    def __len__(self) -> int:
        return len(self.counts)


class NopCache:
    size = 0

    def add(self, row_id: int, count: int) -> None:
        pass

    bulk_add = add

    def get(self, row_id: int) -> int:
        return 0

    def ids(self) -> List[int]:
        return []

    def top(self) -> List[Tuple[int, int]]:
        return []

    def invalidate(self) -> None:
        pass

    def __len__(self) -> int:
        return 0


def new_cache(cache_type: str, size: int):
    if cache_type == CACHE_TYPE_RANKED:
        return RankedCache(size)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(size)
    if cache_type == CACHE_TYPE_NONE:
        return NopCache()
    raise ValueError(f"invalid cache type: {cache_type}")


# Sidecar header magic; bumped when the format changes. v2 adds a stamp
# binding the sidecar to the exact storage bytes it was computed from, so
# a cache written before ops that reached disk without a clean close can
# never be mistaken for complete (TopN's warm-cache shortcut relies on
# completeness implying exactness).
CACHE_MAGIC = 0x70635632  # "pcV2"


def save_cache(cache, path: str, stamp: bytes = b"") -> None:
    # A saturated ranked cache stopped tracking writes: its counts may
    # be stale and it can never serve a read, so persist it empty (a
    # cold reload) rather than as plausible-looking numbers.
    pairs = [] if getattr(cache, "saturated", False) else cache.top()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<IH", CACHE_MAGIC, len(stamp)))
        f.write(stamp)
        f.write(struct.pack("<Q", len(pairs)))
        for row_id, count in pairs:
            f.write(struct.pack("<QQ", row_id, count))
    os.replace(tmp, path)


def load_cache(cache, path: str, stamp: bytes = b"") -> bool:
    """Load the sidecar into `cache`. Returns False (loading nothing) when
    the file is absent, pre-v2, or its stamp does not match `stamp` —
    i.e. the storage bytes changed since the cache was saved."""
    if not os.path.exists(path):
        return False
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 6:
        return False
    magic, stamp_len = struct.unpack_from("<IH", data, 0)
    if magic != CACHE_MAGIC:
        return False  # legacy/foreign sidecar: treat as cold
    off = 6 + stamp_len
    if data[6:off] != stamp:
        return False
    (n,) = struct.unpack_from("<Q", data, off)
    off += 8
    for i in range(n):
        row_id, count = struct.unpack_from("<QQ", data, off + 16 * i)
        cache.add(row_id, count)
    return True


# ---------------------------------------------------------------------
# Device-resident rank cache (ROADMAP item 3b): the RankedCache idea —
# per-row counts maintained so TopN never rescans rows (reference
# cache.go:136) — promoted onto a [row_capacity] device vector in HBM.
# Where the host RankedCache dies the moment cardinality exceeds its
# bound (saturation latch above), the device vector covers EVERY bank
# slot at 4 bytes/row, so leaderboard TopN over a warm bank becomes a
# device top-k over precomputed counts instead of a [R, S, W] popcount
# sweep. Entries validate lazily against fragment write versions:
# unchanged versions reuse the vector as-is, small churn patches only
# the written rows (executor._rank_counts), anything else rebuilds
# with the one sweep TopN would have paid anyway.

# Kill switch (mirrors PILOSA_TPU_RESULT_CACHE for the result tier).
RANK_CACHE_ENV = "PILOSA_TPU_RANK_CACHE"


def _rank_env_enabled() -> bool:
    return os.environ.get(RANK_CACHE_ENV, "1") != "0"


class RankEntry:
    """One cached per-row count vector: `counts` is a device [Rcap]
    array aligned with the ViewBank slot layout it was computed from.
    `row_ids` is the SLOT-ordered row tuple of that bank (not the
    sorted row set): equality proves the exact slot layout matches, so
    the vector — and any incremental patch scattered into it — indexes
    the same rows. Append-grown banks (`_patch_bank`) and freshly
    sorted rebuilds hold the same rows in different slots; sorted-set
    equality would wrongly validate across that."""

    __slots__ = ("versions", "row_ids", "counts", "nbytes")

    def __init__(self, versions: Dict[int, int], row_ids: tuple,
                 counts: Any, nbytes: int) -> None:
        self.versions = versions    # {shard: fragment.version} at build
        self.row_ids = row_ids      # slot-ordered row-id tuple
        self.counts = counts        # device [Rcap] int32
        self.nbytes = nbytes


class RankCacheStore:
    """Process-wide LRU registry of RankEntry vectors, keyed
    (view identity, shard tuple, width) — the BankBudget idiom for a
    much smaller resource (4 B/row vs 4*S*W B/row for the bank
    itself). Bounded by entry count; every admit/evict is mirrored
    into the HBM memory ledger under category "rank_cache" so
    /debug/memory totals stay provable and the watchdog sees it."""

    def __init__(self, max_entries: int = 64) -> None:
        self.enabled = _rank_env_enabled()
        self.max_entries = max(1, int(max_entries))
        self._lock = make_lock("RankCacheStore._lock")
        self._entries: "OrderedDict[tuple, Tuple[Any, RankEntry]]" = \
            OrderedDict()
        self.evictions = 0
        # Entries dropped because a resize moved shard ownership
        # (server/api.py _note_placement_change).
        self.placement_invalidations = 0

    def configure(self, enabled: Optional[bool] = None,
                  max_entries: Optional[int] = None) -> None:
        """[cache] config wiring; the env kill switch always wins."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled) and _rank_env_enabled()
            if max_entries is not None:
                self.max_entries = max(1, int(max_entries))

    def get(self, view: Any, key: tuple) -> Optional[RankEntry]:
        with self._lock:
            ent = self._entries.get((id(view), key))
            if ent is None:
                return None
            self._entries.move_to_end((id(view), key))
            return ent[1]

    def put(self, view: Any, key: tuple, entry: RankEntry) -> None:
        from pilosa_tpu.utils.memledger import LEDGER
        ek = (id(view), key)
        with self._lock:
            self._entries.pop(ek, None)
            while len(self._entries) >= self.max_entries:
                (_vid, vkey), (v, _e) = self._entries.popitem(last=False)
                self.evictions += 1
                # Under the store lock (ledger lock is a leaf): an
                # evict/re-put interleave must not unregister another
                # thread's freshly registered entry. The ledger scopes
                # owner-registered keys to the owner, so unregister
                # must name the same (owner, key) pair register did.
                LEDGER.unregister("rank_cache", vkey, owner=v)
            self._entries[ek] = (view, entry)
            LEDGER.register(
                "rank_cache", key, entry.nbytes, owner=view,
                index=getattr(view, "index", ""),
                field=getattr(view, "field", ""),
                view=getattr(view, "name", ""),
                rows=len(entry.row_ids))

    def forget_view(self, view: Any) -> None:
        """Drop every entry of a closing view (View.close calls this);
        ledger rows unregister so /debug/memory never counts freed
        HBM."""
        from pilosa_tpu.utils.memledger import LEDGER
        vid = id(view)
        with self._lock:
            dead = [ek for ek in self._entries if ek[0] == vid]
            for ek in dead:
                self._entries.pop(ek, None)
                LEDGER.unregister("rank_cache", ek[1], owner=view)

    def clear(self) -> None:
        from pilosa_tpu.utils.memledger import LEDGER
        with self._lock:
            for ek, (v, _e) in list(self._entries.items()):
                self._entries.pop(ek, None)
                LEDGER.unregister("rank_cache", ek[1], owner=v)

    def invalidate_shards(self, moved: Any) -> int:
        """Drop entries whose count vector covers a shard whose owner
        set changed in a resize (`moved`: set of ``(index, shard)``
        pairs). The per-shard version stamps already refuse a stale
        reuse; this reclaims the HBM at the placement transition and
        makes the drop observable (placement_invalidations). Returns
        the number of entries dropped."""
        if not moved:
            return 0
        from pilosa_tpu.utils.memledger import LEDGER
        by_index: Dict[str, set] = {}
        for iname, shard in moved:
            by_index.setdefault(str(iname), set()).add(int(shard))
        with self._lock:
            dead = []
            for ek, (v, e) in self._entries.items():
                shs = by_index.get(str(getattr(v, "index", "")))
                if shs and shs & {int(s) for s in e.versions}:
                    dead.append(ek)
            for ek in dead:
                v, _e = self._entries.pop(ek)
                LEDGER.unregister("rank_cache", ek[1], owner=v)
            self.placement_invalidations += len(dead)
            return len(dead)

    def nbytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for _, e in self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "entries": len(self._entries),
                "bytes": sum(e.nbytes
                             for _, e in self._entries.values()),
                "maxEntries": self.max_entries,
                "evictions": self.evictions,
                "placementInvalidations": self.placement_invalidations,
            }


# The process-wide rank-cache store (one process, one HBM pool — the
# BANK_BUDGET convention).
RANK_CACHE = RankCacheStore()


class Pairs:
    """Merge helper for reducing TopN results across shards (reference
    Pairs.Add, cache.go:356)."""

    @staticmethod
    def merge(*pair_lists: Iterable[Tuple[int, int]]) -> List[Tuple[int, int]]:
        acc: Dict[int, int] = {}
        for pairs in pair_lists:
            for row_id, count in pairs:
                acc[row_id] = acc.get(row_id, 0) + count
        return sorted(acc.items(), key=lambda kv: (-kv[1], kv[0]))
