"""Holder: owns every index under one data directory.

Reference: /root/reference/holder.go:50. Responsibilities kept here: open =
walk the data dir rebuilding schema from the directory tree + `.meta` files
(holder.go:132), periodic cache flush (holder.go:487-530), node ID
persistence (holder.go:580). The anti-entropy syncer lives in
pilosa_tpu/parallel (it needs the cluster view).
"""

from __future__ import annotations

import os
import shutil
from pilosa_tpu.utils.locks import make_rlock
import uuid
from typing import Dict, List, Optional

from pilosa_tpu.core.index import Index


class Holder:
    def __init__(self, path: str):
        self.path = path
        self.indexes: Dict[str, Index] = {}
        self._lock = make_rlock("Holder._lock")
        self.node_id: Optional[str] = None
        self.on_new_shard = None  # callback(index, field, shard)

    def open(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        self._raise_file_limit()
        self.node_id = self._load_node_id()
        for name in sorted(os.listdir(self.path)):
            ipath = os.path.join(self.path, name)
            if not os.path.isdir(ipath) or name.startswith("."):
                continue
            idx = Index(ipath, name)
            idx.open()
            idx.on_new_shard = self._notify_shard
            self.indexes[name] = idx

    @staticmethod
    def _raise_file_limit() -> None:
        """Raise RLIMIT_NOFILE to its hard limit (reference
        holder.go:532): every open fragment keeps an op-log append
        handle, and a 1024-shard index breaches the common 1024-fd
        default immediately."""
        try:
            import resource
            soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
            # RLIM_INFINITY is -1: a signed soft < hard comparison would
            # skip the raise exactly when the hard limit is unlimited.
            if hard == resource.RLIM_INFINITY or soft < hard:
                try:
                    resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
                except (ValueError, OSError):
                    # Some kernels refuse an infinite soft limit (macOS
                    # caps at kern.maxfilesperproc); fall back to a large
                    # finite value below the refusal point.
                    finite = 10240 if hard == resource.RLIM_INFINITY \
                        else min(hard, 10240)
                    if finite > soft:
                        resource.setrlimit(resource.RLIMIT_NOFILE,
                                           (finite, hard))
        except (ImportError, ValueError, OSError):
            pass  # best effort; not available on all platforms

    def close(self) -> None:
        with self._lock:
            for idx in self.indexes.values():
                idx.close()

    def _load_node_id(self) -> str:
        """Stable node identity persisted to `.id` (reference holder.go:580)."""
        id_path = os.path.join(self.path, ".id")
        if os.path.exists(id_path):
            with open(id_path) as f:
                return f.read().strip()
        node_id = uuid.uuid4().hex
        with open(id_path, "w") as f:
            f.write(node_id)
        return node_id

    def _notify_shard(self, index: str, field: str, shard: int) -> None:
        if self.on_new_shard is not None:
            self.on_new_shard(index, field, shard)

    # -- indexes ------------------------------------------------------------

    def index(self, name: str) -> Optional[Index]:
        return self.indexes.get(name)

    def create_index(self, name: str, keys: bool = False,
                     track_existence: bool = True,
                     error_if_exists: bool = True) -> Index:
        with self._lock:
            if name in self.indexes:
                if error_if_exists:
                    raise ValueError(f"index already exists: {name}")
                return self.indexes[name]
            if not name or not name.islower() or not name[0].isalpha():
                raise ValueError(f"invalid index name: {name!r}")
            idx = Index(os.path.join(self.path, name), name, keys=keys,
                        track_existence=track_existence)
            idx.save_meta()
            idx.open()
            idx.on_new_shard = self._notify_shard
            self.indexes[name] = idx
            return idx

    def delete_index(self, name: str) -> None:
        with self._lock:
            idx = self.indexes.pop(name, None)
            if idx is None:
                raise KeyError(f"index not found: {name}")
            idx.close()
            shutil.rmtree(idx.path, ignore_errors=True)

    def schema(self) -> List[dict]:
        """Schema description (feeds /schema and schema broadcasts)."""
        out = []
        for iname in sorted(self.indexes):
            idx = self.indexes[iname]
            fields = []
            for fname in sorted(idx.fields):
                if fname.startswith("_"):
                    continue
                f = idx.fields[fname]
                fields.append({
                    "name": fname,
                    "options": {
                        "type": f.options.type,
                        "cacheType": f.options.cache_type,
                        "cacheSize": f.options.cache_size,
                        "min": f.options.min,
                        "max": f.options.max,
                        "timeQuantum": f.options.time_quantum,
                        "keys": f.options.keys,
                        "noStandardView": f.options.no_standard_view,
                        "maxColumns": f.options.max_columns,
                    },
                })
            out.append({"name": iname,
                        "options": {"keys": idx.keys,
                                    "trackExistence": idx.track_existence},
                        "fields": fields,
                        "shards": idx.available_shards()})
        return out

    def iter_fragments(self):
        """Every open fragment across all indexes/fields/views."""
        for idx in self.indexes.values():
            for f in idx.fields.values():
                for v in f.views.values():
                    yield from v.fragments.values()

    def flush_caches(self) -> None:
        for frag in self.iter_fragments():
            frag.flush_cache()

    def tail_dropped_bytes(self) -> int:
        """Total torn op-log tail bytes sidecarred across all open
        fragments (ADVICE r2: losing data to a torn tail must be visible
        to operators through stats/health, not only a log line)."""
        return sum(frag.tail_dropped_bytes
                   for frag in self.iter_fragments())
