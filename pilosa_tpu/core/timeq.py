"""Time quantum views.

A time field stores each bit in one view per enabled time unit
(reference: viewsByTime /root/reference/time.go:91, view name formats
time.go:70-88). Range queries union the minimal set of views covering
[start, end) (viewsByTimeRange, time.go:104-177): walk up from the finest
unit until aligned on the next coarser unit, emit coarse views while they
fit, then walk back down.
"""

from __future__ import annotations

import functools
from datetime import datetime, timedelta
from typing import List

VALID_UNITS = "YMDH"


def validate_quantum(q: str) -> None:
    if q and (any(u not in VALID_UNITS for u in q)
              or [u for u in VALID_UNITS if u in q] != list(q)):
        raise ValueError(f"invalid time quantum: {q!r}")


def view_by_time_unit(name: str, t: datetime, unit: str) -> str:
    if unit == "Y":
        return f"{name}_{t.strftime('%Y')}"
    if unit == "M":
        return f"{name}_{t.strftime('%Y%m')}"
    if unit == "D":
        return f"{name}_{t.strftime('%Y%m%d')}"
    if unit == "H":
        return f"{name}_{t.strftime('%Y%m%d%H')}"
    return ""


def views_by_time(name: str, t: datetime, quantum: str) -> List[str]:
    """All views one timestamped bit lands in — one per enabled unit."""
    return [v for u in quantum if (v := view_by_time_unit(name, t, u))]


def _next_hour(t: datetime) -> datetime:
    return t + timedelta(hours=1)


def _next_day(t: datetime) -> datetime:
    return t + timedelta(days=1)


def _add_month(t: datetime) -> datetime:
    # Clamp to day 1 for day>28 to avoid Jan 31 + 1mo = Mar 2
    # (reference addMonth, time.go:182-192).
    if t.day > 28:
        t = t.replace(day=1)
    if t.month == 12:
        return t.replace(year=t.year + 1, month=1)
    return t.replace(month=t.month + 1)


def _next_year(t: datetime) -> datetime:
    return t.replace(year=t.year + 1)


def views_by_time_range(name: str, start: datetime, end: datetime,
                        quantum: str) -> List[str]:
    """Minimal view cover of [start, end)."""
    has = {u: u in quantum for u in VALID_UNITS}
    t = start
    results: List[str] = []

    def year_fits(t):
        nxt = _next_year(t)
        return nxt.year == end.year or end > nxt

    def month_fits(t):
        nxt = t.replace(day=1)
        nxt = _next_year(nxt.replace(month=1)) if t.month == 12 else nxt.replace(month=t.month + 1)
        return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt

    def day_fits(t):
        nxt = _next_day(t.replace(hour=0, minute=0, second=0, microsecond=0))
        return nxt.date() == end.date() or end > nxt

    # Walk up: emit fine-grained views until aligned on the next coarser unit.
    if has["H"] or has["D"] or has["M"]:
        while t < end:
            if has["H"]:
                if not day_fits(t):
                    break
                if t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t = _next_hour(t)
                    continue
            if has["D"]:
                if not month_fits(t):
                    break
                if t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t = _next_day(t)
                    continue
            if has["M"]:
                if not year_fits(t):
                    break
                if t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_month(t)
                    continue
            break

    # Walk down: largest unit that still fits, repeatedly.
    while t < end:
        if has["Y"] and year_fits(t):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _next_year(t)
        elif has["M"] and month_fits(t):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_month(t)
        elif has["D"] and day_fits(t):
            results.append(view_by_time_unit(name, t, "D"))
            t = _next_day(t)
        elif has["H"]:
            results.append(view_by_time_unit(name, t, "H"))
            t = _next_hour(t)
        else:
            break

    return results


def view_time_part(v: str) -> str:
    """Time suffix of a view name, e.g. "standard_201901" -> "201901"
    (reference viewTimePart, time.go:330)."""
    return v.rsplit("_", 1)[1] if "_" in v else ""


def min_max_views(views: List[str], quantum: str) -> tuple:
    """(min, max) views among `views` at the quantum's coarsest unit
    (reference minMaxViews, time.go:240 — "chars" picks the first unit of
    YMDH present in the quantum; views sort chronologically because the
    time suffix is zero-padded)."""
    chars = 0
    for unit, n in (("Y", 4), ("M", 6), ("D", 8), ("H", 10)):
        if unit in quantum:
            chars = n
            break
    lo = hi = ""
    for v in sorted(views):
        if len(view_time_part(v)) == chars:
            if not lo:
                lo = v
            hi = v
    return lo, hi


def time_of_view(v: str, adj: bool) -> datetime:
    """Start time of a view name; with adj=True the exclusive end
    (reference timeOfView, time.go:279)."""
    part = view_time_part(v)
    fmt = {4: "%Y", 6: "%Y%m", 8: "%Y%m%d", 10: "%Y%m%d%H"}.get(len(part))
    if fmt is None:
        raise ValueError(f"invalid time format on view: {v}")
    t = datetime.strptime(part, fmt)
    if adj:
        t = {4: _next_year, 6: _add_month, 8: _next_day,
             10: _next_hour}[len(part)](t)
    return t


@functools.lru_cache(maxsize=4096)
def parse_timestamp(s: str) -> datetime:
    """PQL timestamp formats (reference pql.peg timestampfmt). Cached:
    strptime costs ~15 us and dashboards re-issue the same literal
    range bounds on every query (datetime is immutable, so sharing the
    parse is safe)."""
    for fmt in ("%Y-%m-%dT%H:%M", "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M",
                "%Y-%m-%d"):
        try:
            return datetime.strptime(s, fmt)
        except ValueError:
            continue
    raise ValueError(f"cannot parse timestamp {s!r}")
