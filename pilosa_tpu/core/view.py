"""View: a named collection of fragments, one per shard.

Reference: /root/reference/view.go:41. View names: "standard", time views
"standard_YYYY[MM[DD[HH]]]", and BSI views "bsig_<field>" (view.go:35-37).
Fragments are created lazily on first write (CreateFragmentIfNotExists,
view.go:207).
"""

from __future__ import annotations

import os
from pilosa_tpu.utils.locks import make_lock, make_rlock
from typing import Dict, List, Optional

import numpy as np

from pilosa_tpu.core.fragment import CONTAINER_BITS, Fragment
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.utils.hotspots import WORKLOAD
from pilosa_tpu.utils.memledger import LEDGER

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsig_"

# Sparse chunk upload (kill switch): single-shard narrow-layout chunk
# banks ship u16 bit POSITIONS (~2 B/set bit) and expand to the dense
# bank on device with one scatter — ~5x less host->device traffic for
# fingerprint-shaped fields, where the transfer (not the sweep)
# dominates on a tunnel-attached chip.
SPARSE_UPLOAD = os.environ.get("PILOSA_TPU_SPARSE_UPLOAD", "1") != "0"

# Demotion-ranked BankBudget eviction (hybrid layout satellite): under
# HBM pressure the sparsest-coldest cached bank is evicted first
# instead of the merely-oldest. 0 restores pure LRU.
SMART_EVICT = os.environ.get("PILOSA_TPU_LAYOUT_EVICT", "1") != "0"

_EXPAND_FN = None
_EXPAND_SENTINEL = 0xFFFFFFFF


def _expand_sparse_chunk(pos16: np.ndarray, lens: np.ndarray,
                         rows_at: np.ndarray, cap: int, width: int):
    """Device [cap, 1, width] u32 bank from concatenated per-row sorted
    UNIQUE positions. Uniqueness matters: the expansion scatter uses
    add, and two set bits only OR because distinct powers of two add
    without carries — container arrays guarantee it. Position arrays
    pad to power-of-two buckets so XLA compiles O(log P) variants, not
    one per chunk cardinality; sentinel entries land on a scratch word
    past the bank and add zero."""
    global _EXPAND_FN
    import functools

    import jax
    import jax.numpy as jnp

    if _EXPAND_FN is None:
        # graftlint: disable=GL006 — process-global build memoized in
        # _EXPAND_FN; static (cap, width) + pow2-padded positions keep
        # the variant count O(log P), not per-query churn.
        @functools.partial(jax.jit, static_argnums=(2, 3))
        def expand(pos, row_of, cap, width):
            total = cap * width
            sent = pos == jnp.uint32(_EXPAND_SENTINEL)
            word = jnp.where(
                sent, total,
                row_of * width + (pos >> 5)).astype(jnp.int32)
            bit = jnp.where(
                sent, jnp.uint32(0),
                jnp.left_shift(jnp.uint32(1),
                               (pos & 31).astype(jnp.uint32)))
            flat = jnp.zeros((total + 1,), jnp.uint32)
            flat = flat.at[word].add(bit, mode="drop",
                                     unique_indices=False)
            return flat[:total].reshape(cap, 1, width)

        _EXPAND_FN = expand
    n = len(pos16)
    padded = 1 << max(10, (n - 1).bit_length() if n else 0)
    pos = np.full(padded, _EXPAND_SENTINEL, np.uint32)
    pos[:n] = pos16
    row_of = np.zeros(padded, np.uint32)
    if n:
        row_of[:n] = np.repeat(rows_at.astype(np.uint32), lens)
    return _EXPAND_FN(jnp.asarray(pos), jnp.asarray(row_of), cap, width)


class BankBudget:
    """Process-wide accounting of cached device banks, bounding total
    HBM spent on operand banks. The reference never needs this because it
    streams one shard at a time from mmap (executor.go:2377); here banks
    persist in HBM across queries for reuse, so an explicit budget decides
    what stays resident. Evicted banks drop out of their view's cache (the
    device array frees once the last query referencing it drains).

    Eviction is demotion-ranked, not pure LRU: under pressure the
    victim is the entry with the highest workload-plane demotion score
    ((1 - live density) * bytes / (1 + read rate), the same ranking
    /debug/hotspots serves) so the sparsest-coldest bank goes first;
    entries the ledger/workload plane cannot score fall back to score
    0, and ties break LRU (oldest insertion wins) — a process with no
    workload data evicts exactly as the old pure-LRU budget did.
    PILOSA_TPU_LAYOUT_EVICT=0 restores pure LRU outright."""

    # Ledger categories a view registers its cached entries under; an
    # eviction must clear whichever one the key belongs to (keys are
    # disjoint across categories, and unregister is idempotent, so
    # clearing each is one cheap dict miss per non-owner).
    LEDGER_CATEGORIES = ("bank", "pbank", "sparse_bank", "host_block")

    def __init__(self, budget_bytes: int, cache_attr: str = "_bank_cache"):
        self.budget = budget_bytes
        self.cache_attr = cache_attr
        self._lock = make_lock("BankBudget._lock")
        # (id(view), key) -> (view, nbytes), in LRU order (oldest first).
        from collections import OrderedDict
        self._entries: "OrderedDict" = OrderedDict()
        self.total = 0
        self.evictions = 0

    def _eviction_scores(self):
        """Demotion scores for the current entries (computed ONCE per
        admit's eviction run, under the lock — scores cannot move
        mid-admit while the lock is held). The scorer reads the memory
        ledger + workload recorder — both leaf locks acquired strictly
        after this one (BankBudget -> Ledger/Workload is the only
        nesting direction, so the order graph stays acyclic under
        PILOSA_TPU_LOCK_CHECK)."""
        if not SMART_EVICT or len(self._entries) < 2:
            return {}
        try:
            from pilosa_tpu.core.layout import demotion_scores
            return demotion_scores(self._entries)
        except Exception:
            return {}

    def _pick_victim(self, scores):
        """Key of the entry to evict (called under the lock): highest
        demotion score wins, ties (and unscorable entries) resolve to
        the LRU-oldest."""
        if scores:
            best_ek, best = None, -1.0
            for ek in self._entries:  # oldest first -> LRU ties
                s = scores.get(ek, 0.0)
                if s > best:
                    best_ek, best = ek, s
            if best_ek is not None and best > 0.0:
                return best_ek
        return next(iter(self._entries))

    def admit(self, view: "View", key, nbytes: Optional[int] = None
              ) -> None:
        cache = getattr(view, self.cache_attr)
        if nbytes is None:
            bank = cache.get(key)
            if bank is None:
                return
            nbytes = int(np.prod(bank.array.shape)) * 4
        ek = (id(view), key)
        with self._lock:
            old = self._entries.pop(ek, None)
            if old is not None:
                self.total -= old[1]
            scores = None
            while self._entries and self.total + nbytes > self.budget:
                if scores is None:
                    scores = self._eviction_scores()
                vid, vkey = self._pick_victim(scores)
                scores.pop((vid, vkey), None)
                v, nb = self._entries.pop((vid, vkey))
                self.total -= nb
                self.evictions += 1
                getattr(v, self.cache_attr).pop(vkey, None)
                for cat in self.LEDGER_CATEGORIES:
                    LEDGER.unregister(cat, (vid, vkey))
            self._entries[ek] = (view, nbytes)
            self.total += nbytes

    def touch(self, view: "View", key) -> None:
        ek = (id(view), key)
        with self._lock:
            if ek in self._entries:
                self._entries.move_to_end(ek)

    def forget(self, view: "View", key) -> None:
        with self._lock:
            old = self._entries.pop((id(view), key), None)
            if old is not None:
                self.total -= old[1]
        for cat in self.LEDGER_CATEGORIES:
            LEDGER.unregister(cat, (id(view), key))


# Default sized for a v5e-class chip (16 GiB HBM): 12 GiB of resident
# banks leaves ~4 GiB for transient chunk banks, filter rows, sparse
# expansions, and XLA scratch. The 100M-fingerprint positions bank
# (~9.6 GiB) must fit WITH its filter banks or the LRU thrashes it on
# every query — the round-3 8 GiB default did exactly that.
BANK_BUDGET = BankBudget(
    int(os.environ.get("PILOSA_TPU_HBM_BUDGET_BYTES", 12 << 30)))

# Process-wide host-RAM budget for cached packed chunk blocks (the
# chunked-TopN repeat-query shortcut). 0 disables caching.
HOST_BLOCK_BUDGET = BankBudget(
    int(os.environ.get("PILOSA_TPU_HOST_BLOCK_CACHE_BYTES", 1 << 30)),
    cache_attr="_host_blocks")


class ViewBank:
    """A view's rows stacked across shards as ONE device array
    [row_capacity, n_shards, WORDS_PER_SHARD] (uint32) in HBM.

    This is the executor's operand format: a row leaf is `bank[slot]` with
    the slot passed as a *traced* index, so an entire PQL tree over any rows
    of any shards compiles once and runs as a single device program — the
    TPU replacement for goroutine-per-shard fan-out (executor.go:2377).
    The last slot is always all-zeros (rows absent from the view resolve
    there). Capacity is padded to a power of two so adding rows rarely
    changes the compiled shape.
    """

    def __init__(self, array, slots, zero_slot, versions):
        self.array = array          # jnp [Rcap, S, W]
        self.slots = slots          # row id -> slot
        self.zero_slot = zero_slot
        self.versions = versions    # {shard: fragment.version} at build time

    def slot(self, row_id: int) -> int:
        return self.slots.get(row_id, self.zero_slot)


class PositionsBank:
    """Device-RESIDENT sparse view for single-shard narrow layouts:
    rows' sorted u16 bit positions — ~2 bytes per SET bit instead of 64
    per bit-slot, so a 100M-row fingerprint field (~10 GB) stays
    resident in one chip's HBM where its dense banks (~51 GB) cannot.
    Filtered TopN then needs NO per-query upload or chunk streaming
    (executor._topn_positions). Two segment layouts, distinguished by
    the position array's RANK (every consumer must dispatch on it):

    - flat:  (row_lo, n_rows, pos u16 [Ppad], starts i32 [n_rows+1],
      p_real) — |row ∧ filter| = membership bits + cumsum differenced
      at starts; handles arbitrary per-row lengths.
    - fixed: (row_lo, n_rows, pos u16 [n_rows, L], lens i32 [n_rows],
      p_real) — rows padded to L slots with 0xFFFF; counts are one
      axis-1 reduce, no cumsum. Chosen per segment when every row fits
      PBANK_FIXED_ROW_SLOTS and density clears PBANK_FIXED_MIN_DENSITY.

    Segmented on row boundaries so every segment's position count fits
    i32 offsets."""

    __slots__ = ("segments", "row_ids", "versions", "nbytes")

    def __init__(self, segments, row_ids, versions, nbytes):
        self.segments = segments
        self.row_ids = row_ids      # global sorted row ids
        self.versions = versions
        self.nbytes = nbytes


class SparseBank:
    """First-class QUERY-SERVABLE sparse device bank (the hybrid
    layout's compact representation): every row's SET bit positions as
    one encoded uint32 array plus a per-row-slot offset table —
    ~4 bytes per set bit instead of ``4 * width`` per row slot, which
    is the shards-per-chip capacity win for sparse/cold views. This
    generalizes :class:`PositionsBank` (a TopN-sweep special case)
    into the executor's operand format: a Row leaf over a sparse-
    resident view stages an ``("xslot", ...)`` IR node whose program
    scatter-expands ``rows[slot]`` to the dense ``[S, W]`` register on
    device (ops/megakernel.expand_positions) — bit-identical to the
    dense bank row because expansion is exactly the inverse of the
    positions gather.

    Encoding: ``pos[k] = (shard_idx << 16) | bitpos`` (bitpos < 2^16
    because sparse banks exist only for trimmed widths within one
    container, the same constraint as Fragment.rows_positions);
    ``starts`` has ``capacity + 1`` i32 offsets with rows beyond the
    real set left empty, so absent rows resolve to the zero register
    through ``zero_slot`` exactly like a dense bank's all-zero slot.
    ``arrays`` is a stable ``(pos, starts)`` tuple — fusion groups and
    the megakernel lowering key operand identity on it."""

    __slots__ = ("arrays", "slots", "zero_slot", "versions", "nbytes",
                 "width", "n_shards", "n_rows")

    def __init__(self, arrays, slots, zero_slot, versions, nbytes,
                 width, n_shards, n_rows):
        self.arrays = arrays        # (pos u32 [Ppad], starts i32 [cap+1])
        self.slots = slots          # row id -> slot
        self.zero_slot = zero_slot
        self.versions = versions    # {shard: fragment.version} at build
        self.nbytes = nbytes
        self.width = width          # the dense width expansion targets
        self.n_shards = n_shards
        self.n_rows = n_rows

    def slot(self, row_id: int) -> int:
        return self.slots.get(row_id, self.zero_slot)


# Positions per device segment. The TopN kernel's cumsum array is
# i32-indexed (x64 stays off), so segment position counts must stay
# well under 2^31; the build enforces the cap EXACTLY by splitting
# gather chunks on row boundaries (a row contributes at most 2^16
# positions, so no single row can break it). 2^27 keeps each segment
# program's workspace a few hundred MB so several can queue beside a
# ~10 GB resident bank without exhausting HBM (2^29 segments put
# multi-GB transients next to the bank and OOMed the 100M run); the
# extra dispatches are cheap — results fetch as one batched
# device_get. The host gather chunk bounds the build's temporaries.
PBANK_SEGMENT_POSITIONS = int(os.environ.get(
    "PILOSA_TPU_PBANK_SEGMENT", 1 << 27))
PBANK_GATHER_ROWS = 1 << 20
# Fixed-width segment eligibility: every row in the segment must fit
# this many position slots, and real positions must fill at least this
# fraction of the padded matrix (bounds the padding overhead to 2x the
# flat bytes in the worst admitted case).
PBANK_FIXED_ROW_SLOTS = int(os.environ.get(
    "PILOSA_TPU_PBANK_FIXED_SLOTS", 128))
PBANK_FIXED_MIN_DENSITY = 0.5
# Segment row counts round up to this multiple so kernel shapes repeat
# across segments (one compile per bank instead of one per segment).
PBANK_FIXED_ROW_PAD = int(os.environ.get(
    "PILOSA_TPU_PBANK_ROW_PAD", 1 << 16))


def view_bsi_name(field: str) -> str:
    return VIEW_BSI_PREFIX + field


def bank_capacity(n_rows: int) -> int:
    """Slot capacity for a bank of n_rows: next power of two above
    n_rows + 1 (one all-zero slot) — the single source of truth shared
    with the executor's HBM budget check."""
    cap = 1
    while cap < n_rows + 1:
        cap *= 2
    return cap


class View:
    def __init__(self, path: str, index: str, field: str, name: str,
                 cache_type: str = cache_mod.CACHE_TYPE_RANKED,
                 cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
                 max_columns: int = 0):
        self.path = path  # .../<field>/views/<name>
        self.index = index
        self.field = field
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.max_columns = max_columns  # declared column bound (0 = full)
        self.fragments: Dict[int, Fragment] = {}
        self._lock = make_rlock("View._lock")
        self.on_new_shard = None  # callback(shard) for shard broadcasts
        # Hybrid device layout (core/layout.py): "dense" serves Row
        # leaves from ViewBanks, "sparse" from SparseBanks (set by the
        # background re-layout pass or an operator). Planning snapshots
        # the mode once per staged query; a flip mid-flight only
        # changes which (correct) representation the NEXT staging
        # picks, never the bits — cache safety needs no layout epoch
        # because the two layouts compile under DISTINCT signatures
        # (the x-vs-r sig parts + sparse expansion widths) and data
        # validity is already guarded by the fragment versions.
        self.layout_mode = "dense"
        self._bank_cache: Dict[tuple, ViewBank] = {}
        # Host-side packed blocks for transient row-subset banks (the
        # chunked-TopN stream): repeated sweeps over an unchanged
        # fragment skip the whole container gather and go straight to
        # device_put. LRU-bounded process-wide by HOST_BLOCK_BUDGET.
        self._host_blocks: Dict[tuple, tuple] = {}  # key -> (arr, vers)
        # Merged row-id tuples per shard set, keyed on fragment
        # versions: multi-shard TopN was re-unioning + re-sorting every
        # per-fragment row list PER QUERY — O(N log N) Python at
        # millions of rows (code-review r4 / VERDICT #7).
        self._merged_rows: Dict[tuple, tuple] = {}  # shards -> (vers, rows)

    def open(self) -> None:
        frag_dir = os.path.join(self.path, "fragments")
        if not os.path.isdir(frag_dir):
            return
        for name in os.listdir(frag_dir):
            if name.endswith(".cache") or name.endswith(".snapshotting"):
                continue
            try:
                shard = int(name)
            except ValueError:
                continue
            frag = self._new_fragment(shard)
            frag.open()
            # graftlint: disable=GL008 — one fragment per shard of
            # stored data: the map IS the view's contents, bounded by
            # the dataset, not by request traffic.
            self.fragments[shard] = frag

    def close(self) -> None:
        with self._lock:
            for key in list(self._bank_cache):
                BANK_BUDGET.forget(self, key)
            self._bank_cache.clear()
            for key in list(self._host_blocks):
                HOST_BLOCK_BUDGET.forget(self, key)
            self._host_blocks.clear()
            # Rank-cache vectors are keyed on this view's identity;
            # drop them (and their ledger rows) with the banks.
            cache_mod.RANK_CACHE.forget_view(self)
            for frag in self.fragments.values():
                frag.close()

    def _new_fragment(self, shard: int) -> Fragment:
        return Fragment(
            os.path.join(self.path, "fragments", str(shard)),
            self.index, self.field, self.name, shard,
            cache_type=self.cache_type, cache_size=self.cache_size)

    def fragment(self, shard: int) -> Optional[Fragment]:
        return self.fragments.get(shard)

    def version_stamp(self) -> tuple:
        """Every fragment's write version as one orderable tuple — the
        generation stamp the request-level result cache validates
        against. ANY mutation anywhere in the view changes it: every
        write funnels through Fragment._touch_row (version bump), and
        a fragment created or recreated starts at a fresh process-
        unique epoch, so a stamp can never read as current across a
        resize."""
        with self._lock:
            return tuple(sorted((s, f.version)
                                for s, f in self.fragments.items()))

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                frag = self._new_fragment(shard)
                frag.open()
                self.fragments[shard] = frag
                if self.on_new_shard is not None:
                    self.on_new_shard(shard)
            return frag

    def available_shards(self) -> List[int]:
        return sorted(self.fragments)

    # -- device bank --------------------------------------------------------

    def _ledger_bank(self, cache_key, bank: "ViewBank", n_rows: int,
                     live_density=None) -> None:
        """Register a cached dense bank with the HBM ledger: total vs
        pow2-pad bytes (capacity rows beyond n_rows + the zero slot),
        tagged so /debug/memory's top-K names the occupant, plus the
        popcount-sampled TRUE live-bit density of the real rows (the
        hotspots demotion quadrants' input — pow2-pad share alone
        scores a full-width-but-sparse row as dense). Keyed identically
        to the BankBudget entry, which unregisters it on eviction."""
        cap, s, w = (int(x) for x in bank.array.shape)
        row_bytes = s * w * 4
        meta = dict(index=self.index, field=self.field, view=self.name,
                    nShards=s, rows=n_rows)
        if live_density is not None:
            meta["liveDensity"] = round(float(live_density), 6)
            # Feed the plan optimizer's cost model: fold operands sort
            # cheapest-first by this sampled density (order-only — a
            # stale value can cost speed, never bits).
            from pilosa_tpu.ops import plan_opt
            plan_opt.note_bank_density(bank.array, live_density)
        LEDGER.register(
            "bank", cache_key, cap * row_bytes,
            padded_bytes=max(0, cap - n_rows - 1) * row_bytes,
            owner=self, **meta)

    # Rows popcount-sampled per bank build for the true-density meta:
    # enough to place a bank in its density quadrant, cheap enough
    # (storage count_range, no device work) to ride every build/patch.
    DENSITY_SAMPLE_ROWS = 256

    def _sampled_live_density(self, frags, row_set, width, shards):
        """Fraction of the bank's REAL row slots' bits that are set,
        estimated from an even sample of rows (popcount via the
        fragments' storage count — host-side only). None when there is
        nothing to sample."""
        if not row_set or not shards or width <= 0:
            return None
        step = max(1, len(row_set) // self.DENSITY_SAMPLE_ROWS)
        sample = row_set[::step][:self.DENSITY_SAMPLE_ROWS]
        try:
            bits = 0
            for s in shards:
                f = frags.get(s) if isinstance(frags, dict) else None
                if f is None:
                    continue
                for r in sample:
                    bits += f.row_count(r)
            denom = len(sample) * len(shards) * width * 32
            return min(1.0, bits / denom) if denom else None
        except Exception:
            return None  # density is telemetry; never fail a build

    # Word granularity of declared-bound trims: 128 u32 words = 4096
    # bits = one full VPU lane row, and exactly a Morgan fingerprint.
    TRIM_GRANULE = 128

    def trimmed_words(self) -> int:
        """Bank word width (uint32) covering every set column of every
        fragment. With a declared max_columns the width is exact to a
        128-word granule (a 4096-bit fingerprint field stores 512 B/row
        in HBM); otherwise it derives from fragment container keys,
        rounded up to whole containers (2048 u32 words = 2^16 bits — the
        container granularity of the key-based bound)."""
        from pilosa_tpu.core.fragment import CONTAINER_BITS
        from pilosa_tpu.ops.bitset import WORDS_PER_SHARD
        if self.max_columns:
            words = (self.max_columns + 31) // 32
            g = self.TRIM_GRANULE
            return min(WORDS_PER_SHARD, (words + g - 1) // g * g)
        cwords = CONTAINER_BITS // 32
        with self._lock:
            frags = list(self.fragments.values())
        max_off = -1
        for f in frags:
            max_off = max(max_off, f.max_column_offset())
        if max_off < 0:
            return cwords
        words = (max_off // 32) + 1
        return min(WORDS_PER_SHARD, ((words + cwords - 1) // cwords)
                   * cwords)

    def device_bank(self, shards, rows=None, mesh=None,
                    trim: bool = False, cache_rows: bool = False
                    ) -> ViewBank:
        """Bank for `shards` covering `rows` (default: all rows present in
        any of the shards). Cached per (shard tuple, mesh, trim); rebuilt
        when any fragment's write version moved. `rows` subsets build
        transient banks unless cache_rows=True, which caches them under a
        rows-inclusive key — used by the executor's Row-leaf path when
        the FULL view bank would blow the HBM budget (a single Row(f=x)
        on a million-row field must not upload the whole field; reference
        never faces this because it streams per-shard, executor.go:2377)
        and by chunked TopN when its whole stream fits the budget.
        Either way the packed HOST block is cached (HOST_BLOCK_BUDGET)
        so a device-side eviction rebuilds by re-upload, not re-gather. All cached banks are LRU-accounted against
        BANK_BUDGET. trim=True narrows the word axis to trimmed_words() —
        valid only for whole-row consumers since the dropped tail is
        all-zero by construction. With a MeshContext the array is
        device_put sharded over the mesh's shard axis, which is all the
        executor needs to run SPMD."""
        import jax.numpy as jnp
        from pilosa_tpu.ops.bitset import WORDS_PER_SHARD

        shards = tuple(shards)
        mesh_key = mesh.cache_key() if mesh else None
        with self._lock:
            frags = {s: self.fragments.get(s) for s in shards}
            versions = {s: (f.version if f else -1) for s, f in frags.items()}
            # Width AFTER the version snapshot: a write racing in between
            # bumps a version, so a bank truncated by the pre-write width
            # reads as stale and rebuilds — never silently wrong.
            width = self.trimmed_words() if trim else WORDS_PER_SHARD
            if rows is None:
                cache_key = (shards, mesh_key, trim)
                cached = self._bank_cache.get(cache_key)
                if cached is not None and cached.array.shape[-1] == width \
                        and cached.versions == versions:
                    # Unchanged versions imply an unchanged row set
                    # (every mutation bumps its fragment's version), so
                    # the bank provably covers every present row — no
                    # per-row membership scan on the warm path (it cost
                    # ~150 ms/query at 500k rows).
                    BANK_BUDGET.touch(self, cache_key)
                    return cached
                if cached is not None:
                    # Write churn just cost a device-bank patch/rebuild
                    # — record WHICH fragments moved (the shards whose
                    # version diverged) for the workload plane's churn
                    # ranking (utils/hotspots.py).
                    moved = [s for s, v in versions.items()
                             if cached.versions.get(s) != v]
                    WORKLOAD.record_invalidation(
                        self.index, self.field, self.name,
                        moved or list(shards))
                row_set = sorted({r for f in frags.values() if f
                                  for r in f.row_ids()})
                if cached is not None and cached.array.shape[-1] == width:
                    patched = self._patch_bank(cached, frags, versions,
                                               row_set, shards, width)
                    if patched is not None:
                        # Patch path: carry the PRIOR density estimate
                        # forward — a <=half-bank cell patch moves the
                        # true density negligibly, and resampling here
                        # would put 256 x nShards row popcounts on the
                        # incremental fast path the patch exists for.
                        prior = LEDGER.entry_info(
                            ("bank",), (id(self), cache_key))
                        self._bank_cache[cache_key] = patched
                        BANK_BUDGET.touch(self, cache_key)
                        self._ledger_bank(
                            cache_key, patched, len(row_set),
                            live_density=(prior or {}).get(
                                "liveDensity"))
                        return patched
            else:
                row_set = sorted(set(rows))
                cache_key = (shards, mesh_key, trim, tuple(row_set))
                if cache_rows:
                    cached = self._bank_cache.get(cache_key)
                    if cached is not None \
                            and cached.array.shape[-1] == width \
                            and cached.versions == versions:
                        BANK_BUDGET.touch(self, cache_key)
                        # Keep the backing host block warm too: if HBM
                        # pressure later evicts this bank, the rebuild
                        # should re-upload, not re-gather.
                        HOST_BLOCK_BUDGET.touch(
                            self, (shards, width, tuple(row_set)))
                        return cached
            cap = bank_capacity(len(row_set))
            # Host blocks back ALL row-subset builds (cache_rows device
            # banks included): when HBM pressure evicts the device bank,
            # the rebuild skips the container gather and only re-uploads.
            hb_key = None
            host = slots = None
            if rows is not None:
                hb_key = (shards, width, tuple(row_set))
                entry = self._host_blocks.get(hb_key)
                if entry is not None:
                    if entry[1] == versions:
                        host, _v, slots = entry
                        HOST_BLOCK_BUDGET.touch(self, hb_key)
                    else:
                        self._host_blocks.pop(hb_key, None)
                        HOST_BLOCK_BUDGET.forget(self, hb_key)
            array = None
            if host is None and SPARSE_UPLOAD \
                    and mesh is None and len(shards) == 1 \
                    and trim and width * 32 <= CONTAINER_BITS \
                    and cap * width < (1 << 31):
                # (cap*width bound: the expansion scatter indexes with
                # i32 — an operator-raised bank budget must fall back
                # to the dense path, not wrap indices.)
                # Sparse upload (chunk AND full-bank builds): ship
                # positions, expand to the dense bank on device.
                f = frags[shards[0]]
                sp = (f.rows_positions(row_set, width)
                      if f is not None else
                      (np.empty(0, np.uint16), np.empty(0, np.int64),
                       np.empty(0, np.int64)))
                if sp is not None:
                    array = _expand_sparse_chunk(*sp, cap, width)
                    slots = {r: i for i, r in enumerate(row_set)}
            if array is None:
                if host is None:
                    host = np.zeros((cap, len(shards), width),
                                    dtype=np.uint32)
                    for si, s in enumerate(shards):
                        f = frags[s]
                        if f is not None:
                            host[:len(row_set), si] = f.rows_dense(
                                row_set, width)
                    # Cached alongside so a hit is O(1) host-side — no
                    # 65k-entry dict rebuild per chunk per repeat query.
                    slots = {r: i for i, r in enumerate(row_set)}
                    # The slots dict is real host RAM too (~100 B/entry
                    # of dict overhead + int pair; several MB at 65k
                    # rows): account it, or a budget-full cache
                    # overshoots by the sum of its mappings (ADVICE r2).
                    entry_bytes = host.nbytes + 100 * len(row_set)
                    if hb_key is not None and \
                            0 < entry_bytes <= HOST_BLOCK_BUDGET.budget:
                        self._host_blocks[hb_key] = (host, versions,
                                                     slots)
                        HOST_BLOCK_BUDGET.admit(self, hb_key,
                                                nbytes=entry_bytes)
                        LEDGER.register(
                            "host_block", hb_key, entry_bytes,
                            padded_bytes=max(0, cap - len(row_set) - 1)
                            * len(shards) * width * 4,
                            owner=self, index=self.index,
                            field=self.field, view=self.name,
                            nShards=len(shards), rows=len(row_set))
                array = mesh.put_bank(host) if mesh else jnp.asarray(host)
            bank = ViewBank(array, slots, cap - 1, versions)
            if rows is None or cache_rows:
                self._bank_cache[cache_key] = bank
                BANK_BUDGET.admit(self, cache_key)
                self._ledger_bank(
                    cache_key, bank, len(row_set),
                    live_density=self._sampled_live_density(
                        frags, row_set, width, shards))
            return bank

    def _build_pbank_segments(self, frag, rows: list, width: int,
                              row_lo0: int):
        """Gather `rows` (sorted) into device segments starting at
        global row index `row_lo0`: [(row_lo, n_rows, pos_dev,
        starts_dev, p_real)], total nbytes — or None when too dense."""
        import jax.numpy as jnp

        segments: list = []
        nbytes = 0
        pos_parts: list = []
        lens_parts: list = []
        cur_p = 0
        row_lo = row_lo0

        def flush():
            nonlocal pos_parts, lens_parts, cur_p, row_lo, nbytes
            if not lens_parts:
                return
            pos16 = (np.concatenate(pos_parts) if pos_parts
                     else np.empty(0, np.uint16))
            lens = np.concatenate(lens_parts)
            p = len(pos16)
            n = len(lens)
            # FIXED-WIDTH layout when the segment's rows are uniform
            # enough: positions as [n_rows, L] (0xFFFF pad) + per-row
            # real lengths. The TopN kernel then row-sums with one
            # axis-1 reduce — no O(P) cumsum, no starts gathers (the
            # two ops left in the warm flagship profile once the
            # membership gather fell, docs/perf.md §4b). Fingerprint
            # banks are ~99% dense at L=48; the density guard keeps
            # padding ≤ 2x the flat bytes. Kind is carried by array
            # rank (pos 2D = fixed), so every 5-tuple consumer —
            # patcher, tests, benches — is untouched.
            # Row-count pad (both layouts): kernels compile per array
            # SHAPE, and every remote compile crosses the tunnel — a
            # 36-segment bank with 36 distinct row counts cost 36 cold
            # compiles (one tunnel-window died mid-query paying them).
            # Padding rows to a 2^16 multiple collapses the shapes to
            # one or two per bank (+<3% rows). Pad rows carry zero
            # lengths, so their counts are 0 and can never rank.
            # The multiple is the largest power of two (1024..2^16)
            # whose padding stays <= n/8: interior segments at scale
            # still land on the big multiple (shapes repeat, compile
            # reuse holds), while row counts just above a multiple
            # (e.g. n=65537) no longer pad toward 2x their HBM
            # (advisor r4 — the old two-point 1024/65536 rule).
            # Tension accepted: mixed-density banks whose segments'
            # row counts straddle the 65536..8*65536 band can see a
            # few more distinct padded shapes (=cold compiles) than
            # the old always-65536 rule; real banks split segments at
            # the POSITION cap, so same-density interior segments
            # share one shape either way.
            row_pad = min(1024, PBANK_FIXED_ROW_PAD)
            cand = row_pad * 2
            while cand <= PBANK_FIXED_ROW_PAD:
                if -n % cand <= n // 8:
                    row_pad = cand
                cand *= 2
            n_pad = -n % row_pad
            L = int(lens.max()) if n else 0
            if 0 < L <= PBANK_FIXED_ROW_SLOTS \
                    and p >= PBANK_FIXED_MIN_DENSITY * n * L:
                mat = np.full((n + n_pad, L), 0xFFFF, np.uint16)
                mat[:n][np.arange(L)[None, :] < lens[:, None]] = pos16
                lens32 = np.zeros(n + n_pad, np.int32)
                lens32[:n] = lens
                seg = (row_lo, n, jnp.asarray(mat),
                       jnp.asarray(lens32), p)
                segments.append(seg)
                nbytes += (n + n_pad) * L * 2 + (n + n_pad) * 4
            else:
                starts = np.zeros(n + n_pad + 1, np.int64)
                np.cumsum(lens, out=starts[1:n + 1])
                starts[n + 1:] = starts[n]  # pad rows: empty ranges
                # Pad to a 1M multiple, NOT a power of two: segments
                # build once (per version), so compile reuse matters
                # little, and pow2 padding nearly doubled a ~10 GiB
                # bank — pushing it over the HBM budget and into
                # rebuild-per-query thrash (caught by the 100M run).
                padded = max(1 << 20, -(-p // (1 << 20)) * (1 << 20))
                buf = np.full(padded, 0xFFFF, np.uint16)  # OOB pad
                buf[:p] = pos16
                seg = (row_lo, n, jnp.asarray(buf),
                       jnp.asarray(starts.astype(np.int32)), p)
                segments.append(seg)
                nbytes += padded * 2 + (n + n_pad + 1) * 4
            pos_parts, lens_parts = [], []
            cur_p = 0
            row_lo += n

        for c0 in range(0, len(rows), PBANK_GATHER_ROWS):
            chunk = rows[c0:c0 + PBANK_GATHER_ROWS]
            rp = frag.rows_positions(chunk, width)
            if rp is None:
                return None  # too dense for the sparse layout
            pos16, lens, rows_at = rp
            # Align lens to EVERY chunk row (a present row always has
            # real positions, but stay defensive about empties).
            if len(rows_at) != len(chunk):
                full = np.zeros(len(chunk), np.int64)
                full[rows_at] = lens
                lens = full
                # positions already concatenated in rows_at order ==
                # ascending row order; empties contribute nothing.
            # Enforce the segment cap EXACTLY, splitting this chunk on
            # row boundaries if needed — checking only after a whole
            # chunk appends would let dense-heavy rows blow a segment
            # past the kernel's i32 index space (up to 2^16
            # positions/row x 2^20 rows/chunk).
            ends = np.cumsum(lens)
            taken = 0
            while taken < len(lens):
                room = PBANK_SEGMENT_POSITIONS - cur_p
                # Rows of this chunk (beyond `taken`) that fit in room.
                hi = int(np.searchsorted(ends, ends[taken - 1] + room
                                         if taken else room, "right"))
                if hi <= taken:
                    flush()
                    continue
                lo_p = int(ends[taken - 1]) if taken else 0
                hi_p = int(ends[hi - 1])
                pos_parts.append(pos16[lo_p:hi_p])
                lens_parts.append(lens[taken:hi])
                cur_p += hi_p - lo_p
                taken = hi
                if cur_p >= PBANK_SEGMENT_POSITIONS:
                    flush()
        flush()
        return segments, nbytes

    def merged_row_ids(self, shards) -> tuple:
        """Sorted union of row_ids() across `shards`, cached per shard
        set and invalidated by any member fragment's version bump —
        repeat queries over unchanged fragments alias the same tuple
        (no per-query union/sort; reference fragment.top reads its
        rankCache per fragment, fragment.go:1067). The merge itself is
        one C-speed np.unique over the concatenated sorted lists."""
        key = tuple(shards)
        frags = [f for s in key for f in [self.fragment(s)]
                 if f is not None]
        versions = tuple(f.version for f in frags)
        with self._lock:
            ent = self._merged_rows.get(key)
            if ent is not None and ent[0] == versions:
                # Refresh LRU order on hit (dict preserves insertion
                # order; re-inserting moves this key to the back, so
                # eviction below pops the genuinely coldest entry).
                self._merged_rows.pop(key)
                self._merged_rows[key] = ent
                return ent[1]
        per = [f.row_ids() for f in frags]
        per = [p for p in per if p]
        if not per:
            merged: tuple = ()
        elif len(per) == 1:
            merged = per[0]  # already a sorted immutable tuple
        else:
            merged = tuple(np.unique(np.concatenate(
                [np.asarray(p, dtype=np.uint64) for p in per])).tolist())
        with self._lock:
            self._merged_rows.pop(key, None)  # re-insert at the back
            self._merged_rows[key] = (versions, merged)
            while len(self._merged_rows) > 8:  # a few live shard sets
                self._merged_rows.pop(next(iter(self._merged_rows)))
        return merged

    def positions_bank(self, shard: int, width: int
                       ) -> Optional[PositionsBank]:
        """Device-resident PositionsBank for one shard, or None when
        the layout doesn't qualify: no fragment, width spanning a full
        container (the 0xFFFF pad sentinel must gather out of range),
        or a genuinely dense field (>25% dense-encoded containers in
        some gather chunk — a FEW densified rows, e.g. from point
        writes, are extracted and stay in-bank). Cached per
        (shard, width) under the HBM budget. A write invalidates by
        version; the rebuild is INCREMENTAL when the row set is
        unchanged — only segments containing written rows regather,
        the rest reuse their device arrays (at 100M rows a point write
        costs ~1/segment-count of the full build, not minutes)."""
        if width * 32 >= CONTAINER_BITS:
            return None
        key = ("pbank", shard, width)
        with self._lock:
            frag = self.fragments.get(shard)
            versions = {shard: (frag.version if frag else -1)}
            cached = self._bank_cache.get(key)
            if isinstance(cached, PositionsBank) \
                    and cached.versions == versions:
                BANK_BUDGET.touch(self, key)
                return cached
            if frag is None:
                return None
        row_ids = frag.row_ids()  # sorted immutable tuple (contract)
        built = None
        # graftlint: disable=GL015 — deliberate lock-free rebuild: the
        # bank is stamped with the versions read under the first
        # acquisition, so a write landing during the build makes the
        # stamp stale and the next probe rebuilds (write-back is
        # last-writer-wins; a stale bank is never SERVED, only stored).
        if isinstance(cached, PositionsBank) \
                and cached.row_ids == row_ids:
            # graftlint: disable=GL015 — same version-stamp argument.
            built = self._patch_pbank(cached, frag, width)
        if built is None:
            # graftlint: disable=GL015 — same version-stamp argument.
            built = self._build_pbank_segments(frag, row_ids, width, 0)
        if built is None:
            return None
        segments, nbytes = built
        bank = PositionsBank(segments, row_ids, versions, nbytes)
        with self._lock:
            self._bank_cache[key] = bank
        BANK_BUDGET.admit(self, key, nbytes=nbytes)
        # Ideal (pad-free) footprint: 2 B per real position + one i32
        # aux word per row (+1); the rest is pow2 / fixed-width / row
        # padding — the number the padding gauge exists to surface.
        ideal = sum(p * 2 + (n + 1) * 4 for _, n, _, _, p in segments)
        LEDGER.register(
            "pbank", key, nbytes,
            padded_bytes=max(0, nbytes - ideal), owner=self,
            index=self.index, field=self.field, view=self.name,
            shard=shard, rows=len(row_ids))
        return bank

    def _patch_pbank(self, cached: PositionsBank, frag, width: int):
        """Regather only the segments whose row ranges contain rows
        written since the cached build; clean segments carry over with
        their device arrays. Same-row-set only (the caller checked):
        global row indexes then stay aligned except where segment
        boundaries move, handled by rebuilding dirty ranges in place.
        Returns (segments, nbytes) or None to force a full rebuild."""
        changed = frag.rows_changed_since(
            next(iter(cached.versions.values())))
        if not changed or len(changed) > len(cached.row_ids) // 4:
            return None  # nothing known, or patch ~= rebuild
        dirty = set(changed)
        segments: list = []
        nbytes = 0
        row_lo = 0
        for seg in cached.segments:
            s_lo, n_rows, pos_dev, starts_dev, p_real = seg
            seg_rows = cached.row_ids[s_lo:s_lo + n_rows]
            if dirty.isdisjoint(seg_rows):
                # Clean: reuse the device arrays; only the global row
                # offset may have shifted if an earlier dirty range
                # re-split (row COUNT per range is unchanged, so it
                # cannot — assert the invariant cheaply).
                segments.append((row_lo, n_rows, pos_dev, starts_dev,
                                 p_real))
                # aux is lens (fixed) or starts (flat), both i32 and
                # possibly row-padded — its own size is the truth.
                nbytes += int(pos_dev.size) * 2 + int(starts_dev.size) * 4
                row_lo += n_rows
                continue
            rebuilt = self._build_pbank_segments(frag, seg_rows, width,
                                                 row_lo)
            if rebuilt is None:
                return None
            new_segs, nb = rebuilt
            # The clean-segment reuse above depends on every dirty
            # range rebuilding to the SAME real row count (row_lo
            # offsets of later clean segments assume it). A mismatch
            # falls back to the full rebuild — same path as
            # rebuilt-is-None — rather than serving misaligned rows.
            if sum(s[1] for s in new_segs) != n_rows:
                return None
            segments.extend(new_segs)
            nbytes += nb
            row_lo += n_rows
        return segments, nbytes

    # -- hybrid layout (driven by core/layout.py) ----------------------------

    def set_layout(self, mode: str) -> bool:
        """Flip this view's serving layout ("dense" | "sparse").
        Returns True when the mode actually changed. The flip drops
        the OTHER representation's cached device banks so the HBM
        frees immediately (the byte delta the re-layout pass proves
        against the ledger); host blocks stay —
        they are host RAM and make a later promotion re-upload instead
        of re-gather. Data is never touched, so a stale *hit* is
        impossible: a query staged before the flip keeps serving from
        the representation it planned against, both of which hold the
        same bits (pinned by tests/test_layout.py)."""
        if mode not in ("dense", "sparse"):
            raise ValueError(f"unknown layout mode {mode!r}")
        with self._lock:
            if self.layout_mode == mode:
                return False
            self.layout_mode = mode
            drop = []
            for key in list(self._bank_cache):
                tagged = isinstance(key, tuple) and key \
                    and isinstance(key[0], str)
                sparse_key = tagged and key[0] == "sbank"
                pbank_key = tagged and key[0] == "pbank"
                if mode == "sparse" and not (sparse_key or pbank_key):
                    drop.append(key)
                elif mode == "dense" and sparse_key:
                    drop.append(key)
            for key in drop:
                self._bank_cache.pop(key, None)
                BANK_BUDGET.forget(self, key)
        return True

    def sparse_bank(self, shards) -> Optional["SparseBank"]:
        """Device-resident :class:`SparseBank` over `shards` covering
        every present row, or None when the layout does not qualify
        (width spanning a full container — the u16 bitpos encoding
        needs sub-container trim — or a genuinely dense view, where
        ``rows_positions`` bails and dense banks win anyway). Cached
        per (shard tuple, width) under the HBM budget with the same
        stamp-then-read version discipline as ``device_bank``: a write
        racing the build bumps a fragment version, the cached versions
        read stale, and the next query rebuilds — spurious miss
        allowed, stale hit never. A None return self-heals the layout
        back to dense so staging stops asking."""
        import jax.numpy as jnp

        shards = tuple(shards)
        with self._lock:
            frags = {s: self.fragments.get(s) for s in shards}
            versions = {s: (f.version if f else -1)
                        for s, f in frags.items()}
            width = self.trimmed_words()
            if width * 32 > CONTAINER_BITS:
                return None
            key = ("sbank", shards, width)
            cached = self._bank_cache.get(key)
            if isinstance(cached, SparseBank) \
                    and cached.versions == versions:
                BANK_BUDGET.touch(self, key)
                return cached
            row_set = sorted({r for f in frags.values() if f
                              for r in f.row_ids()})
            n_rows = len(row_set)
            per_shard = []
            for si, s in enumerate(shards):
                f = frags[s]
                if f is None:
                    per_shard.append((np.empty(0, np.uint32),
                                      np.zeros(n_rows, np.int64)))
                    continue
                rp = f.rows_positions(row_set, width)
                if rp is None:
                    return None  # too dense for the sparse layout
                pos16, lens, rows_at = rp
                if len(rows_at) != n_rows:
                    full = np.zeros(n_rows, np.int64)
                    full[rows_at] = lens
                    lens = full
                per_shard.append(
                    (pos16.astype(np.uint32) | np.uint32(si << 16),
                     lens.astype(np.int64)))
            cap = bank_capacity(n_rows)
            if per_shard and n_rows:
                lens_mat = np.stack([ls for _, ls in per_shard])
            else:
                lens_mat = np.zeros((len(shards), n_rows), np.int64)
            row_tot = lens_mat.sum(axis=0)
            total = int(row_tot.sum())
            if total >= (1 << 31):
                return None  # starts are i32; such a view is not sparse
            starts = np.zeros(cap + 1, np.int64)
            np.cumsum(row_tot, out=starts[1:n_rows + 1])
            starts[n_rows + 1:] = starts[n_rows]
            # Per-(row, shard) destination: row start + the exclusive
            # prefix of earlier shards' lengths for that row, so each
            # row's positions concatenate shard-ascending (the encoded
            # shard index keeps them decodable either way).
            prior = np.cumsum(lens_mat, axis=0) - lens_mat
            p_pad = 1 << max(10, (total - 1).bit_length() if total
                             else 0)
            pos = np.zeros(p_pad, np.uint32)
            for si, (enc, _ls) in enumerate(per_shard):
                if not len(enc):
                    continue
                ls = lens_mat[si]
                dst0 = starts[:n_rows] + prior[si]
                within = np.arange(len(enc)) \
                    - np.repeat(np.cumsum(ls) - ls, ls)
                pos[np.repeat(dst0, ls) + within] = enc
            starts32 = starts.astype(np.int32)
            arrays = (jnp.asarray(pos), jnp.asarray(starts32))
            nbytes = int(pos.nbytes + starts32.nbytes)
            slots = {r: i for i, r in enumerate(row_set)}
            bank = SparseBank(arrays, slots, cap - 1, versions, nbytes,
                              width, len(shards), n_rows)
            self._bank_cache[key] = bank
            BANK_BUDGET.admit(self, key, nbytes=nbytes)
            # Ideal footprint: 4 B per real position + one i32 offset
            # per real row (+1); the rest is pow2 pos/row-capacity pad.
            ideal = total * 4 + (n_rows + 1) * 4
            LEDGER.register(
                "sparse_bank", key, nbytes,
                padded_bytes=max(0, nbytes - ideal), owner=self,
                index=self.index, field=self.field, view=self.name,
                nShards=len(shards), rows=n_rows, positions=total,
                liveDensity=1.0, width=width)
            return bank

    def _patch_bank(self, cached: "ViewBank", frags, versions, row_set,
                    shards, width):
        """Incrementally refresh a cached bank: re-upload only (row, shard)
        cells whose fragment reports a newer row version. Returns None when
        a rebuild is required (new rows exceed capacity, or the patch would
        touch most of the bank anyway)."""
        import jax.numpy as jnp

        new_rows = [r for r in row_set if r not in cached.slots]
        if len(cached.slots) + len(new_rows) + 1 > cached.array.shape[0]:
            return None
        for s, newv in versions.items():
            old = cached.versions.get(s, -1)
            if old != newv and (old < 0 or (old >> 48) != (newv >> 48)):
                # Version epoch moved: the fragment was recreated since
                # this bank was built (pop + reload), so its
                # _row_versions no longer attributes writes made in the
                # old incarnation — rows_changed_since below would
                # under-patch. Rebuild.
                return None
        patches = []  # (slot, shard_idx, words)
        for si, s in enumerate(shards):
            f = frags[s]
            if f is None or f.version == cached.versions.get(s):
                continue
            for r in f.rows_changed_since(cached.versions.get(s, -1)):
                if r in cached.slots:
                    patches.append((cached.slots[r], si,
                                    f.row_dense(r, u32_words=width)))
        slots = dict(cached.slots)
        for r in new_rows:
            slot = len(slots)
            slots[r] = slot
            for si, s in enumerate(shards):
                f = frags[s]
                if f is not None:
                    patches.append((slot, si,
                                    f.row_dense(r, u32_words=width)))
        total_cells = cached.array.shape[0] * cached.array.shape[1]
        if len(patches) > max(16, total_cells // 2):
            return None
        array = cached.array
        if patches:
            rows_idx = np.asarray([p[0] for p in patches], dtype=np.int32)
            shard_idx = np.asarray([p[1] for p in patches], dtype=np.int32)
            words = np.stack([p[2] for p in patches])
            array = array.at[jnp.asarray(rows_idx),
                             jnp.asarray(shard_idx)].set(jnp.asarray(words))
        return ViewBank(array, slots, cached.zero_slot, versions)

    # Pass-throughs (reference view.go:294-421).

    def set_bit(self, row_id: int, column_id: int) -> bool:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        return self.create_fragment_if_not_exists(
            column_id // SHARD_WIDTH).set_bit(row_id, column_id)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        frag = self.fragment(column_id // SHARD_WIDTH)
        return frag.clear_bit(row_id, column_id) if frag else False

    def set_value(self, column_id: int, bit_depth: int, value: int) -> bool:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        return self.create_fragment_if_not_exists(
            column_id // SHARD_WIDTH).set_value(column_id, bit_depth, value)

    def value(self, column_id: int, bit_depth: int):
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        frag = self.fragment(column_id // SHARD_WIDTH)
        if frag is None:
            return 0, False
        return frag.value(column_id, bit_depth)
