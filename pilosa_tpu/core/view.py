"""View: a named collection of fragments, one per shard.

Reference: /root/reference/view.go:41. View names: "standard", time views
"standard_YYYY[MM[DD[HH]]]", and BSI views "bsig_<field>" (view.go:35-37).
Fragments are created lazily on first write (CreateFragmentIfNotExists,
view.go:207).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core import cache as cache_mod

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsig_"


def view_bsi_name(field: str) -> str:
    return VIEW_BSI_PREFIX + field


class View:
    def __init__(self, path: str, index: str, field: str, name: str,
                 cache_type: str = cache_mod.CACHE_TYPE_RANKED,
                 cache_size: int = cache_mod.DEFAULT_CACHE_SIZE):
        self.path = path  # .../<field>/views/<name>
        self.index = index
        self.field = field
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.fragments: Dict[int, Fragment] = {}
        self._lock = threading.RLock()
        self.on_new_shard = None  # callback(shard) for shard broadcasts

    def open(self) -> None:
        frag_dir = os.path.join(self.path, "fragments")
        if not os.path.isdir(frag_dir):
            return
        for name in os.listdir(frag_dir):
            if name.endswith(".cache") or name.endswith(".snapshotting"):
                continue
            try:
                shard = int(name)
            except ValueError:
                continue
            frag = self._new_fragment(shard)
            frag.open()
            self.fragments[shard] = frag

    def close(self) -> None:
        with self._lock:
            for frag in self.fragments.values():
                frag.close()

    def _new_fragment(self, shard: int) -> Fragment:
        return Fragment(
            os.path.join(self.path, "fragments", str(shard)),
            self.index, self.field, self.name, shard,
            cache_type=self.cache_type, cache_size=self.cache_size)

    def fragment(self, shard: int) -> Optional[Fragment]:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                frag = self._new_fragment(shard)
                frag.open()
                self.fragments[shard] = frag
                if self.on_new_shard is not None:
                    self.on_new_shard(shard)
            return frag

    def available_shards(self) -> List[int]:
        return sorted(self.fragments)

    # Pass-throughs (reference view.go:294-421).

    def set_bit(self, row_id: int, column_id: int) -> bool:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        return self.create_fragment_if_not_exists(
            column_id // SHARD_WIDTH).set_bit(row_id, column_id)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        frag = self.fragment(column_id // SHARD_WIDTH)
        return frag.clear_bit(row_id, column_id) if frag else False

    def set_value(self, column_id: int, bit_depth: int, value: int) -> bool:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        return self.create_fragment_if_not_exists(
            column_id // SHARD_WIDTH).set_value(column_id, bit_depth, value)

    def value(self, column_id: int, bit_depth: int):
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        frag = self.fragment(column_id // SHARD_WIDTH)
        if frag is None:
            return 0, False
        return frag.value(column_id, bit_depth)
