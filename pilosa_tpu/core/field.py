"""Field: a typed bitmap matrix within an index.

Reference: /root/reference/field.go:62. Types (field.go:42-45):
  set   — multi-valued rows, TopN cache (default ranked/50k)
  int   — BSI bit-sliced integers with [min, max] and offset encoding
  time  — set + per-time-unit views (quantum "YMDH" subsets)
  mutex — one row per column (set clears previous value)
  bool  — mutex with rows {0: false, 1: true}

A timestamped write fans one bit into one view per quantum unit
(SetBit, field.go:799-837). Field metadata persists as JSON `.meta`
(the reference uses protobuf, field.go:431-476; disk metadata here is
JSON by design — wire parity lives at the HTTP layer, not on disk).
"""

from __future__ import annotations

import json
import os
from pilosa_tpu.utils.locks import make_rlock
from dataclasses import asdict, dataclass
from datetime import datetime
from typing import Dict, List, Optional, Tuple

import numpy as np

from pilosa_tpu.core.view import View, VIEW_STANDARD, view_bsi_name
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core import timeq

FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"
FIELD_TYPE_MUTEX = "mutex"
FIELD_TYPE_BOOL = "bool"

FALSE_ROW_ID = 0
TRUE_ROW_ID = 1


@dataclass
class FieldOptions:
    type: str = FIELD_TYPE_SET
    cache_type: str = cache_mod.CACHE_TYPE_RANKED
    cache_size: int = cache_mod.DEFAULT_CACHE_SIZE
    min: int = 0
    max: int = 0
    time_quantum: str = ""
    keys: bool = False
    no_standard_view: bool = False
    # Declared in-shard column bound (0 = full 2^20 shard width). A
    # TPU-first extension with no reference counterpart: fields whose
    # columns span a small fixed universe (4096-bit molecule
    # fingerprints) declare it so device banks trim to the real span —
    # 512 B/row instead of the 8 KiB container floor — which is 16x less
    # HBM, upload, and sweep traffic. Writes past the bound are
    # rejected.
    max_columns: int = 0

    def validate(self) -> None:
        if self.type not in (FIELD_TYPE_SET, FIELD_TYPE_INT, FIELD_TYPE_TIME,
                             FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL):
            raise ValueError(f"invalid field type: {self.type}")
        if self.type == FIELD_TYPE_INT:
            if self.max < self.min:
                raise ValueError("int field max must be >= min")
            # Predicates ride as two u32 limbs in device params
            # (executor/bsi.py _vbit), covering the reference's int64
            # range (bsiGroup, field.go:1360): up to 63 bit planes.
            if (self.max - self.min).bit_length() > 63:
                raise ValueError(
                    "int field range too large: max-min must fit in 63 bits")
        if self.type == FIELD_TYPE_TIME:
            timeq.validate_quantum(self.time_quantum)
            if not self.time_quantum:
                raise ValueError("time field requires a time quantum")
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        if not 0 <= self.max_columns <= SHARD_WIDTH:
            raise ValueError(
                f"max_columns must be in [0, {SHARD_WIDTH}]")


def bit_depth_for_range(min_v: int, max_v: int) -> int:
    """Bits needed for offset-encoded values in [min, max] (reference
    bitDepth via bsiGroup, field.go:1360-1381). Always at least 1."""
    span = max_v - min_v
    return max(1, span.bit_length())


class BSIGroup:
    """Offset-encoded integer group (reference bsiGroup, field.go:1352)."""

    def __init__(self, name: str, min_v: int, max_v: int):
        self.name = name
        self.min = min_v
        self.max = max_v

    @property
    def bit_depth(self) -> int:
        return bit_depth_for_range(self.min, self.max)

    def base_value(self, value: int) -> int:
        if not (self.min <= value <= self.max):
            raise ValueError(
                f"value {value} outside field range [{self.min}, {self.max}]")
        return value - self.min

    def base_value_clamped(self, value: int, op: str) -> Tuple[int, bool]:
        """Clamp a predicate operand into range; bool=False means the
        predicate can be answered without scanning (reference baseValue,
        field.go:1381-1429)."""
        if op in ("<", "<="):
            if value < self.min:
                return 0, False
            return min(value, self.max) - self.min, True
        if op in (">", ">="):
            if value > self.max:
                return 0, False
            return max(value, self.min) - self.min, True
        if value < self.min or value > self.max:
            return 0, False
        return value - self.min, True


class Field:
    def __init__(self, path: str, index: str, name: str,
                 options: Optional[FieldOptions] = None):
        self.path = path  # .../<index>/<field>
        self.index = index
        self.name = name
        self.options = options or FieldOptions()
        self.options.validate()
        self.views: Dict[str, View] = {}
        self.bsi_groups: Dict[str, BSIGroup] = {}
        self._lock = make_rlock("Field._lock")
        self.on_new_shard = None
        from pilosa_tpu.core.attrs import AttrStore
        self.row_attr_store = AttrStore(os.path.join(self.path, ".row_attrs"))
        self.row_attr_store.open()
        self._row_translator = None  # lazy: only keyed fields pay for one
        if self.options.type == FIELD_TYPE_INT:
            # graftlint: disable=GL008 — one BSI group per int field
            # name: schema-keyed, not request-driven.
            self.bsi_groups[name] = BSIGroup(name, self.options.min,
                                             self.options.max)

    # -- lifecycle ----------------------------------------------------------

    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def save_meta(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        tmp = self.meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(asdict(self.options), f)
        os.replace(tmp, self.meta_path())

    def load_meta(self) -> None:
        if os.path.exists(self.meta_path()):
            with open(self.meta_path()) as f:
                self.options = FieldOptions(**json.load(f))
            if self.options.type == FIELD_TYPE_INT:
                self.bsi_groups[self.name] = BSIGroup(
                    self.name, self.options.min, self.options.max)

    def open(self) -> None:
        self.load_meta()
        views_dir = os.path.join(self.path, "views")
        if os.path.isdir(views_dir):
            for name in os.listdir(views_dir):
                v = self._new_view(name)
                v.open()
                # graftlint: disable=GL008 — the view map IS the
                # field's on-disk contents (standard + time-quantum
                # views): data-plane state whose lifetime is the
                # field's, not an accumulator.
                self.views[name] = v

    @property
    def row_translator(self):
        from pilosa_tpu.core.translate import TranslateStore
        with self._lock:
            if self._row_translator is None:
                self._row_translator = TranslateStore(
                    os.path.join(self.path, ".row_keys"))
                self._row_translator.open()
            return self._row_translator

    def close(self) -> None:
        with self._lock:
            for v in self.views.values():
                v.close()
            if self._row_translator is not None:
                self._row_translator.close()
            self.row_attr_store.close()

    def _new_view(self, name: str) -> View:
        v = View(os.path.join(self.path, "views", name), self.index,
                 self.name, name, cache_type=self.options.cache_type,
                 cache_size=self.options.cache_size,
                 max_columns=self.options.max_columns)
        v.on_new_shard = self._notify_shard
        return v

    def _notify_shard(self, shard: int) -> None:
        if self.on_new_shard is not None:
            self.on_new_shard(self.name, shard)

    def view(self, name: str = VIEW_STANDARD) -> Optional[View]:
        return self.views.get(name)

    def create_view_if_not_exists(self, name: str) -> View:
        with self._lock:
            v = self.views.get(name)
            if v is None:
                v = self._new_view(name)
                v.open()
                self.views[name] = v
            return v

    # -- shard tracking -----------------------------------------------------

    def available_shards(self) -> List[int]:
        shards = set()
        for v in self.views.values():
            shards.update(v.available_shards())
        return sorted(shards)

    # -- writes -------------------------------------------------------------

    def _check_column_bound(self, column_ids) -> None:
        """Writes past a declared max_columns are rejected — the bound is
        a storage/bank-width contract, so an out-of-range bit must fail
        loudly rather than silently vanish from trimmed banks."""
        mc = self.options.max_columns
        if not mc:
            return
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        offs = np.asarray(column_ids, dtype=np.uint64) % \
            np.uint64(SHARD_WIDTH)
        if len(offs) and int(offs.max()) >= mc:
            raise ValueError(
                f"column offset {int(offs.max())} outside the field's "
                f"declared max_columns={mc}")

    def set_bit(self, row_id: int, column_id: int,
                timestamp: Optional[datetime] = None) -> bool:
        """Set a bit, fanning into time views when timestamped (reference
        SetBit, field.go:799-837)."""
        self._check_column_bound([column_id])
        changed = False
        if not self.options.no_standard_view:
            view = self.create_view_if_not_exists(VIEW_STANDARD)
            if self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL):
                changed |= self._set_mutex(view, row_id, column_id)
            else:
                changed |= view.set_bit(row_id, column_id)
        if timestamp is not None:
            if self.options.type != FIELD_TYPE_TIME:
                raise ValueError(
                    f"cannot set timestamp on {self.options.type} field")
            for vname in timeq.views_by_time(VIEW_STANDARD, timestamp,
                                             self.options.time_quantum):
                changed |= self.create_view_if_not_exists(vname).set_bit(
                    row_id, column_id)
        elif self.options.type == FIELD_TYPE_TIME and self.options.no_standard_view:
            raise ValueError("time field with no standard view requires timestamp")
        return changed

    def _set_mutex(self, view: View, row_id: int, column_id: int) -> bool:
        """Mutex semantics: clear the column's existing row first (reference
        handleMutex, fragment.go:416)."""
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        frag = view.create_fragment_if_not_exists(column_id // SHARD_WIDTH)
        existing = frag.mutex_vector(column_id)
        if existing is not None and existing != row_id:
            frag.clear_bit(existing, column_id)
        return frag.set_bit(row_id, column_id)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        changed = False
        for v in self.views.values():
            changed |= v.clear_bit(row_id, column_id)
        return changed

    def set_value(self, column_id: int, value: int) -> bool:
        self._check_column_bound([column_id])
        bsig = self.bsi_groups.get(self.name)
        if bsig is None:
            raise ValueError(f"field {self.name} is not an int field")
        base = bsig.base_value(value)
        view = self.create_view_if_not_exists(view_bsi_name(self.name))
        return view.set_value(column_id, bsig.bit_depth, base)

    def value(self, column_id: int) -> Tuple[int, bool]:
        bsig = self.bsi_groups.get(self.name)
        if bsig is None:
            raise ValueError(f"field {self.name} is not an int field")
        view = self.view(view_bsi_name(self.name))
        if view is None:
            return 0, False
        base, exists = view.value(column_id, bsig.bit_depth)
        return base + bsig.min if exists else 0, exists

    # -- bulk import (reference Import, field.go:1054) -----------------------

    def import_bits(self, row_ids: np.ndarray, column_ids: np.ndarray,
                    timestamps: Optional[List[Optional[datetime]]] = None,
                    clear: bool = False) -> None:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        self._check_column_bound(column_ids)

        # Route (row, col) pairs per target view. None = every pair (no
        # index array, no copy: a 10M-pair fingerprint import must not
        # build a 10M-entry Python list just to select "all").
        by_view: Dict[str, Optional[List[int]]] = {}
        if timestamps is None or self.options.no_standard_view is False:
            by_view[VIEW_STANDARD] = None
        if timestamps is not None:
            if self.options.type != FIELD_TYPE_TIME:
                raise ValueError("timestamps on non-time field")
            for i, ts in enumerate(timestamps):
                if ts is None:
                    continue
                for vname in timeq.views_by_time(VIEW_STANDARD, ts,
                                                 self.options.time_quantum):
                    by_view.setdefault(vname, []).append(i)

        for vname, idxs in by_view.items():
            if vname == VIEW_STANDARD and self.options.no_standard_view:
                continue
            view = self.create_view_if_not_exists(vname)
            if idxs is None:
                rows, cols = row_ids, column_ids
            else:
                sel = np.asarray(idxs, dtype=np.int64)
                rows = row_ids[sel]
                cols = column_ids[sel]
            shards = cols // np.uint64(SHARD_WIDTH)
            for shard in np.unique(shards):
                m = shards == shard
                frag = view.create_fragment_if_not_exists(int(shard))
                if self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL) \
                        and not clear:
                    frag.bulk_import_mutex(rows[m], cols[m])
                else:
                    frag.bulk_import(rows[m], cols[m], clear=clear)

    def import_values(self, column_ids: np.ndarray, values: np.ndarray,
                      clear: bool = False) -> None:
        from pilosa_tpu.ops.bitset import SHARD_WIDTH
        bsig = self.bsi_groups.get(self.name)
        if bsig is None:
            raise ValueError(f"field {self.name} is not an int field")
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        self._check_column_bound(column_ids)
        values = np.asarray(values, dtype=np.int64)
        if len(values) and (values.min() < bsig.min or values.max() > bsig.max):
            raise ValueError("value outside field range")
        base = (values - bsig.min).astype(np.uint64)
        view = self.create_view_if_not_exists(view_bsi_name(self.name))
        shards = column_ids // np.uint64(SHARD_WIDTH)
        for shard in np.unique(shards):
            m = shards == shard
            frag = view.create_fragment_if_not_exists(int(shard))
            frag.import_values(column_ids[m], base[m], bsig.bit_depth,
                               clear=clear)

    # -- time range reads ---------------------------------------------------

    def views_for_range(self, start: datetime, end: datetime) -> List[str]:
        return timeq.views_by_time_range(VIEW_STANDARD, start, end,
                                         self.options.time_quantum)

    def row_time(self, row_id: int, t: datetime, quantum: str):
        """Row restricted to one time view (reference RowTime, field.go:662)."""
        vname = timeq.view_by_time_unit(VIEW_STANDARD, t, quantum)
        return self.view(vname)
