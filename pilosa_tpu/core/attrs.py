"""Attribute store: arbitrary KV attributes on rows and columns.

Reference: /root/reference/attr.go:34 (AttrStore interface) with the BoltDB
implementation (boltdb/attrstore.go:82) and 100-id block checksums for
diff-sync (attr.go:80-119). Host-side by design — attributes never touch
the device (the reference likewise keeps them out of fragments).

Implementation: in-memory dict + JSON file persisted atomically on every
mutation batch; block checksums over sorted (id, sorted-attr) tuples give
the same diff-sync capability the reference gets from BoltDB blocks.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

ATTR_BLOCK_SIZE = 100


class AttrStore:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.attrs: Dict[int, Dict[str, Any]] = {}
        self._lock = threading.RLock()

    def open(self) -> None:
        if self.path and os.path.exists(self.path):
            with open(self.path) as f:
                raw = json.load(f)
            self.attrs = {int(k): v for k, v in raw.items()}

    def close(self) -> None:
        pass

    def _save(self) -> None:
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self.attrs.items()}, f)
        os.replace(tmp, self.path)

    def get(self, id_: int) -> Dict[str, Any]:
        with self._lock:
            return dict(self.attrs.get(id_, {}))

    def set(self, id_: int, attrs: Dict[str, Any]) -> None:
        """Merge attrs for id; null values delete keys (reference
        boltdb/attrstore.go upsert semantics)."""
        with self._lock:
            cur = self.attrs.setdefault(id_, {})
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            if not cur:
                self.attrs.pop(id_, None)
            self._save()

    def set_bulk(self, items: Dict[int, Dict[str, Any]]) -> None:
        with self._lock:
            for id_, attrs in items.items():
                cur = self.attrs.setdefault(id_, {})
                for k, v in attrs.items():
                    if v is None:
                        cur.pop(k, None)
                    else:
                        cur[k] = v
                if not cur:
                    self.attrs.pop(id_, None)
            self._save()

    def ids_matching(self, key: str, values: List[Any]) -> List[int]:
        """Row ids whose attr `key` is in `values` (TopN attrName/attrValues
        filter, executor.go:764)."""
        vals = values if isinstance(values, list) else [values]
        with self._lock:
            # Linear compare, not set membership: stored values may be
            # unhashable (lists are legal attr values).
            return sorted(i for i, a in self.attrs.items()
                          if any(a.get(key) == v for v in vals))

    def blocks(self) -> List[Tuple[int, bytes]]:
        """(block, checksum) pairs over 100-id blocks (reference
        attr.go:80-119) for anti-entropy diffing."""
        with self._lock:
            by_block: Dict[int, List[Tuple[int, str]]] = {}
            for id_, attrs in self.attrs.items():
                by_block.setdefault(id_ // ATTR_BLOCK_SIZE, []).append(
                    (id_, json.dumps(attrs, sort_keys=True)))
            out = []
            for blk in sorted(by_block):
                h = hashlib.blake2b(digest_size=16)
                for id_, payload in sorted(by_block[blk]):
                    h.update(f"{id_}:{payload};".encode())
                out.append((blk, h.digest()))
            return out

    def block_data(self, block: int) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {i: dict(a) for i, a in self.attrs.items()
                    if i // ATTR_BLOCK_SIZE == block}
