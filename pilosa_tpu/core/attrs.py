"""Attribute store: arbitrary KV attributes on rows and columns.

Reference: /root/reference/attr.go:34 (AttrStore interface) with the BoltDB
implementation (boltdb/attrstore.go:82) and 100-id block checksums for
diff-sync (attr.go:80-119). Host-side by design — attributes never touch
the device (the reference likewise keeps them out of fragments).

Implementation: in-memory dict + snapshot file + append-only delta log.
A mutation appends ONE log line (the delta batch) — O(batch), flat in
store size, the analog of the reference's per-key BoltDB upserts
(boltdb/attrstore.go:218-280); the earlier whole-store rewrite per set()
fell over on attr-heavy imports. The log compacts back into the
snapshot when it grows past bounds; open() loads the snapshot, replays
complete log lines, and truncates a torn tail (a crash mid-append loses
at most the in-flight batch, never the store — same discipline as the
fragment oplog). Block checksums over sorted (id, sorted-attr) tuples
give the same diff-sync capability the reference gets from BoltDB
blocks.
"""

from __future__ import annotations

import hashlib
import json
import os
from pilosa_tpu.utils.locks import make_rlock
from typing import Any, Dict, List, Optional, Tuple

ATTR_BLOCK_SIZE = 100
# Compaction bounds: replay work stays O(entries), disk stays O(bytes).
LOG_COMPACT_ENTRIES = int(os.environ.get("PILOSA_TPU_ATTR_LOG_ENTRIES",
                                         4096))
LOG_COMPACT_BYTES = int(os.environ.get("PILOSA_TPU_ATTR_LOG_BYTES",
                                       8 << 20))


class AttrStore:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.attrs: Dict[int, Dict[str, Any]] = {}
        self._lock = make_rlock("AttrStore._lock")
        self._log_fh = None
        self._log_entries = 0
        self._log_bytes = 0
        # Monotonic mutation counter: attr writes do NOT bump fragment
        # generations, so generation-keyed caches whose values embed
        # attrs (the executor's request-level result cache) stamp this
        # alongside — any set()/set_bulk() invalidates them.
        self.gen = 0

    @property
    def _log_path(self) -> str:
        return self.path + ".log"

    def open(self) -> None:
        if not self.path:
            return
        # Under the lock: open() normally runs before the store is
        # shared, but the acquisition costs nothing and makes the
        # publication of `attrs` ordered against concurrent get()s if
        # a holder ever reopens live.
        with self._lock:
            if os.path.exists(self.path):
                with open(self.path) as f:
                    raw = json.load(f)
                self.attrs = {int(k): v for k, v in raw.items()}
            if os.path.exists(self._log_path):
                keep = 0
                with open(self._log_path, "rb") as f:
                    for line in f:
                        try:
                            delta = json.loads(line)
                        except ValueError:
                            break  # torn tail: stop at the first bad
                            # line
                        self._apply(
                            {int(k): v for k, v in delta.items()})
                        keep += len(line)
                        self._log_entries += 1
                if keep < os.path.getsize(self._log_path):
                    with open(self._log_path, "ab") as f:
                        f.truncate(keep)
                self._log_bytes = keep

    def close(self) -> None:
        with self._lock:
            if self._log_fh is not None:
                self._log_fh.close()
                self._log_fh = None

    def _apply(self, items: Dict[int, Dict[str, Any]]) -> None:
        """Merge a delta batch into memory (null values delete keys)."""
        self.gen += 1
        for id_, attrs in items.items():
            cur = self.attrs.setdefault(id_, {})
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            if not cur:
                self.attrs.pop(id_, None)

    def _append(self, items: Dict[int, Dict[str, Any]]) -> None:
        """One log line per mutation batch — the O(batch) write path."""
        if not self.path:
            return
        line = json.dumps({str(k): v for k, v in items.items()},
                          separators=(",", ":")) + "\n"
        if self._log_fh is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._log_fh = open(self._log_path, "a")
        self._log_fh.write(line)
        self._log_fh.flush()
        self._log_entries += 1
        self._log_bytes += len(line)
        if self._log_entries >= LOG_COMPACT_ENTRIES or \
                self._log_bytes >= LOG_COMPACT_BYTES:
            self._compact()

    def _compact(self) -> None:
        """Fold the log into the snapshot (atomic replace, then reset
        the log). Crash between the replace and the reset replays the
        already-folded deltas on next open — merges are idempotent."""
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self.attrs.items()}, f)
            f.flush()
            os.fsync(f.fileno())  # the log truncates right after: the
            # snapshot must be durable first or a crash loses BOTH
            # (same discipline as Fragment._snapshot).
        os.replace(tmp, self.path)
        if self._log_fh is not None:
            self._log_fh.close()
        self._log_fh = open(self._log_path, "w")
        self._log_entries = 0
        self._log_bytes = 0

    def get(self, id_: int) -> Dict[str, Any]:
        with self._lock:
            return dict(self.attrs.get(id_, {}))

    def set(self, id_: int, attrs: Dict[str, Any]) -> None:
        """Merge attrs for id; null values delete keys (reference
        boltdb/attrstore.go upsert semantics)."""
        with self._lock:
            self._apply({id_: attrs})
            self._append({id_: attrs})

    def set_bulk(self, items: Dict[int, Dict[str, Any]]) -> None:
        with self._lock:
            self._apply(items)
            self._append(items)

    def ids_matching(self, key: str, values: List[Any]) -> List[int]:
        """Row ids whose attr `key` is in `values` (TopN attrName/attrValues
        filter, executor.go:764)."""
        vals = values if isinstance(values, list) else [values]
        with self._lock:
            # Linear compare, not set membership: stored values may be
            # unhashable (lists are legal attr values).
            return sorted(i for i, a in self.attrs.items()
                          if any(a.get(key) == v for v in vals))

    def blocks(self) -> List[Tuple[int, bytes]]:
        """(block, checksum) pairs over 100-id blocks (reference
        attr.go:80-119) for anti-entropy diffing."""
        with self._lock:
            by_block: Dict[int, List[Tuple[int, str]]] = {}
            for id_, attrs in self.attrs.items():
                by_block.setdefault(id_ // ATTR_BLOCK_SIZE, []).append(
                    (id_, json.dumps(attrs, sort_keys=True)))
            out = []
            for blk in sorted(by_block):
                h = hashlib.blake2b(digest_size=16)
                for id_, payload in sorted(by_block[blk]):
                    h.update(f"{id_}:{payload};".encode())
                out.append((blk, h.digest()))
            return out

    def block_data(self, block: int) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {i: dict(a) for i, a in self.attrs.items()
                    if i // ATTR_BLOCK_SIZE == block}
