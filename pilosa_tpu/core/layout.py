"""Adaptive hybrid bank layout: the workload-driven re-layout pass
that closes the measure→act loop (ROADMAP item 1).

PR 5's memledger *quantifies* HBM waste (live vs padded bytes per
bank), PR 6's workload plane *ranks* demotion candidates (the
``demotionScore = (1 - density) * bytes / (1 + rate)`` quadrants at
``/debug/hotspots``) — and until this module nothing acted on either
signal: every view served queries from dense ``ViewBank``s whose rows
pad to the full trimmed width, so a sparse row costs the same HBM as a
full one. This is exactly the array-vs-bitmap decision Roaring makes
per container (PAPERS.md 1402.6407/1603.06549, ``storage/roaring.py``
host-side); here it is made per VIEW for the device-resident banks:

- **Hot/dense views** stay in dense ``ViewBank``s — the gather-only
  hot path is untouched, which is what bounds the q/s regression.
- **Sparse/cold views** demote to :class:`~pilosa_tpu.core.view.
  SparseBank`s (encoded set-bit positions, ~4 B/set bit), served
  through the megakernel IR's ``OP_EXPAND`` opcode / the jitted
  ``expand_positions`` scatter — bit-identical to dense by the same
  carry-free-add argument as the sparse-upload path, pinned by the
  plan fuzzer's three-way differential.

:class:`LayoutManager` is the background pass (modeled on
``Bitmap.optimize``, storage/roaring.py): each run joins the ledger's
bank entries (bytes, pad share, sampled live-bit density) against the
workload recorder's per-view read rates, demotes the highest-scoring
sparse-cold banks — always when the memledger watchdog's HBM
watermark is crossed, otherwise only banks under the density
threshold — and promotes sparse views whose read rate climbed back
above the promotion threshold. Every flip follows the rank-cache
epoch discipline PR 10 proved: representations change, DATA never
does, so a racing query can at worst take a spurious cache miss or
serve from the representation it planned against — never a stale hit
(tests/test_layout.py pins the interleavings under
``PILOSA_TPU_LOCK_CHECK``).

Kill switch: ``PILOSA_TPU_HYBRID_LAYOUT=0`` disables sparse planning
AND the re-layout pass outright; results are byte-identical either
way (tools/layout_smoke.py gates exactly that).

Host-side module: the pass itself never touches the device beyond the
``sparse_bank`` builds it explicitly requests (which are ordinary
bank builds under the HBM budget).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from pilosa_tpu.core.view import VIEW_BSI_PREFIX
from pilosa_tpu.utils.hotspots import WORKLOAD
from pilosa_tpu.utils.locks import make_lock
from pilosa_tpu.utils.memledger import LEDGER

# The blunt kill switch over the whole hybrid layout: planning never
# emits sparse leaves and the re-layout pass refuses to run. Module
# attribute like executor.FUSION_ENABLED — tests toggle it directly,
# the env var sets the process default.
HYBRID_LAYOUT_ENABLED = os.environ.get(
    "PILOSA_TPU_HYBRID_LAYOUT", "1") != "0"


def entry_density_score(
        info: Dict[str, Any],
        rate: float) -> Optional[Tuple[float, float]]:
    """(density, demotionScore) of one ledger bank entry: density is
    the pad-share times the clamped sampled live-bit density, score is
    ``(1 - density) * bytes / (1 + rate)`` — THE quadrant formula, the
    single implementation behind BankBudget eviction and the re-layout
    ranking. (hotspots._bank_quadrants keeps a self-contained copy of
    the same formula: that module is deliberately import-light and
    importing it from here would close a cycle — a formula change must
    land in both, pinned by the tests comparing their rankings.)
    Returns None for unpriceable entries."""
    nbytes = int(info.get("bytes", 0) or 0)
    if nbytes <= 0:
        return None
    padded = int(info.get("paddedBytes", 0) or 0)
    density = max(0.0, 1.0 - padded / nbytes)
    live = info.get("liveDensity")
    if live is not None:
        try:
            density *= max(0.0, min(1.0, float(live)))
        except (TypeError, ValueError):
            pass
    return density, (1.0 - density) * nbytes / (1.0 + rate)


def demotion_scores(entries: Iterable[Any]) -> Dict[Any, float]:
    """Demotion score per BankBudget entry key ((id(view), cache_key)
    -> score) for the entries the ledger + workload plane can price —
    applied at eviction time so HBM pressure evicts the
    sparsest-coldest bank first. Unpriceable entries are simply absent
    (the caller treats them as score 0 and falls back to LRU)."""
    from pilosa_tpu.core.view import BankBudget

    rates = WORKLOAD.view_read_rates()
    out: Dict[Any, float] = {}
    for ek in entries:
        info = LEDGER.entry_info(BankBudget.LEDGER_CATEGORIES, ek)
        if info is None:
            continue
        ds = entry_density_score(
            info, rates.get((info.get("index", ""),
                             info.get("field", ""),
                             info.get("view", "")), 0.0))
        if ds is not None:
            out[ek] = ds[1]
    return out


class LayoutManager:
    """The background re-layout pass + its counters/gauges (the
    ``pilosa_layout_*`` family on /metrics, the ``layout`` stanza in
    /debug/memory and /internal/health).

    ``relayout_once()`` is one complete pass (the thread just calls it
    every ``interval_s``); it is also the unit tests and the smoke
    drive directly. Thread-safe: one pass at a time, counters under a
    leaf lock."""

    def __init__(self, holder: Any, interval_s: float = 30.0,
                 demote_density: float = 0.25,
                 min_bytes: int = 1 << 20,
                 promote_rate: float = 0.5,
                 watermark_bytes: int = 0,
                 stats: Optional[Any] = None,
                 logger: Optional[Any] = None) -> None:
        self.holder = holder
        self.enabled = True
        self.interval_s = max(0.0, float(interval_s))
        self.demote_density = float(demote_density)
        self.min_bytes = int(min_bytes)
        self.promote_rate = float(promote_rate)
        self.watermark_bytes = int(watermark_bytes)
        self.stats = stats
        self.logger = logger
        self._lock = make_lock("LayoutManager._lock")
        self._run_lock = make_lock("LayoutManager._run_lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Cumulative counters (monotone; also stats.count-ed at event
        # time so the exported pilosa_layout_* stay true counters).
        self.relayout_runs = 0
        self.demotions = 0
        self.promotions = 0
        self.demote_failures = 0
        self.bytes_reclaimed = 0   # device-byte drop summed over runs
        self.last_run_at: Optional[float] = None
        self.last_delta_bytes = 0  # signed device delta of the last run

    # ---------------------------------------------------------- configure

    def configure(self, enabled: Optional[bool] = None,
                  interval_s: Optional[float] = None,
                  demote_density: Optional[float] = None,
                  min_bytes: Optional[int] = None,
                  promote_rate: Optional[float] = None,
                  watermark_bytes: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if interval_s is not None:
                self.interval_s = max(0.0, float(interval_s))
            if demote_density is not None:
                self.demote_density = float(demote_density)
            if min_bytes is not None:
                self.min_bytes = int(min_bytes)
            if promote_rate is not None:
                self.promote_rate = float(promote_rate)
            if watermark_bytes is not None:
                self.watermark_bytes = int(watermark_bytes)

    # ------------------------------------------------------------ the pass

    def _resolve_view(self, index: str, field: str,
                      view: str) -> Optional[Any]:
        idx = self.holder.index(index)
        f = idx.field(field) if idx is not None else None
        return f.view(view) if f is not None else None

    @staticmethod
    def _eligible(view: Any) -> bool:
        """A view the hybrid layout may demote: a row-leaf view (BSI
        plane banks gather depth+1 rows per leaf and stay dense) whose
        trimmed width fits the u16 bitpos encoding."""
        from pilosa_tpu.core.fragment import CONTAINER_BITS
        if view is None or view.name.startswith(VIEW_BSI_PREFIX):
            return False
        if not view.fragments:
            return False
        return bool(view.trimmed_words() * 32 <= CONTAINER_BITS)

    def _sparse_views(self) -> List[Any]:
        out: List[Any] = []
        for idx in list(self.holder.indexes.values()):
            for f in list(idx.fields.values()):
                for v in list(f.views.values()):
                    if v.layout_mode == "sparse":
                        out.append(v)
        return out

    def demote(self, view: Any) -> bool:
        """Dense -> sparse: drop the view's dense cached banks and
        prebuild the SparseBank so the before/after byte delta is
        ledger-provable immediately (lazy rebuild would defer the
        *gain*, not just the cost). Host storage is compacted first
        (``Fragment.optimize_storage`` — the ``Bitmap.optimize`` this
        pass is modeled on): point writes densify their row's
        container for mutation, and a Set-built view would otherwise
        read as "too dense" for the positions gather even though its
        rows are nearly empty. Reverts (and counts a failure) when the
        view is GENUINELY too dense for the sparse codec."""
        if not self._eligible(view):
            return False
        for frag in list(view.fragments.values()):
            try:
                frag.optimize_storage()
            except Exception:
                pass  # compaction is an optimization, never a gate
        view.set_layout("sparse")
        shards = tuple(view.available_shards())
        bank = view.sparse_bank(shards) if shards else None
        if shards and bank is None:
            view.set_layout("dense")
            with self._lock:
                self.demote_failures += 1
            return False
        with self._lock:
            self.demotions += 1
        if self.stats is not None:
            self.stats.count("layout.demotions", 1)
        if self.logger is not None:
            self.logger.printf(
                "layout: demoted %s/%s/%s to sparse (%d rows, %d "
                "bytes resident)", view.index, view.field, view.name,
                bank.n_rows if bank else 0,
                bank.nbytes if bank else 0)
        return True

    def promote(self, view: Any) -> bool:
        """Sparse -> dense: drop the SparseBank; the dense bank
        rebuilds lazily on the next query (promotion is triggered by
        heat, so "next query" is imminent and pays one build — the
        same cost a cold dense view pays today)."""
        if not view.set_layout("dense"):
            return False
        with self._lock:
            self.promotions += 1
        if self.stats is not None:
            self.stats.count("layout.promotions", 1)
        if self.logger is not None:
            self.logger.printf("layout: promoted %s/%s/%s to dense",
                               view.index, view.field, view.name)
        return True

    def relayout_once(self) -> Dict[str, Any]:
        """One complete re-layout pass; returns its summary (also the
        shape of the health/debug stanza's lastRun)."""
        if not (self.enabled and HYBRID_LAYOUT_ENABLED):
            return {"ran": False, "reason": "disabled"}
        with self._run_lock:
            device_before = LEDGER.total_bytes(device_only=True)
            over = bool(self.watermark_bytes
                        and device_before >= self.watermark_bytes)
            rates = WORKLOAD.view_read_rates()
            demoted = promoted = 0
            # Demotion leg: ledger dense-bank entries scored by the
            # quadrant formula, sparsest-coldest first.
            cands: List[Tuple[float, float, Dict[str, Any]]] = []
            for e in LEDGER.entries("bank"):
                if int(e.get("bytes", 0) or 0) < self.min_bytes \
                        or not e.get("view"):
                    continue
                rate = rates.get((e["index"], e["field"], e["view"]),
                                 0.0)
                ds = entry_density_score(e, rate)
                if ds is None:
                    continue
                density, score = ds
                cands.append((score, density, e))
            cands.sort(key=lambda c: -c[0])
            for score, density, e in cands:
                # Watermark pressure demotes the ranking top-down;
                # below the watermark only genuinely sparse banks
                # (density under the threshold) move — a merely-cold
                # dense bank is the LRU budget's job, not ours.
                if not over and density > self.demote_density:
                    continue
                view = self._resolve_view(e["index"], e["field"],
                                          e["view"])
                if view is None or view.layout_mode == "sparse":
                    continue
                rate = rates.get((e["index"], e["field"], e["view"]),
                                 0.0)
                if rate > self.promote_rate and not over:
                    continue  # hot stays dense unless pressure forces
                if self.demote(view):
                    demoted += 1
            # Promotion leg: sparse views whose read rate climbed back.
            for view in self._sparse_views():
                rate = rates.get((view.index, view.field, view.name),
                                 0.0)
                if rate > self.promote_rate:
                    if self.promote(view):
                        promoted += 1
            device_after = LEDGER.total_bytes(device_only=True)
            delta = device_after - device_before
            with self._lock:
                self.relayout_runs += 1
                self.last_run_at = time.time()
                self.last_delta_bytes = delta
                if delta < 0:
                    self.bytes_reclaimed += -delta
            if self.stats is not None:
                self.stats.count("layout.relayout_runs", 1)
            return {"ran": True, "overWatermark": over,
                    "demoted": demoted, "promoted": promoted,
                    "deviceBytesBefore": device_before,
                    "deviceBytesAfter": device_after,
                    "deltaBytes": delta}

    # ------------------------------------------------------------- reading

    def snapshot(self) -> Dict[str, Any]:
        """The layout stanza for /debug/memory and /internal/health."""
        sparse = self._sparse_views()
        sparse_bytes = sum(
            int(t.get("bytes", 0))
            for t in [LEDGER.totals().get("sparse_bank", {})])
        with self._lock:
            return {
                "enabled": bool(self.enabled and HYBRID_LAYOUT_ENABLED),
                "sparseViews": len(sparse),
                "sparseBankBytes": sparse_bytes,
                "relayoutRuns": self.relayout_runs,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "demoteFailures": self.demote_failures,
                "bytesReclaimed": self.bytes_reclaimed,
                "lastRunAt": self.last_run_at,
                "lastDeltaBytes": self.last_delta_bytes,
                "watermarkBytes": self.watermark_bytes,
            }

    def publish(self, stats: Optional[Any]) -> None:
        """Scrape-time gauges (counters increment at event time, so
        pilosa_layout_{demotions,promotions,relayout_runs}_total stay
        true monotone counters)."""
        if stats is None:
            return
        s = self.snapshot()
        stats.gauge("layout.sparse_views", s["sparseViews"])
        stats.gauge("layout.sparse_bank_bytes", s["sparseBankBytes"])
        stats.gauge("layout.bytes_reclaimed", s["bytesReclaimed"])

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None or self.interval_s <= 0:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.relayout_once()
                except Exception:
                    pass  # a bad pass must not end the layout plane

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="layout-relayout")
        self._thread.start()

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s + 5)
            self._thread = None
