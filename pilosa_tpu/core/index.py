"""Index: a namespace sharing one column space.

Reference: /root/reference/index.go:35. Owns fields, the optional existence
field `_exists` used by Not()/existence tracking (index.go:167-175,
holder.go:46), and `keys`/`trackExistence` options (index.go:469).
Available shards for the index = union over fields (index.go:238).
"""

from __future__ import annotations

import json
import os
import shutil
from pilosa_tpu.utils.locks import make_rlock
from typing import Dict, List, Optional

import numpy as np

from pilosa_tpu.core.field import Field, FieldOptions, FIELD_TYPE_SET
from pilosa_tpu.core import cache as cache_mod

EXISTENCE_FIELD_NAME = "_exists"


class Index:
    def __init__(self, path: str, name: str, keys: bool = False,
                 track_existence: bool = True):
        self.path = path  # <data>/<index>
        self.name = name
        self.keys = keys
        self.track_existence = track_existence
        self.fields: Dict[str, Field] = {}
        self._lock = make_rlock("Index._lock")
        self.on_new_shard = None  # callback(field, shard)
        from pilosa_tpu.core.attrs import AttrStore
        self.column_attr_store = AttrStore(os.path.join(path, ".col_attrs"))
        self.column_attr_store.open()
        self._column_translator = None  # lazy: only keyed indexes pay

    # -- lifecycle ----------------------------------------------------------

    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def save_meta(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        tmp = self.meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"keys": self.keys,
                       "trackExistence": self.track_existence}, f)
        os.replace(tmp, self.meta_path())

    def load_meta(self) -> None:
        if os.path.exists(self.meta_path()):
            with open(self.meta_path()) as f:
                meta = json.load(f)
            self.keys = meta.get("keys", False)
            self.track_existence = meta.get("trackExistence", True)

    def open(self) -> None:
        self.load_meta()
        for name in sorted(os.listdir(self.path)) if os.path.isdir(self.path) else []:
            fpath = os.path.join(self.path, name)
            if not os.path.isdir(fpath):
                continue
            f = Field(fpath, self.name, name)
            f.open()
            f.on_new_shard = self._notify_shard
            self.fields[name] = f
        if self.track_existence and EXISTENCE_FIELD_NAME not in self.fields:
            self._create_existence_field()

    @property
    def column_translator(self):
        from pilosa_tpu.core.translate import TranslateStore
        with self._lock:
            if self._column_translator is None:
                self._column_translator = TranslateStore(
                    os.path.join(self.path, ".keys"))
                self._column_translator.open()
            return self._column_translator

    def close(self) -> None:
        with self._lock:
            for f in self.fields.values():
                f.close()
            if self._column_translator is not None:
                self._column_translator.close()
            self.column_attr_store.close()

    def _notify_shard(self, field: str, shard: int) -> None:
        if self.on_new_shard is not None:
            self.on_new_shard(self.name, field, shard)

    # -- fields -------------------------------------------------------------

    def _create_existence_field(self) -> Field:
        opts = FieldOptions(type=FIELD_TYPE_SET,
                            cache_type=cache_mod.CACHE_TYPE_NONE, cache_size=0)
        f = Field(os.path.join(self.path, EXISTENCE_FIELD_NAME), self.name,
                  EXISTENCE_FIELD_NAME, opts)
        f.save_meta()
        f.on_new_shard = self._notify_shard
        self.fields[EXISTENCE_FIELD_NAME] = f
        return f

    def existence_field(self) -> Optional[Field]:
        return self.fields.get(EXISTENCE_FIELD_NAME)

    def field(self, name: str) -> Optional[Field]:
        return self.fields.get(name)

    def create_field(self, name: str, options: Optional[FieldOptions] = None,
                     error_if_exists: bool = True) -> Field:
        with self._lock:
            if name in self.fields:
                if error_if_exists:
                    raise ValueError(f"field already exists: {name}")
                return self.fields[name]
            if name.startswith("_") and name != EXISTENCE_FIELD_NAME:
                raise ValueError(f"invalid field name: {name}")
            f = Field(os.path.join(self.path, name), self.name, name, options)
            f.save_meta()
            f.on_new_shard = self._notify_shard
            self.fields[name] = f
            return f

    def delete_field(self, name: str) -> None:
        with self._lock:
            f = self.fields.pop(name, None)
            if f is None:
                raise KeyError(f"field not found: {name}")
            f.close()
            shutil.rmtree(f.path, ignore_errors=True)

    # -- existence tracking --------------------------------------------------

    def add_existence(self, column_ids: np.ndarray) -> None:
        """Mark columns as existing (driven by every write path when
        trackExistence; reference importExistenceColumns, api.go:908)."""
        ef = self.existence_field()
        if ef is None:
            return
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        ef.import_bits(np.zeros(len(column_ids), dtype=np.uint64), column_ids)

    # -- shards --------------------------------------------------------------

    def available_shards(self) -> List[int]:
        shards = set()
        for f in self.fields.values():
            shards.update(f.available_shards())
        return sorted(shards)
