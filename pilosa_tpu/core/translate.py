"""Key translation: string keys <-> uint64 ids.

Reference: /root/reference/translate.go (TranslateStore interface :40,
TranslateFile :56 — an append-only mmap log with an in-memory index,
chained-replicated between nodes over HTTP: each node streams the log from
its predecessor, translate.go:400, holder.go:626).

Here: an append-only log of explicit (key, id) records. In a cluster, only
the translation primary allocates ids (via POST /internal/translate/keys,
the reference's handler.go:274 endpoint); replicas replay the primary's
log — explicit ids make replication exact regardless of replay order.

On-disk file format: an 8-byte header (magic "PTLT" + uint32 version),
then records of: uint32 key length, utf-8 key bytes, uint64 id. The
replication stream (`read_log_from`) carries records only. A file whose
header does not match errors loudly on open — silently misparsing another
format's length prefixes would map garbage keys to live ids.
"""

from __future__ import annotations

import os
import struct
from pilosa_tpu.utils.locks import make_rlock
from typing import Dict, Iterable, List, Optional

import numpy as np


class TranslateStore:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._ids: Dict[str, int] = {}
        self._keys: Dict[int, str] = {}
        self._next_id = 1
        self._file = None
        self._lock = make_rlock("TranslateStore._lock")
        # Byte cursor into the replication PRIMARY's log (see apply_log);
        # in-memory only — a restart re-replays from 0, idempotently.
        self.replica_offset = 0
        # How many bytes of our id-ordered log are safe to SERVE to a
        # chained successor (read_log_from): None = all (we allocate,
        # so our id-ordered log IS the stream). On a replica it equals
        # replica_offset: the primary allocates ids monotonically and
        # streams id-ordered, so ids <= the last streamed id are
        # exactly the streamed prefix, and any out-of-band adopted
        # entry (apply_entries) has a HIGHER id — serving past the
        # streamed prefix would splice those holes into a successor's
        # stream at wrong byte positions.
        self.served_limit: Optional[int] = None

    # -- lifecycle ----------------------------------------------------------

    MAGIC = b"PTLT" + struct.pack("<I", 1)

    def open(self) -> None:
        if self.path is None:
            return
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            with open(self.path, "rb") as f:
                data = f.read()
            if len(data) < len(self.MAGIC) \
                    and self.MAGIC.startswith(data):
                # Crash mid-initial-header-write: no records can exist yet,
                # so rewrite the header and treat the log as empty.
                with open(self.path, "wb") as f:
                    f.write(self.MAGIC)
                self._file = open(self.path, "ab")
                return
            if not data.startswith(self.MAGIC):
                raise ValueError(
                    f"{self.path}: bad translate log header "
                    f"{data[:8]!r}; expected {self.MAGIC!r}")
            self.apply_log(data[len(self.MAGIC):], _persist=False)
            self._file = open(self.path, "ab")
        else:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._file = open(self.path, "ab")
            if self._file.tell() == 0:
                self._file.write(self.MAGIC)
                self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.flush()
            self._file.close()
            self._file = None

    # -- core ---------------------------------------------------------------

    def _insert(self, key: str, id_: int, persist: bool = True) -> None:
        # graftlint: disable=GL008 — the translate store is append-only
        # BY CONTRACT (ids, once handed out, stay resolvable for the
        # life of the index; the reference never shrinks it either).
        self._ids[key] = id_
        self._keys[id_] = key  # graftlint: disable=GL008 — same contract
        self._next_id = max(self._next_id, id_ + 1)
        if persist and self._file is not None:
            raw = key.encode("utf-8")
            self._file.write(struct.pack("<I", len(raw)) + raw
                             + struct.pack("<Q", id_))
            self._file.flush()

    def translate_key(self, key: str, create: bool = True) -> Optional[int]:
        with self._lock:
            id_ = self._ids.get(key)
            if id_ is None and create:
                id_ = self._next_id
                self._insert(key, id_)
                # Allocating locally means we ARE the (possibly just
                # promoted) primary: our id-ordered log is the stream,
                # serve all of it. A promoted node's pre-promotion
                # catch-up made its prefix complete; its new
                # allocations extend the id order at the end.
                self.served_limit = None
            return id_

    def translate_keys(self, keys: Iterable[str], create: bool = True
                       ) -> np.ndarray:
        """(reference TranslateColumnsToUint64, translate.go:473)."""
        return np.array([self.translate_key(k, create) or 0 for k in keys],
                        dtype=np.uint64)

    def translate_id(self, id_: int) -> Optional[str]:
        with self._lock:
            return self._keys.get(int(id_))

    def translate_ids(self, ids: Iterable[int]) -> List[Optional[str]]:
        return [self.translate_id(int(i)) for i in ids]

    def apply_entries(self, pairs: Iterable[tuple]) -> None:
        """Adopt (key, id) allocations made by the translation primary."""
        with self._lock:
            for key, id_ in pairs:
                cur = self._ids.get(key)
                if cur is None:
                    self._insert(key, int(id_))
                    # Out-of-band adoption marks us a replica: successors
                    # may only be served the streamed prefix.
                    if self.served_limit is None:
                        self.served_limit = self.replica_offset
                elif cur != id_:
                    raise ValueError(
                        f"translate conflict for {key!r}: {cur} != {id_}")

    def size(self) -> int:
        """Number of allocated (key, id) entries (cheap; used to version
        negative reverse-lookup caches)."""
        with self._lock:
            return len(self._ids)

    def entries(self) -> List[tuple]:
        with self._lock:
            return sorted(self._ids.items(), key=lambda kv: kv[1])

    # -- replication (reference /internal/translate/data) --------------------

    def log_bytes(self) -> bytes:
        with self._lock:
            out = bytearray()
            for key, id_ in self.entries():
                raw = key.encode("utf-8")
                out += struct.pack("<I", len(raw)) + raw
                out += struct.pack("<Q", id_)
            return bytes(out)

    def read_log_from(self, offset: int) -> bytes:
        """Serve the replication stream from a byte offset. All nodes
        serve the SAME byte stream (the primary's id-ordered log), so
        one cursor is valid against any source in the chain; replicas
        serve only their streamed prefix (served_limit)."""
        with self._lock:
            data = self.log_bytes()
            if self.served_limit is not None:
                data = data[:self.served_limit]
            return data[offset:]

    def apply_log(self, data: bytes, _persist: bool = True,
                  resume: bool = False) -> int:
        """Replay streamed records (replica side of replication,
        translate.go:400). `resume=True` advances `replica_offset` by the
        bytes fully consumed — the cursor into the PRIMARY's log stream.
        The cursor, not our own log size, is the resume point: replicas
        also adopt out-of-order entries from primary-fallback lookups
        (apply_entries), so the local log is not a prefix of the
        primary's. Replay is idempotent (known keys skip), so a stale or
        reset cursor only costs re-download, never correctness."""
        applied = 0
        pos = 0
        with self._lock:
            while pos + 4 <= len(data):
                (n,) = struct.unpack_from("<I", data, pos)
                if pos + 4 + n + 8 > len(data):
                    # Truncated tail (crash mid-append): stop here, like
                    # the reference trimming a torn op-log record.
                    break
                key = data[pos + 4: pos + 4 + n].decode("utf-8")
                (id_,) = struct.unpack_from("<Q", data, pos + 4 + n)
                if key not in self._ids:
                    self._insert(key, id_, persist=_persist)
                    applied += 1
                pos += 4 + n + 8
            if resume:
                self.replica_offset += pos
                # Streaming marks us a replica (until/unless promoted);
                # the safe-to-serve prefix grows with the cursor.
                self.served_limit = self.replica_offset
        return applied
