"""Key translation: string keys <-> uint64 ids.

Reference: /root/reference/translate.go (TranslateStore interface :40,
TranslateFile :56 — an append-only mmap log with an in-memory hash index,
chained-replicated between nodes over HTTP). Here: an append-only record
log replayed into a host dict. IDs are allocated sequentially from 1 in
append order, so replicas that replay the same log derive the same
mapping — the same property the reference's chained replication relies on
(translate.go:400). The log is exposed for streaming from an offset
(/internal/translate/data parity).

Record format: uint32 length + utf-8 key bytes. Record i (0-based) maps to
id i+1.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Dict, Iterable, List, Optional

import numpy as np


class TranslateStore:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._ids: Dict[str, int] = {}
        self._keys: List[str] = []
        self._file = None
        self._lock = threading.RLock()

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> None:
        if self.path is None:
            return
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                data = f.read()
            pos = 0
            while pos + 4 <= len(data):
                (n,) = struct.unpack_from("<I", data, pos)
                key = data[pos + 4: pos + 4 + n].decode("utf-8")
                self._register(key)
                pos += 4 + n
        else:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._file = open(self.path, "ab")

    def close(self) -> None:
        if self._file is not None:
            self._file.flush()
            self._file.close()
            self._file = None

    def _register(self, key: str) -> int:
        id_ = len(self._keys) + 1
        self._keys.append(key)
        self._ids[key] = id_
        return id_

    # -- translation --------------------------------------------------------

    def translate_key(self, key: str, create: bool = True) -> Optional[int]:
        with self._lock:
            id_ = self._ids.get(key)
            if id_ is None and create:
                id_ = self._register(key)
                if self._file is not None:
                    raw = key.encode("utf-8")
                    self._file.write(struct.pack("<I", len(raw)) + raw)
                    self._file.flush()
            return id_

    def translate_keys(self, keys: Iterable[str], create: bool = True
                       ) -> np.ndarray:
        """(reference TranslateColumnsToUint64, translate.go:473)."""
        return np.array([self.translate_key(k, create) or 0 for k in keys],
                        dtype=np.uint64)

    def translate_id(self, id_: int) -> Optional[str]:
        with self._lock:
            if 1 <= id_ <= len(self._keys):
                return self._keys[id_ - 1]
            return None

    def translate_ids(self, ids: Iterable[int]) -> List[Optional[str]]:
        return [self.translate_id(int(i)) for i in ids]

    # -- replication --------------------------------------------------------

    def log_size(self) -> int:
        with self._lock:
            return sum(4 + len(k.encode("utf-8")) for k in self._keys)

    def read_log_from(self, offset: int) -> bytes:
        """Serialized records from a byte offset (the replica streaming
        endpoint /internal/translate/data, http/handler.go:273)."""
        with self._lock:
            out = bytearray()
            for k in self._keys:
                raw = k.encode("utf-8")
                out += struct.pack("<I", len(raw)) + raw
            return bytes(out[offset:])

    def apply_log(self, data: bytes) -> int:
        """Replay streamed records appended after our current tail
        (replica side of chained replication, translate.go:400)."""
        applied = 0
        pos = 0
        with self._lock:
            while pos + 4 <= len(data):
                (n,) = struct.unpack_from("<I", data, pos)
                key = data[pos + 4: pos + 4 + n].decode("utf-8")
                if key not in self._ids:
                    self.translate_key(key, create=True)
                    applied += 1
                pos += 4 + n
        return applied
