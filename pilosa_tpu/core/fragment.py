"""Fragment: the storage/compute unit for one (index, field, view, shard).

Reference: /root/reference/fragment.go:87. A fragment stores bit
(row i, column c) at position i*2^20 + (c % 2^20) in one flat roaring bitmap
(pos, fragment.go:1036); durability is snapshot + ops log with a rewrite
after MaxOpN=10,000 logged ops (fragment.go:79,1769-1843).

TPU redesign: the host roaring bitmap stays the mutable source of truth and
the durable format, but queries never walk containers. Each fragment
maintains a *device bank* — a dense `uint32[slots, WORDS_PER_SHARD]` array
in HBM holding one slot per materialized row. Reads are gathers from the
bank; multi-row ops (TopN, Rows, GroupBy, BSI) are single batched kernels
over it. Writes mutate the host bitmap, append to the ops log, and mark the
row dirty; dirty slots are re-uploaded lazily before the next device read
(the snapshot ⊕ delta overlay the survey's §7 "Mutability" plan calls for).
"""

from __future__ import annotations

import hashlib
import itertools
import os
from pilosa_tpu.utils.locks import make_rlock
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pilosa_tpu.ops.bitset import (
    SHARD_WIDTH,
    SHARD_WIDTH_EXP,
    WORDS_PER_SHARD,
    u64_to_words,
)
from pilosa_tpu.storage.roaring import Bitmap, CONTAINER_BITS
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.utils.hotspots import WORKLOAD
from pilosa_tpu.utils.logger import default_logger
from pilosa_tpu.utils.memledger import LEDGER

# Snapshot after this many logged single-bit ops (reference MaxOpN,
# fragment.go:79).
DEFAULT_MAX_OP_N = 10000

# Batch import records (compact roaring payloads) fold into a snapshot by
# SIZE, not count: snapshot when the op-log tail since the last snapshot
# exceeds max(this floor, half the last snapshot's size). Divergence from
# the reference, which snapshots after every >MaxOpN-bit import
# (fragment.go:1769) — an O(fragment) rewrite per batch that made ingest
# the bottleneck; the byte-based rule keeps reopen replay O(snapshot
# size) while amortizing rewrites across many batches.
OPLOG_FOLD_MIN_BYTES = 32 << 20

# Bulk imports are split into chunks of this many (row, col) pairs: caps
# a single op record (so MAX_TORN_TAIL_BYTES really does exceed any
# legitimate record) and bounds the scatter's peak working memory.
IMPORT_CHUNK_PAIRS = 4 << 20

# Torn-tail tolerance bound (ADVICE r2): a dangling tail larger than any
# plausible single record is mid-file corruption, not a torn append —
# refuse to open rather than silently sidecar a huge valid suffix. The
# worst legitimate OP_ADD_ROARING record is an IMPORT_CHUNK_PAIRS batch
# where every pair lands in a distinct container: 18 bytes/container
# (12-byte descriptor + 4-byte offset + one 2-byte array value,
# roaring._serialize_container_seq) ≈ 72 MiB at 4M pairs — so the bound
# is sized FROM that worst case with 2x headroom (ADVICE r3: the old
# fixed 64 MiB sat below it, making a crash mid-append of a legitimate
# record unopenable).
MAX_TORN_TAIL_BYTES = 2 * (18 * IMPORT_CHUNK_PAIRS + (1 << 16))

# Containers per shard row: 2^20 / 2^16.
CONTAINERS_PER_ROW = SHARD_WIDTH // CONTAINER_BITS

# Block size for anti-entropy checksums (reference HashBlockSize,
# fragment.go:76): 100 rows per block.
HASH_BLOCK_SIZE = 100


class Fragment:
    # Process-wide fragment epoch allocator — see `self.version` below.
    _VERSION_EPOCH = itertools.count(1)

    def __init__(self, path: str, index: str, field: str, view: str,
                 shard: int, cache_type: str = cache_mod.CACHE_TYPE_RANKED,
                 cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
                 max_op_n: int = DEFAULT_MAX_OP_N):
        self.path = path
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.max_op_n = max_op_n
        self.storage = Bitmap()
        # Size of the last on-disk snapshot section; drives the
        # byte-based op-log fold policy for batch imports.
        self._last_snapshot_bytes = 0
        # Cumulative torn-tail bytes sidecarred at open (ADVICE r2:
        # surfaced through holder stats/health, not just a log line).
        self.tail_dropped_bytes = 0
        self.cache = cache_mod.new_cache(cache_type, cache_size)
        self.cache_type = cache_type
        self._file = None
        self._lock = make_rlock("Fragment._lock")
        # Device bank state.
        self._bank = None          # jnp uint32 [slots, WORDS_PER_SHARD]
        self._slots: Dict[int, int] = {}   # row id -> bank slot
        self._dirty: set = set()   # row ids needing re-upload
        self._bank_all_rows = False  # bank covers every present row
        # Monotonic write version; executors key leaf caches on it. The
        # per-row last-touch versions let view banks patch incrementally.
        # Based at a process-unique epoch (not 0): fragments are popped
        # and recreated across resizes (syncer clean_unowned), and a
        # recreated fragment restarting at version 0 would satisfy any
        # version-keyed cache entry (view banks, merged row lists)
        # built against its predecessor — serving pre-resize data. The
        # 2^48 stride keeps per-fragment write counts from ever
        # reaching the next epoch.
        self.version = next(Fragment._VERSION_EPOCH) << 48
        self._row_versions: Dict[int, int] = {}
        # Block-checksum cache (anti-entropy): block id -> digest, plus
        # the blocks dirtied since it was built. None = cold (full pass
        # on next checksum_blocks call).
        self._block_digests: Optional[Dict[int, bytes]] = None
        self._dirty_blocks: set = set()

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> None:
        with self._lock:
            if os.path.exists(self.path):
                with open(self.path, "rb") as f:
                    data = f.read()
                if data:
                    self.storage.read_bytes(data, tolerate_torn_tail=True)
                    if self.storage.tail_dropped > MAX_TORN_TAIL_BYTES:
                        # A dangling "record" bigger than any plausible
                        # single append is a corrupted mid-file length
                        # field swallowing a valid suffix — fail hard
                        # like the reference (roaring.go:3659) instead
                        # of silently sidecarring megabytes of data
                        # (ADVICE r2).
                        raise ValueError(
                            f"{self.path}: {self.storage.tail_dropped}"
                            "-byte dangling op tail exceeds the torn-"
                            "append bound; refusing to truncate")
                    if self.storage.tail_dropped:
                        # Torn tail append from a crash: move the partial
                        # record to a .torn sidecar (never destroy bytes —
                        # the tail may hold salvageable ops), then
                        # truncate so new appends start at a clean
                        # boundary. Divergence: the reference refuses to
                        # open on any op error (roaring.go:3659). The
                        # drop is surfaced via tail_dropped_bytes for
                        # stats/health, not just this log line.
                        nd = self.storage.tail_dropped
                        self.tail_dropped_bytes += nd
                        default_logger.printf(
                            "%s: moving %d-byte torn op-log tail to "
                            "sidecar", self.path, nd)
                        with open(self.path + ".torn", "ab") as f:
                            f.write(data[len(data) - nd:])
                        with open(self.path, "r+b") as f:
                            f.truncate(len(data) - nd)
            else:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                with open(self.path, "wb") as f:
                    data = self.storage.write_bytes()
                    f.write(data)
                self.storage.snapshot_bytes = len(data)
            self._last_snapshot_bytes = self.storage.snapshot_bytes
            # Unbuffered append: every op record is one write syscall
            # straight to the OS page cache (Go file-write
            # semantics) — a killed PROCESS loses nothing; only
            # a machine crash can tear the tail, which open()
            # recovery already handles.
            self._file = open(self.path, "ab", buffering=0)
            self.storage.op_writer = self._file
            cache_mod.load_cache(self.cache, self.cache_path(),
                                 stamp=self._storage_stamp())
            # If the op log had grown past either limit, fold it now.
            if self._oplog_over_limit():
                self._snapshot()
            # Replay may have materialized containers the snapshot stored
            # as arrays; re-compress sparse ones (reference Optimize,
            # roaring.go:1745).
            self.storage.optimize()

    def optimize_storage(self) -> int:
        """Re-encode sparse containers as u16 arrays (host-memory
        compaction for fingerprint-shaped data; see Bitmap.optimize)."""
        with self._lock:
            return self.storage.optimize()

    def close(self) -> None:
        with self._lock:
            self.flush_cache()
            if self._file is not None:
                self._file.flush()
                self._file.close()
                self._file = None
            self.storage.op_writer = None

    def cache_path(self) -> str:
        return self.path + ".cache"

    def _storage_stamp(self) -> bytes:
        """Fingerprint of the on-disk storage bytes: size + FNV of the
        final 64 bytes. Binds the .cache sidecar to the exact storage
        state it was computed from — ops append and snapshots rewrite, so
        any write that reached disk after the sidecar was saved changes
        the stamp and the loaded cache is treated as cold (an unclean
        shutdown must not let TopN's warm-cache shortcut serve stale
        counts)."""
        import struct
        from pilosa_tpu.storage.roaring import fnv1a32
        try:
            size = os.path.getsize(self.path)
            with open(self.path, "rb") as f:
                f.seek(max(0, size - 64))
                tail = f.read(64)
        except OSError:
            return b""
        return struct.pack("<QI", size, fnv1a32(tail))

    def flush_cache(self) -> None:
        if self.cache_type != cache_mod.CACHE_TYPE_NONE:
            try:
                # The stamp must cover every op already issued: drain the
                # op-writer buffer to disk before fingerprinting.
                if self._file is not None:
                    self._file.flush()
                cache_mod.save_cache(self.cache, self.cache_path(),
                                     stamp=self._storage_stamp())
            except OSError:
                pass

    def _snapshot(self) -> None:
        """Rewrite the storage file without its op-log tail (reference
        snapshot, fragment.go:1793: write .snapshotting, rename, remap)."""
        tmp = self.path + ".snapshotting"
        if self._file is not None:
            self._file.flush()
            self._file.close()
            self._file = None
            self.storage.op_writer = None
        try:
            with open(tmp, "wb") as f:
                f.write(self.storage.write_bytes())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self.storage.op_n = 0
            self.storage.op_n_small = 0
            self.storage.oplog_bytes = 0
            self._last_snapshot_bytes = os.path.getsize(self.path)
            self.storage.snapshot_bytes = self._last_snapshot_bytes
        finally:
            # Restore the append handle even on failure: the old file is
            # still in place and later op appends must keep working on a
            # fragment whose snapshot failed (batch records are already
            # in the log, so no data is at risk — only future appends).
            # Unbuffered append: every op record is one write syscall
            # straight to the OS page cache (Go file-write
            # semantics) — a killed PROCESS loses nothing; only
            # a machine crash can tear the tail, which open()
            # recovery already handles.
            self._file = open(self.path, "ab", buffering=0)
            self.storage.op_writer = self._file

    def _oplog_over_limit(self) -> bool:
        """Snapshot policy: single-bit ops by COUNT (reference MaxOpN
        semantics, fragment.go:79), batch records by op-log BYTES
        relative to the snapshot size (amortized O(1) per imported bit;
        see OPLOG_FOLD_MIN_BYTES)."""
        s = self.storage
        if s.op_n_small >= self.max_op_n:
            return True
        return s.oplog_bytes >= max(OPLOG_FOLD_MIN_BYTES,
                                    self._last_snapshot_bytes // 2)

    def _maybe_snapshot(self) -> None:
        if self._oplog_over_limit():
            self._snapshot()

    # -- position helpers ---------------------------------------------------

    def pos(self, row_id: int, column_id: int) -> int:
        """Bit position for (row, column) (reference pos, fragment.go:1036)."""
        if not (self.shard * SHARD_WIDTH <= column_id
                < (self.shard + 1) * SHARD_WIDTH):
            raise ValueError(
                f"column {column_id} out of shard {self.shard} bounds")
        return row_id * SHARD_WIDTH + (column_id % SHARD_WIDTH)

    # -- single-bit writes --------------------------------------------------

    def set_bit(self, row_id: int, column_id: int) -> bool:
        with self._lock:
            changed = self.storage.add(self.pos(row_id, column_id))
            if changed:
                self._touch_row(row_id)
                self._cache_update(row_id)
                self._maybe_snapshot()
            return changed

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        with self._lock:
            changed = self.storage.remove(self.pos(row_id, column_id))
            if changed:
                self._touch_row(row_id)
                self._cache_update(row_id)
                self._maybe_snapshot()
            return changed

    def bit(self, row_id: int, column_id: int) -> bool:
        return self.storage.contains(self.pos(row_id, column_id))

    # -- row reads ----------------------------------------------------------

    def row_ids(self) -> Tuple[int, ...]:
        """Sorted ids of rows that contain any bit, as an IMMUTABLE
        tuple: the same cached object is returned to every caller until
        the write version bumps (TopN aliases it straight into its
        query row set — a mutable list here would let any caller
        silently corrupt every later query's view of the fragment).
        Cached per write version — TopN/Rows walk this per query and
        fragments can hold hundreds of thousands of containers."""
        with self._lock:
            cached = getattr(self, "_row_ids_cache", None)
            if cached is not None and cached[0] == self.version:
                return cached[1]
            version = self.version  # snapshot BEFORE the walk
            rows = set()
            for key in self.storage.containers:
                if self.storage.container_count(key):
                    rows.add(key // CONTAINERS_PER_ROW)
            out = tuple(sorted(rows))
            self._row_ids_cache = (version, out)
            return out

    def row_count(self, row_id: int) -> int:
        return self.storage.count_range(row_id * SHARD_WIDTH,
                                        (row_id + 1) * SHARD_WIDTH)

    @staticmethod
    def _gather_row_arrays(containers, row_ids, total64, cwords64):
        """Single-container-layout gather shared by rows_dense and
        rows_positions: (u16_arrays, their_row_indexes, dense_items)
        where dense_items are the (row_index, dense_container) pairs the
        u16 path can't carry. Bulk probe: map(dict.get, ...) runs the
        65k-per-chunk lookup loop in C — the pure-Python for/get/append
        form was the dominant host cost of the whole chunked sweep."""
        keys = (np.asarray(row_ids, dtype=np.uint64)
                * np.uint64(CONTAINERS_PER_ROW)).tolist()
        cs = list(map(containers.get, keys))
        arrays, rows_at, dense_items = [], [], []
        u16dt = np.dtype(np.uint16)
        trim = total64 != cwords64
        lim = np.uint16(total64 * 64 - 1) if trim else None
        ap_a, ap_r = arrays.append, rows_at.append
        for i, c in enumerate(cs):
            if c is None:
                continue
            if c.dtype is not u16dt:
                dense_items.append((i, c))
                continue
            if trim and c[-1] > lim:
                # Sorted array: slice the in-range prefix rather
                # than boolean-masking every element.
                c = c[:np.searchsorted(c, lim, "right")]
            ap_a(c)
            ap_r(i)
        return arrays, rows_at, dense_items

    def rows_positions(self, row_ids, u32_words: int):
        """Sparse chunk payload for the single-container narrow layout:
        (pos16 concat, lens, rows_at) — the SET bit positions of each
        row, ~2 bytes each, versus the 4*u32_words a dense row costs.
        The chunked-TopN upload path expands these to the dense bank ON
        DEVICE (view._expand_sparse_chunk) and the positions bank keeps
        them resident, so a tunnel-attached chip transfers only real
        data. Dense-ENCODED containers still qualify (a point write
        densifies its row's container for mutation — one Set must not
        disqualify a 100M-row field): their positions are extracted,
        bailing to None only when >25% of rows are dense (a genuinely
        dense field belongs on the dense paths) or a row spans more
        than one container."""
        from pilosa_tpu.storage.roaring import _dense_to_array

        bits = u32_words * 32
        if bits > CONTAINER_BITS or bits % 64:
            return None
        total64 = u32_words // 2
        with self._lock:
            arrays, rows_at, dense_items = self._gather_row_arrays(
                self.storage.containers, row_ids, total64,
                CONTAINER_BITS // 64)
            if dense_items:
                if len(dense_items) * 4 > max(1, len(row_ids)):
                    return None
                lim = np.uint16(bits - 1) if bits < CONTAINER_BITS \
                    else None
                for i, c in dense_items:
                    pos = _dense_to_array(c)
                    if lim is not None and len(pos) and pos[-1] > lim:
                        pos = pos[:np.searchsorted(pos, lim, "right")]
                    arrays.append(pos)
                    rows_at.append(i)
        if not arrays:
            return (np.empty(0, np.uint16), np.empty(0, np.int64),
                    np.empty(0, np.int64))
        if dense_items:
            # Re-establish ascending row order after the appends.
            order = np.argsort(np.asarray(rows_at), kind="stable")
            arrays = [arrays[j] for j in order]
            rows_at = [rows_at[j] for j in order]
        lens = np.fromiter(map(len, arrays), dtype=np.int64,
                           count=len(arrays))
        return (np.concatenate(arrays),
                lens, np.asarray(rows_at, dtype=np.int64))

    def row_dense(self, row_id: int, u32_words: Optional[int] = None
                  ) -> np.ndarray:
        """Row as uint32 words (host). `u32_words` materializes only the
        leading prefix — the width-trimmed bank path would otherwise
        build (and immediately slice away) 128 KiB per row."""
        bits = SHARD_WIDTH if u32_words is None else u32_words * 32
        # dense_range is container-aligned; fetch the covering superset
        # and slice (sub-container trim widths, e.g. 4096-bit
        # fingerprint banks).
        aligned = (bits + CONTAINER_BITS - 1) // CONTAINER_BITS \
            * CONTAINER_BITS
        u64 = self.storage.dense_range(row_id * SHARD_WIDTH,
                                       row_id * SHARD_WIDTH + aligned)
        return u64_to_words(u64)[:bits // 32]

    def rows_dense(self, row_ids, u32_words: int) -> np.ndarray:
        """Bulk [len(row_ids), u32_words] u32 prefix block — the chunk-bank
        fast path. One dict probe + one memcpy per (row, container)
        instead of a full row_dense call per row: chunked TopN streams
        65k-row chunks, where per-row Python overhead would dominate the
        sweep itself."""
        bits = u32_words * 32
        assert bits % 64 == 0
        n_containers = (bits + CONTAINER_BITS - 1) // CONTAINER_BITS
        cwords64 = CONTAINER_BITS // 64
        total64 = u32_words // 2
        out = np.zeros((len(row_ids), total64), dtype=np.uint64)
        one = np.uint64(1)
        with self._lock:
            containers = self.storage.containers
            # Fast path for the narrow single-container layout (declared
            # max_columns <= 2^16, e.g. fingerprints): gather every
            # row's u16 array and do ONE flat scatter over the whole
            # block — no per-row Python work beyond the dict probe.
            if n_containers == 1:
                flat = out.reshape(-1)
                arrays, rows_at, dense_items = self._gather_row_arrays(
                    containers, row_ids, total64, cwords64)
                n_dense = min(cwords64, total64)
                for i, c in dense_items:
                    out[i, :n_dense] = c[:n_dense]
                if arrays:
                    from pilosa_tpu import native
                    lens = np.fromiter(map(len, arrays),
                                       dtype=np.int64, count=len(arrays))
                    pos16 = np.concatenate(arrays)
                    if not native.scatter_rows(
                            pos16, lens,
                            np.asarray(rows_at, dtype=np.uint64),
                            total64, out):
                        pos = pos16.astype(np.uint32)
                        base = np.repeat(
                            np.asarray(rows_at, dtype=np.int64) * total64,
                            lens)
                        np.bitwise_or.at(
                            flat, base + (pos >> 6),
                            np.left_shift(one,
                                          (pos & 63).astype(np.uint64)))
            else:
                for i, r in enumerate(row_ids):
                    k0 = r * CONTAINERS_PER_ROW
                    row = out[i]
                    for j in range(n_containers):
                        c = containers.get(k0 + j)
                        if c is None:
                            continue
                        lo = j * cwords64
                        n = min(cwords64, total64 - lo)
                        if c.dtype == np.uint16:
                            # Array-encoded: scatter positions straight
                            # into the output row, no materialization.
                            v = c if n == cwords64 else c[c < n * 64]
                            v = v.astype(np.uint32)
                            np.bitwise_or.at(
                                row, lo + (v >> 6),
                                np.left_shift(one,
                                              (v & 63).astype(np.uint64)))
                        else:
                            row[lo:lo + n] = c[:n]
        from pilosa_tpu.ops.bitset import u64_to_words
        return u64_to_words(out).reshape(len(row_ids), u32_words)

    def max_column_offset(self) -> int:
        """Largest in-shard column offset with any bit set in any row, or
        -1 when empty. Drives width-trimmed TopN banks: fingerprint-style
        fields use a tiny prefix of the 2^20-wide shard, so banks can
        drop the all-zero word tail."""
        with self._lock:
            cached = getattr(self, "_max_col_cache", None)
            if cached is not None and cached[0] == self.version:
                return cached[1]
            # Container-granular bound: the only consumer
            # (View.trimmed_words) rounds up to whole containers anyway,
            # so the container key alone decides the width — no dense
            # scans. A lingering all-zero container only widens the
            # bank, never corrupts it.
            best = -1
            for key in self.storage.containers:
                best = max(best, ((key % CONTAINERS_PER_ROW) + 1)
                           * CONTAINER_BITS - 1)
            self._max_col_cache = (self.version, best)
            return best

    def row_columns(self, row_id: int) -> np.ndarray:
        """Absolute column ids set in a row."""
        pos = self.storage.for_each_range(row_id * SHARD_WIDTH,
                                          (row_id + 1) * SHARD_WIDTH)
        return (pos - np.uint64(row_id * SHARD_WIDTH)
                + np.uint64(self.shard * SHARD_WIDTH))

    def mutex_vector(self, column_id: int, limit_rows: Optional[Sequence[int]] = None
                     ) -> Optional[int]:
        """Which row holds `column` in a mutex/bool fragment (reference
        vector lookup, fragment.go:2486-2553). Host scan over present rows —
        mutex fragments have at most one bit per column, and their row count
        is bounded by field cardinality."""
        for row_id in (limit_rows if limit_rows is not None else self.row_ids()):
            if self.bit(row_id, column_id):
                return row_id
        return None

    # -- device bank --------------------------------------------------------

    def _cache_update(self, row_id: int) -> None:
        """Refresh the TopN cache entry for a written row. Skipped when
        the ranked cache has saturated (cardinality exceeded its bound):
        the warm-read path can never fire again, so neither the
        row_count recount nor the cache upkeep buys anything — reads
        take the exact device sweep (cache.RankedCache docstring;
        reference keeps paying this cost, fragment.go:1067/cache.go:136)."""
        if self.cache_type == cache_mod.CACHE_TYPE_NONE:
            return
        if getattr(self.cache, "saturated", False):
            return
        self.cache.bulk_add(row_id, self.row_count(row_id))

    def _touch_row(self, row_id: int) -> None:
        self._touch_rows((row_id,))

    def _touch_rows(self, row_ids) -> None:
        """Batched generation bump: ONE version increment and ONE
        workload-plane record per (fragment, batch). A bulk import
        touching R rows used to bump per row — R version increments
        and R hotspot records whose only consumer effect is "something
        changed since the cached generation" (measured 2.4 µs/row,
        ~10 ms per 4096-row import batch). Every generation consumer
        compares for equality or `> stamp` (result/rank caches,
        rows_changed_since, version_stamp), so one bump shared by the
        whole batch invalidates exactly the same set."""
        rows = [int(r) for r in row_ids]
        if not rows:
            return
        self.version += 1
        v = self.version
        for row_id in rows:
            self._dirty.add(row_id)
            # graftlint: disable=GL008 — one slot per materialized row
            # of THIS fragment: grows with the stored data (like the
            # row containers themselves), not with request traffic.
            self._row_versions[row_id] = v
            # Anti-entropy dirty tracking: every mutation path funnels
            # through here, so the block-checksum cache re-hashes only
            # blocks written since the last pass.
            self._dirty_blocks.add(row_id // HASH_BLOCK_SIZE)
        # Workload plane: every mutation path funnels through here too,
        # so this one call records write churn AND the generation bump
        # caches key on (utils/hotspots.py; host dict work only).
        WORKLOAD.record_write(self.index, self.field, self.view,
                              self.shard, generation=v, n=len(rows))

    def rows_changed_since(self, version: int) -> List[int]:
        return [r for r, v in self._row_versions.items() if v > version]

    def invalidate_bank(self) -> None:
        with self._lock:
            self._bank = None
            self._slots = {}
            self._dirty = set()
            self._bank_all_rows = False
            # Under the lock: a straggling unregister after release
            # could delete the entry a concurrent bank() rebuild just
            # re-registered (same invariant as Executor._jit_put).
            LEDGER.unregister("fragment_bank", "bank", owner=self)

    def bank(self, row_ids: Optional[Sequence[int]] = None):
        """Return (device bank [slots, W] uint32, row->slot map) guaranteed
        to contain `row_ids` (default: every present row), with dirty rows
        refreshed. The bank is append-only: slots are stable across calls
        until invalidate_bank()."""
        import jax.numpy as jnp

        with self._lock:
            if row_ids is None:
                row_ids = self.row_ids()
                self._bank_all_rows = True
            missing = [r for r in row_ids if r not in self._slots]
            refresh = [r for r in self._dirty if r in self._slots]
            if self._bank is None:
                base = np.zeros((0, WORDS_PER_SHARD), dtype=np.uint32)
            else:
                # np.asarray of a device array is read-only; copy only when
                # we actually need to mutate host-side.
                base = np.asarray(self._bank)
                if refresh:
                    base = base.copy()
            if missing or refresh:
                if missing:
                    new_rows = np.stack([self.row_dense(r) for r in missing]) \
                        if missing else np.zeros((0, WORDS_PER_SHARD), np.uint32)
                    for r in missing:
                        self._slots[r] = len(self._slots)
                    base = np.concatenate([base, new_rows], axis=0)
                for r in refresh:
                    base[self._slots[r]] = self.row_dense(r)
                self._dirty -= set(refresh) | set(missing)
                self._bank = jnp.asarray(base)
                self._ledger_bank()
            elif self._bank is None:
                self._bank = jnp.asarray(base)
                self._ledger_bank()
            return self._bank, dict(self._slots)

    def _ledger_bank(self) -> None:
        """(Re-)register the per-fragment append-only bank with the HBM
        ledger — rebuilds replace the entry in place (same key), and a
        collected fragment purges it via the ledger's owner tracking."""
        LEDGER.register(
            "fragment_bank", "bank",
            len(self._slots) * WORDS_PER_SHARD * 4, owner=self,
            index=self.index, field=self.field, view=self.view,
            shard=self.shard, rows=len(self._slots))

    def row_device(self, row_id: int):
        """One row as a device array (gather from the bank)."""
        bank, slots = self.bank([row_id])
        return bank[slots[row_id]]

    # -- bulk import --------------------------------------------------------

    def bulk_import(self, row_ids: np.ndarray, column_ids: np.ndarray,
                    clear: bool = False) -> None:
        """Bulk bit import (reference bulkImportStandard → importPositions,
        fragment.go:1508-1604): the fused storage scatter builds
        per-container masks without sorting, appends ONE compact
        roaring-payload op record, and merges — then per-row cache
        refresh and the amortized snapshot check (_oplog_over_limit)."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if len(row_ids) == 0:
            return
        with self._lock:
            if clear:
                positions = np.unique(
                    row_ids * np.uint64(SHARD_WIDTH)
                    + (column_ids % np.uint64(SHARD_WIDTH)))
                # Chunked like the add path: one op record must stay
                # well under MAX_TORN_TAIL_BYTES.
                for i in range(0, len(positions), IMPORT_CHUNK_PAIRS):
                    self.storage.remove_batch(
                        positions[i:i + IMPORT_CHUNK_PAIRS])
                touched = np.unique(positions >> np.uint64(SHARD_WIDTH_EXP))
            else:
                key_chunks = [
                    self.storage.import_batch(
                        row_ids[i:i + IMPORT_CHUNK_PAIRS],
                        column_ids[i:i + IMPORT_CHUNK_PAIRS],
                        SHARD_WIDTH_EXP)
                    for i in range(0, len(row_ids), IMPORT_CHUNK_PAIRS)]
                keys = (np.concatenate(key_chunks) if len(key_chunks) > 1
                        else key_chunks[0])
                touched = np.unique(keys // np.uint64(CONTAINERS_PER_ROW))
            self._prelatch_cache_saturation(touched)
            self._touch_rows(touched.tolist())
            for r in touched.tolist():
                self._cache_update(int(r))
            self._maybe_snapshot()

    def _prelatch_cache_saturation(self, touched) -> None:
        """If this batch's row set will blow the ranked-cache bound
        anyway, latch saturation up front: the per-row recount loop is
        pure waste when the cache can never prove completeness
        afterwards (see RankedCache — adds past the bound would latch
        it during the loop regardless)."""
        cache = self.cache
        if not isinstance(cache, cache_mod.RankedCache) or cache.saturated:
            return
        total = len(cache.counts.keys()
                    | {int(r) for r in touched.tolist()})
        if total > cache.size * cache_mod.THRESHOLD_FACTOR:
            cache.saturated = True

    def bulk_import_mutex(self, row_ids: np.ndarray, column_ids: np.ndarray
                          ) -> None:
        """Mutex import: setting (row, col) clears any other row's bit in
        that column (reference bulkImportMutex, fragment.go:1605).

        Vectorized: pack the incoming column set into one dense word mask,
        then make ONE dense AND pass per present row to find conflicting
        bits — O(rows × words) word ops instead of the reference's (and a
        prior revision's) per-column row probes, which degrade to
        O(columns × rows) single-bit reads on wide imports."""
        from pilosa_tpu.ops.bitset import pack_positions

        with self._lock:
            # Within-batch dedup first: the reference applies mutex sets
            # sequentially, so for duplicate columns the LAST pair wins.
            last_for_col: Dict[int, int] = {}
            for r, c in zip(np.asarray(row_ids, np.uint64).tolist(),
                            np.asarray(column_ids, np.uint64).tolist()):
                last_for_col[c] = r
            row_ids = np.array(list(last_for_col.values()), np.uint64)
            column_ids = np.array(list(last_for_col.keys()), np.uint64)
            offsets = column_ids % np.uint64(SHARD_WIDTH)
            incoming_mask = pack_positions(offsets)
            # Conflict offsets skip clearing when the existing bit IS the
            # incoming target row; map offset -> target row for that test.
            target_of = dict(zip(offsets.tolist(),
                                 row_ids.astype(np.int64).tolist()))
            shard_base = np.uint64(self.shard * SHARD_WIDTH)
            to_clear_rows, to_clear_cols = [], []
            for r in self.row_ids():
                hit = self.row_dense(r) & incoming_mask
                nz = np.nonzero(hit)[0]
                if not len(nz):
                    continue
                bits = np.unpackbits(hit[nz].view(np.uint8),
                                     bitorder="little")
                local = np.nonzero(bits)[0]
                conflict = nz[local // 32] * 32 + local % 32
                for off in conflict.tolist():
                    if target_of.get(off) != r:
                        to_clear_rows.append(r)
                        to_clear_cols.append(off + int(shard_base))
            if to_clear_rows:
                self.bulk_import(np.array(to_clear_rows, np.uint64),
                                 np.array(to_clear_cols, np.uint64), clear=True)
            self.bulk_import(np.asarray(row_ids, np.uint64),
                             np.asarray(column_ids, np.uint64))

    def import_roaring(self, data: bytes, clear: bool = False) -> None:
        """Union (or overwrite-clear) a pre-serialized roaring bitmap into
        storage — the fastest import path (reference ImportRoaring,
        fragment.go:1721)."""
        other = Bitmap.from_bytes(data)
        with self._lock:
            if clear:
                from pilosa_tpu.storage.roaring import _as_dense
                for key in list(self.storage.containers):
                    if key in other.containers:
                        c = self.storage._container(key)
                        c &= ~_as_dense(other.containers[key])
                        self.storage._invalidate(key)
                        self.storage._drop_empty(key)
            else:
                self.storage.union_in_place(other)
            rows = sorted({k // CONTAINERS_PER_ROW
                           for k in other.containers})
            self._touch_rows(rows)
            for r in rows:
                self._cache_update(int(r))
            self._snapshot()

    def replace_with_bytes(self, data: bytes) -> None:
        """Overwrite the whole fragment from serialized roaring bytes —
        the reference's resize data motion (followResizeInstruction
        streams the fragment file in place, cluster.go:1251,
        http/client.go:711). Unlike import_roaring's union, bits absent
        from `data` are dropped: a stale local copy must not resurrect
        columns cleared in epochs this node missed."""
        other = Bitmap.from_bytes(data)
        with self._lock:
            old_rows = set(self.row_ids())
            self.storage.containers = other.containers
            self.storage._counts = {}
            self.storage.optimize()
            rows = old_rows | {k // CONTAINERS_PER_ROW
                               for k in self.storage.containers}
            self._touch_rows(rows)
            for r in rows:
                self._cache_update(int(r))
            self._snapshot()

    def set_row(self, row_id: int, words: np.ndarray) -> None:
        """Replace a row's bits wholesale (reference setRow, fragment.go:522
        — the Store() write path). `words` is uint32, up to
        WORDS_PER_SHARD; a width-trimmed result clears the untouched
        tail (overwrite semantics: bits past the operand width are 0)."""
        from pilosa_tpu.ops.bitset import words_to_u64
        with self._lock:
            words = np.ascontiguousarray(words, dtype=np.uint32)
            cw = CONTAINER_BITS // 32
            if words.size % cw:
                # Sub-container widths (128-word-granular trimmed banks)
                # zero-pad up to the container boundary: identical
                # overwrite semantics, and the tail-clear below can keep
                # popping whole containers.
                words = np.concatenate(
                    [words, np.zeros(cw - words.size % cw, np.uint32)])
            self.storage.set_dense_range(row_id * SHARD_WIDTH,
                                         words_to_u64(words))
            bits = words.size * 32
            if bits < SHARD_WIDTH:
                k0 = (row_id * SHARD_WIDTH + bits) >> 16
                k1 = ((row_id + 1) * SHARD_WIDTH - 1) >> 16
                for k in range(k0, k1 + 1):
                    if self.storage.containers.pop(k, None) is not None:
                        self.storage._invalidate(k)
            self._touch_row(row_id)
            self._cache_update(row_id)
            # A whole-row overwrite isn't representable as an op-log record;
            # fold it into a snapshot for durability.
            self._snapshot()

    # -- BSI (bit-sliced index) values --------------------------------------
    # Layout (reference fragment.value, fragment.go:618): rows 0..bitDepth-1
    # hold value bits LSB-first; row bitDepth is the not-null marker.

    def value(self, column_id: int, bit_depth: int) -> Tuple[int, bool]:
        with self._lock:
            if not self.bit(bit_depth, column_id):
                return 0, False
            v = 0
            for i in range(bit_depth):
                if self.bit(i, column_id):
                    v |= 1 << i
            return v, True

    def set_value(self, column_id: int, bit_depth: int, value: int) -> bool:
        with self._lock:
            changed = False
            for i in range(bit_depth):
                if value & (1 << i):
                    changed |= self.storage.add(self.pos(i, column_id))
                else:
                    changed |= self.storage.remove(self.pos(i, column_id))
            changed |= self.storage.add(self.pos(bit_depth, column_id))
            self._touch_rows(range(bit_depth + 1))
            self._maybe_snapshot()
            return changed

    def clear_value(self, column_id: int, bit_depth: int) -> bool:
        with self._lock:
            changed = False
            for i in range(bit_depth + 1):
                changed |= self.storage.remove(self.pos(i, column_id))
            self._touch_rows(range(bit_depth + 1))
            self._maybe_snapshot()
            return changed

    def import_values(self, column_ids: np.ndarray, values: np.ndarray,
                      bit_depth: int, clear: bool = False) -> None:
        """Vectorized BSI import (reference importValue, fragment.go column
        loop at :679 via positionsForValue). One fused batch import
        carries ALL planes' set bits (rows = plane ids through the same
        native scatter as bulk_import); zero-bit clears run only for
        columns that ALREADY held a value (not-null probe) — a fresh
        import skips every remove pass, which halved the taxi/BSI load
        benchmarks. Duplicate columns within a batch resolve last-wins
        (the reference applies columns sequentially)."""
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        values = np.asarray(values, dtype=np.uint64)
        with self._lock:
            # Last-wins dedup: keep the final occurrence per column.
            offsets_all = column_ids % np.uint64(SHARD_WIDTH)
            _, last_idx = np.unique(offsets_all[::-1], return_index=True)
            keep = len(offsets_all) - 1 - last_idx
            offsets = offsets_all[keep]
            vals = values[keep]
            if clear:
                for i in range(bit_depth):
                    self.storage.remove_batch(
                        np.uint64(i * SHARD_WIDTH) + offsets)
                self.storage.remove_batch(
                    np.uint64(bit_depth * SHARD_WIDTH) + offsets)
                self._touch_rows(range(bit_depth + 1))
                self._maybe_snapshot()
                return
            # Columns that already hold a value need their zero planes
            # cleared; fresh columns don't (their plane bits are absent).
            nn = self.row_dense(bit_depth)  # u32 words of the not-null row
            w = (offsets >> np.uint64(5)).astype(np.int64)
            existed = ((nn[w] >> (offsets & np.uint64(31)).astype(np.uint32))
                       & np.uint32(1)).astype(bool)
            if existed.any():
                eoff, evals = offsets[existed], vals[existed]
                for i in range(bit_depth):
                    zero = ((evals >> np.uint64(i)) & np.uint64(1)) == 0
                    if zero.any():
                        self.storage.remove_batch(
                            np.uint64(i * SHARD_WIDTH) + eoff[zero])
            # ONE fused import for every plane's set bits + not-null.
            plane_rows = []
            plane_cols = []
            for i in range(bit_depth):
                m = ((vals >> np.uint64(i)) & np.uint64(1)).astype(bool)
                if m.any():
                    plane_cols.append(offsets[m])
                    plane_rows.append(np.full(int(m.sum()), i, np.uint64))
            plane_cols.append(offsets)
            plane_rows.append(np.full(len(offsets), bit_depth, np.uint64))
            all_rows = np.concatenate(plane_rows)
            all_cols = np.concatenate(plane_cols)
            # Chunked like bulk_import: bounds the scatter's transient
            # memory and each op record's size.
            for c0 in range(0, len(all_rows), IMPORT_CHUNK_PAIRS):
                self.storage.import_batch(
                    all_rows[c0:c0 + IMPORT_CHUNK_PAIRS],
                    all_cols[c0:c0 + IMPORT_CHUNK_PAIRS],
                    SHARD_WIDTH_EXP)
            self._touch_rows(range(bit_depth + 1))
            self._maybe_snapshot()

    def bsi_bank(self, bit_depth: int):
        """Device array [(bit_depth+1), W]: bit planes 0..bit_depth-1 then
        the not-null plane — the operand layout for vectorized BSI kernels."""
        bank, slots = self.bank(list(range(bit_depth + 1)))
        import jax.numpy as jnp
        idx = jnp.asarray([slots[i] for i in range(bit_depth + 1)])
        return bank[idx]

    # -- block checksums (anti-entropy unit) --------------------------------

    def checksum_blocks(self) -> List[Tuple[int, bytes]]:
        """Per-block digests over 100-row blocks (reference Blocks,
        fragment.go:1275). Hash input is the sorted absolute positions in
        the block, so equal bit-sets hash equal regardless of encoding.

        Incremental (VERDICT r2 weak #5): digests are cached and only
        blocks dirtied by a write since the last pass are re-hashed —
        an idle fragment's anti-entropy round costs O(dirty)=0 instead
        of a full bitmap extraction (the reference re-hashes every
        block every sync, fragment.go:1259-1355)."""
        with self._lock:
            known = (0 if self._block_digests is None
                     else len(self._block_digests))
            if self._block_digests is None or \
                    len(self._dirty_blocks) * 4 > known + 4:
                # Cold, or enough churn that re-extracting most of the
                # bitmap anyway makes the full pass cheaper.
                self._block_digests = self._checksum_all_blocks()
            elif self._dirty_blocks:
                # ONE container scan selects every dirty block's
                # containers (a per-block for_each_range would pay an
                # O(containers) dict walk per dirty block), then one
                # extraction + boundary split re-hashes them.
                keys_per_block = (HASH_BLOCK_SIZE * SHARD_WIDTH) >> 16
                dirty = self._dirty_blocks
                sub = Bitmap()
                sub.containers = {
                    k: c for k, c in self.storage.containers.items()
                    if k // keys_per_block in dirty}
                pos = sub.slice()
                for blk in dirty:
                    self._block_digests.pop(blk, None)
                if len(pos):
                    span = np.uint64(HASH_BLOCK_SIZE * SHARD_WIDTH)
                    blk_of = pos // span
                    cuts = np.nonzero(np.diff(blk_of))[0] + 1
                    bounds = np.concatenate(([0], cuts, [len(pos)]))
                    for i in range(len(bounds) - 1):
                        seg = pos[bounds[i]:bounds[i + 1]]
                        h = hashlib.blake2b(seg.astype("<u8").tobytes(),
                                            digest_size=16)
                        self._block_digests[int(blk_of[bounds[i]])] = \
                            h.digest()
            self._dirty_blocks.clear()
            return sorted(self._block_digests.items())

    def _checksum_all_blocks(self) -> Dict[int, bytes]:
        # One whole-bitmap extraction + boundary split beats a per-block
        # range scan: for_each_range would touch the container dict once
        # per 100-row block (O(blocks x containers)).
        pos = self.storage.slice()
        if not len(pos):
            return {}
        span = np.uint64(HASH_BLOCK_SIZE * SHARD_WIDTH)
        blk_of = pos // span
        # slice() output is sorted, so block segments are contiguous:
        # O(n) boundary scan, no sort.
        cuts = np.nonzero(np.diff(blk_of))[0] + 1
        bounds = np.concatenate(([0], cuts, [len(pos)]))
        out: Dict[int, bytes] = {}
        for i in range(len(bounds) - 1):
            seg = pos[bounds[i]:bounds[i + 1]]
            h = hashlib.blake2b(seg.astype("<u8").tobytes(), digest_size=16)
            out[int(blk_of[bounds[i]])] = h.digest()
        return out

    def _invalidate_block_checksums(self) -> None:
        # Reentrant lock: callers (benches, maintenance) may or may
        # not hold it; checksum_blocks reads both fields under it.
        with self._lock:
            self._block_digests = None
            self._dirty_blocks.clear()

    def block_data(self, block: int) -> Tuple[np.ndarray, np.ndarray]:
        """(row_ids, column_ids) pairs in a block (reference blockData,
        fragment.go:1356)."""
        lo = block * HASH_BLOCK_SIZE * SHARD_WIDTH
        hi = (block + 1) * HASH_BLOCK_SIZE * SHARD_WIDTH
        pos = self.storage.for_each_range(lo, hi)
        rows = pos // np.uint64(SHARD_WIDTH)
        cols = (pos % np.uint64(SHARD_WIDTH)
                + np.uint64(self.shard * SHARD_WIDTH))
        return rows, cols

    def merge_block(self, block: int, their_rows: np.ndarray,
                    their_cols: np.ndarray) -> Tuple[Tuple[np.ndarray, np.ndarray],
                                                     Tuple[np.ndarray, np.ndarray]]:
        """Merge a peer's block pairs with union semantics; returns the
        (sets, clears) deltas to push back to peers (reference mergeBlock,
        fragment.go:1372 — here without the clear side since union-merge;
        clears flow through the import clear flag)."""
        their_pos = (np.asarray(their_rows, np.uint64) * np.uint64(SHARD_WIDTH)
                     + np.asarray(their_cols, np.uint64) % np.uint64(SHARD_WIDTH))
        lo = block * HASH_BLOCK_SIZE * SHARD_WIDTH
        hi = (block + 1) * HASH_BLOCK_SIZE * SHARD_WIDTH
        ours = self.storage.for_each_range(lo, hi)
        missing_here = np.setdiff1d(their_pos, ours)
        missing_there = np.setdiff1d(ours, their_pos)
        if len(missing_here):
            rows = missing_here // np.uint64(SHARD_WIDTH)
            cols = missing_here % np.uint64(SHARD_WIDTH) \
                + np.uint64(self.shard * SHARD_WIDTH)
            self.bulk_import(rows, cols)
        rows_t = missing_there // np.uint64(SHARD_WIDTH)
        cols_t = (missing_there % np.uint64(SHARD_WIDTH)
                  + np.uint64(self.shard * SHARD_WIDTH))
        here_rows = missing_here // np.uint64(SHARD_WIDTH)
        here_cols = (missing_here % np.uint64(SHARD_WIDTH)
                     + np.uint64(self.shard * SHARD_WIDTH))
        return (here_rows, here_cols), (rows_t, cols_t)

    # -- export -------------------------------------------------------------

    def write_bytes(self) -> bytes:
        """Serialized fragment (snapshot form, no op tail) for streaming to
        peers / backup (reference fragment.WriteTo, fragment.go:1885)."""
        with self._lock:
            return self.storage.write_bytes()
