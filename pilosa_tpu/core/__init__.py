"""Data model layer: Holder → Index → Field → View → Fragment.

Mirrors the reference hierarchy (/root/reference/holder.go, index.go,
field.go, view.go, fragment.go) with one structural change: a fragment's
query-facing representation is a device-resident dense bitset bank
(rows × packed words in HBM) instead of per-container Go loops; the host
roaring bitmap underneath is the durable, mutable source of truth.
"""

from pilosa_tpu.core.holder import Holder  # noqa: F401
from pilosa_tpu.core.field import FieldOptions  # noqa: F401
