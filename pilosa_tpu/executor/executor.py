"""PQL executor: batched device evaluation of call trees.

Reference: /root/reference/executor.go:84 (Execute), :245 (executeCall
dispatch), :2277 (mapReduce). Structural translation to TPU:

- The reference evaluates each shard in its own goroutine and merges row
  results pairwise (executor.go:2377, row.go:60). Here the operands live in
  per-view HBM banks shaped [rows, shards, words] (core/view.py ViewBank)
  and a whole PQL tree runs as ONE jitted XLA program over the stacked
  shard axis.
- Row identity and BSI predicate operands enter the program as *traced*
  gather indices / scalars, so the compile cache keys only on tree shape
  and bank shapes: `Count(Intersect(Row(f=X), Row(g=Y)))` compiles once for
  all X, Y — and fuses into a single AND+popcount pass, the generalization
  of the reference's hand-fused intersectionCountBitmapBitmap
  (roaring.go:2438) to arbitrary trees.
- Cross-shard reduction (the reference's reduceFn, HTTP scatter-gather) is
  a reduction over the shard axis inside the same program; the multi-chip
  version shard_maps these kernels over a mesh with psum on ICI
  (pilosa_tpu/parallel).
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import logging
import os
import threading
import time
from pilosa_tpu.utils.locks import make_lock
from dataclasses import dataclass, field as dc_field
from datetime import datetime
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pilosa_tpu.core import timeq
from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL, FIELD_TYPE_INT, FIELD_TYPE_MUTEX, FIELD_TYPE_SET,
    FIELD_TYPE_TIME, Field,
)
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.view import VIEW_STANDARD, view_bsi_name
from pilosa_tpu.executor import bsi
from pilosa_tpu.executor.results import (
    FieldRow, GroupCount, PairsResult, RowIdentifiers, RowResult, ValCount,
)
from pilosa_tpu.ops.bitset import SHARD_WIDTH, WORDS_PER_SHARD, \
    transfer_nbytes
from pilosa_tpu.pql import Call, Condition, Query, parse_string_cached
from pilosa_tpu.pql.ast import BETWEEN, EQ, GT, GTE, LT, LTE, NEQ
from pilosa_tpu.utils.fingerprint import request_key
from pilosa_tpu.utils.hotspots import WORKLOAD
from pilosa_tpu.utils.memledger import LEDGER
from pilosa_tpu.utils.timeline import (
    LANE_CACHE, LANE_DEVICE, LANE_DISPATCH, LANE_FETCH, LANE_PLAN,
    TIMELINE,
)

_LOG = logging.getLogger("pilosa_tpu.executor")

_BITMAP_CALLS = {"Row", "Range", "Threshold",
                 "Intersect", "Union", "Difference", "Xor",
                 "Not", "Shift"}

# Calls that mutate fragment bitmaps. Used to decide whether a deferred
# read in the same multi-call query may lazily re-read fragment state in
# finalize (safe only when no later call writes — reference executes calls
# strictly sequentially, executor.go:245).
_WRITE_CALLS = {"Set", "Clear", "ClearRow", "Store"}
# All writes, for the max-writes-per-request limit (reference
# Query.WriteCallN counts these, pql/ast.go).
ALL_WRITE_CALLS = _WRITE_CALLS | {"SetRowAttrs", "SetColumnAttrs"}


def write_call_count(query) -> int:
    return sum(1 for c in query.calls
               if _peel_options(c).name in ALL_WRITE_CALLS)


def query_is_write(query) -> bool:
    """True when `query` (PQL string, Call, or Query) contains any write
    call. Used by the serving-path coalescer to flush its window on
    write arrival and to disable read-dedup for the flush. A parse error
    reads as False — the dispatch path reports it per-request."""
    try:
        if isinstance(query, str):
            query = parse_string_cached(query)
        if isinstance(query, Call):
            query = Query([query])
        return write_call_count(query) > 0
    except Exception:
        return False


def _peel_options(call: "Call") -> "Call":
    while call.name == "Options" and call.children:
        call = call.children[0]
    return call

# Expand time-range unions statically up to this many views; beyond it the
# union is precomputed eagerly into a literal operand (keeps compile sizes
# bounded for hour-grain multi-year ranges).
MAX_STATIC_RANGE_VIEWS = 8

# TopN uses the cached full view bank while it fits this HBM byte budget
# (banks are width-trimmed, so fingerprint-style fields with small column
# spans cache hundreds of thousands of rows); beyond it rows stream
# through transient chunk banks.
TOPN_MAX_BANK_BYTES = int(os.environ.get("PILOSA_TPU_TOPN_BANK_BYTES",
                                         2 << 30))
# Rows per streamed chunk on the over-budget TopN path. Larger chunks
# amortize dispatch/transfer overhead (100M-fingerprint sweeps want
# 64k-row chunks); the default keeps at most two ~modest chunk banks
# live at narrow widths.
TOPN_CHUNK_ROWS = int(os.environ.get("PILOSA_TPU_TOPN_CHUNK_ROWS", 1024))

# Device-resident positions bank for over-budget TopN (kill switch):
# when a narrow single-shard view outgrows TOPN_MAX_BANK_BYTES, keep
# its u16 positions resident (~2 B/set bit) and answer filtered TopN
# with one gather+cumsum pass per query instead of streaming dense
# chunk banks (view.PositionsBank).
PBANK_ENABLED = os.environ.get("PILOSA_TPU_PBANK", "1") != "0"

# Filters with at most this many set bits take the positions-bank
# kernel's gather-free compare path (see _pbank_kernel.bits_compare);
# denser filters use the table gather. 64 covers every fingerprint
# query (48 draws) with headroom; raising it grows the [P, QCAP]
# compare fan-out linearly.
PBANK_SPARSE_FILTER_BITS = int(os.environ.get(
    "PILOSA_TPU_PBANK_SPARSE_BITS", 64))

# Membership form for the sparse-filter pbank kernel: "compare" (the
# [P] x [QCAP] equality fan-out, the r4 default and measured floor on
# the v5e VPU), "search" (binary search in the sorted filter positions,
# log2(QCAP) compare-select rounds), or "auto" (default): search on the
# XLA CPU backend — measured 1.33x warmer p50 and 7.7x faster cold
# compile at 1M molecules (docs/round5-notes.md §3) — compare on
# devices until benches/pbank_membership_probe.py proves otherwise.
# Selection is a compile key, resolved per backend at kernel build.
PBANK_MEMBERSHIP = os.environ.get("PILOSA_TPU_PBANK_MEMBERSHIP", "auto")
if PBANK_MEMBERSHIP not in ("auto", "compare", "search"):
    raise ValueError(
        f"PILOSA_TPU_PBANK_MEMBERSHIP={PBANK_MEMBERSHIP!r}: "
        "must be 'auto', 'compare', or 'search'")

# Max positions-bank segment programs enqueued before a sync (see
# _topn_positions): bounds how many programs' workspaces (~2x segment
# positions x 4 B at the 2^27 default segment size, i.e. ~1.1 GB each)
# can coexist in HBM beside a resident bank that may itself be ~10 GB.
# Each wave sync costs one tunnel RTT, so the cap trades fetch latency
# against OOM headroom; 4 keeps 100M-row queries ~4.4 GB of transients.
PBANK_INFLIGHT_SEGMENTS = int(os.environ.get(
    "PILOSA_TPU_PBANK_INFLIGHT", 4))

# Same-signature batch fusion (kill switch): N structurally identical
# queries in one execute_batch stack their traced operands and run as
# ONE vmapped XLA program (executor/fusion.py). Per-query results are
# bit-identical to the unfused path; disabling trades dispatch
# amortization back for the pre-fusion per-program pipeline.
FUSION_ENABLED = os.environ.get("PILOSA_TPU_FUSION", "1") != "0"

# Warm-cache TopN self-check sampling: 1 in this many warm hits ALSO
# runs the exact device sweep and compares (VERDICT r3 weak #5: the
# shortcut's correctness rests on every write path refreshing cached
# counts — a missed path would silently serve wrong TopN forever; the
# sample converts that into a logged counter + cache repair). 0
# disables. The first warm hit after startup is always checked.
TOPN_SELFCHECK_EVERY = int(os.environ.get("PILOSA_TPU_TOPN_SELFCHECK",
                                          256))


class _Pending:
    """A dispatched-but-unfetched call result. The device program is
    already queued; finalize() blocks on the transfer and builds the
    host-side result. Lets _execute_query overlap every read call's
    device work and device→host drain across a multi-call query.

    `arrays` (optional) are the device arrays finalize will fetch.
    Exposing them lets the executor start EVERY result's device→host
    copy asynchronously before blocking on any (prefetch_pendings) —
    N calls then share one overlapped drain instead of paying N
    serial fetch RTTs, which is what makes 1 ms-class queries batch
    usefully through a ~70 ms-RTT tunnel."""

    __slots__ = ("finalize", "arrays", "__weakref__")

    def __init__(self, finalize, arrays=()):
        self.finalize = finalize
        self.arrays = arrays
        if arrays:
            # Ledger the not-yet-fetched device outputs (category
            # "pending"): keyed on this object, auto-unregistered when
            # finalize drops the last reference — so /debug/memory
            # counts result arrays queued behind a slow drain.
            LEDGER.track(self, "pending",
                         sum(int(getattr(a, "nbytes", 0) or 0)
                             for a in arrays))


def prefetch_pendings(staged) -> None:
    """Kick off async device→host copies for every _Pending's declared
    arrays. jax.Array.copy_to_host_async is a no-op on host-resident
    (CPU backend) arrays and caches the fetched copy so the later
    np.asarray/device_get inside finalize reuses it."""
    for _, result in staged:
        if isinstance(result, _Pending):
            for a in result.arrays:
                fn = getattr(a, "copy_to_host_async", None)
                if fn is not None:
                    try:
                        fn()
                    except Exception:
                        pass  # transfer still happens in finalize


class _BatchInFlight:
    """A dispatched-but-undrained execute_batch: every request's device
    programs are launched (operand banks snapshotted, fusion groups
    resolved, async prefetch started); execute_batch_finish blocks on
    the transfers and builds host results. The handle the pipelined
    serving path double-buffers on."""

    __slots__ = ("staged_q", "out", "profs", "deps_l")

    def __init__(self, staged_q, out, profs, deps_l):
        self.staged_q = staged_q
        self.out = out
        self.profs = profs
        self.deps_l = deps_l


class _ShapedInFlight:
    """execute_batch_shaped's in-flight handle: the underlying
    _BatchInFlight plus the request-cache bookkeeping the shaping half
    needs (keys/deps for fills, positions of cache hits already
    answered)."""

    __slots__ = ("flight", "out", "keys", "deps_l", "run", "requests")

    def __init__(self, flight, out, keys, deps_l, run, requests):
        self.flight = flight
        self.out = out
        self.keys = keys
        self.deps_l = deps_l
        self.run = run
        self.requests = requests


class _CacheFillEval:
    """Stands between a terminal eval's device output (device array or
    fusion FusedEval handle) and its consumers so the first HOST
    materialization also fills the result cache's eval tier — the
    "existing materialize seam": no extra fence, no extra transfer,
    the fill rides the fetch the consumer was paying anyway. Mirrors
    the slice of the FusedEval surface result/finalize code touches."""

    __slots__ = ("inner", "cache", "key", "gen", "_host")

    def __init__(self, inner, cache, key, gen):
        self.inner = inner
        self.cache = cache
        self.key = key
        self.gen = gen
        self._host = None

    @property
    def shape(self):
        return self.inner.shape

    @property
    def nbytes(self) -> int:
        return int(getattr(self.inner, "nbytes", 0) or 0)

    def device_words(self):
        """Device-side view for consumers that avoid the host bounce
        (RowResult.count)."""
        dw = getattr(self.inner, "device_words", None)
        return dw() if dw is not None else self.inner

    def copy_to_host_async(self) -> None:
        fn = getattr(self.inner, "copy_to_host_async", None)
        if fn is not None:
            fn()

    # graftlint: materialize — this IS the device->host boundary for
    # cached terminal evals (the FusedEval.host convention): the fetch
    # happens exactly once, and the host copy both serves the caller
    # and fills the cache.
    def __array__(self, dtype=None, copy=None):
        host = self._host
        if host is None:
            host = np.asarray(self.inner)
            self._host = host
            self.cache.fill(self.key, self.gen, host, host.nbytes,
                            tier="eval")
        return np.asarray(host, dtype=dtype) if dtype is not None \
            else host


# graftlint: materialize — sampled device-time fence: reached ONLY when
# the active QueryProfile requests device sampling (?profile=true or the
# configured 1-in-N sample). The unprofiled hot path never calls it, so
# the dispatch queue stays async (tests/test_profile.py asserts zero
# calls without a sampling profile).
def _fence_device(out) -> float:
    import jax
    t0 = time.perf_counter()
    jax.block_until_ready(out)
    return time.perf_counter() - t0


class ExecutionError(ValueError):
    pass


@dataclass
class ExecOptions:
    """Per-query execution options (reference execOptions, executor.go:36,
    set by the Options() call, executor.go:317-361)."""
    exclude_row_attrs: bool = False
    exclude_columns: bool = False
    column_attrs: bool = False


def column_attr_sets(idx: Index, ids: Sequence[int],
                     resolve=None) -> List[Dict[str, Any]]:
    """Non-empty column attr sets for `ids`, key-translated when the index
    is keyed (reference readColumnAttrSets, executor.go:180-200 +
    translation :155-162). `resolve(ids) -> keys` overrides the local
    translator (cluster mode resolves through the primary so attr keys
    match the result keys in the same response)."""
    withattrs = [(int(cid), idx.column_attr_store.get(int(cid)))
                 for cid in ids]
    withattrs = [(cid, attrs) for cid, attrs in withattrs if attrs]
    if not idx.keys:
        return [{"id": cid, "attrs": attrs} for cid, attrs in withattrs]
    if resolve is None:
        resolve = idx.column_translator.translate_ids
    keys = resolve([cid for cid, _ in withattrs])
    return [({"key": key, "attrs": attrs} if key is not None
             else {"id": cid, "attrs": attrs})
            for (cid, attrs), key in zip(withattrs, keys)]


def _topn_candidates(rows_arr: np.ndarray, counts_arr: np.ndarray,
                     n: int) -> Tuple[np.ndarray, np.ndarray]:
    """Shrink a (rows, counts) set to the rows that can appear in an
    exact top-n: everything with count >= the n-th largest count
    (boundary ties kept in full, so the later (-count, row) lexsort
    still breaks them exactly). O(N) partition instead of an O(N log N)
    full sort — 40 ms -> 2.6 ms per TopN at 500k fingerprint rows."""
    if not n or len(counts_arr) <= max(4096, 4 * n):
        return rows_arr, counts_arr
    kth = np.partition(counts_arr, len(counts_arr) - n)[len(counts_arr) - n]
    sel = counts_arr >= kth
    return rows_arr[sel], counts_arr[sel]


def _align_words(words, width: int):
    """Slice or zero-pad the trailing word axis to exactly `width`
    (None passes through). Both directions are semantically safe for
    intersection-style consumers — see _dispatch_counts."""
    if words is None or words.shape[-1] == width:
        return words
    if words.shape[-1] > width:
        return words[..., :width]
    return _pad_words(words, width)


def _pad_words(words, width: int):
    """Zero-pad the trailing word axis up to `width` (no-op when equal).
    Leaves gather from width-trimmed banks (view.trimmed_words) and pad to
    the plan-wide width, so operands of one tree always align while each
    bank stays as narrow as its data."""
    import jax.numpy as jnp
    d = width - words.shape[-1]
    if d <= 0:
        return words
    return jnp.pad(words, [(0, 0)] * (words.ndim - 1) + [(0, d)])


@dataclass
class _Plan:
    """Everything the jitted tree program needs, gathered in one host pass.
    Banks are NOT built during planning: leaves record (bank key, row id)
    references, and _eval_tree builds each bank once afterwards — with the
    exact row set the tree needs, so an over-budget view can be served by
    a row-subset bank instead of materializing every row in HBM."""
    sig_parts: List[str] = dc_field(default_factory=list)
    bank_keys: List[Tuple[str, str]] = dc_field(default_factory=list)
    bank_pos: Dict[Tuple[str, str], int] = dc_field(default_factory=dict)
    idxs: List[int] = dc_field(default_factory=list)       # traced gather slots
    params: List[int] = dc_field(default_factory=list)     # traced u32 scalars
    literals: List[Any] = dc_field(default_factory=list)   # eager [S, W] ops
    widths: List[int] = dc_field(default_factory=list)     # operand widths
    # slot placeholders: (position in idxs, bank key, row id), resolved
    # once banks exist; rows_for[key] = every row the tree reads from it.
    slot_refs: List[Tuple[int, Tuple[str, str], int]] = \
        dc_field(default_factory=list)
    rows_for: Dict[Tuple[str, str], set] = dc_field(default_factory=dict)
    shift_bits: int = 0    # total Shift() distance; widens the plan
    width: int = 0         # resolved by _eval_tree before tracing
    # Megakernel IR (ops/megakernel.py): a postfix record of the same
    # tree the closures trace, appended by the _plan_* recursion so a
    # heterogeneous flush can lower N different staged programs into
    # ONE opcode plan buffer. `ir_ok=False` (eager literals, Shift)
    # means the staged eval is not lowerable and takes the per-group
    # fusion path instead.
    ir: List[tuple] = dc_field(default_factory=list)
    ir_ok: bool = True
    # Hybrid layout (core/layout.py): per bank key, whether its leaves
    # serve from the view's SparseBank ("xslot" IR nodes + the
    # expand_positions program) instead of the dense ViewBank. The
    # decision snapshots the view's layout mode ONCE per key per plan
    # (a background flip mid-staging cannot split one bank between two
    # representations); force_dense carries keys whose sparse build
    # bailed so the restage plans them dense.
    bank_sparse: Dict[Tuple[str, str], bool] = \
        dc_field(default_factory=dict)
    force_dense: set = dc_field(default_factory=set)
    # bank pos -> the built SparseBank's dense expansion width, filled
    # by _stage_tree once banks exist (leaf closures read it at trace
    # time, after staging resolved every width).
    sparse_widths: Dict[int, int] = dc_field(default_factory=dict)

    def bank(self, key: Tuple[str, str]) -> int:
        pos = self.bank_pos.get(key)
        if pos is None:
            pos = len(self.bank_keys)
            self.bank_pos[key] = pos
            self.bank_keys.append(key)
        return pos

    def resolve_width(self) -> int:
        from pilosa_tpu.ops.bitset import WORDS_PER_SHARD
        from pilosa_tpu.core.fragment import CONTAINER_BITS
        w = max(self.widths, default=CONTAINER_BITS // 32)
        if self.shift_bits:
            # Shifted bits may cross the trim boundary; widen to cover.
            extra = (self.shift_bits + CONTAINER_BITS - 1) // CONTAINER_BITS
            w += extra * (CONTAINER_BITS // 32)
        self.width = min(WORDS_PER_SHARD, w)
        return self.width


@dataclass
class _StagedEval:
    """One planned-but-not-run tree program: the output of
    Executor._stage_tree, consumed either by _run_staged (solo) or by
    the batch fusion pass (executor/fusion.py), which stacks the
    operand vectors of same-`sig` stages along a new leading batch
    axis and runs them through one vmapped program. Everything that
    differs between same-signature queries lives in `idxs`/`params`/
    `lits`; everything that must be IDENTICAL for two stages to fuse
    is covered by `sig` plus bank-array identity."""
    mode: str              # "row" -> [S, W] words | "count" -> [S]
    sig: str               # compile-cache key (tree shape + shapes)
    expr: Callable         # expr(banks, idxs, params, lits) -> [S, W]
    width: int             # resolved plan word width
    n_shards: int
    bank_arrays: tuple     # device operand banks (shared, not stacked)
    idxs: List[int]        # traced gather slots (host values)
    params: List[int]      # traced u32 scalars (host values)
    lits: Any              # stacked [L, S, W] device literals or None
    # Workload-recorder AND result-cache identity: the semantic
    # fingerprint (sig + row ids + params — row IDS, not bank slots,
    # so it is stable across bank rebuilds), and the operand banks'
    # generation (fragment write versions) it was staged against —
    # together the exact (key, generation) pair the eval tier of
    # executor/result_cache.py caches under. None when both the
    # workload recorder and the result cache are off.
    fp: Any = None
    gen: Any = None
    # False when the plan carries eager literal operands (the
    # >MAX_STATIC_RANGE_VIEWS time-range union): literal content is
    # not named by fp/gen, so such evals must never be served from or
    # fill the result cache.
    cacheable: bool = True
    # Megakernel IR: the postfix opcode record _Plan collected, or
    # None when the tree is not lowerable — such evals keep the
    # per-signature-group vmap fusion path (executor/megakernel.py).
    ir: Any = None

    def runner(self) -> Callable:
        """The traceable program body: expr + the mode's reduction."""
        expr, mode = self.expr, self.mode

        def run(bank_arrays, idxs, params, lits):
            out = expr(bank_arrays, idxs, params, lits)
            if mode == "count":
                from pilosa_tpu.ops.bitset import popcount
                return popcount(out, axis=-1)  # [S]
            return out
        return run


class Executor:
    """Single-controller executor. With `mesh=None` everything runs on the
    local device; with a MeshContext the shard list is padded onto the mesh
    and banks are sharded over its shard axis — the same compiled query
    programs then run SPMD with XLA-inserted ICI collectives (the TPU
    replacement for mapReduce over HTTP, executor.go:2277)."""

    def __init__(self, holder: Holder, mesh=None):
        self.holder = holder
        self.mesh = mesh
        # Reject queries carrying more write calls than this; 0 = no limit
        # (reference executor.MaxWritesPerRequest, executor.go:53,106).
        self.max_writes_per_request = 0
        # Compiled-program cache, shape-keyed and LRU-bounded (see
        # JIT_CACHE_MAX): holds ONLY jitted callables. Device-resident
        # placeholder banks live in _bank_cache — mixing the two in one
        # unbounded dict previously meant an eviction policy could
        # never be added without throwing ViewBanks away with programs.
        self._jit_cache: Dict[str, Callable] = {}
        self._jit_cache_lock = make_lock("Executor._jit_cache_lock")
        # Shared all-zero placeholder banks (absent views), keyed by
        # shard count + mesh. Shard counts grow with the index, so the
        # cache is LRU-bounded (BANK_CACHE_MAX, see _empty_bank) with
        # ledger unregister on evict; the lock makes the
        # pop/evict/reinsert dance atomic across request threads.
        self._bank_cache: Dict[str, Any] = {}
        self._bank_cache_lock = make_lock("Executor._bank_cache_lock")
        # Device copies of the tiny per-query idxs/params arrays, keyed
        # by their values: repeated warm queries skip two host->device
        # transfers per execution (a large share of small-query latency).
        # The executor is shared across request threads; the lock makes
        # the pop/evict/reinsert LRU dance atomic (VERDICT r3 weak #6 —
        # it previously leaned on dict-internals tolerance).
        self._arg_cache: Dict[tuple, tuple] = {}
        self._arg_cache_lock = make_lock("Executor._arg_cache_lock")
        # Per-thread dispatch context (one executor serves all request
        # threads): whether calls after the one being dispatched write.
        self._tls = threading.local()
        # Process-wide retrace counter: every shape-keyed jit-cache miss
        # (a fresh XLA trace+compile) across the instance's jit sites.
        # An unexpected climb under steady traffic means some query
        # attribute leaked into a compile key (utils/profile.py surfaces
        # it per query; /metrics exports the running total). Incremented
        # via _note_jit_compile — request threads race here.
        self.jit_compiles = 0
        self._jit_stats_lock = make_lock("Executor._jit_stats_lock")
        # Batch fusion counters (executor/fusion.py): fused program
        # dispatches (one per >=2-query group) and the queries they
        # covered. /metrics exports them as
        # pilosa_executor_fused_{dispatches,queries}_total.
        self.fused_dispatches = 0
        self.fused_queries = 0
        # Heterogeneous megakernel counters (executor/megakernel.py):
        # plan-buffer launches (one per mixed cohort), the queries they
        # covered, total plan entries interpreted and plan bytes
        # uploaded. /metrics exports them as
        # pilosa_executor_mega_{launches,queries,plan_entries,plan_bytes}_total.
        self.mega_launches = 0
        self.mega_queries = 0
        self.mega_plan_entries = 0
        self.mega_plan_bytes = 0
        # Mesh cohort launches (executor/megakernel.py under a
        # MeshContext, PILOSA_TPU_MESH): one plan buffer dispatched
        # SPMD over the mesh shard axis, reductions finished in-kernel
        # by the collective epilogue. collective_bytes is the modeled
        # ICI wire traffic (psum + all_gather, ops/megakernel.
        # plan_cost). /metrics exports pilosa_executor_mesh_
        # {launches,collective_bytes}_total.
        self.mesh_launches = 0
        self.mesh_collective_bytes = 0
        # Launch cost attribution (ops/megakernel.plan_cost, the
        # roofline plane): HBM bytes each launch moved split by kind,
        # plus per-opcode instruction totals. /metrics exports
        # pilosa_executor_launch_bytes_total{kind=gather|compute|
        # expand|pad} and pilosa_executor_opcode_total{op=...}.
        self.launch_bytes_gather = 0
        self.launch_bytes_compute = 0
        self.launch_bytes_expand = 0
        self.launch_bytes_pad = 0
        self.opcode_counts: Dict[str, int] = {}
        # Plan-IR verification gate (ops/megakernel.verify_plan,
        # PILOSA_TPU_PLAN_VERIFY): plans checked before dispatch and
        # plans rejected (a reject means a lowering bug — the launch
        # raised instead of executing wrong bits). /metrics exports
        # pilosa_executor_plan_verify_{passes,rejects}_total.
        self.plan_verify_passes = 0
        self.plan_verify_rejects = 0
        # Plan optimizer (ops/plan_opt.py, PILOSA_TPU_PLAN_OPT):
        # plans rewritten, CSE fingerprint hits, instructions
        # eliminated, fold chains density-reordered, and slab +
        # plan-buffer bytes the rewrites dropped. /metrics exports
        # pilosa_executor_opt_{plans,cse_hits,entries_eliminated,
        # folds_reordered,bytes_saved}_total.
        self.opt_plans = 0
        self.opt_cse_hits = 0
        self.opt_entries_eliminated = 0
        self.opt_folds_reordered = 0
        self.opt_bytes_saved = 0
        # Optional stats sink (utils/stats interface) the API layer
        # attaches; batch-scoped signals (fusion group sizes) that have
        # no per-query profile to ride report through it.
        self.stats = None
        # Generation-keyed cross-request result cache (ROADMAP item
        # 3a; executor/result_cache.py): request tier keyed on the
        # coalescer's request identity, eval tier keyed on the staged
        # fingerprint + bank generations. PILOSA_TPU_RESULT_CACHE=0
        # kills it.
        from pilosa_tpu.executor.result_cache import ResultCache
        self.result_cache = ResultCache()
        # Device rank-cache counters (core/cache.RANK_CACHE holds the
        # vectors; the store is process-wide, the counters per
        # executor so tests and /metrics attribute them): hits reuse a
        # warm [R] count vector, patches recompute only written rows,
        # rebuilds pay the full sweep TopN would have paid anyway.
        self.rank_cache_hits = 0
        self.rank_cache_patches = 0
        self.rank_cache_rebuilds = 0
        # Observability: TopN answers served from warm ranked caches
        # without any device work (reference fragment.top, fragment.go:1067).
        self.topn_cache_hits = 0
        # Sampled warm-cache self-checks run / mismatches found (a
        # mismatch means some write path failed to refresh cached
        # counts; the caches involved are repaired from storage).
        self.topn_selfchecks = 0
        self.topn_selfcheck_mismatches = 0
        # Times a GroupBy frontier outgrew GROUPBY_CHUNK_BYTES and was
        # spilled to host memory (re-uploaded per expansion chunk).
        self.groupby_spill_events = 0
        # Cluster mode installs a resolver that allocates keys on the
        # translation primary (reference: primary-owned TranslateFile with
        # chained replication, translate.go:56,400). None = local stores.
        self.key_resolver = None
        # Reverse (id -> key) resolver with primary fallback for replicas
        # whose translate-log replay lags the allocation.
        self.id_resolver = None

    def _resolve_col_keys(self, idx: Index, keys: List[str]) -> List[int]:
        if self.key_resolver is not None:
            return self.key_resolver(idx.name, None, keys)
        return [int(i) for i in idx.column_translator.translate_keys(keys)]

    def _resolve_row_keys(self, idx: Index, field: Field,
                          keys: List[str]) -> List[int]:
        if self.key_resolver is not None:
            return self.key_resolver(idx.name, field.name, keys)
        return [int(i) for i in field.row_translator.translate_keys(keys)]

    def _resolve_col_key(self, idx: Index, key: str) -> int:
        return self._resolve_col_keys(idx, [key])[0]

    def _resolve_col_ids(self, idx: Index, ids) -> List[Optional[str]]:
        if self.id_resolver is not None:
            return self.id_resolver(idx.name, None, list(ids))
        return idx.column_translator.translate_ids(ids)

    def _resolve_row_ids(self, idx: Index, field: Field,
                         ids) -> List[Optional[str]]:
        if self.id_resolver is not None:
            return self.id_resolver(idx.name, field.name, list(ids))
        return field.row_translator.translate_ids(ids)

    def _resolve_row_key(self, idx: Index, field: Field, key: str) -> int:
        return self._resolve_row_keys(idx, field, [key])[0]

    # --------------------------------------------------------- compile cache

    # Max cached compiled programs. Keys are shape signatures, so
    # steady-state serving traffic converges on a small working set; the
    # bound protects against signature churn (schema growth, width
    # drift, many distinct fused batch sizes) pinning dead programs —
    # and their XLA executables — forever.
    JIT_CACHE_MAX = int(os.environ.get("PILOSA_TPU_JIT_CACHE_MAX", 512))

    def _jit_get(self, key: str) -> Optional[Callable]:
        """Compile-cache lookup; a hit is re-inserted at the tail so
        plain dict insertion order doubles as LRU order."""
        with self._jit_cache_lock:
            fn = self._jit_cache.pop(key, None)
            if fn is not None:
                self._jit_cache[key] = fn
            return fn

    def _jit_put(self, key: str, fn: Callable) -> None:
        # Compiled XLA executables occupy HBM too; their sizes are not
        # introspectable from here, so the ledger carries the entry
        # COUNT (bytes 0) — eviction decrements the gauge (pinned by
        # tests/test_memledger.py). Ledger updates happen UNDER the
        # cache lock (the ledger lock is a leaf, so the nesting is
        # safe): deferring them would let an evict/recompile interleave
        # unregister another thread's freshly re-registered entry.
        with self._jit_cache_lock:
            while len(self._jit_cache) >= max(1, self.JIT_CACHE_MAX):
                old = next(iter(self._jit_cache))
                self._jit_cache.pop(old)
                LEDGER.unregister("jit_cache", old, owner=self)
            self._jit_cache[key] = fn
            LEDGER.register("jit_cache", key, 0, owner=self,
                            sig=str(key)[:120])

    def jit_cache_size(self) -> int:
        """Live compiled-program count (the pilosa_executor_jit_cache_size
        gauge on /metrics)."""
        with self._jit_cache_lock:
            return len(self._jit_cache)

    # ------------------------------------------------------- profiling hooks

    def _note_jit_compile(self) -> None:
        """Count one fresh XLA trace+compile (jit-cache miss). '+= 1'
        is not atomic and every request thread can land here."""
        with self._jit_stats_lock:
            self.jit_compiles += 1

    def _profile(self):
        """The QueryProfile attached to the current thread's in-flight
        query, or None (the common, zero-overhead case)."""
        return getattr(self._tls, "profile", None)

    @contextlib.contextmanager
    def _profiled(self, profile):
        """Attach `profile` (may be None) to this thread for the
        duration — the executor's instrumentation points read it via
        _profile(). Thread-local because one executor serves every
        request thread."""
        prev = getattr(self._tls, "profile", None)
        self._tls.profile = profile
        try:
            yield
        finally:
            self._tls.profile = prev

    @contextlib.contextmanager
    def _fusing(self, collector):
        """Install a FusionCollector for this thread: terminal evals
        dispatched inside the context stage into it instead of running
        (execute_batch's dispatch loop wraps each fusible request)."""
        prev = getattr(self._tls, "fuser", None)
        self._tls.fuser = collector
        try:
            yield
        finally:
            self._tls.fuser = prev

    def _note_fused(self, group_size: int) -> None:
        """Account one fused dispatch covering `group_size` queries
        (called by FusionCollector.flush; '+=' is not atomic and
        batches can run from several threads)."""
        with self._jit_stats_lock:
            self.fused_dispatches += 1
            self.fused_queries += group_size
        if self.stats is not None:
            self.stats.count("executor.fused_dispatches", 1)
            self.stats.count("executor.fused_queries", group_size)
            self.stats.histogram("executor.fusion_group_size", group_size)

    def _note_mega(self, queries: int, plan_entries: int,
                   plan_bytes: int) -> None:
        """Account one megakernel launch covering `queries` staged
        evals via `plan_entries` interpreted instructions ('+=' is not
        atomic and batches can run from several threads)."""
        with self._jit_stats_lock:
            self.mega_launches += 1
            self.mega_queries += queries
            self.mega_plan_entries += plan_entries
            self.mega_plan_bytes += plan_bytes
        if self.stats is not None:
            self.stats.count("executor.mega_launches", 1)
            self.stats.count("executor.mega_queries", queries)
            self.stats.count("executor.mega_plan_entries", plan_entries)
            self.stats.count("executor.mega_plan_bytes", plan_bytes)
            self.stats.histogram("executor.mega_batch_size", queries)

    def _note_mesh(self, n_devices: int, collective_bytes: int) -> None:
        """Account one mesh cohort launch: the plan buffer ran SPMD
        over `n_devices` device slices and the epilogue's collectives
        moved `collective_bytes` over ICI ('+=' is not atomic and
        batches can run from several threads)."""
        with self._jit_stats_lock:
            self.mesh_launches += 1
            self.mesh_collective_bytes += collective_bytes
        if self.stats is not None:
            self.stats.count("executor.mesh_launches", 1)
            self.stats.count("executor.mesh_collective_bytes",
                             collective_bytes)
            self.stats.histogram("executor.mesh_devices", n_devices)

    def _note_launch_cost(self, cost: Dict[str, Any]) -> None:
        """Account one launch's HBM traffic attribution (ops/
        megakernel.plan_cost — the roofline plane's byte splits and
        per-opcode histogram). '+=' is not atomic and batches can run
        from several threads."""
        with self._jit_stats_lock:
            self.launch_bytes_gather += cost["gatherBytes"]
            self.launch_bytes_compute += cost["computeBytes"]
            self.launch_bytes_expand += cost["expandBytes"]
            self.launch_bytes_pad += cost["padBytes"]
            for name, n in cost["opcodeHist"].items():
                # graftlint: disable=GL008 — keyed by opcode name:
                # bounded by the (8-entry) plan-IR opcode table.
                self.opcode_counts[name] = \
                    self.opcode_counts.get(name, 0) + n
        if self.stats is not None:
            for kind, key in (("gather", "gatherBytes"),
                              ("compute", "computeBytes"),
                              ("expand", "expandBytes"),
                              ("pad", "padBytes")):
                self.stats.with_tags(f"kind:{kind}").count(
                    "executor.launch_bytes", cost[key])
            for name, n in cost["opcodeHist"].items():
                self.stats.with_tags(f"op:{name}").count(
                    "executor.opcode", n)

    def _note_plan_verify(self, ok: bool) -> None:
        """Account one pre-launch plan verification (ops/megakernel.
        verify_plan). A reject is a lowering bug surfacing as a
        request error instead of wrong bits — the counter pair is the
        production signal that the gate is live and clean."""
        with self._jit_stats_lock:
            if ok:
                self.plan_verify_passes += 1
            else:
                self.plan_verify_rejects += 1
        if self.stats is not None:
            self.stats.count("executor.plan_verify_passes" if ok
                             else "executor.plan_verify_rejects", 1)

    def _note_opt(self, opt: Any) -> None:
        """Account one optimized plan launch (ops/plan_opt.OptStats —
        the before/after the megakernel leg attaches to the plan).
        '+=' is not atomic and batches can run from several
        threads."""
        with self._jit_stats_lock:
            self.opt_plans += 1
            self.opt_cse_hits += opt.cse_hits
            self.opt_entries_eliminated += opt.entries_eliminated
            self.opt_folds_reordered += opt.folds_reordered
            self.opt_bytes_saved += opt.bytes_saved
        if self.stats is not None:
            self.stats.count("executor.opt_plans", 1)
            self.stats.count("executor.opt_cse_hits", opt.cse_hits)
            self.stats.count("executor.opt_entries_eliminated",
                             opt.entries_eliminated)
            self.stats.count("executor.opt_folds_reordered",
                             opt.folds_reordered)
            self.stats.count("executor.opt_bytes_saved",
                             opt.bytes_saved)

    # -------------------------------------------- request-level result cache

    @contextlib.contextmanager
    def _dep_capture(self, deps: Optional[dict]):
        """Attach a request-tier dependency collector to this thread
        for the duration (None = no capture, zero overhead). The
        staging seam and the attr/translation read points record the
        version stamps the cached response will later be validated
        against."""
        if deps is None:
            yield
            return
        prev = getattr(self._tls, "deps", None)
        self._tls.deps = deps
        try:
            yield
        finally:
            self._tls.deps = prev

    def _request_cache_key(self, index_name: str, query, shards
                           ) -> Optional[tuple]:
        """The request tier's cache key, or None when the request is
        ineligible: cache off, mesh/cluster deployment (remote legs
        cache per node through the eval tier instead), non-string
        query, unparseable, or any call outside the staged-eval family
        (Count + bitmap calls — the flood workload; TopN rides the
        device rank cache, writes are never cacheable)."""
        if not self.result_cache.enabled or self.mesh is not None \
                or self.key_resolver is not None:
            return None
        if not isinstance(query, str):
            return None
        try:
            q = parse_string_cached(query)
        except Exception:
            return None
        calls = q.calls if isinstance(q, Query) else [q]
        for c in calls:
            if c.name != "Count" and c.name not in _BITMAP_CALLS:
                return None
        return ("req",) + request_key(index_name, query, shards)

    def _request_deps_current(self, deps: dict) -> bool:
        """Revalidate a request-tier dependency snapshot with pure
        host dict reads — the whole point: a hit touches no parser, no
        planner, no device."""
        for dk, val in deps.items():
            if not isinstance(dk, tuple):
                return False  # e.g. a stray "uncacheable" marker
            kind = dk[0]
            if kind == "view":
                _, iname, fname, vname = dk
                idx = self.holder.index(iname)
                f = idx.field(fname) if idx is not None else None
                view = f.view(vname) if f is not None else None
                cur = view.version_stamp() if view is not None else ()
            elif kind == "rattr":
                _, iname, fname = dk
                idx = self.holder.index(iname)
                f = idx.field(fname) if idx is not None else None
                cur = f.row_attr_store.gen if f is not None else -1
            elif kind == "ctrans":
                _, iname = dk
                idx = self.holder.index(iname)
                cur = idx.column_translator.size() \
                    if idx is not None else -1
            else:
                return False
            if cur != val:
                return False
        return True

    def _request_cache_get(self, key: tuple, profile=None
                           ) -> Optional[Dict[str, Any]]:
        """Request-tier lookup + hit attribution (cacheHit profile op,
        timeline `cache` lane slice)."""
        t0 = time.perf_counter()
        val = self.result_cache.lookup_request(
            key, self._request_deps_current)
        if val is None:
            return None
        if profile is not None:
            dur = time.perf_counter() - t0
            op = profile.begin_op("cache")
            op.attrs["cacheHit"] = True
            profile.end_op(op, dur)
            tl = getattr(profile, "timeline", None)
            if tl is not None:
                TIMELINE.event(tl, "cache", LANE_CACHE, t0, dur,
                               hit=True)
        return val

    def _request_cache_fill(self, key: tuple, deps: dict,
                            resp: Dict[str, Any],
                            opts: Optional["ExecOptions"] = None
                            ) -> None:
        """Fill the request tier after shaping. Refused when the
        capture flagged a dependency it cannot name (literal operands)
        or the response embeds columnAttrs (shaped outside the
        capture window)."""
        if "uncacheable" in deps or not deps:
            return
        if opts is not None and opts.column_attrs:
            return
        from pilosa_tpu.executor.result_cache import approx_nbytes
        self.result_cache.fill(key, dict(deps), resp,
                               approx_nbytes(resp), tier="request")

    # ------------------------------------------------------------------ API

    def execute(self, index_name: str, query, shards: Optional[Sequence[int]]
                = None, profile=None) -> List[Any]:
        """Execute every call in `query` (reference executor.Execute,
        executor.go:84). `profile` is an optional utils/profile
        QueryProfile the run fills in."""
        results, _ = self._execute_query(index_name, query, shards,
                                         profile=profile)
        return results

    def _execute_query(self, index_name: str, query, shards, profile=None
                       ) -> Tuple[List[Any], "ExecOptions"]:
        # Two phases: dispatch every call's device program in call order
        # (jax dispatch is async — programs queue on the device), then
        # fetch/finalize. A multi-call query thus pays one pipelined
        # device→host drain instead of a blocking round trip per call —
        # the TPU analog of the reference streaming per-shard results
        # into reduceFn as they arrive (executor.go:2277).
        with self._profiled(profile):
            idx, staged, opts = self._dispatch_query(index_name, query,
                                                     shards)
            prefetch_pendings(staged)
            return self._finalize_staged(idx, staged), opts

    def _dispatch_query(self, index_name: str, query, shards,
                        batch_tail_writes: bool = False):
        """Parse/validate/translate and dispatch every call's device
        program; returns (idx, staged, opts) with results still pending.
        `batch_tail_writes`: a later query in the same batch writes, so
        deferred reads must snapshot (see _tls.later_writes)."""
        if isinstance(query, str):
            query = parse_string_cached(query)
        if isinstance(query, Call):
            query = Query([query])
        if self.max_writes_per_request > 0 and \
                write_call_count(query) > self.max_writes_per_request:
            raise ExecutionError("too many write commands")
        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecutionError(f"index not found: {index_name}")
        opts = ExecOptions()
        staged = []
        calls = list(query.calls)
        prof = self._profile()
        if prof is not None:
            # Rebase finish_op indices: a profile may span several
            # dispatch/finalize rounds (the cluster path runs one
            # execute() per PQL call against the same profile).
            prof.mark_dispatch()
        try:
            for i, call in enumerate(calls):
                op = prof.begin_op(call.name) if prof is not None else None
                t0 = time.perf_counter() if prof is not None else 0.0
                try:
                    self._translate_call(idx, call)
                    # Deferred reads (TopN chunking) consult this to know
                    # whether lazily re-reading fragment state in finalize
                    # is still safe.
                    self._tls.later_writes = batch_tail_writes or any(
                        _peel_options(c).name in _WRITE_CALLS
                        for c in calls[i + 1:])
                    staged.append((call, self._execute_call(idx, call,
                                                            shards, opts)))
                finally:
                    if op is not None:
                        prof.end_op(op, time.perf_counter() - t0)
        finally:
            self._tls.later_writes = False
        return idx, staged, opts

    def _finalize_staged(self, idx: Index, staged) -> List[Any]:
        prof = self._profile()
        tl = prof.timeline if prof is not None else None
        results = []
        for i, (call, result) in enumerate(staged):
            t0 = time.perf_counter() if prof is not None else 0.0
            d2h = 0
            if isinstance(result, _Pending):
                if prof is not None:
                    d2h = transfer_nbytes(result.arrays)
                result = result.finalize()
            self._translate_result(idx, call, result)
            if prof is not None:
                mat_s = time.perf_counter() - t0
                prof.finish_op(i, mat_s, d2h)
                if tl is not None:
                    TIMELINE.event(tl, "materialize", LANE_FETCH, t0,
                                   mat_s, op=call.name, d2hBytes=d2h)
            results.append(result)
        return results

    def execute_batch(self, requests: Sequence[Tuple[str, Any, Optional[
            Sequence[int]]]], profiles: Optional[Sequence[Any]] = None,
            deps: Optional[Sequence[Optional[dict]]] = None
            ) -> List[Any]:
        """Execute N independent queries with ONE pipelined device
        drain: every query's calls are dispatched before any result is
        fetched, and all pending transfers start asynchronously before
        the first blocking finalize. The cross-request extension of
        the multi-call pipeline (reference executor.go:84 evaluates a
        query's calls together; clients batch calls per request) —
        this is the API-layer amortization that makes 1 ms-class
        queries serve efficiently through a high-RTT link.

        Each element of `requests` is (index_name, query, shards).
        `profiles` (optional, aligned with `requests`) carries a
        QueryProfile per request; each request's dispatch and finalize
        phases run with its profile attached (the coalesced serving
        path feeds these).
        Returns one entry per request: a (results, opts) tuple on
        success — opts drives response shaping (columnAttrs), see
        shape_response — or the exception instance for that request
        (per-request errors don't fail the batch).

        `deps` (optional, aligned with `requests`) carries per-request
        dependency-capture dicts for the request-tier result cache:
        a non-None entry is attached to the thread while that
        request's dispatch and finalize phases run (execute_batch_
        shaped feeds these and fills the cache after shaping)."""
        return self.execute_batch_finish(
            self.execute_batch_begin(requests, profiles, deps))

    def execute_batch_begin(self, requests: Sequence[Tuple[str, Any,
            Optional[Sequence[int]]]],
            profiles: Optional[Sequence[Any]] = None,
            deps: Optional[Sequence[Optional[dict]]] = None
            ) -> "_BatchInFlight":
        """The dispatch half of execute_batch: parse, plan, fuse and
        LAUNCH every request's device programs, then start the async
        result prefetch — and return with results still pending. The
        pipelined serving path (server/coalescer.py) runs this for
        batch K+1 while batch K's execute_batch_finish is still
        draining, overlapping plan build + H2D with device time — the
        RTT the dispatch floor (docs/perf.md §5) charges per batch."""
        from pilosa_tpu.executor.fusion import FusionCollector
        profs = list(profiles) if profiles is not None \
            else [None] * len(requests)
        deps_l = list(deps) if deps is not None \
            else [None] * len(requests)
        staged_q: List[Any] = []
        out: List[Any] = [None] * len(requests)
        # Parse ONCE per request (the parsed tree is handed straight to
        # _dispatch_query — no second parse/clone) and pre-scan for
        # writes so earlier requests' deferred reads know to snapshot.
        parsed: List[Any] = [None] * len(requests)
        writes_after = [False] * len(requests)
        has_writes = [False] * len(requests)
        any_writes = False
        for j in range(len(requests) - 1, -1, -1):
            writes_after[j] = any_writes
            q = requests[j][1]
            try:
                if isinstance(q, str):
                    q = parse_string_cached(q)
                if isinstance(q, Call):
                    q = Query([q])
                parsed[j] = q
                if write_call_count(q) > 0:
                    has_writes[j] = True
                    any_writes = True
            except Exception as e:
                out[j] = e  # parse error: reported for this item only
        # Same-signature fusion across the batch's read-only requests:
        # terminal evals stage into the collector during dispatch and
        # flush as ONE vmapped program per signature group. A write-
        # containing request is a fence — groups open before it run
        # before its dispatch, and the request itself dispatches
        # uncollected — so every read observes exactly the fragment
        # state sequential execution would have shown it.
        fuser = FusionCollector(self)
        try:
            for j, (index_name, _, shards) in enumerate(requests):
                if parsed[j] is None:
                    continue
                try:
                    if has_writes[j]:
                        fuser.flush()
                    with self._profiled(profs[j]), \
                            self._dep_capture(deps_l[j]):
                        if has_writes[j]:
                            ctx = contextlib.nullcontext()
                        else:
                            ctx = self._fusing(fuser)
                        with ctx:
                            staged_q.append(
                                (j, self._dispatch_query(
                                    index_name, parsed[j], shards,
                                    batch_tail_writes=writes_after[j])))
                except Exception as e:
                    out[j] = e
        finally:
            # Groups must resolve before any result is consumed —
            # prefetch/finalize below read through FusedEval handles.
            fuser.flush()
        for _, (_, staged, _) in staged_q:
            prefetch_pendings(staged)
        return _BatchInFlight(staged_q, out, profs, deps_l)

    def execute_batch_finish(self, flight: "_BatchInFlight") -> List[Any]:
        """The drain half of execute_batch: block on every pending
        transfer and build host results. Safe to run from a different
        thread than the begin (the pipelined coalescer's finalizer):
        profile/deps contexts re-attach per request below, and all
        device programs were dispatched with their operand banks
        snapshotted."""
        out = flight.out
        for j, (idx, staged, opts) in flight.staged_q:
            try:
                with self._profiled(flight.profs[j]), \
                        self._dep_capture(flight.deps_l[j]):
                    out[j] = (self._finalize_staged(idx, staged), opts)
            except Exception as e:
                out[j] = e
        return out

    def execute_batch_shaped(self, requests: Sequence[Tuple[
            str, Any, Optional[Sequence[int]]]],
            profiles: Optional[Sequence[Any]] = None) -> List[Any]:
        """execute_batch + per-request JSON shaping: one entry per
        request, either the shaped {"results": ...} dict or the
        exception instance for that request. Shared by API.query_batch
        (the /batch/query route) and the serving-path coalescer — one
        place owns the shape-or-error contract.

        This is the batch seam of the request-tier result cache:
        eligible requests are answered from cache before anything
        dispatches, and misses execute under dependency capture and
        fill after shaping. A request positioned AFTER a
        write-containing batchmate never consults the cache — its
        lookup would run before that write does, and sequential
        semantics demand it observe post-write state."""
        return self.execute_batch_shaped_finish(
            self.execute_batch_shaped_begin(requests, profiles))

    def execute_batch_shaped_begin(self, requests: Sequence[Tuple[
            str, Any, Optional[Sequence[int]]]],
            profiles: Optional[Sequence[Any]] = None) -> "_ShapedInFlight":
        """Cache lookups + the dispatch half of the shaped batch (see
        execute_batch_begin); execute_batch_shaped_finish drains,
        shapes and fills the cache — possibly from another thread."""
        n = len(requests)
        profs = list(profiles) if profiles is not None else [None] * n
        out: List[Any] = [None] * n
        keys: List[Optional[tuple]] = [None] * n
        deps_l: List[Optional[dict]] = [None] * n
        run: List[int] = []
        write_seen = False
        for j, (index_name, q, shards) in enumerate(requests):
            forced = profs[j] is not None and getattr(
                profs[j], "forced", False)
            key = None
            if not write_seen and not forced:
                key = self._request_cache_key(index_name, q, shards)
            if not write_seen and query_is_write(q):
                write_seen = True
            if key is not None:
                hit = self._request_cache_get(key, profs[j])
                if hit is not None:
                    out[j] = hit
                    continue
                keys[j] = key
                deps_l[j] = {}
            run.append(j)
        flight = self.execute_batch_begin(
            [requests[j] for j in run],
            profiles=[profs[j] for j in run],
            deps=[deps_l[j] for j in run])
        return _ShapedInFlight(flight, out, keys, deps_l, run,
                               list(requests))

    def execute_batch_shaped_finish(self, sh: "_ShapedInFlight"
                                    ) -> List[Any]:
        out, keys, deps_l, run, requests = (sh.out, sh.keys, sh.deps_l,
                                            sh.run, sh.requests)
        res = self.execute_batch_finish(sh.flight)
        for j, r in zip(run, res):
            index_name = requests[j][0]
            if isinstance(r, Exception):
                out[j] = r
                continue
            results, opts = r
            try:
                shaped = self.shape_response(index_name, results, opts)
            except Exception as e:
                out[j] = e
                continue
            if deps_l[j] is not None:
                self._request_cache_fill(keys[j], deps_l[j], shaped,
                                         opts)
            out[j] = shaped
        return out

    def execute_full(self, index_name: str, query,
                     shards: Optional[Sequence[int]] = None, profile=None
                     ) -> Dict[str, Any]:
        """Execute and return the full JSON-shaped response, including
        `columnAttrs` when an Options(columnAttrs=true) call requested them
        (reference executor.Execute, executor.go:134-165).

        Eligible read-only requests ride the request tier of the
        result cache: a generation-valid repeat returns the cached
        shaped response without parsing, planning, compiling or
        dispatching anything; misses execute under dependency capture
        and fill after shaping. Forced (?profile=true) profiles bypass
        the lookup — their tree must describe a real execution — but
        still refresh the fill."""
        key = self._request_cache_key(index_name, query, shards)
        forced = profile is not None and getattr(profile, "forced",
                                                 False)
        if key is not None and not forced:
            hit = self._request_cache_get(key, profile)
            if hit is not None:
                return hit
        deps: Optional[dict] = {} if key is not None else None
        with self._dep_capture(deps):
            results, opts = self._execute_query(index_name, query,
                                                shards, profile=profile)
            resp = self.shape_response(index_name, results, opts)
        if deps is not None:
            self._request_cache_fill(key, deps, resp, opts)
        return resp

    def shape_response(self, index_name: str, results, opts: "ExecOptions"
                       ) -> Dict[str, Any]:
        """JSON-shape executed results, attaching columnAttrs via the
        LOCAL translator when requested (shared by execute_full and the
        single-node batch path)."""
        from pilosa_tpu.executor.results import result_to_json
        resp: Dict[str, Any] = {"results": [result_to_json(r)
                                            for r in results]}
        if opts.column_attrs:
            idx = self.holder.index(index_name)
            ids = sorted({int(c) for r in results if isinstance(r, RowResult)
                          for c in r.columns().tolist()})
            resp["columnAttrs"] = column_attr_sets(
                idx, ids, resolve=lambda xs: self._resolve_col_ids(idx, xs))
        return resp

    # ------------------------------------------------------- key translation

    def _translate_call(self, idx: Index, call: Call) -> None:
        """String keys -> ids in place (reference translateCall,
        executor.go:2417-2505). Translation is call-shape-aware: only the
        row/column-bearing args of each call form are touched — generic
        string args (e.g. SetRowAttrs attribute values) pass through even
        when an equally-named keyed field exists. Keys are allocated on
        first use (TranslateColumnsToUint64 get-or-create semantics)."""
        col = call.args.get("_col")
        if isinstance(col, str):
            if not idx.keys:
                raise ExecutionError(
                    f"index {idx.name} does not use column keys")
            call.args["_col"] = self._resolve_col_key(idx, col)
        row = call.args.get("_row")
        fname = call.args.get("_field")
        if isinstance(row, str):
            field = idx.field(fname) if fname else None
            if field is None or not field.options.keys:
                raise ExecutionError(
                    f"string row value not allowed on field {fname}")
            call.args["_row"] = self._resolve_row_key(idx, field, row)
        # The one field=row arg of Row/Range/Set/Clear/ClearRow/Store.
        if call.name in ("Row", "Range", "Set", "Clear", "ClearRow",
                         "Store"):
            try:
                k, v = self._row_call_field(call)
            except ExecutionError:
                k, v = None, None
            if isinstance(v, str):
                field = idx.field(k)
                if field is None or not field.options.keys:
                    raise ExecutionError(
                        f"string row value not allowed on field {k}")
                call.args[k] = self._resolve_row_key(idx, field, v)
        # Rows(previous=..., column=...) (reference executor.go:2443-2460).
        if call.name in ("Rows", "TopN"):
            field = idx.field(fname) if fname else None
            prev = call.args.get("previous")
            if isinstance(prev, str):
                if field is None or not field.options.keys:
                    raise ExecutionError(
                        f"string previous not allowed on field {fname}")
                call.args["previous"] = self._resolve_row_key(idx, field,
                                                              prev)
            column = call.args.get("column")
            if isinstance(column, str):
                if not idx.keys:
                    raise ExecutionError(
                        f"index {idx.name} does not use column keys")
                call.args["column"] = self._resolve_col_key(idx, column)
        # GroupBy(previous=[...]): one entry per Rows child, translated
        # against that child's field (reference translateGroupByCall,
        # executor.go:2522-2577).
        if call.name == "GroupBy":
            prev = call.args.get("previous")
            if prev is not None:
                if not isinstance(prev, list):
                    raise ExecutionError(
                        "'previous' argument must be a list")
                if len(prev) != len(call.children):
                    raise ExecutionError(
                        f"mismatched lengths for previous: {len(prev)} "
                        f"and children: {len(call.children)}")
                for i, (p, child) in enumerate(zip(prev, call.children)):
                    if isinstance(p, str):
                        field = idx.field(child.args.get("_field"))
                        if field is None or not field.options.keys:
                            raise ExecutionError(
                                "prev value must be a row id (int) when "
                                "field doesn't have keys")
                        prev[i] = self._resolve_row_key(idx, field, p)
        filt = call.args.get("filter")
        if isinstance(filt, Call):
            self._translate_call(idx, filt)
        for child in call.children:
            self._translate_call(idx, child)

    def _translate_result(self, idx: Index, call: Call, result) -> None:
        """Ids -> string keys on results (reference translateResults,
        executor.go:2577)."""
        while call.name == "Options" and call.children:
            call = call.children[0]
        if isinstance(result, RowResult) and idx.keys:
            cap = getattr(self._tls, "deps", None)
            if cap is not None:
                # The response embeds translated column keys. The
                # store is append-only (an allocated mapping never
                # changes), but an id unresolved at fill time can gain
                # a key later — the size stamp invalidates then.
                # Stamp-then-read (first stamp wins): taken BEFORE the
                # resolve, so a key allocated mid-resolve leaves the
                # stored size behind and the entry fails validation
                # instead of caching the decimal fallback as current.
                cap.setdefault(("ctrans", idx.name),
                               idx.column_translator.size())
            cols = result.columns()  # cached on the result for to_json
            # Keep 1:1 alignment with columns; ids set outside the
            # translator (raw-id imports) fall back to their decimal form.
            result.keys = [k if k is not None else str(int(c))
                           for c, k in zip(
                               cols, self._resolve_col_ids(idx, cols))]
            return
        fname = call.args.get("_field")
        field = idx.field(fname) if fname else None
        keyed = field is not None and field.options.keys
        if isinstance(result, PairsResult) and keyed:
            result.keys = [k or str(r) for (r, _), k in zip(
                result.pairs,
                self._resolve_row_ids(idx, field,
                                      [r for r, _ in result.pairs]))]
        elif isinstance(result, RowIdentifiers) and keyed:
            result.keys = [k or str(r) for r, k in zip(
                result.rows, self._resolve_row_ids(idx, field, result.rows))]
        elif isinstance(result, list):
            for gc in result:
                if isinstance(gc, GroupCount):
                    for fr in gc.group:
                        gf = idx.field(fr.field)
                        if gf is not None and gf.options.keys:
                            fr.row_key = gf.row_translator.translate_id(
                                fr.row_id)

    # -------------------------------------------------------- call dispatch

    def _execute_call(self, idx: Index, call: Call,
                      shards: Optional[Sequence[int]],
                      opts: Optional["ExecOptions"] = None) -> Any:
        name = call.name
        cap = getattr(self._tls, "deps", None)
        if cap is not None and name != "Count" \
                and name not in _BITMAP_CALLS:
            # Belt and braces: _request_cache_key already filters to
            # the staged-eval call family, but any path that slips a
            # non-staged read under capture must poison the fill, not
            # cache with incomplete dependencies.
            cap["uncacheable"] = True
        if name == "Options":
            return self._execute_options(idx, call, shards, opts)
        if name == "Count":
            return self._execute_count(idx, call, shards)
        if name in _BITMAP_CALLS:
            return self._execute_bitmap(idx, call, shards, opts)
        if name == "TopN":
            return self._execute_topn(idx, call, shards)
        if name == "Rows":
            return self._execute_rows(idx, call, shards)
        if name == "GroupBy":
            return self._execute_group_by(idx, call, shards)
        if name in ("Sum", "Min", "Max"):
            return self._execute_val_count(idx, call, shards, name)
        if name == "Set":
            return self._execute_set(idx, call)
        if name == "Clear":
            return self._execute_clear(idx, call)
        if name == "ClearRow":
            return self._execute_clear_row(idx, call, shards)
        if name == "Store":
            return self._execute_store(idx, call, shards)
        if name == "SetRowAttrs":
            return self._execute_set_row_attrs(idx, call)
        if name == "SetColumnAttrs":
            return self._execute_set_column_attrs(idx, call)
        raise ExecutionError(f"unknown call: {name}")

    def _shards(self, idx: Index, shards, pad: bool = True) -> List[int]:
        available = idx.available_shards()
        out = list(shards) if shards is not None else (available or [0])
        if pad and self.mesh is not None:
            # Padding ids must be absent from the whole index, not just
            # the requested subset.
            floor = (max(available) + 1) if available else 0
            out = self.mesh.pad_shards(out, floor=floor)
        return out

    def _referenced_fields(self, idx: Index, call: Call,
                           out: set) -> bool:
        """Collect every field a row-call tree reads; False when the
        tree contains a construct this walk doesn't model (caller then
        keeps the full shard list)."""
        name = call.name
        if name in ("Row", "Range"):
            try:
                fname, _ = self._row_call_field(call)
            except ExecutionError:
                return False
            f = idx.field(fname)
            if f is None:
                return False
            out.add(f)
            return True
        if name == "Not":
            ef = idx.existence_field()
            if ef is None:
                return False
            out.add(ef)
            return all(self._referenced_fields(idx, c, out)
                       for c in call.children)
        if name in ("Intersect", "Union", "Difference", "Xor", "Shift",
                    "Threshold"):
            return bool(call.children) and all(
                self._referenced_fields(idx, c, out)
                for c in call.children)
        return False

    def _restrict_shards(self, idx: Index, call: Call,
                         shards: List[int]) -> List[int]:
        """Drop shards where NO referenced field has data — a leaf over
        an absent fragment is all-zeros, and zeros through any bitmap
        expression stay zeros, so dropped shards cannot contribute
        columns or counts. This is what keeps a narrow field (e.g. a
        time field covering one shard) from sweeping every shard of a
        wide index (the reference's executeRowShard likewise skips
        absent fragments, executor.go:1265). Field granularity: one
        availableShards union per field, no per-view walk."""
        fields: set = set()
        if not self._referenced_fields(idx, call, fields) or not fields:
            return shards
        covered: set = set()
        for f in fields:
            covered.update(f.available_shards())
        out = [s for s in shards if s in covered]
        # Keep one shard when nothing is covered: zero-size device
        # shapes are not worth the special-casing for an all-empty
        # result.
        return out or shards[:1]

    # ----------------------------------------------------- bitmap call eval

    def _execute_options(self, idx: Index, call: Call, shards,
                         opts: Optional["ExecOptions"]) -> Any:
        """Options(child, columnAttrs=…, excludeRowAttrs=…,
        excludeColumns=…, shards=[…]) — reference executeOptionsCall,
        executor.go:317-361. `columnAttrs` mutates the *outer* options (it
        shapes the whole response); the exclude flags apply to a copy used
        for the child only."""
        if len(call.children) != 1:
            raise ExecutionError("Options() takes exactly one child call")
        child_opts = ExecOptions(**vars(opts)) if opts is not None \
            else ExecOptions()
        for arg in ("columnAttrs", "excludeRowAttrs", "excludeColumns"):
            if arg in call.args and not isinstance(call.args[arg], bool):
                raise ExecutionError(f"Query(): {arg} must be a bool")
        if call.args.get("columnAttrs") and opts is not None:
            opts.column_attrs = True
        if "excludeRowAttrs" in call.args:
            child_opts.exclude_row_attrs = call.args["excludeRowAttrs"]
        if "excludeColumns" in call.args:
            child_opts.exclude_columns = call.args["excludeColumns"]
        if "shards" in call.args:
            arg = call.args["shards"]
            if not isinstance(arg, (list, tuple)) or not all(
                    isinstance(s, int) and not isinstance(s, bool)
                    and s >= 0 for s in arg):
                raise ExecutionError(
                    "Query(): shards must be a list of unsigned integers")
            shards = [int(s) for s in arg]
        return self._execute_call(idx, call.children[0], shards, child_opts)

    def _execute_bitmap(self, idx: Index, call: Call, shards,
                        opts: Optional["ExecOptions"] = None) -> RowResult:
        shards = self._shards(idx, self._restrict_shards(
            idx, call, self._shards(idx, shards, pad=False)))
        words = self._eval_tree(idx, call, shards, mode="row",
                                fusible=True)
        res = RowResult(shards, words)
        if opts is not None and opts.exclude_row_attrs:
            res.attrs = {}
        else:
            self._attach_row_attrs(idx, call, res)
        if opts is not None and opts.exclude_columns:
            res.clear_columns()
        return res

    def _execute_count(self, idx: Index, call: Call, shards) -> "_Pending":
        if len(call.children) != 1:
            raise ExecutionError("Count() takes exactly one row argument")
        shards = self._shards(idx, self._restrict_shards(
            idx, call.children[0], self._shards(idx, shards, pad=False)))
        # `counts` may be a FusedEval handle under execute_batch; both
        # it and a plain device array resolve through np.asarray (the
        # handle shares ONE host fetch across its whole fusion group).
        counts = self._eval_tree(idx, call.children[0], shards,
                                 mode="count", fusible=True)
        return _Pending(
            lambda: int(np.asarray(counts, dtype=np.int64).sum()),
            arrays=(counts,))

    def _eval_tree(self, idx: Index, call: Call, shards: List[int],
                   mode: str, fusible: bool = False):
        """Plan + compile (cached by shape) + run the call tree.

        `fusible=True` marks a TERMINAL eval: the program's output
        feeds only result finalization, never another device
        expression of the same query (Count's tree, a top-level
        bitmap call). When a fusion collector is installed
        (execute_batch) such evals stage instead of running — same-
        signature stages from different batched queries later run as
        ONE vmapped XLA program (executor/fusion.py) and the returned
        FusedEval handle resolves to this query's slice."""
        prof = self._profile()
        t_plan0 = time.perf_counter() if prof is not None else 0.0
        staged = self._stage_tree(idx, call, shards, mode)
        ckey = None
        rc = self.result_cache
        forced = prof is not None and getattr(prof, "forced", False)
        if fusible and rc.enabled and not forced \
                and self.mesh is None \
                and staged.fp is not None and staged.cacheable:
            # Eval-tier result cache (executor/result_cache.py): the
            # lookup sits BEFORE the fusion collector, so a hit skips
            # compile, dispatch and fetch — and a fusion group whose
            # members all hit simply never forms, let alone launches.
            # The key adds the index name (fp's operand keys are only
            # (field, view) — two indexes with same-named fields and
            # matching bank shapes would otherwise share one key and
            # evict each other on every lookup) and the concrete shard
            # tuple (fp covers shard COUNT via the signature; identity
            # must cover shard IDS); generation equality against the
            # operand banks' fragment versions is the implicit write
            # invalidation.
            ckey = ("eval", idx.name, staged.fp,
                    tuple(int(s) for s in shards))
            hit = rc.lookup(ckey, staged.gen)
            if hit is not None:
                if prof is not None:
                    plan_s = time.perf_counter() - t_plan0
                    node = prof.tree(staged.mode, staged.sig, None,
                                     plan_s, 0, staged.n_shards)
                    node.attrs["cacheHit"] = True
                    tl = prof.timeline
                    if tl is not None:
                        TIMELINE.event(tl, "cache", LANE_CACHE,
                                       t_plan0, plan_s, hit=True)
                return hit
        if fusible and FUSION_ENABLED and (
                self.mesh is None or self._mesh_fusion_enabled()):
            fuser = getattr(self._tls, "fuser", None)
            if fuser is not None:
                out = fuser.add(staged, prof, t_plan0)
                return _CacheFillEval(out, rc, ckey, staged.gen) \
                    if ckey is not None else out
        out = self._run_staged(staged, prof, t_plan0)
        return _CacheFillEval(out, rc, ckey, staged.gen) \
            if ckey is not None else out

    def _mesh_fusion_enabled(self) -> bool:
        """Mesh requests enter the fusion collector exactly when the
        mesh megakernel path can take the staged evals (executor/
        megakernel.py's MESH_ENABLED + MEGAKERNEL_ENABLED switches):
        the collector is the gateway to the mesh cohort launch, and
        groups the launch doesn't take run per-group — the solo path
        is byte-identical to the unfused mesh path. With
        PILOSA_TPU_MESH=0 (or the megakernel off) mesh requests skip
        the collector entirely, the pre-mesh behavior."""
        from pilosa_tpu.executor import megakernel as megamod
        return megamod.MEGAKERNEL_ENABLED and megamod.MESH_ENABLED

    def _stage_tree(self, idx: Index, call: Call, shards: List[int],
                    mode: str) -> "_StagedEval":
        """Plan phase: walk the tree, build banks, resolve slots and
        the shape signature. Stages everything the compiled program
        needs without running (or even compiling) it — the seam the
        batch fusion pass groups on."""
        import jax.numpy as jnp

        from pilosa_tpu.core.view import SparseBank

        # Hybrid layout restage loop: a sparse-planned key whose
        # SparseBank build bails (the view densified since the layout
        # decision) self-heals the view to dense and replans ONCE with
        # that key forced dense — bounded by the key count, and in
        # practice one extra host walk on a rare transition. The deps
        # stamps inside the loop keep the STAMP-THEN-READ order (first
        # stamp wins, so a restage cannot move a stamp past a read).
        force_dense: set = set()
        while True:
            plan = _Plan()
            plan.force_dense = force_dense
            expr = self._plan_call(idx, call, shards, plan)
            self._capture_deps(idx, plan)
            known = len(force_dense)
            banks, retry = self._stage_banks(idx, plan, shards,
                                             force_dense)
            if not retry:
                break
            if len(force_dense) == known:  # pragma: no cover
                # Each retry forces one MORE key dense, so the loop is
                # bounded by the plan's distinct sparse keys; a bail
                # that adds nothing would mean _stage_banks broke that
                # contract — fail loudly instead of spinning.
                raise ExecutionError(
                    "hybrid-layout staging failed to settle on a "
                    "bank representation")
        for i, key, row in plan.slot_refs:
            plan.idxs[i] = banks[plan.bank_pos[key]].slot(row)
        # Width resolves AFTER banks are built: a write landing between
        # planning and bank build can widen a view, and the plan width
        # must cover every actual bank width or _align_words would slice
        # off real set bits (plan-time widths alone are a TOCTOU). A
        # SparseBank's width is the dense width its rows expand to.
        plan.widths.extend(
            b.width if isinstance(b, SparseBank) else b.array.shape[-1]
            for b in banks)
        plan.resolve_width()
        bank_arrays = tuple(
            b.arrays if isinstance(b, SparseBank) else b.array
            for b in banks)
        lits = None
        if plan.literals:
            lits = jnp.stack([_align_words(a, plan.width)
                              for a in plan.literals])
            if self.mesh is not None:
                lits = self.mesh.put_row(lits)
        # Sparse operands show as their (pos, starts) shape pair: a
        # layout flip must land in a DIFFERENT signature (different
        # program) even when the dense bank shape matches. Their dense
        # EXPANSION widths are part of the signature too — the leaf
        # closure bakes plan.sparse_widths[pos] as a trace constant,
        # and a view widening can change the width while leaving every
        # array SHAPE (pow2 pos pad, row capacity) and plan.width
        # untouched, so without this a stale compiled program would
        # silently drop the widened bits (dense leaves are covered
        # because their bank width IS the array's last dim).
        bshapes = [tuple(x.shape for x in a) if isinstance(a, tuple)
                   else a.shape for a in bank_arrays]
        xw = sorted(plan.sparse_widths.items())
        sig = (f"{mode}|{''.join(plan.sig_parts)}|W{plan.width}"
               f"|B{bshapes}{f'|XW{xw}' if xw else ''}"
               f"|L{None if lits is None else lits.shape}|S{len(shards)}")
        fp = gen = None
        if WORKLOAD.enabled or self.result_cache.enabled:
            # The fingerprint uses ROW IDS from slot_refs (bank slots
            # are append-order-dependent across rebuilds); the
            # generation is the operand banks' fragment-version map —
            # together the key BOTH the workload recorder's repeat
            # tracking and the result cache's eval tier use (one
            # identity, so /debug/hotspots' predicted savings and the
            # observed hit ratio describe the same keys). Host dict
            # work only, no device interaction (GL003-clean).
            fp = (sig, tuple((key, row) for _, key, row in
                             plan.slot_refs), tuple(plan.params))
            gen = tuple(tuple(sorted(b.versions.items())) for b in banks)
        if WORKLOAD.enabled:
            WORKLOAD.record_query(fp, gen, index=idx.name, mode=mode,
                                  n_shards=len(shards), sig=sig)
            prof = self._profile()
            for key in plan.bank_keys:
                WORKLOAD.record_read(idx.name, key[0], key[1], shards,
                                     rows=plan.rows_for.get(key))
                if prof is not None:
                    prof.touch_fragments(idx.name, key[0], key[1],
                                         shards)
        return _StagedEval(mode=mode, sig=sig, expr=expr,
                           width=plan.width, n_shards=len(shards),
                           bank_arrays=bank_arrays,
                           idxs=list(plan.idxs), params=list(plan.params),
                           lits=lits, fp=fp, gen=gen,
                           cacheable=not plan.literals,
                           ir=tuple(plan.ir) if plan.ir_ok else None)

    def _capture_deps(self, idx: Index, plan: _Plan) -> None:
        """Request-tier dependency capture, STAMP-THEN-READ: the
        version stamp is taken BEFORE the banks are fetched, so a
        write racing the build leaves the stored stamp behind the
        current one and the entry fails validation (a harmless
        spurious invalidation). Stamping after the read would let that
        race cache pre-write data under a post-write stamp — stale
        forever. First stamp wins across a multi-call query (and
        across hybrid-layout restages) for the same reason. One stamp
        per operand VIEW (coarser than the per-shard bank versions —
        any write or new fragment anywhere in the view invalidates —
        which is exactly what makes it airtight: shard-restriction
        (_restrict_shards) and default-shard growth cannot leak a
        stale hit past it)."""
        cap = getattr(self._tls, "deps", None)
        if cap is None:
            return
        for key in plan.bank_keys:
            dk = ("view", idx.name, key[0], key[1])
            if dk not in cap:
                f = idx.field(key[0])
                view = f.view(key[1]) if f is not None else None
                cap[dk] = view.version_stamp() \
                    if view is not None else ()
        if plan.literals:
            # Literal operand content is not named by the deps.
            cap["uncacheable"] = True

    def _stage_banks(self, idx: Index, plan: _Plan, shards,
                     force_dense: set):
        """Build every operand bank the plan names — SparseBanks for
        sparse-planned keys, dense (possibly row-subset) ViewBanks for
        the rest. Returns (banks, retry): retry=True means a sparse
        build bailed, the offending key is now in `force_dense`, and
        the caller must replan."""
        banks: List[Any] = []
        for key in plan.bank_keys:
            if plan.bank_sparse.get(key):
                bank = self._get_sparse_bank(idx, key, shards)
                if bank is None:
                    force_dense.add(key)
                    return banks, True
                plan.sparse_widths[plan.bank_pos[key]] = bank.width
                banks.append(bank)
            else:
                banks.append(self._get_bank(
                    idx, key, shards,
                    rows_needed=plan.rows_for.get(key)))
        return banks, False

    def _get_sparse_bank(self, idx: Index, key: Tuple[str, str],
                         shards):
        """The SparseBank operand for a sparse-planned leaf, or None
        when the build bails (too dense / view gone) — in which case
        the view self-heals to dense so staging stops asking."""
        field = idx.field(key[0])
        view = field.view(key[1]) if field is not None else None
        if view is None:
            return None
        bank = view.sparse_bank(tuple(shards))
        if bank is None:
            view.set_layout("dense")
        return bank

    def _tree_fn(self, staged: "_StagedEval") -> Tuple[Callable, bool]:
        """Compile phase: the jitted program for a staged eval, from
        the shape-keyed cache when present. Returns (fn, jit_hit)."""
        import jax
        fn = self._jit_get(staged.sig)
        hit = fn is not None
        if fn is None:
            self._note_jit_compile()
            fn = jax.jit(staged.runner())
            self._jit_put(staged.sig, fn)
        return fn, hit

    def _cached_args(self, akey: tuple, build: Callable):
        """LRU arg-cache get-or-build: returns (arrays, uploaded).
        `build()` runs OUTSIDE the lock (device puts can block on the
        transfer); two threads racing the same new key just put twice,
        and last-insert wins."""
        with self._arg_cache_lock:
            cached = self._arg_cache.pop(akey, None)
        uploaded = cached is None
        if cached is None:
            cached = build()
        with self._arg_cache_lock:
            while len(self._arg_cache) >= 1024:
                # Evict oldest (dicts iterate in insertion order; the
                # pop-and-reinsert on hit makes this an LRU).
                self._arg_cache.pop(next(iter(self._arg_cache)))
            self._arg_cache[akey] = cached
        return cached, uploaded

    def _staged_args(self, staged: "_StagedEval"):
        """Device copies of a staged eval's idxs/params operand
        vectors, via the LRU arg cache. Returns (idxs, params,
        uploaded) — uploaded=True when this call paid the two
        host->device puts."""
        import jax.numpy as jnp

        def build():
            # graftlint: disable=GL003 — staged.idxs/params are host
            # lists; np.asarray here marshals them for upload (the
            # device transfer is the jnp.asarray), it fetches nothing.
            idxs = jnp.asarray(np.asarray(staged.idxs, dtype=np.int32))
            # graftlint: disable=GL003 — host-list upload, as above.
            params = jnp.asarray(np.asarray(staged.params,
                                            dtype=np.uint32))
            return idxs, params

        akey = (staged.sig, tuple(staged.idxs), tuple(staged.params))
        (idxs, params), uploaded = self._cached_args(akey, build)
        return idxs, params, uploaded

    def _call_program(self, fn, *args):
        """Run phase: the single funnel every compiled tree-program
        invocation goes through — fused and unfused alike. Tests stub
        this to count real XLA dispatches. The timeline's dispatch-gap
        analyzer taps the funnel (host wall timestamps of the async
        enqueue — zero fences), so `pilosa_device_idle_ratio` sees
        every dispatch however it was reached."""
        t0 = time.perf_counter()
        out = fn(*args)
        TIMELINE.note_dispatch(t0, time.perf_counter() - t0)
        return out

    def _run_staged(self, staged: "_StagedEval", prof, t_plan0: float):
        """Compile + run one staged eval on its own (the unfused
        path). `prof`/`t_plan0` carry the profiling context captured
        when planning started."""
        fn, jit_hit = self._tree_fn(staged)
        idxs, params, uploaded = self._staged_args(staged)
        if prof is None:
            return self._call_program(fn, staged.bank_arrays, idxs,
                                      params, staged.lits)
        # Profiled run: planS covers planning + bank/operand staging up
        # to the program call; dispatchS is the fn() call itself (async
        # enqueue on a cache hit, trace+compile on a miss); deviceS is
        # the fenced XLA execution time — sampled queries only, so the
        # unprofiled path keeps its fully-async dispatch queue.
        h2d = (transfer_nbytes((idxs, params)) if uploaded else 0) \
            + (staged.lits.nbytes if staged.lits is not None else 0)
        plan_s = time.perf_counter() - t_plan0
        node = prof.tree(staged.mode, staged.sig, jit_hit, plan_s, h2d,
                         staged.n_shards)
        tl = prof.timeline
        if tl is not None:
            TIMELINE.event(tl, "plan", LANE_PLAN, t_plan0, plan_s,
                           jit="hit" if jit_hit else "miss")
        t_disp = time.perf_counter()
        out = self._call_program(fn, staged.bank_arrays, idxs, params,
                                 staged.lits)
        dispatch_s = time.perf_counter() - t_disp
        prof.tree_dispatch(node, dispatch_s)
        if tl is not None:
            TIMELINE.event(tl, "dispatch", LANE_DISPATCH, t_disp,
                           dispatch_s, shards=staged.n_shards)
        device_s = 0.0
        if prof.sample_device:
            # Device slices exist ONLY when the profiler already fenced
            # this query (?profile=true / sampled 1-in-N) — the
            # timeline adds zero fences of its own.
            t_dev = time.perf_counter()
            device_s = _fence_device(out)
            prof.tree_device(node, device_s)
            if tl is not None:
                TIMELINE.event(tl, "device", LANE_DEVICE, t_dev,
                               device_s)
        if staged.fp is not None:
            # Feed the cache-opportunity estimator: what one eval of
            # this signature actually cost (dispatch enqueue + fenced
            # device time when sampled) — the seconds a result-cache
            # hit would have saved.
            WORKLOAD.note_eval_seconds(staged.fp, dispatch_s + device_s)
        return out

    # -- planning: one host walk resolving banks/slots/params ---------------

    def _plan_call(self, idx: Index, call: Call, shards, plan: _Plan):
        """Returns expr(banks, idxs, params, lits) -> [S, W], appending to
        the plan. Mirrors executeBitmapCallShard's recursion
        (executor.go:540)."""
        import jax.numpy as jnp
        name = call.name

        if name in ("Row", "Range"):
            return self._plan_row_leaf(idx, call, shards, plan)
        if name in ("Not", "Shift") and len(call.children) != 1:
            raise ExecutionError(f"{name}() takes exactly one row argument")
        if name == "Not":
            ef = idx.existence_field()
            if ef is None:
                raise ExecutionError(
                    f"index {idx.name} does not support existence (Not)")
            ex = self._plan_slot_leaf(ef, VIEW_STANDARD, 0, shards, plan)
            sub = self._plan_call(idx, call.children[0], shards, plan)
            plan.sig_parts.append("!")
            # Not(x) IS existence \ x: the same left-fold "diff" node
            # the Difference lowering uses (operands pushed in order).
            plan.ir.append(("fold", "diff", 2))
            return lambda b, i, p, l: jnp.bitwise_and(
                ex(b, i, p, l), jnp.bitwise_not(sub(b, i, p, l)))
        if name == "Shift":
            n = call.uint_arg("n") or 1
            sub = self._plan_call(idx, call.children[0], shards, plan)
            plan.sig_parts.append(f"S{n}")
            plan.shift_bits += n  # widen the plan so bits can't fall off
            plan.ir_ok = False  # word-carry shifts have no mega opcode
            from pilosa_tpu.ops.bitset import shift_bits
            return lambda b, i, p, l: shift_bits(sub(b, i, p, l), n)
        if name in ("Intersect", "Union", "Difference", "Xor"):
            if not call.children:
                raise ExecutionError(f"{name}() requires row arguments")
            subs = [self._plan_call(idx, c, shards, plan)
                    for c in call.children]
            plan.sig_parts.append(f"{name[0]}{len(subs)}")
            ops = {"Intersect": jnp.bitwise_and, "Union": jnp.bitwise_or,
                   "Xor": jnp.bitwise_xor,
                   "Difference": lambda a, c: jnp.bitwise_and(
                       a, jnp.bitwise_not(c))}
            fold = {"Intersect": "and", "Union": "or", "Xor": "xor",
                    "Difference": "diff"}[name]
            plan.ir.append(("fold", fold, len(subs)))
            op = ops[name]
            return lambda b, i, p, l: functools.reduce(
                op, [s(b, i, p, l) for s in subs])
        if name == "Threshold":
            # Threshold(k=K, r1, ..., rN): columns set in at least K of
            # the N operand rows (the N-of-M / θ-threshold operator of
            # the bitmap-index literature). K=1 degenerates to Union,
            # K=N to Intersect; both reuse the fold node so they CSE
            # with real folds of the same operands.
            if not call.children:
                raise ExecutionError("Threshold() requires row arguments")
            k = call.args.get("k")
            # Strict integer: uint_arg would silently truncate k=1.5,
            # and an off-by-one threshold is a silent wrong answer.
            if isinstance(k, bool) or not isinstance(k, int) or k < 1:
                raise ExecutionError(
                    "Threshold() requires an integer argument k >= 1")
            n = len(call.children)
            subs = [self._plan_call(idx, c, shards, plan)
                    for c in call.children]
            plan.sig_parts.append(f"T{k}n{n}")
            if k > n:
                # More votes required than operands supplied: the
                # empty row. The operands were still planned (deps
                # capture and width resolution stay uniform), so the
                # lowering consumes them via the thresh node, which
                # maps k > n to a zeroed register.
                plan.ir.append(("thresh", k, n))
                return lambda b, i, p, l: jnp.zeros_like(
                    subs[0](b, i, p, l))
            if k == 1:
                plan.ir.append(("fold", "or", n))
                return lambda b, i, p, l: functools.reduce(
                    jnp.bitwise_or, [s(b, i, p, l) for s in subs])
            if k == n:
                plan.ir.append(("fold", "and", n))
                return lambda b, i, p, l: functools.reduce(
                    jnp.bitwise_and, [s(b, i, p, l) for s in subs])
            plan.ir.append(("thresh", k, n))

            def _thresh(b, i, p, l, _k=k, _subs=subs):
                # Thermometer accumulate: t[j] holds "at least j+1 of
                # the operands seen so far" — word-parallel, no
                # per-bit popcount (cf. bit-sliced N-of-M evaluation).
                vals = [s(b, i, p, l) for s in _subs]
                t = [jnp.zeros_like(vals[0]) for _ in range(_k)]
                for x in vals:
                    for j in range(_k - 1, 0, -1):
                        t[j] = jnp.bitwise_or(
                            t[j], jnp.bitwise_and(t[j - 1], x))
                    t[0] = jnp.bitwise_or(t[0], x)
                return t[_k - 1]
            return _thresh
        raise ExecutionError(f"{name} is not a row query")

    def _view_width(self, field: Field, view_name: str) -> int:
        """Bank word width without building the bank (matches what
        device_bank(trim=True) / _empty_bank will produce)."""
        from pilosa_tpu.core.fragment import CONTAINER_BITS
        view = field.view(view_name)
        if view is None:
            return CONTAINER_BITS // 32
        return view.trimmed_words()

    def _leaf_sparse(self, field: Field, view_name: str, key,
                     plan: _Plan) -> bool:
        """Hybrid-layout decision for one bank key: True when the
        view's leaves serve from its SparseBank. Snapshot of the
        view's layout mode — the plan's choice stays authoritative for
        this staging even if the background pass flips the mode
        mid-flight (both representations hold the same bits, so the
        only cost of racing is which correct program compiles)."""
        from pilosa_tpu.core import layout as layout_mod
        from pilosa_tpu.core.fragment import CONTAINER_BITS
        if not layout_mod.HYBRID_LAYOUT_ENABLED or self.mesh is not None:
            return False
        if key in plan.force_dense:
            return False
        view = field.view(view_name)
        if view is None or view.layout_mode != "sparse":
            return False
        return view.trimmed_words() * 32 <= CONTAINER_BITS

    def _plan_slot_leaf(self, field: Field, view_name: str, row_id: int,
                        shards, plan: _Plan):
        """A single-row leaf: bank[slot] with the slot traced, padded to
        the plan width (banks are width-trimmed per view). The slot value
        is a placeholder until _eval_tree builds the bank. Over a
        sparse-resident view (hybrid layout) the leaf instead stages an
        "xslot": the program scatter-expands the SparseBank row to the
        dense register on device (ops/megakernel.expand_positions) —
        bit-identical to the dense gather, under a distinct signature
        so the two layouts never share a compiled program or a cached
        result entry."""
        key = (field.name, view_name)
        pos = plan.bank(key)
        sparse = plan.bank_sparse.get(key)
        if sparse is None:
            sparse = self._leaf_sparse(field, view_name, key, plan)
            plan.bank_sparse[key] = sparse
        plan.widths.append(self._view_width(field, view_name))
        i = len(plan.idxs)
        plan.idxs.append(0)
        plan.slot_refs.append((i, key, row_id))
        plan.rows_for.setdefault(key, set()).add(row_id)
        if sparse:
            from pilosa_tpu.ops.megakernel import expand_positions
            plan.sig_parts.append(f"x{pos}")
            plan.ir.append(("xslot", pos, i))
            n_shards = len(shards)
            return lambda b, idxs, p, l: _align_words(
                expand_positions(b[pos][0], b[pos][1], idxs[i],
                                 n_shards, plan.sparse_widths[pos]),
                plan.width)
        plan.sig_parts.append(f"r{pos}")
        plan.ir.append(("slot", pos, i))
        return lambda b, idxs, p, l: _align_words(b[pos][idxs[i]],
                                                  plan.width)

    def _plan_row_leaf(self, idx: Index, call: Call, shards, plan: _Plan):
        import jax.numpy as jnp
        field_name, row_ref = self._row_call_field(call)
        field = idx.field(field_name)
        if field is None:
            raise ExecutionError(f"field not found: {field_name}")
        if isinstance(row_ref, Condition):
            return self._plan_bsi_leaf(field, row_ref, shards, plan)
        if field.options.type == FIELD_TYPE_INT:
            raise ExecutionError(
                f"int field {field_name} requires a comparison, not =")
        row_id = self._row_id(field, row_ref)
        frm, to = call.arg("from"), call.arg("to")
        if frm is not None or to is not None:
            if field.options.type != FIELD_TYPE_TIME:
                raise ExecutionError(f"from/to on non-time field {field_name}")
            start = timeq.parse_timestamp(frm) if frm else datetime.min
            end = timeq.parse_timestamp(to) if to else datetime.max
            views = [v for v in field.views_for_range(start, end)
                     if field.view(v) is not None]
            if not views:
                plan.ir.append(("zero",))
                return (lambda b, i, p, l:
                        jnp.zeros((len(shards), plan.width), jnp.uint32))
            if len(views) <= MAX_STATIC_RANGE_VIEWS:
                subs = [self._plan_slot_leaf(field, vn, row_id, shards, plan)
                        for vn in views]
                plan.sig_parts.append(f"U{len(subs)}")
                plan.ir.append(("fold", "or", len(subs)))
                return lambda b, i, p, l: functools.reduce(
                    jnp.bitwise_or, [s(b, i, p, l) for s in subs])
            # Literal: precompute the union eagerly, pass as one operand.
            # Subset banks of exactly one row per time view — a multi-year
            # hourly range must not materialize every row of every view.
            from pilosa_tpu.ops.bitset import union_many
            stacks = [self._get_bank_for(field, vn, shards,
                                         rows_needed={row_id})
                      for vn in views]
            wmax = max(bk.array.shape[-1] for bk in stacks)
            plan.widths.append(wmax)
            arr = union_many(jnp.stack(
                [_pad_words(bk.array[bk.slot(row_id)], wmax)
                 for bk in stacks]), axis=0)
            k = len(plan.literals)
            plan.literals.append(arr)
            plan.sig_parts.append(f"l{k}")
            plan.ir_ok = False  # literal content is not plan-buffer data
            return lambda b, i, p, l: l[k]
        return self._plan_slot_leaf(field, VIEW_STANDARD, row_id, shards,
                                    plan)

    def _plan_bsi_leaf(self, field: Field, cond: Condition, shards,
                       plan: _Plan):
        """BSI comparison leaf: planes gathered from the bsig view bank via
        traced indices; the predicate operand rides in params."""
        import jax.numpy as jnp
        bsig = field.bsi_groups.get(field.name)
        if bsig is None:
            raise ExecutionError(f"field {field.name} is not an int field")
        depth = bsig.bit_depth
        view_name = view_bsi_name(field.name)
        key = (field.name, view_name)
        pos = plan.bank(key)
        # BSI plane banks stay dense: each leaf gathers depth+1 rows,
        # which the hybrid layout's per-row expansion has no win on.
        plan.bank_sparse.setdefault(key, False)
        plan.widths.append(self._view_width(field, view_name))
        i0 = len(plan.idxs)
        rows_set = plan.rows_for.setdefault(key, set())
        for off, r in enumerate(range(depth + 1)):
            plan.idxs.append(0)
            plan.slot_refs.append((i0 + off, key, r))
            rows_set.add(r)

        def planes_of(b, idxs):
            return _align_words(b[pos][idxs[i0:i0 + depth + 1]],
                                plan.width)

        op = cond.op

        def zeros_leaf():
            plan.ir.append(("zero",))
            return (lambda b, i, p, l:
                    jnp.zeros((len(shards), plan.width), jnp.uint32))

        def push_value(base: int) -> int:
            """Base values ride as two u32 limbs in the traced params
            (depth can reach 63 planes; reference int fields span int64,
            field.go:1360)."""
            j = len(plan.params)
            plan.params.extend([base & 0xFFFFFFFF,
                                (base >> 32) & 0xFFFFFFFF])
            return j

        def limbs(p, j):
            return (p[j], p[j + 1])

        if op == BETWEEN:
            lo_hi = cond.int_slice()
            lo, ok_lo = bsig.base_value_clamped(lo_hi[0], ">=")
            hi, ok_hi = bsig.base_value_clamped(lo_hi[1], "<=")
            if not (ok_lo and ok_hi) or lo > hi:
                plan.sig_parts.append("z")
                return zeros_leaf()
            j = push_value(lo)
            k = push_value(hi)
            plan.sig_parts.append(f"c><{pos}d{depth}")
            plan.ir.append(("bsi", "between", pos, i0, depth, j, k, True))
            return lambda b, i, p, l: bsi.between(
                planes_of(b, i), limbs(p, j), limbs(p, k))
        value = int(cond.value)
        base, in_range = bsig.base_value_clamped(value, op)
        if op in (EQ, NEQ) and not in_range:
            if op == EQ:
                plan.sig_parts.append("z")
                return zeros_leaf()
            plan.sig_parts.append(f"cn{pos}d{depth}")
            plan.ir.append(("bsi", "notnull", pos, i0, depth, 0, 0, False))
            return lambda b, i, p, l: bsi.not_null(planes_of(b, i))
        if op in (LT, LTE, GT, GTE) and not in_range:
            plan.sig_parts.append("z")
            return zeros_leaf()
        if op in (LT, LTE):
            allow_eq = (op == LTE) or (value > bsig.max)
        elif op in (GT, GTE):
            allow_eq = (op == GTE) or (value < bsig.min)
        else:
            allow_eq = False
        j = push_value(base)
        kernels = {
            EQ: lambda pl, v: bsi.eq(pl, v),
            NEQ: lambda pl, v: bsi.neq(pl, v),
            LT: lambda pl, v: bsi.lt(pl, v, allow_eq=allow_eq),
            LTE: lambda pl, v: bsi.lt(pl, v, allow_eq=True),
            GT: lambda pl, v: bsi.gt(pl, v, allow_eq=allow_eq),
            GTE: lambda pl, v: bsi.gt(pl, v, allow_eq=True),
        }
        kern = kernels[op]
        # The megakernel lowering (ops/megakernel.py lower_bsi) expands
        # these into the exact AND/OR/ANDNOT scan executor/bsi.py runs,
        # branch decisions taken on the HOST param values the unfused
        # path feeds the traced jnp.where selects.
        ir_kind = {EQ: "eq", NEQ: "neq", LT: "lt", LTE: "lt",
                   GT: "gt", GTE: "gt"}[op]
        ir_allow = allow_eq if op in (LT, GT) else True
        if op in (EQ, NEQ):
            ir_allow = False
        plan.ir.append(("bsi", ir_kind, pos, i0, depth, j, 0, ir_allow))
        plan.sig_parts.append(f"c{op}{int(allow_eq)}{pos}d{depth}")
        return lambda b, i, p, l: kern(planes_of(b, i), limbs(p, j))

    # ----------------------------------------------------------- bank fetch

    # Per-bank HBM cap: a view whose FULL bank would exceed this is served
    # by a cached row-subset bank holding only the rows the query needs
    # (VERDICT r1 missing #4: unbounded device_bank on the general path).
    BANK_MAX_BYTES = int(os.environ.get("PILOSA_TPU_BANK_BYTES", 2 << 30))

    def _get_bank(self, idx: Index, key: Tuple[str, str], shards,
                  rows_needed=None):
        field = idx.field(key[0])
        return self._get_bank_for(field, key[1], shards,
                                  rows_needed=rows_needed)

    def _get_bank_for(self, field: Field, view_name: str, shards,
                      rows_needed=None):
        view = field.view(view_name)
        if view is None:
            # Reads must not create views; absent view = all-zero rows.
            return self._empty_bank(len(shards))
        shards = tuple(shards)
        if rows_needed is not None:
            from pilosa_tpu.core.view import bank_capacity
            width = view.trimmed_words()
            # Upper bound on the full bank's row count (sum over shards,
            # no union needed): if even the bound fits the budget, the
            # exact full bank certainly does.
            bound = sum(len(f.row_ids())
                        for s in shards
                        for f in [view.fragment(s)] if f is not None)
            full_bytes = bank_capacity(bound) * len(shards) * width * 4
            if full_bytes > self.BANK_MAX_BYTES and len(rows_needed) < bound:
                return view.device_bank(shards, rows=sorted(rows_needed),
                                        mesh=self.mesh, trim=True,
                                        cache_rows=True)
        return view.device_bank(shards, mesh=self.mesh, trim=True)

    # Placeholder zero banks are keyed by shard count, which GROWS
    # with the index: without a bound, every resize strands the old
    # count's bank (and its ledger row) in HBM forever. A handful of
    # live entries is plenty — queries only ever need the current
    # shard counts.
    BANK_CACHE_MAX = 8

    def _empty_bank(self, n_shards: int):
        import jax.numpy as jnp
        from pilosa_tpu.core.view import ViewBank
        mesh_key = self.mesh.cache_key() if self.mesh else None
        key = f"emptybank:{n_shards}:{mesh_key}"
        # Pop-and-reinsert on hit: dict insertion order doubles as LRU
        # order (the _jit_cache idiom). The build runs OUTSIDE the lock
        # (a device put can block on the transfer); two threads racing
        # the same new key both build, first-insert wins and the loser
        # adopts it. Ledger updates happen under the cache lock (the
        # ledger lock is a leaf) so an evict/rebuild interleave cannot
        # unregister another thread's freshly registered entry.
        with self._bank_cache_lock:
            bank = self._bank_cache.pop(key, None)
            if bank is not None:
                self._bank_cache[key] = bank
                return bank
        from pilosa_tpu.core.fragment import CONTAINER_BITS
        host = np.zeros((1, n_shards, CONTAINER_BITS // 32), np.uint32)
        arr = self.mesh.put_bank(host) if self.mesh \
            else jnp.asarray(host)
        built = ViewBank(arr, {}, 0, {})
        with self._bank_cache_lock:
            bank = self._bank_cache.pop(key, None)
            if bank is None:
                bank = built
                while len(self._bank_cache) >= max(1, self.BANK_CACHE_MAX):
                    old = next(iter(self._bank_cache))
                    self._bank_cache.pop(old)
                    LEDGER.unregister("bank", old, owner=self)
                LEDGER.register("bank", key, host.nbytes, owner=self,
                                view="(placeholder)", nShards=n_shards,
                                rows=0)
            self._bank_cache[key] = bank
        return bank

    def _row_call_field(self, call: Call) -> Tuple[str, Any]:
        """Extract (field, row-or-condition) from a Row()/Range() call."""
        for k, v in call.args.items():
            if k in ("from", "to", "_field") or k.startswith("_"):
                continue
            return k, v
        raise ExecutionError(f"{call.name}() requires a field argument")

    def _row_id(self, field: Field, row_ref) -> int:
        if isinstance(row_ref, bool):
            return 1 if row_ref else 0
        if isinstance(row_ref, int):
            return row_ref
        if isinstance(row_ref, str):
            raise ExecutionError(
                f"field {field.name}: row keys require keys=True "
                "(translation handled at the API layer)")
        raise ExecutionError(f"invalid row reference {row_ref!r}")

    # ----------------------------------------------------------------- TopN

    def _counts_fn(self, with_filter: bool, shape) -> Callable:
        """jit: bank chunk [R, S, W] (∧ filter [S, W]) -> counts [R] and raw
        per-row popcounts [R] (for tanimoto)."""
        import jax
        import jax.numpy as jnp
        from pilosa_tpu.ops import pallas_kernels
        from pilosa_tpu.ops.bitset import popcount
        use_pallas = pallas_kernels.enabled() and self.mesh is None
        key = f"topn:{with_filter}:{shape}:{use_pallas}"
        fn = self._jit_get(key)
        if fn is None:
            self._note_jit_compile()
            if with_filter:
                if use_pallas:
                    def run(chunk, filt):
                        return pallas_kernels.bank_row_counts_masked(
                            chunk, filt)
                else:
                    def run(chunk, filt):
                        inter = jnp.bitwise_and(chunk, filt)
                        return (popcount(inter, axis=(-2, -1)),
                                popcount(chunk, axis=(-2, -1)))
            else:
                # Single output: the caller reuses it for both intersection
                # and raw counts (one host fetch instead of two).
                if use_pallas:
                    def run(chunk, filt):
                        return pallas_kernels.bank_row_counts(chunk)
                else:
                    def run(chunk, filt):
                        c = popcount(chunk, axis=(-2, -1))
                        return c
            fn = jax.jit(run)
            self._jit_put(key, fn)
        return fn

    def _dispatch_counts(self, bank_array, filter_words):
        """Queue the counts kernel; returns unfetched device output.
        Width-trimmed banks intersect against the same prefix of the
        filter: slicing a wider filter is safe (bank rows have no bits
        past their width), and padding a narrower one is safe (zeros
        cannot intersect)."""
        filter_words = _align_words(filter_words, bank_array.shape[-1])
        fn = self._counts_fn(filter_words is not None, bank_array.shape)
        # Through the _call_program funnel: TopN sweeps are device
        # dispatches too, and the timeline's dispatch-gap analyzer
        # must see them or idle ratios under TopN traffic would read
        # as pure idle.
        return self._call_program(fn, bank_array, filter_words)

    def _fetch_counts(self, out, filter_words):
        """Block on a _dispatch_counts output: (counts_np, raw_np)."""
        if filter_words is not None:
            return np.asarray(out[0]), np.asarray(out[1])
        c = np.asarray(out)
        return c, c

    def _popcount_row(self, words):
        """Dispatch a total popcount over row words [S, W] (device)."""
        import jax
        from pilosa_tpu.ops.bitset import popcount
        fn = self._jit_get("popcount_row")
        if fn is None:
            self._note_jit_compile()
            fn = jax.jit(lambda w: popcount(w, axis=(-2, -1)))
            self._jit_put("popcount_row", fn)
        return self._call_program(fn, words)

    def _execute_topn(self, idx: Index, call: Call, shards) -> PairsResult:
        """Exact TopN (reference executeTopN 2-phase approximation,
        executor.go:694-733, fragment.top :1067). On TPU exact per-row
        counts are one batched popcount over the view bank, so no candidate
        phase or ranked-cache dependency is needed — strictly stronger than
        the reference's cache-approximate result. Row sets larger than
        TOPN_CHUNK_ROWS stream through the device in chunks."""
        field_name = call.arg("_field")
        field = idx.field(field_name)
        if field is None:
            raise ExecutionError(f"field not found: {field_name}")
        n = call.uint_arg("n") or 0
        shards = self._shards(idx, shards)
        view = field.view(VIEW_STANDARD)
        if view is None:
            return PairsResult([])
        # Sweep only shards this field's view covers (absent fragments
        # contribute zero to every row count, filtered or not) — a
        # narrow field on a wide index must not upload empty bank
        # columns. Restriction happens BEFORE the filter tree runs so
        # filter words stay shard-aligned with the bank.
        covered = [s for s in shards if view.fragment(s) is not None]
        if not covered:
            return PairsResult([])
        if len(covered) < len(shards):
            shards = self._shards(idx, covered)

        filter_words = None
        if call.children:
            filter_words = self._eval_tree(idx, call.children[0], shards,
                                           mode="row")
        attr_name = call.arg("attrName")
        allowed_rows = None
        if attr_name is not None:
            allowed_rows = set(field.row_attr_store.ids_matching(
                attr_name, call.arg("attrValues", [])))
        tanimoto = call.uint_arg("tanimotoThreshold") or 0
        # Candidate restriction + absolute count floor (reference
        # topOptions.RowIDs / MinThreshold, fragment.go:1248,
        # executor.go:698).
        ids_arg = call.arg("ids")
        min_threshold = call.uint_arg("threshold") or 0

        # Merged row list is cached on the view per shard set, keyed on
        # fragment versions — repeat queries alias the same tuple (the
        # per-query union/sort cost ~10 s of the warm 32M-molecule
        # tanimoto p50, benches/pbank_diag2.py; the multi-shard case
        # re-paid it every query until r5). Never mutated downstream —
        # every refinement rebinds.
        view_rows = view.merged_row_ids(shards)
        all_rows = view_rows
        if allowed_rows is not None:
            all_rows = [r for r in all_rows if r in allowed_rows]
        if ids_arg:
            wanted = {int(i) for i in ids_arg}
            all_rows = [r for r in all_rows if r in wanted]
        if not all_rows:
            return PairsResult([])
        if WORKLOAD.enabled:
            # Heatmap the sweep BEFORE the warm-cache shortcut: a
            # cache-served TopN is still workload (host dict work only).
            # Small candidate sets (ids=... leaderboard refreshes)
            # record row identities; full-view sweeps record the
            # aggregate scan size.
            WORKLOAD.record_read(idx.name, field_name, VIEW_STANDARD,
                                 shards, rows=all_rows)
            prof = self._profile()
            if prof is not None:
                prof.touch_fragments(idx.name, field_name,
                                     VIEW_STANDARD, shards)

        # Warm-cache shortcut (reference fragment.top over rankCache,
        # fragment.go:1067, cache.go:136): when every fragment's cache
        # still holds EVERY present row (cardinality within the cache
        # bound, so nothing was ever evicted), the cached per-row counts
        # are exact — every write path refreshes them — and TopN needs no
        # device work at all. Filters and tanimoto need real bitmaps, so
        # they always take the sweep.
        selfcheck_pairs = None  # warm answer being verified this query
        if filter_words is None and not tanimoto:
            cached = self._topn_cached_counts(view, shards)
            if cached is not None:
                self.topn_cache_hits += 1
                rows_arr = np.asarray(all_rows, dtype=np.uint64)
                counts_arr = np.fromiter(
                    (cached.get(r, 0) for r in all_rows),
                    dtype=np.int64, count=len(all_rows))
                keep = counts_arr > max(0, min_threshold - 1)
                rows_arr, counts_arr = rows_arr[keep], counts_arr[keep]
                rows_arr, counts_arr = _topn_candidates(rows_arr,
                                                        counts_arr, n)
                order = np.lexsort((rows_arr, -counts_arr))
                if n:
                    order = order[:n]
                warm = [(int(rows_arr[o]), int(counts_arr[o]))
                        for o in order]
                # == 1 % EVERY, not == 1: at EVERY=1 (check every hit)
                # the residue is 0 and a literal ==1 would never match.
                if not (TOPN_SELFCHECK_EVERY and self.topn_cache_hits
                        % TOPN_SELFCHECK_EVERY == 1 % TOPN_SELFCHECK_EVERY):
                    return PairsResult(warm)
                # Sampled self-check: fall through to the exact sweep
                # and compare in finalize (both orderings are the same
                # deterministic (-count, row) lexsort, so list equality
                # is the correct test).
                self.topn_selfchecks += 1
                selfcheck_pairs = warm

        # Device rank cache (ROADMAP item 3b; core/cache.RANK_CACHE):
        # filterless TopN over a warm bank answers from a cached [R]
        # per-row count vector in HBM — a device top-k (or one tiny
        # host fetch for restricted candidate sets) instead of the
        # [R, S, W] popcount sweep below. Version-validated against
        # the bank's fragment generations: unchanged reuses, small
        # churn patches only the written rows, anything else rebuilds
        # with the sweep this path would have paid anyway. The sampled
        # self-check deliberately bypasses it — its exact leg must
        # exercise the real sweep.
        if filter_words is None and not tanimoto and self.mesh is None \
                and selfcheck_pairs is None:
            from pilosa_tpu.core.cache import RANK_CACHE
            if RANK_CACHE.enabled:
                res = self._topn_rank_cached(view, shards, view_rows,
                                             all_rows, n, min_threshold)
                if res is not None:
                    return res

        # Dispatch phase: queue every device program (counts sweeps, and
        # the tanimoto denominator popcount); nothing is fetched yet.
        # The HBM bound must consider the *bank* size (all view rows), not
        # the attr-filtered subset — the full-bank path materializes every
        # view row.
        dispatched = []  # (rows, bank, device_out)
        chunked: List[List[int]] = []
        # Banks are width-trimmed for the sweep: only whole-row popcounts
        # are computed, and the dropped word tail is all-zero.
        from pilosa_tpu.core.view import bank_capacity
        width = view.trimmed_words()
        bank_bytes = bank_capacity(len(view_rows)) * len(shards) * width * 4
        if bank_bytes <= TOPN_MAX_BANK_BYTES:
            # Hot path: one fused popcount sweep over the whole cached bank
            # (no gather); rows map to slots host-side, unused slots are
            # zero rows and drop out naturally.
            bank = view.device_bank(tuple(shards), mesh=self.mesh,
                                    trim=True)
            dispatched.append(
                (all_rows, bank, self._dispatch_counts(bank.array,
                                                       filter_words)))
        else:
            if PBANK_ENABLED and self.mesh is None and len(shards) == 1 \
                    and allowed_rows is None and not ids_arg and n \
                    and selfcheck_pairs is None:
                # Positions-resident fast path: the whole view's sorted
                # positions live on device; no streaming, no expansion.
                pb = view.positions_bank(shards[0], width)
                if pb is not None:
                    src_pb = None
                    if tanimoto and filter_words is not None:
                        src_pb = self._popcount_row(filter_words)
                    # tanimoto applies only WITH a filter (the dense
                    # finalize's `if tanimoto and filter_words` rule) —
                    # passing it filterless would zero every denominator
                    # and empty the result.
                    # Slice the filter row to the BANK's width: a plan
                    # can be wider than the bank (Not() rides the
                    # existence view, Shift(), a wider sibling field),
                    # and a set bit at word 2047 would otherwise match
                    # the fixed layout's 0xFFFF row pads — the gather's
                    # OOB-fill and the compare's qtop extraction both
                    # become pad-safe once fw stops at the bank width
                    # (real positions are < width*32 <= 65503, so no
                    # real count changes; the tanimoto denominator
                    # src_pb deliberately keeps the FULL row's popcount,
                    # matching the dense path's semantics).
                    fw_b = None
                    if filter_words is not None:
                        fw_b = [filter_words[0][:width]]
                    return self._topn_positions(
                        pb, fw_b, n,
                        tanimoto if filter_words is not None else 0,
                        min_threshold, src_pb)
            # Huge row sets stream through transient chunk banks to bound
            # HBM (the 50k-row ranked-cache shape). Chunks are uploaded
            # lazily in finalize with one-chunk lookahead — dispatching
            # them all here would materialize every chunk bank in HBM at
            # once, the exact blow-up chunking exists to avoid.
            chunked = [all_rows[c0:c0 + TOPN_CHUNK_ROWS]
                       for c0 in range(0, len(all_rows), TOPN_CHUNK_ROWS)]
        src_dev = None
        if tanimoto and filter_words is not None:
            src_dev = self._popcount_row(filter_words)

        # Chunk banks are admitted to the BANK_BUDGET HBM LRU only when
        # the WHOLE stream fits in half the budget: a repeat query over
        # an unchanged fragment then skips every chunk re-upload (on a
        # tunneled chip the upload dominates the sweep). An over-budget
        # stream would be a sequential scan over an LRU — ~0% repeat
        # hits while evicting every other view's banks — so it stays
        # transient. Row churn shifts chunk boundaries and orphans old
        # keys; orphans are bounded by (and aged out of) the budget.
        from pilosa_tpu.core.view import BANK_BUDGET
        cache_chunks = bank_bytes <= BANK_BUDGET.budget // 2

        def dispatch_chunk(rows):
            bank = view.device_bank(tuple(shards), rows=rows,
                                    mesh=self.mesh, trim=True,
                                    cache_rows=cache_chunks)
            return (rows, bank,
                    self._dispatch_counts(bank.array, filter_words))

        def finalize() -> PairsResult:
            parts = []  # (rows_arr, counts_arr, raws_arr)
            pending = list(dispatched)
            if chunked:
                pending.append(dispatch_chunk(chunked[0]))
            i = 0
            while pending:
                rows, bank, out = pending.pop(0)
                # One-chunk lookahead: overlap the next upload+sweep with
                # this fetch while keeping at most two chunk banks live.
                i += 1
                if i < len(chunked):
                    pending.append(dispatch_chunk(chunked[i]))
                counts, raw = self._fetch_counts(out, filter_words)
                # map(dict.get, ...) keeps the 65k-row probe loop in C.
                slot_idx = np.fromiter(
                    map(bank.slots.get, rows,
                        itertools.repeat(bank.zero_slot)),
                    dtype=np.int64, count=len(rows))
                parts.append((np.asarray(rows, dtype=np.uint64),
                              counts[slot_idx].astype(np.int64),
                              raw[slot_idx].astype(np.int64)))
            rows_arr = np.concatenate([p[0] for p in parts])
            counts_arr = np.concatenate([p[1] for p in parts])
            raws_arr = np.concatenate([p[2] for p in parts])
            if tanimoto and filter_words is not None:
                src_total = int(np.asarray(src_dev))
                denom = raws_arr + src_total - counts_arr
                keep = (denom > 0) & (
                    (counts_arr * 100) // np.maximum(denom, 1) >= tanimoto)
                rows_arr, counts_arr = rows_arr[keep], counts_arr[keep]
            keep = counts_arr > max(0, min_threshold - 1)
            rows_arr, counts_arr = rows_arr[keep], counts_arr[keep]
            rows_arr, counts_arr = _topn_candidates(rows_arr, counts_arr,
                                                    n)
            # Sort by (-count, row) — vectorized; Python-loop-free even
            # for 10^5-row fingerprint sweeps.
            order = np.lexsort((rows_arr, -counts_arr))
            if n:
                order = order[:n]
            pairs = [(int(rows_arr[o]), int(counts_arr[o])) for o in order]
            if selfcheck_pairs is not None and selfcheck_pairs != pairs:
                self.topn_selfcheck_mismatches += 1
                _LOG.error(
                    "TopN warm-cache self-check MISMATCH on %s/%s: "
                    "cached %r != exact %r; repairing ranked caches "
                    "from storage", idx.name, field_name,
                    selfcheck_pairs[:5], pairs[:5])
                self._repair_topn_caches(view, shards)
            return PairsResult(pairs)

        if chunked and getattr(self._tls, "later_writes", False):
            # A later call in this query writes fragments. Chunk banks
            # upload lazily inside finalize — which would run AFTER those
            # writes and read post-write state, breaking sequential
            # semantics (reference executes calls in order,
            # executor.go:245). Materialize now, before any write runs;
            # the full-bank path needs no such care because its device
            # arrays snapshot at dispatch.
            return finalize()
        return _Pending(
            finalize,
            arrays=tuple(x for _, _, out in dispatched
                         for x in (out if isinstance(out, tuple) else (out,))
                         ) + ((src_dev,) if src_dev is not None else ()))

    _PBANK_KERNELS: Dict[tuple, Callable] = {}

    @classmethod
    def _pbank_kernel(cls, k: int, has_filter: bool,
                      fixed: bool = False):
        """Jitted per-segment TopN over a PositionsBank: |row ∧ filter|
        = Σ_{p ∈ row} filter_bit[p]. Two layouts (view.py flush):

        - flat (pos [P], starts [R+1]): membership bits + a cumsum
          differenced at row starts (u32 wrap subtraction is exact —
          per-row counts fit u16);
        - fixed (pos [R, L], lens [R]): membership summed with one
          axis-1 reduce — no O(P) cumsum, no starts gathers. The
          0xFFFF row pad matches nothing (compare) / gathers fill-0.

        No dense expansion, no streaming: one pass over the resident
        positions. Unfiltered TopN skips even that — counts are the
        start diffs / lens. Tanimoto/threshold ride as traced params;
        lax.top_k breaks ties by lower index, which IS the (-count,
        row) order because rows are stored ascending."""
        import jax
        import jax.numpy as jnp

        membership = PBANK_MEMBERSHIP
        if membership == "auto":
            membership = ("search"
                          if jax.devices()[0].platform == "cpu"
                          else "compare")
        key = (k, has_filter, fixed, membership)
        fn = cls._PBANK_KERNELS.get(key)
        if fn is not None:
            return fn

        def bits_gather(fw, pos):
            # Pad sentinel 0xFFFF gathers out of range -> fill 0. Casts
            # stay inline (no materialized i32 copy of the whole bank).
            return (jnp.take(fw, (pos >> 5).astype(jnp.int32),
                             mode="fill", fill_value=0)
                    >> (pos & 31).astype(jnp.uint32)) & jnp.uint32(1)

        def bits_compare(fw, pos):
            # Sparse-filter membership WITHOUT the positions gather: a
            # tanimoto query's filter is one fingerprint (~48 set bits),
            # and an element-wise [P] x [QCAP] compare-reduce against
            # its extracted set positions is VPU-shaped where the
            # P-sized dynamic gather is not — measured 3.9x faster at
            # 384M positions on a v5e (benches/pbank_diag3.py; the
            # two-stage top-k variant measured no gain, so top_k stays
            # flat). Extraction: enumerate the filter's 32*W bit
            # positions, keep set ones, take the QCAP smallest (pad
            # 2^30 sorts last; a real position is < 2^16).
            w = jnp.arange(fw.shape[0], dtype=jnp.int32)
            allpos = w[:, None] * 32 + jnp.arange(32, dtype=jnp.int32)
            setmask = ((fw[:, None] >> jnp.arange(32, dtype=jnp.uint32))
                       & jnp.uint32(1)).astype(bool)
            qpos = jnp.where(setmask, allpos, 1 << 30).reshape(-1)
            # Clamp to the filter's bit width: top_k(k > size) raises at
            # TRACE time and lax.cond traces both branches, so a narrow
            # filter row would crash every filtered query. The clamp is
            # exact: popcount(fw) <= 32*W == the clamped k, so the gate
            # below still guarantees every set position is captured.
            qk = min(PBANK_SPARSE_FILTER_BITS, int(qpos.shape[0]))
            qtop = -jax.lax.top_k(-qpos, qk)[0]
            if membership == "search":
                # qtop is sorted ascending: binary-search each position
                # in log2(qk) compare-select rounds instead of a qk-wide
                # compare fan-out (the r4-measured ~1 ns/position floor
                # is this fan-out; VERDICT r5 #2). Positions are < 2^16
                # and the 2^30 pad sorts last, so equality at the found
                # slot is exact membership.
                idx = jnp.clip(jnp.searchsorted(qtop,
                                                pos.astype(jnp.int32)),
                               0, qk - 1)
                return jnp.take(qtop, idx) == pos.astype(jnp.int32)
            # pos is [P] (flat layout) or [R, L] (fixed layout); the
            # trailing broadcast axis makes membership layout-agnostic.
            return (pos[..., None].astype(jnp.int32) == qtop).any(-1)

        # graftlint: disable=GL006 — class-level kernel cache (benches
        # monkeypatch _pbank_kernel as a classmethod, so no instance is
        # available to note compiles on); keys are (k, filter, layout,
        # membership) — a bounded, shape-stable set per deployment.
        @jax.jit
        def kernel(fw, pos, aux, params):
            # aux: starts [R+1] (flat) | lens [R] (fixed)
            raw = aux if fixed else aux[1:] - aux[:-1]
            if has_filter:
                def c_from(bits):
                    # Reduce to per-row counts INSIDE the cond branch:
                    # the branch output is then [R] i32 instead of a
                    # bank-sized bits array — at 100M rows the cond's
                    # branch buffers next to the resident bank were the
                    # difference between fitting HBM and
                    # RESOURCE_EXHAUSTED.
                    if fixed:
                        return bits.sum(axis=1, dtype=jnp.int32)
                    s = jnp.concatenate(
                        [jnp.zeros(1, jnp.uint32),
                         jnp.cumsum(bits, dtype=jnp.uint32)])
                    return (s[aux[1:]] - s[aux[:-1]]).astype(jnp.int32)

                # Exactness gate ON DEVICE (no extra host round trip):
                # the compare form only sees the QCAP smallest filter
                # positions, so any denser filter falls back to the
                # gather form inside the same compiled program.
                fwpop = jnp.sum(
                    jax.lax.population_count(fw)).astype(jnp.int32)
                c = jax.lax.cond(
                    fwpop <= PBANK_SPARSE_FILTER_BITS,
                    lambda: c_from(bits_compare(fw, pos)),
                    lambda: c_from(bits_gather(fw, pos)))
            else:
                c = raw
            thresh, tani, src = (params[0].astype(jnp.int32),
                                 params[1].astype(jnp.int32),
                                 params[2].astype(jnp.int32))
            keep = c >= jnp.maximum(1, thresh)
            denom = raw + src - c
            keep &= jnp.where(tani > 0,
                              (denom > 0) & (c * 100 >= tani * denom),
                              True)
            score = jnp.where(keep, c, -1)
            return jax.lax.top_k(score, k)

        cls._PBANK_KERNELS[key] = kernel
        return kernel

    def _topn_positions(self, pb, filter_words, n: int, tanimoto: int,
                        min_threshold: int, src_dev) -> "_Pending":
        """TopN over a device-resident PositionsBank (see
        view.PositionsBank): per segment one kernel dispatch, host
        merge of k-candidates across segments."""
        import jax.numpy as jnp

        import jax

        fw = None
        if filter_words is not None:
            fw = filter_words[0]  # [W] u32, single shard
        # Params are identical for every segment — build/upload ONCE.
        # (Per-segment rebuilds were one host->device put per segment
        # per query; on a tunneled chip each put costs an RTT.)
        params = jnp.asarray(
            np.asarray([min_threshold, tanimoto, 0], np.uint32))
        if tanimoto and src_dev is not None:
            params = params.at[2].set(
                jnp.asarray(src_dev).astype(jnp.uint32))
        fw_arg = fw if fw is not None else jnp.zeros((1,), jnp.uint32)
        outs = []
        wave = []
        for row_lo, n_rows, pos, aux, _p in pb.segments:
            k = min(n, n_rows)
            if k == 0:
                continue
            kern = self._pbank_kernel(k, fw is not None,
                                      fixed=pos.ndim == 2)
            out = kern(fw_arg, pos, aux, params)
            outs.append((row_lo, out))
            # Bound enqueued-program concurrency: each segment program
            # needs GBs of workspace next to the resident bank, and
            # letting all segments queue at once OOMed the chip at 100M
            # rows (9 x ~4 GB transients + the 9.6 GB bank). A wave
            # sync caps coexisting workspaces; outputs are k-sized so
            # keeping them all is free.
            wave.append(out)
            if len(wave) >= PBANK_INFLIGHT_SEGMENTS:
                # graftlint: disable=GL003 — deliberate wave sync: caps
                # coexisting segment workspaces in HBM (see comment
                # above); removing it re-introduces the 100M-row OOM.
                jax.block_until_ready(wave)
                wave = []

        def finalize() -> PairsResult:
            # ONE batched transfer for all segments' k-candidates
            # (sequential per-segment np.asarray fetches each paid a
            # blocking RTT; the results are ~k ints per segment).
            got = jax.device_get([(v, i) for _, (v, i) in outs])
            pairs = []
            for (row_lo, _), (v, ix) in zip(outs, got):
                for val, i in zip(v.tolist(), ix.tolist()):
                    if val > 0:
                        pairs.append((int(pb.row_ids[row_lo + i]),
                                      int(val)))
            pairs.sort(key=lambda rc: (-rc[1], rc[0]))
            return PairsResult(pairs[:n])

        return _Pending(finalize,
                        arrays=tuple(x for _, vi in outs for x in vi))

    # Row-churn bound for incremental rank-vector patches: more changed
    # rows than this and the full sweep rebuild is cheaper than the
    # gather+scatter (and compiles fewer patch-kernel shapes).
    RANK_PATCH_MAX = int(os.environ.get("PILOSA_TPU_RANK_PATCH_MAX",
                                        4096))

    def _note_rank(self, kind: str) -> None:
        names = {"hit": "hits", "patch": "patches",
                 "rebuild": "rebuilds"}
        with self._jit_stats_lock:
            if kind == "hit":
                self.rank_cache_hits += 1
            elif kind == "patch":
                self.rank_cache_patches += 1
            else:
                self.rank_cache_rebuilds += 1
        if self.stats is not None:
            self.stats.count(f"rank_cache.{names[kind]}", 1)

    def _rank_counts(self, view, bank, shards):
        """Get-or-refresh the device-resident per-row count vector for
        `bank` (RankEntry in core/cache.py): [Rcap] counts aligned
        with the bank's slot layout, validated against its fragment
        versions. Returns the device array (dispatch queued; nothing
        fetched)."""
        import jax
        import jax.numpy as jnp
        from pilosa_tpu.core.cache import RANK_CACHE, RankEntry
        from pilosa_tpu.ops.bitset import popcount

        key = (tuple(int(s) for s in shards),
               int(bank.array.shape[-1]))
        # SLOT-ordered row tuple (dict insertion order == slot order:
        # fresh builds enumerate the sorted row set, _patch_bank
        # appends at len(slots)). Equality must prove SLOT alignment,
        # not just row-set equality — an append-grown layout and a
        # freshly sorted rebuild hold the same rows in different slots,
        # and patching one with indices from the other would scatter
        # counts into the wrong rows.
        bank_rows = tuple(bank.slots)
        entry = RANK_CACHE.get(view, key)
        if entry is not None and entry.versions == bank.versions \
                and entry.row_ids == bank_rows \
                and int(entry.counts.shape[0]) == int(bank.array.shape[0]):
            self._note_rank("hit")
            return entry.counts
        counts = None
        if entry is not None and entry.row_ids == bank_rows \
                and int(entry.counts.shape[0]) == int(bank.array.shape[0]):
            # Same row set, moved versions: patch only the rows the
            # writes touched (Fragment._row_versions names them).
            changed: set = set()
            ok = True
            for s, newv in bank.versions.items():
                old = entry.versions.get(s)
                if old == newv:
                    continue
                frag = view.fragment(s)
                if frag is None or old is None or old < 0 \
                        or (old >> 48) != (newv >> 48):
                    # Epoch mismatch: the fragment was recreated since
                    # the entry was built (pop + reload across a
                    # resize). Its _row_versions died with the old
                    # incarnation, so rows_changed_since(old) cannot
                    # name writes made before the recreation — the
                    # patch set is unprovable. Rebuild.
                    ok = False
                    break
                ch = frag.rows_changed_since(old)
                if not ch:
                    # Version moved without row attribution: cannot
                    # prove the patch set — rebuild.
                    ok = False
                    break
                changed.update(int(r) for r in ch)
            if ok and changed and len(changed) <= self.RANK_PATCH_MAX \
                    and all(r in bank.slots for r in changed):
                sel = sorted(bank.slots[r] for r in changed)
                # Pow2-pad repeating the first slot (idempotent: the
                # duplicate scatter writes the same recount) so patch
                # kernels compile O(log churn) shapes, the fused-batch
                # padding idiom.
                pad = 1 << (len(sel) - 1).bit_length()
                sel = sel + [sel[0]] * (pad - len(sel))
                sel_dev = jnp.asarray(np.asarray(sel, np.int32))
                pkey = f"rankpatch:{bank.array.shape}:{pad}"
                fn = self._jit_get(pkey)
                if fn is None:
                    self._note_jit_compile()

                    def patch(c, bank_arr, sel_ix):
                        new = popcount(bank_arr[sel_ix], axis=(-2, -1))
                        return c.at[sel_ix].set(
                            new.astype(c.dtype))
                    fn = jax.jit(patch)
                    self._jit_put(pkey, fn)
                counts = self._call_program(fn, entry.counts,
                                            bank.array, sel_dev)
                self._note_rank("patch")
        if counts is None:
            counts = self._dispatch_counts(bank.array, None)
            self._note_rank("rebuild")
        RANK_CACHE.put(view, key,
                       RankEntry(dict(bank.versions), bank_rows, counts,
                                 # graftlint: disable=GL003 — .nbytes
                                 # is shape metadata (rows * 4), not a
                                 # transfer; no device sync happens.
                                 int(getattr(counts, "nbytes", 0) or 0)))
        return counts

    def _topn_rank_cached(self, view, shards, view_rows, all_rows,
                          n: int, min_threshold: int):
        """Filterless TopN over the device rank cache, or None when
        the bank is over budget (the pbank/chunked paths own that
        regime). Unrestricted leaderboards run a device top-k over the
        cached counts; candidate-restricted or n=0 calls fetch the [R]
        vector (4 B/row — negligible next to the sweep it replaces)
        and reuse the host merge."""
        import jax
        import jax.numpy as jnp
        from pilosa_tpu.core.view import bank_capacity

        width = view.trimmed_words()
        bank_bytes = bank_capacity(len(view_rows)) * len(shards) \
            * width * 4
        if bank_bytes > TOPN_MAX_BANK_BYTES:
            return None
        bank = view.device_bank(tuple(shards), mesh=self.mesh,
                                trim=True)
        counts = self._rank_counts(view, bank, shards)
        restricted = all_rows is not view_rows
        # Slot-ordered rows (insertion order == slot order). The device
        # top-k leg requires slots to ASCEND with row id: lax.top_k
        # breaks count ties by lower index, which is (-count, row)
        # order — the uncached path's lexsort — only then. An
        # append-grown bank (_patch_bank places new rows at the END)
        # violates it, so that layout takes the host-merge leg below,
        # which maps slots explicitly and is exact for any layout.
        slot_rows = np.fromiter(bank.slots, np.uint64, len(bank.slots))
        ascending = slot_rows.size < 2 \
            or bool(np.all(slot_rows[1:] > slot_rows[:-1]))
        if n and not restricted and ascending:
            k = min(n, len(bank.slots))
            if k == 0:
                return PairsResult([])
            tkey = f"ranktopk:{counts.shape}:{k}"
            fn = self._jit_get(tkey)
            if fn is None:
                self._note_jit_compile()

                def topk(c, params):
                    thr = params[0].astype(jnp.int32)
                    ci = c.astype(jnp.int32)
                    # Zero slots (and sub-threshold rows) score -1 and
                    # are dropped in finalize.
                    score = jnp.where(ci >= jnp.maximum(1, thr),
                                      ci, -1)
                    return jax.lax.top_k(score, k)
                fn = jax.jit(topk)
                self._jit_put(tkey, fn)
            params = jnp.asarray(
                np.asarray([min_threshold], np.uint32))
            out = self._call_program(fn, counts, params)

            def finalize() -> PairsResult:
                vals, idxs = (np.asarray(x) for x in out)
                return PairsResult(
                    [(int(slot_rows[i]), int(v))
                     for v, i in zip(vals.tolist(), idxs.tolist())
                     if v > 0])

            return _Pending(finalize, arrays=tuple(out))

        def finalize() -> PairsResult:
            c = np.asarray(counts).astype(np.int64)
            slot_idx = np.fromiter(
                map(bank.slots.get, all_rows,
                    itertools.repeat(bank.zero_slot)),
                dtype=np.int64, count=len(all_rows))
            rows_arr = np.asarray(all_rows, dtype=np.uint64)
            counts_arr = c[slot_idx]
            keep = counts_arr > max(0, min_threshold - 1)
            rows_arr, counts_arr = rows_arr[keep], counts_arr[keep]
            rows_arr, counts_arr = _topn_candidates(rows_arr,
                                                    counts_arr, n)
            order = np.lexsort((rows_arr, -counts_arr))
            if n:
                order = order[:n]
            return PairsResult([(int(rows_arr[o]), int(counts_arr[o]))
                                for o in order])

        return _Pending(finalize, arrays=(counts,))

    def _repair_topn_caches(self, view, shards) -> None:
        """Rebuild every fragment's cached per-row counts from storage —
        the recovery action when a sampled self-check catches stale
        counts. Restores the warm-path invariant instead of disabling
        the cache."""
        for s in shards:
            frag = view.fragment(s)
            if frag is None:
                continue
            with frag._lock:
                frag.cache.invalidate()
                for r in frag.row_ids():
                    frag.cache.add(r, frag.row_count(r))

    def _topn_cached_counts(self, view, shards) -> Optional[Dict[int, int]]:
        """Summed per-row counts from fragment caches, or None when any
        fragment's cache cannot prove completeness (cache disabled, rows
        evicted, or counts missing)."""
        from pilosa_tpu.core import cache as cache_mod

        total: Dict[int, int] = {}
        for s in shards:
            frag = view.fragment(s)
            if frag is None:
                continue
            if frag.cache_type == cache_mod.CACHE_TYPE_NONE:
                return None
            if getattr(frag.cache, "saturated", False):
                # Saturated caches stop tracking writes entirely, so
                # their counts may be stale even when len() happens to
                # match (e.g. after mass clears).
                return None
            counts = getattr(frag.cache, "counts", None)
            if counts is None:
                return None
            rows = frag.row_ids()
            if len(counts) < len(rows):
                return None
            for r in rows:
                c = counts.get(r)
                if c is None:  # evicted: cache incomplete for this frag
                    return None
                total[r] = total.get(r, 0) + c
        return total

    # ----------------------------------------------------------------- Rows

    def _execute_rows(self, idx: Index, call: Call, shards
                      ) -> RowIdentifiers:
        """Row-id enumeration with previous/limit/column filters and, for
        time fields, a from/to view-range filter (reference
        executeRowsShard, executor.go:1143; time-view selection
        executor.go:1160-1218)."""
        field_name = call.arg("_field")
        field = idx.field(field_name)
        if field is None:
            raise ExecutionError(f"field not found: {field_name}")
        shards = self._shards(idx, shards)
        previous = call.arg("previous")
        limit = call.uint_arg("limit")
        column = call.arg("column")
        frm, to = call.arg("from"), call.arg("to")
        if (frm is not None or to is not None) and \
                field.options.type != FIELD_TYPE_TIME:
            raise ExecutionError(f"from/to on non-time field {field_name}")

        view_names = [VIEW_STANDARD]
        if field.options.type == FIELD_TYPE_TIME and (
                frm is not None or to is not None
                or field.options.no_standard_view):
            # Clamp the requested range to the min/max existing time
            # views, then take the minimal view cover — exactly the
            # reference's shape (minMaxViews + viewsByTimeRange).
            q = field.options.time_quantum
            if not q:
                return RowIdentifiers([])
            vmin, vmax = timeq.min_max_views(list(field.views), q)
            if not vmin or not vmax:
                return RowIdentifiers([])
            start = timeq.parse_timestamp(frm) if frm else None
            end = timeq.parse_timestamp(to) if to else None
            min_t = timeq.time_of_view(vmin, False)
            max_t = timeq.time_of_view(vmax, True)
            if start is None or start < min_t:
                start = min_t
            if end is None or end > max_t:
                end = max_t
            view_names = field.views_for_range(start, end)

        rows: set = set()
        for vname in view_names:
            view = field.view(vname)
            if view is None:
                continue
            for shard in shards:
                frag = view.fragment(shard)
                if frag is None:
                    continue
                if column is not None:
                    if column // SHARD_WIDTH != shard:
                        continue
                    for r in frag.row_ids():
                        if frag.bit(r, column):
                            rows.add(r)
                else:
                    rows.update(frag.row_ids())
        out = sorted(rows)
        if previous is not None:
            out = [r for r in out if r > previous]
        if limit is not None:
            out = out[:limit]
        if WORKLOAD.enabled:
            for vname in view_names:
                if field.view(vname) is not None:
                    WORKLOAD.record_read(idx.name, field_name, vname,
                                         shards,
                                         rows_scanned=len(out))
        return RowIdentifiers(out)

    # -------------------------------------------------------------- GroupBy

    # Device bytes one GroupBy expansion chunk may materialize. Bounds the
    # [P*R, S, W] intermediate: prefixes stream through in chunks of
    # GROUPBY_CHUNK_BYTES / (R*S*W*4) at a time.
    GROUPBY_CHUNK_BYTES = int(os.environ.get("PILOSA_TPU_GROUPBY_CHUNK_BYTES",
                                             256 << 20))

    # graftlint: materialize — GroupBy is level-synchronous by design:
    # the host reads each depth's [P, R] count matrix to prune empty
    # prefixes, page (`previous`), and decide HBM spills before
    # expanding the next level. Those per-level fetches ARE the
    # algorithm's materialization boundary (see docstring below).
    def _execute_group_by(self, idx: Index, call: Call, shards
                          ) -> List[GroupCount]:
        """Cross-product of Rows() children with intersection counts
        (reference executeGroupByShard, executor.go:1062 + groupByIterator
        :2820). TPU shape: level-synchronous — ALL prefixes at a depth
        expand against ALL of the next field's rows in one batched
        [P, R, S, W] AND+popcount kernel (chunked over P to bound HBM),
        instead of one device dispatch per prefix row. Empty prefixes are
        pruned between levels, which the reference's iterator cannot do
        (it re-walks the full cross product, executor.go:2820-2996)."""
        import jax
        import jax.numpy as jnp
        from pilosa_tpu.ops.bitset import popcount

        if not call.children or any(c.name != "Rows" for c in call.children):
            raise ExecutionError("GroupBy requires Rows() arguments")
        shards = self._shards(idx, shards, pad=False)
        # GroupBy only ANDs, so a group's count is zero on any shard
        # some child field doesn't cover — restrict to the INTERSECTION
        # of the children's availableShards (narrow fields keep a wide
        # index's empty shards out of the [P, R, S, W] expansions).
        child_fields = [idx.field(c.arg("_field")) for c in call.children]
        if all(f is not None for f in child_fields):
            covered = set(child_fields[0].available_shards())
            for f in child_fields[1:]:
                covered &= set(f.available_shards())
            shards = [s for s in shards if s in covered]
            if not shards:
                return []
        shards = self._shards(idx, shards)
        limit = call.uint_arg("limit") or 0
        previous = call.arg("previous")
        if previous is not None:
            if not isinstance(previous, list) or \
                    len(previous) != len(call.children):
                raise ExecutionError(
                    "'previous' must be a list with one entry per Rows "
                    "child")
            previous = tuple(int(p) for p in previous)
        filter_call = call.arg("filter")
        filter_words = None
        if isinstance(filter_call, Call):
            filter_words = self._eval_tree(idx, filter_call, shards,
                                           mode="row")

        child_rows: List[Tuple[str, List[int]]] = []
        for child in call.children:
            ids = self._execute_rows(idx, child, shards).rows
            child_rows.append((child.arg("_field"), ids))
            if not ids:
                return []
        if WORKLOAD.enabled:
            # Each child's rows feed the [P, R, S, W] expansion sweep.
            for fname, ids_ in child_rows:
                WORKLOAD.record_read(idx.name, fname, VIEW_STANDARD,
                                     shards, rows=ids_)

        # Keyed by child INDEX, not field name: GroupBy(Rows(f), Rows(f))
        # is legal, and with subset banks the two children may need
        # different row sets of the same field.
        banks = []
        for fname, ids_ in child_rows:
            f = idx.field(fname)
            banks.append(self._get_bank_for(f, VIEW_STANDARD, shards,
                                            rows_needed=set(ids_)))
        # GroupBy only intersects, so all operands can slice down to the
        # NARROWEST width: bits past the narrowest operand AND to zero.
        wmin = min(b.array.shape[-1] for b in banks)
        if filter_words is not None:
            wmin = min(wmin, filter_words.shape[-1])
            filter_words = filter_words[..., :wmin]

        def _jit(key, builder):
            fn = self._jit_get(key)
            if fn is None:
                self._note_jit_compile()
                fn = jax.jit(builder)
                self._jit_put(key, fn)
            return fn

        def stacks_at(depth):
            _, ids = child_rows[depth]
            bank = banks[depth]
            sel = jnp.asarray(np.asarray([bank.slot(r) for r in ids],
                                         dtype=np.int32))
            return bank.array[sel][..., :wmin]  # [R, S, Wmin]

        n_shards, depth_n = len(shards), len(child_rows)
        # prefixes: the surviving frontier [P, S, W] — a jnp array while
        # its total bytes fit GROUPBY_CHUNK_BYTES, spilled to a host
        # numpy array beyond that and re-uploaded chunk by chunk (the
        # frontier of a deep high-cardinality GroupBy is P*S*W words and
        # must not live unbudgeted in HBM; the reference iterates
        # host-side throughout, executor.go:2820-2996). None means the
        # full universe. prefix_rows[i] = row-id tuple.
        prefixes = filter_words[None] if filter_words is not None else None
        prefix_rows: List[tuple] = [()]

        def frontier_chunk(frontier, c0, c1):
            sub = frontier[c0:c1]
            return sub if isinstance(sub, jnp.ndarray) else jnp.asarray(sub)

        for depth in range(depth_n - 1):
            stacks = stacks_at(depth)
            R = stacks.shape[0]
            if prefixes is None:
                cnt = _jit(f"gb_cnt0:{stacks.shape}",
                           lambda st: popcount(st, axis=(-2, -1)))
                # graftlint: disable=GL003 — GroupBy frontier pruning
                # is a host decision by design: one [R] count vector
                # per depth gates which prefixes expand.
                nz = np.asarray(cnt(stacks)) > 0
                keep_idx = np.where(nz)[0]
                prefixes = stacks[jnp.asarray(keep_idx.astype(np.int32))]
                prefix_rows = [(int(child_rows[depth][1][i]),)
                               for i in keep_idx]
            else:
                per_new = n_shards * wmin * 4
                chunk_p = max(1, self.GROUPBY_CHUNK_BYTES // (per_new * R))
                kept_words, kept_rows = [], []
                kept_bytes = 0
                spilled = False
                for c0 in range(0, len(prefix_rows), chunk_p):
                    sub = frontier_chunk(prefixes, c0, c0 + chunk_p)
                    expand = _jit(
                        f"gb_exp:{sub.shape}:{stacks.shape}",
                        lambda s, st: (
                            lambda new: (new, popcount(new, axis=(-2, -1))))(
                            jnp.bitwise_and(s[:, None], st[None]).reshape(
                                -1, st.shape[-2], st.shape[-1])))
                    new, counts = expand(sub, stacks)
                    nz = np.asarray(counts) > 0
                    keep_idx = np.where(nz)[0]
                    if len(keep_idx) == 0:
                        continue
                    kept = new[jnp.asarray(keep_idx.astype(np.int32))]
                    kept_bytes += kept.nbytes
                    if not spilled and kept_bytes > self.GROUPBY_CHUNK_BYTES:
                        # Survivors exceed the device budget: collect
                        # the rest of this depth's frontier in host
                        # memory (chunks re-upload at the next depth).
                        spilled = True
                        self.groupby_spill_events += 1
                        kept_words = [np.asarray(w) for w in kept_words]
                    kept_words.append(np.asarray(kept) if spilled else kept)
                    ids = child_rows[depth][1]
                    kept_rows.extend(
                        prefix_rows[c0 + int(k) // R] + (int(ids[k % R]),)
                        for k in keep_idx)
                if not kept_words:
                    return []
                if len(kept_words) == 1:
                    prefixes = kept_words[0]
                elif spilled:
                    prefixes = np.concatenate(kept_words)
                else:
                    prefixes = jnp.concatenate(kept_words)
                prefix_rows = kept_rows

        # Final depth: count every (prefix × row) pair in chunked batches.
        stacks = stacks_at(depth_n - 1)
        R = stacks.shape[0]
        ids = child_rows[depth_n - 1][1]
        fields = [f for f, _ in child_rows]
        results: List[GroupCount] = []
        if prefixes is None:
            cnt = _jit(f"gb_cnt0:{stacks.shape}",
                       lambda st: popcount(st, axis=(-2, -1)))
            counts = np.asarray(cnt(stacks))[None, :]  # [1, R]
        else:
            counts = None
        chunk_p = max(1, self.GROUPBY_CHUNK_BYTES //
                      max(1, n_shards * wmin * 4 * R))
        for c0 in range(0, len(prefix_rows), chunk_p):
            if limit and len(results) >= limit:
                break
            if counts is None:
                sub = frontier_chunk(prefixes, c0, c0 + chunk_p)
                cntk = _jit(
                    f"gb_cntN:{sub.shape}:{stacks.shape}",
                    lambda s, st: popcount(
                        jnp.bitwise_and(s[:, None], st[None]),
                        axis=(-2, -1)))
                chunk_counts = np.asarray(cntk(sub, stacks))  # [p, R]
            else:
                chunk_counts = counts[c0:c0 + chunk_p]
            for pi in range(chunk_counts.shape[0]):
                row_pre = prefix_rows[c0 + pi]
                # Paging: results are lexicographic by row-id tuple, so a
                # prefix strictly below previous's prefix can't produce
                # anything after `previous` (reference groupByIterator
                # seek, executor.go:2878-2900).
                if previous is not None and \
                        row_pre < previous[:len(row_pre)]:
                    continue
                crow = chunk_counts[pi]
                for ri in np.nonzero(crow)[0]:
                    if limit and len(results) >= limit:
                        break
                    tup = row_pre + (int(ids[ri]),)
                    if previous is not None and tup <= previous:
                        continue
                    group = [FieldRow(f, rid) for f, rid in
                             zip(fields, tup)]
                    results.append(GroupCount(group, int(crow[ri])))
        return results

    # -------------------------------------------------------- Sum/Min/Max

    def _execute_val_count(self, idx: Index, call: Call, shards, op: str
                           ) -> ValCount:
        """(reference executeSumCountShard :569, executeMinShard :610,
        executeMaxShard :651)."""
        import jax
        import jax.numpy as jnp

        field_name = call.arg("field") or call.arg("_field")
        if field_name is None:
            raise ExecutionError(f"{op}() requires a field argument")
        field = idx.field(field_name)
        if field is None:
            raise ExecutionError(f"field not found: {field_name}")
        bsig = field.bsi_groups.get(field_name)
        if bsig is None:
            raise ExecutionError(f"field {field_name} is not an int field")
        shards = self._shards(idx, shards)
        depth = bsig.bit_depth
        bank = self._get_bank_for(field, view_bsi_name(field_name), shards)
        # Plane-slot vector memoized on the bank object: banks rebuild
        # when fragment versions change, so the memo invalidates with
        # them, and repeat Sum/Min/Max calls skip a host build + device
        # upload (~1 ms/call, comparable to the whole device sweep).
        sel = getattr(bank, "_bsi_sel", None)
        if sel is None or int(sel.shape[0]) != depth + 1:
            sel = jnp.asarray(np.asarray(
                [bank.slot(r) for r in range(depth + 1)], dtype=np.int32))
            bank._bsi_sel = sel
        filter_words = None
        if call.children:
            filter_words = _align_words(
                self._eval_tree(idx, call.children[0], shards, mode="row"),
                bank.array.shape[-1])

        key = f"val:{op}:{bank.array.shape}:d{depth}:" \
              f"{filter_words is not None}"
        fn = self._jit_get(key)
        if fn is None:
            self._note_jit_compile()
            from pilosa_tpu.ops.bitset import popcount
            if op == "Sum":
                def run(bank_arr, sel, filt):
                    return bsi.sum_count(bank_arr[sel], filt)
            else:
                kernel = bsi.min_mask if op == "Min" else bsi.max_mask

                def run(bank_arr, sel, filt):
                    bits, cand = kernel(bank_arr[sel], filt)
                    return bits, popcount(cand, axis=(-2, -1))
            fn = jax.jit(run)
            self._jit_put(key, fn)
        a, b = fn(bank.array, sel, filter_words)

        def finalize() -> ValCount:
            if op == "Sum":
                counts = np.asarray(a, dtype=np.int64)
                cnt = int(np.asarray(b))
                total = sum(int(c) << i
                            for i, c in enumerate(counts.tolist()))
                return ValCount(total + bsig.min * cnt, cnt)
            count = int(np.asarray(b))
            if count == 0:
                return ValCount(0, 0)
            base = sum(int(v) << i
                       for i, v in enumerate(np.asarray(a).tolist()))
            return ValCount(base + bsig.min, count)

        return _Pending(finalize, arrays=(a, b))

    # --------------------------------------------------------------- writes

    def _set_args(self, idx: Index, call: Call) -> Tuple[Field, int, Any]:
        col = call.arg("_col")
        if not isinstance(col, int):
            raise ExecutionError("column keys require keys=True (API layer)")
        fname, row_ref = self._row_call_field(call)
        field = idx.field(fname)
        if field is None:
            raise ExecutionError(f"field not found: {fname}")
        return field, col, row_ref

    def _execute_set(self, idx: Index, call: Call) -> bool:
        """(reference executeSet, executor.go:1889)."""
        field, col, row_ref = self._set_args(idx, call)
        if field.options.type == FIELD_TYPE_INT:
            changed = field.set_value(col, int(row_ref))
        else:
            ts = call.arg("_timestamp")
            timestamp = timeq.parse_timestamp(ts) if ts else None
            row_id = self._row_id(field, row_ref)
            changed = field.set_bit(row_id, col, timestamp=timestamp)
        idx.add_existence(np.array([col], dtype=np.uint64))
        return changed

    def _execute_clear(self, idx: Index, call: Call) -> bool:
        field, col, row_ref = self._set_args(idx, call)
        if field.options.type == FIELD_TYPE_INT:
            bsig = field.bsi_groups[field.name]
            view = field.view(view_bsi_name(field.name))
            if view is None:
                return False
            frag = view.fragment(col // SHARD_WIDTH)
            return frag.clear_value(col, bsig.bit_depth) if frag else False
        row_id = self._row_id(field, row_ref)
        return field.clear_bit(row_id, col)

    def _execute_clear_row(self, idx: Index, call: Call, shards) -> bool:
        """(reference executeClearRowShard, executor.go:1761)."""
        fname, row_ref = self._row_call_field(call)
        field = idx.field(fname)
        if field is None:
            raise ExecutionError(f"field not found: {fname}")
        if field.options.type not in (FIELD_TYPE_SET, FIELD_TYPE_TIME,
                                      FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL):
            raise ExecutionError(
                f"ClearRow() is not supported on {field.options.type} fields")
        row_id = self._row_id(field, row_ref)
        shards = self._shards(idx, shards, pad=False)  # host-side write
        changed = False
        for view in field.views.values():
            for shard in shards:
                frag = view.fragment(shard)
                if frag is None:
                    continue
                cols = frag.row_columns(row_id)
                if len(cols):
                    frag.bulk_import(np.full(len(cols), row_id, np.uint64),
                                     cols, clear=True)
                    changed = True
        return changed

    def _execute_store(self, idx: Index, call: Call, shards) -> bool:
        """Store(Row(...), f=row): write a computed row (reference
        executeSetRowShard, executor.go:1834)."""
        if len(call.children) != 1:
            raise ExecutionError("Store() takes exactly one row argument")
        fname, row_ref = self._row_call_field(call)
        field = idx.field(fname)
        if field is None:
            field = idx.create_field(fname)
        elif field.options.type not in (FIELD_TYPE_SET, FIELD_TYPE_TIME):
            raise ExecutionError(
                f"Store() is not supported on {field.options.type} fields")
        row_id = self._row_id(field, row_ref)
        real_shards = self._shards(idx, shards, pad=False)
        padded = self._shards(idx, shards)
        words = np.asarray(self._eval_tree(idx, call.children[0], padded,
                                           mode="row"))
        view = field.create_view_if_not_exists(VIEW_STANDARD)
        # Write only real shards — mesh padding appends at the tail and
        # must never materialize phantom fragments.
        for i, shard in enumerate(real_shards):
            frag = view.create_fragment_if_not_exists(shard)
            frag.set_row(row_id, words[i])
        return True

    def _execute_set_row_attrs(self, idx: Index, call: Call) -> None:
        """(reference executeSetRowAttrs, executor.go:2029)."""
        fname = call.arg("_field")
        field = idx.field(fname)
        if field is None:
            raise ExecutionError(f"field not found: {fname}")
        row_id = call.arg("_row")
        attrs = {k: v for k, v in call.args.items() if not k.startswith("_")}
        field.row_attr_store.set(int(row_id), attrs)

    def _execute_set_column_attrs(self, idx: Index, call: Call) -> None:
        col = call.arg("_col")
        attrs = {k: v for k, v in call.args.items() if not k.startswith("_")}
        idx.column_attr_store.set(int(col), attrs)

    # ------------------------------------------------------------ row attrs

    def _attach_row_attrs(self, idx: Index, call: Call, res: RowResult
                          ) -> None:
        if call.name not in ("Row", "Range"):
            return
        try:
            fname, row_ref = self._row_call_field(call)
        except ExecutionError:
            return
        field = idx.field(fname)
        if field is None or isinstance(row_ref, Condition):
            return
        if isinstance(row_ref, int) and not isinstance(row_ref, bool):
            cap = getattr(self._tls, "deps", None)
            if cap is not None:
                # The response embeds row attrs, whose mutations do
                # NOT bump fragment generations — stamp the attr
                # store's own counter into the request deps.
                # Stamp-then-read (first stamp wins): a set_bulk racing
                # the get() below leaves the stored gen behind, so the
                # fill can never validate pre-write attrs as current.
                cap.setdefault(("rattr", idx.name, field.name),
                               field.row_attr_store.gen)
            res.attrs = field.row_attr_store.get(row_ref)
