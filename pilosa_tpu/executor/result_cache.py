"""Generation-keyed cross-request query result cache (ROADMAP item 3a).

The serving path already amortizes dispatch (coalescer) and compiles
(fusion), and PR 6's workload plane *measures* heavy cross-request
repetition (``coalescer.window_repeat``, the cache-opportunity
``estSavedS`` estimator) without acting on it. This module acts on it:
the cheapest query is the one never compiled or dispatched.

Two tiers share one LRU byte budget and one counter set:

- **request tier** — key = the canonical request identity from
  ``utils/fingerprint.request_key`` (the SAME key the coalescer dedups
  on); value = the fully shaped ``{"results": ...}`` response dict.
  Validation is by *dependency snapshot*: at fill time the executor
  records every operand view's ``version_stamp()`` (fragment write
  versions — bumped by ``Fragment._touch_row`` on every mutation) plus
  the attr-store and key-translator stamps the response embedded; a
  hit revalidates them all with pure host dict reads. A hit therefore
  skips parse, translate, plan, compile, dispatch AND fetch.

- **eval tier** — key = the staged-eval fingerprint carried on
  ``_StagedEval`` (tree signature + row ids + predicate params — the
  identity ``utils/hotspots`` records) plus the concrete shard tuple;
  generation = the operand banks' fragment-version map captured at
  staging. Value = the eval's HOST output array ([S] counts or [S, W]
  row words). Hits short-circuit ``_eval_tree`` after planning —
  before the fusion collector, so a group whose members all hit never
  launches — and misses fill at the existing materialize seam (the
  first host fetch of the device output).

Writes invalidate implicitly: any mutation bumps its fragment's
version, so the stored generation/deps no longer match and the stale
entry is dropped on its next lookup (or ages out of the LRU).

Observability: hits/misses/evictions counters (per tier and total,
exported as ``pilosa_result_cache_{hits,misses,evictions}_total``),
live ``bytes``/``entries`` gauges, a ledger entry under category
``result_cache`` (host RAM — values are host objects) so
``/debug/memory`` totals stay provable and the watchdog sees it, and
a snapshot joined against the workload plane's predicted savings at
``/debug/hotspots``.

Pure host module: no jax imports, dict work under one leaf lock; the
only nested acquisition is the memory ledger (itself a leaf), the same
discipline as ``Executor._jit_put``. ``PILOSA_TPU_RESULT_CACHE=0`` is
the kill switch; ``[cache]`` config keys layer on top but can never
re-enable past the env switch.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

from pilosa_tpu.utils.locks import make_lock
from pilosa_tpu.utils.memledger import LEDGER

RESULT_CACHE_ENV = "PILOSA_TPU_RESULT_CACHE"
DEFAULT_MAX_BYTES = int(os.environ.get(
    "PILOSA_TPU_RESULT_CACHE_BYTES", 256 << 20))

TIERS = ("request", "eval")


def _env_enabled() -> bool:
    return os.environ.get(RESULT_CACHE_ENV, "1") != "0"


def approx_nbytes(obj: Any) -> int:
    """Cheap recursive host-size estimate of a shaped JSON response —
    the LRU byte budget needs a consistent approximation, not an exact
    figure, and a full json.dumps purely for sizing would double the
    serialization cost of every miss (the HTTP layer serializes the
    same dict again right after)."""
    if isinstance(obj, str):
        return 48 + len(obj)
    if isinstance(obj, (list, tuple)):
        return 56 + 8 * len(obj) + sum(map(approx_nbytes, obj))
    if isinstance(obj, dict):
        return 64 + sum(approx_nbytes(k) + approx_nbytes(v)
                        for k, v in obj.items())
    return 28  # ints/floats/bools/None: CPython small-object cost


class _Entry:
    __slots__ = ("gen", "value", "nbytes", "tier")

    def __init__(self, gen: Any, value: Any, nbytes: int,
                 tier: str) -> None:
        self.gen = gen        # generation snapshot / deps dict
        self.value = value    # shaped dict (request) | host array (eval)
        self.nbytes = int(nbytes)
        self.tier = tier


class ResultCache:
    """LRU byte-budgeted, generation-validated result store (see
    module docstring). One instance per Executor."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 enabled: Optional[bool] = None) -> None:
        self.enabled = _env_enabled() if enabled is None else (
            bool(enabled) and _env_enabled())
        self.max_bytes = max(0, int(max_bytes))
        self._lock = make_lock("ResultCache._lock")
        self._entries: "OrderedDict[Any, _Entry]" = OrderedDict()
        self.bytes = 0
        # Cumulative counters, per tier + derived totals. Kept on the
        # cache (not only in stats) so embedded users and tests read
        # them without a stats client.
        self.hits: Dict[str, int] = {t: 0 for t in TIERS}
        self.misses: Dict[str, int] = {t: 0 for t in TIERS}
        self.evictions = 0
        self.invalidations = 0  # stale entries dropped on lookup
        # Entries dropped because a resize moved their shards' ownership
        # (API._note_placement_change — the placement epoch guard).
        self.placement_invalidations = 0
        # Optional utils/stats sink (attached by the API layer, the
        # WORKLOAD.stats convention) so /metrics counters increment at
        # event time and stay true monotone counters.
        self.stats: Optional[Any] = None

    # ---------------------------------------------------------- config

    def configure(self, enabled: Optional[bool] = None,
                  max_bytes: Optional[int] = None) -> None:
        """[cache] config wiring. The env kill switch always wins:
        config can disable a cache the env allows, never the reverse."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled) and _env_enabled()
            if max_bytes is not None:
                self.max_bytes = max(0, int(max_bytes))
                self._evict_over_budget()
                self._ledger()  # a shrink evicts; keep /debug/memory true

    # --------------------------------------------------------- helpers

    def _count(self, name: str, tier: str) -> None:
        stats = self.stats
        if stats is not None:
            stats.count(f"result_cache.{name}", 1)
            stats.count(f"result_cache.{tier}.{name}", 1)

    def _note_hit(self, tier: str) -> None:
        # graftlint: disable=GL008 — closed key space: both counter
        # dicts are pre-seeded with exactly the TIERS keys and only
        # ever incremented, never grown.
        self.hits[tier] += 1
        self._count("hits", tier)

    def _note_miss(self, tier: str) -> None:
        # graftlint: disable=GL008 — same closed TIERS key space.
        self.misses[tier] += 1
        self._count("misses", tier)

    def _ledger(self) -> None:
        # Lock held (ledger lock is a leaf — the _jit_put precedent):
        # the aggregate entry tracks the cache's live host bytes so
        # /debug/memory totals include it and the watchdog's flight
        # recorder samples it without polling us.
        LEDGER.register("result_cache", "entries", self.bytes,
                        owner=self, entries=len(self._entries))

    def _drop_locked(self, key: Any, entry: _Entry) -> None:
        self._entries.pop(key, None)
        self.bytes -= entry.nbytes

    def _evict_over_budget(self) -> None:
        while self._entries and self.bytes > self.max_bytes:
            _, old = self._entries.popitem(last=False)
            self.bytes -= old.nbytes
            self.evictions += 1
            self._count("evictions", old.tier)

    # ----------------------------------------------------------- reads

    def lookup(self, key: Any, gen: Any, tier: str = "eval"
               ) -> Optional[Any]:
        """Eval-tier lookup: hit iff the stored generation equals
        `gen` exactly. A stale entry is dropped immediately (its bytes
        are dead weight — the generation can never match again)."""
        if not self.enabled:
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.gen == gen:
                self._entries.move_to_end(key)
                self._note_hit(tier)
                return e.value
            if e is not None:
                self._drop_locked(key, e)
                self.invalidations += 1
                self._ledger()
            self._note_miss(tier)
            return None

    def lookup_request(self, key: Any,
                       validate: Callable[[Dict[Any, Any]], bool]
                       ) -> Optional[Any]:
        """Request-tier lookup: the entry's stored dependency snapshot
        is revalidated by `validate` (holder version-stamp reads). The
        validator runs OUTSIDE the cache lock — it takes view locks,
        and holding ours across that would invert against nothing
        today but costs nothing to keep leaf-clean."""
        if not self.enabled:
            return None
        with self._lock:
            e = self._entries.get(key)
            deps = e.gen if e is not None else None
            value = e.value if e is not None else None
        if e is None:
            with self._lock:
                self._note_miss("request")
            return None
        if validate(deps):
            with self._lock:
                if self._entries.get(key) is e:
                    self._entries.move_to_end(key)
                self._note_hit("request")
            return value
        with self._lock:
            cur = self._entries.get(key)
            if cur is e:
                self._drop_locked(key, e)
                self.invalidations += 1
                self._ledger()
            self._note_miss("request")
        return None

    # ---------------------------------------------------------- writes

    def fill(self, key: Any, gen: Any, value: Any, nbytes: int,
             tier: str = "eval") -> None:
        if not self.enabled or self.max_bytes <= 0:
            return
        nbytes = max(0, int(nbytes))
        if nbytes > self.max_bytes:
            return  # one oversized value must not flush the cache
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = _Entry(gen, value, nbytes, tier)
            self.bytes += nbytes
            self._evict_over_budget()
            self._ledger()

    def clear(self) -> None:
        with self._lock:
            self._entries = OrderedDict()
            self.bytes = 0
            self._ledger()

    def invalidate_placement(self, moved: Any) -> int:
        """Drop eval-tier entries whose shard tuple intersects `moved`
        (a set of ``(index, shard)`` pairs whose owner set changed in a
        resize — API._moved_shards). The generation stamps already make
        a stale HIT impossible; this makes the dead bytes provably gone
        at the placement transition instead of lingering until an LRU
        eviction. Request-tier entries are untouched: that tier only
        fills on non-clustered deployments (no placement to move).
        Returns the number of entries dropped."""
        if not moved:
            return 0
        moved = {(str(i), int(s)) for i, s in moved}
        with self._lock:
            dead = []
            for key, e in self._entries.items():
                if not (isinstance(key, tuple) and len(key) >= 4
                        and key[0] == "eval"):
                    continue
                iname, shard_tuple = key[1], key[3]
                if any((iname, int(s)) in moved for s in shard_tuple):
                    dead.append((key, e))
            for key, e in dead:
                self._drop_locked(key, e)
            if dead:
                self.placement_invalidations += len(dead)
                self.invalidations += len(dead)
                self._ledger()
            return len(dead)

    # ------------------------------------------------------- reporting

    def __len__(self) -> int:
        return len(self._entries)

    def snapshot(self) -> Dict[str, Any]:
        """The /debug/hotspots `resultCache` stanza: observed hit
        ratios the opportunity estimator's predictions are judged
        against."""
        with self._lock:
            hits = dict(self.hits)
            misses = dict(self.misses)
            h = sum(hits.values())
            m = sum(misses.values())
            return {
                "enabled": self.enabled,
                "bytes": self.bytes,
                "maxBytes": self.max_bytes,
                "entries": len(self._entries),
                "hits": h,
                "misses": m,
                "hitRatio": (h / (h + m)) if (h + m) else 0.0,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "placementInvalidations": self.placement_invalidations,
                "tiers": {t: {"hits": hits[t], "misses": misses[t]}
                          for t in TIERS},
            }

    def publish(self, stats: Optional[Any]) -> None:
        """Scrape-time gauges (counters were incremented at event
        time): pilosa_result_cache_bytes / _entries / _hit_ratio."""
        if stats is None:
            return
        snap = self.snapshot()
        stats.gauge("result_cache.bytes", snap["bytes"])
        stats.gauge("result_cache.entries", snap["entries"])
        stats.gauge("result_cache.hit_ratio", snap["hitRatio"])
