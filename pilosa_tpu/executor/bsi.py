"""Bit-sliced-index device kernels.

The reference's BSI engine (/root/reference/fragment.go:767-1035) runs
O(bitDepth) passes of whole-row bitmap algebra per shard. Here each op is a
single fused device expression over `planes` shaped [bit_depth+1, S, W]
(bit planes LSB-first, then the not-null plane; S = shards batch axis).
Bit-plane loops are Python-unrolled (bit_depth is static per field), so XLA
sees one straight-line graph and fuses it.

All comparison values are *base values* (offset-encoded by the field's
bsiGroup, field.go:1381) — callers clamp/offset before lowering here.
"""

from __future__ import annotations

import jax.numpy as jnp

from pilosa_tpu.ops.bitset import popcount


def not_null(planes):
    return planes[-1]


def _vbit(value, i):
    """Bit i of a (possibly traced) comparison value, as a bool scalar —
    keeps predicate values out of the compile cache key. `value` may be
    a single u32 scalar (depth <= 32) or a (lo, hi) pair of u32 limbs
    carrying a 64-bit base value (JAX runs without x64 on TPU, so wide
    predicates ride as two u32 params; the limb choice is static because
    the plane index is)."""
    if isinstance(value, tuple):
        limb, j = (value[0], i) if i < 32 else (value[1], i - 32)
        return _vbit(limb, j)
    return (jnp.right_shift(jnp.uint32(value) if isinstance(value, int)
                            else value.astype(jnp.uint32),
                            jnp.uint32(i)) & jnp.uint32(1)).astype(bool)


def eq(planes, value):
    """Columns whose value == `value` (reference rangeEQ, fragment.go:871)."""
    m = planes[-1]
    depth = planes.shape[0] - 1
    for i in range(depth):
        m = jnp.bitwise_and(
            m, jnp.where(_vbit(value, i), planes[i],
                         jnp.bitwise_not(planes[i])))
    return m


def neq(planes, value: int):
    return jnp.bitwise_and(planes[-1], jnp.bitwise_not(eq(planes, value)))


def lt(planes, value, allow_eq: bool = False):
    """Columns with value < (or <=) `value` (reference rangeLT,
    fragment.go:907): MSB-first scan keeping an equality prefix mask."""
    depth = planes.shape[0] - 1
    matched = jnp.zeros_like(planes[-1])
    eq_prefix = planes[-1]
    for i in reversed(range(depth)):
        bit = planes[i]
        vb = _vbit(value, i)
        # predicate bit 1: values with 0 here are smaller; bit 0: only the
        # equality prefix narrows.
        matched = jnp.bitwise_or(
            matched,
            jnp.where(vb, jnp.bitwise_and(eq_prefix, jnp.bitwise_not(bit)),
                      jnp.zeros_like(bit)))
        eq_prefix = jnp.bitwise_and(
            eq_prefix, jnp.where(vb, bit, jnp.bitwise_not(bit)))
    if allow_eq:
        matched = jnp.bitwise_or(matched, eq_prefix)
    return matched


def gt(planes, value, allow_eq: bool = False):
    """(reference rangeGT, fragment.go:949)."""
    depth = planes.shape[0] - 1
    matched = jnp.zeros_like(planes[-1])
    eq_prefix = planes[-1]
    for i in reversed(range(depth)):
        bit = planes[i]
        vb = _vbit(value, i)
        # predicate bit 0: values with 1 here are larger.
        matched = jnp.bitwise_or(
            matched,
            jnp.where(vb, jnp.zeros_like(bit),
                      jnp.bitwise_and(eq_prefix, bit)))
        eq_prefix = jnp.bitwise_and(
            eq_prefix, jnp.where(vb, bit, jnp.bitwise_not(bit)))
    if allow_eq:
        matched = jnp.bitwise_or(matched, eq_prefix)
    return matched


def between(planes, low, high):
    """low <= value <= high (reference rangeBetween, fragment.go:1002)."""
    return jnp.bitwise_and(gt(planes, low, allow_eq=True),
                           lt(planes, high, allow_eq=True))


def sum_count(planes, filter_mask=None):
    """(sum of base values, count) over not-null (∧ filter) columns
    (reference fragment.sum, fragment.go:767). Returns device scalars;
    sum excludes the base offset — caller adds min*count."""
    m = planes[-1]
    if filter_mask is not None:
        m = jnp.bitwise_and(m, filter_mask)
    depth = planes.shape[0] - 1
    # Per-plane counts fit uint32; the 2^i weighting can exceed 32 bits, so
    # the weighted sum happens on the host over exact Python ints.
    axes = (-2, -1) if planes.ndim == 3 else -1
    counts = [popcount(jnp.bitwise_and(planes[i], m), axis=axes)
              for i in range(depth)]
    cnt = popcount(m, axis=axes)
    return jnp.stack(counts), cnt


def min_mask(planes, filter_mask=None):
    """Mask of columns holding the minimum base value + the value itself.
    Greedy MSB descent (reference fragment.min, fragment.go:794). Fully
    on-device via where-selects; returns (value_planes_selector, candidates)
    where the caller popcounts candidates for the count. Value is returned
    as a vector of chosen bits [depth] (uint32 0/1) to stay traceable."""
    m = planes[-1]
    if filter_mask is not None:
        m = jnp.bitwise_and(m, filter_mask)
    depth = planes.shape[0] - 1
    chosen = []
    cand = m
    for i in reversed(range(depth)):
        zeros = jnp.bitwise_and(cand, jnp.bitwise_not(planes[i]))
        has_zero = jnp.any(zeros != 0)
        cand = jnp.where(has_zero, zeros, cand)
        chosen.append(jnp.where(has_zero, jnp.uint32(0), jnp.uint32(1)))
    bits = jnp.stack(chosen[::-1])  # LSB-first
    return bits, cand


def max_mask(planes, filter_mask=None):
    """(reference fragment.max, fragment.go:827)."""
    m = planes[-1]
    if filter_mask is not None:
        m = jnp.bitwise_and(m, filter_mask)
    depth = planes.shape[0] - 1
    chosen = []
    cand = m
    for i in reversed(range(depth)):
        ones = jnp.bitwise_and(cand, planes[i])
        has_one = jnp.any(ones != 0)
        cand = jnp.where(has_one, ones, cand)
        chosen.append(jnp.where(has_one, jnp.uint32(1), jnp.uint32(0)))
    bits = jnp.stack(chosen[::-1])
    return bits, cand
