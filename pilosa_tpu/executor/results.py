"""Query result types.

Mirrors the reference result shapes (Row row.go:27, Pairs cache.go:305,
ValCount executor.go, GroupCount executor.go:1009) with one change: a Row
result keeps its per-shard device words until something asks for columns —
most pipelines (Count, sub-expressions) never materialize host columns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pilosa_tpu.ops.bitset import SHARD_WIDTH, unpack_positions


class RowResult:
    """A query-result bitmap partitioned by shard (reference Row/rowSegment,
    row.go:27,297)."""

    def __init__(self, shards: List[int], words):
        # words: device or numpy array [len(shards), WORDS_PER_SHARD]
        self.shards = list(shards)
        self.words = words
        self.attrs: Dict[str, Any] = {}
        self.keys: Optional[List[str]] = None
        self._columns: Optional[np.ndarray] = None

    # graftlint: materialize — columns() IS the device->host boundary:
    # callers ask for host column ids exactly once, and the fetch is
    # cached on the result.
    def columns(self) -> np.ndarray:
        if self._columns is not None:
            return self._columns
        # `words` may be a fusion handle (executor/fusion.FusedEval):
        # np.asarray resolves it against the fused batch output, one
        # shared transfer per fusion group.
        host = np.asarray(self.words)
        out = []
        for i, shard in enumerate(self.shards):
            pos = unpack_positions(host[i])
            if len(pos):
                out.append(pos + np.uint64(shard * SHARD_WIDTH))
        self._columns = (np.concatenate(out) if out
                         else np.empty(0, dtype=np.uint64))
        return self._columns

    def clear_columns(self) -> None:
        """Drop column data, keeping attrs (reference ExcludeColumns empties
        the row's segments, executor.go:532-534)."""
        self.words = np.zeros((len(self.shards),
                               self.words.shape[-1] if hasattr(
                                   self.words, "shape") else 0),
                              dtype=np.uint32)
        self._columns = np.empty(0, dtype=np.uint64)

    # graftlint: materialize — scalar count for response shaping; the
    # executor's fused Count path never routes through here.
    def count(self) -> int:
        from pilosa_tpu.ops.bitset import popcount
        import jax.numpy as jnp
        words = self.words
        dw = getattr(words, "device_words", None)
        if dw is not None:  # fusion handle: slice on device, no bounce
            words = dw()
        return int(np.asarray(popcount(jnp.asarray(words),
                                       axis=(-2, -1))))

    def to_json(self) -> dict:
        d = {"columns": self.columns().tolist()}
        if self.attrs:
            d["attrs"] = self.attrs
        if self.keys is not None:
            d["keys"] = self.keys
        return d


@dataclass
class PairsResult:
    """TopN result: (id, count) pairs sorted desc (reference Pairs)."""
    pairs: List[Tuple[int, int]]
    keys: Optional[List[str]] = None

    def to_json(self):
        if self.keys is not None:
            return [{"key": k, "count": int(c)}
                    for (r, c), k in zip(self.pairs, self.keys)]
        return [{"id": int(r), "count": int(c)} for r, c in self.pairs]


@dataclass
class ValCount:
    """Sum/Min/Max result (reference ValCount)."""
    value: int
    count: int

    def to_json(self):
        return {"value": int(self.value), "count": int(self.count)}


@dataclass
class RowIdentifiers:
    """Rows() result (reference RowIdentifiers)."""
    rows: List[int]
    keys: Optional[List[str]] = None

    def to_json(self):
        if self.keys is not None:
            return {"keys": self.keys}
        return {"rows": [int(r) for r in self.rows]}


@dataclass
class FieldRow:
    field: str
    row_id: int
    row_key: Optional[str] = None

    def to_json(self):
        d = {"field": self.field}
        if self.row_key is not None:
            d["rowKey"] = self.row_key
        else:
            d["rowID"] = int(self.row_id)
        return d


@dataclass
class GroupCount:
    """One GroupBy group (reference GroupCount, executor.go:1009)."""
    group: List[FieldRow]
    count: int

    def to_json(self):
        return {"group": [g.to_json() for g in self.group],
                "count": int(self.count)}


def result_to_json(result) -> Any:
    if hasattr(result, "to_json"):
        return result.to_json()
    if isinstance(result, list):
        return [result_to_json(r) for r in result]
    if isinstance(result, (bool, int, str, type(None))):
        return result
    if isinstance(result, np.integer):
        return int(result)
    raise TypeError(f"unserializable result {type(result)}")
