"""Executor leg of the heterogeneous megakernel (ops/megakernel.py).

FusionCollector.flush hands its signature groups here first: groups
whose staged evals lowered to megakernel IR are packed — across
DIFFERENT signatures — into one plan buffer and ONE compiled-program
launch per shard-count cohort; everything else (literal operands,
Shift, solo cohorts where the vmapped per-group program is already
optimal) flows back to the per-group fusion path untouched.

The launch stands UNDER the existing _FuseGroup plumbing: each taken
group's ``out`` becomes a _MegaView selecting its member lanes from
the launch's shared (counts, rows) outputs, so every FusedEval handle
already returned to result code resolves unchanged — one host fetch
per launch output, per-entry slices bit-identical to the unfused path
(tests/test_megakernel.py pins this op-by-op).

Mesh cohorts: when the executor carries a MeshContext the SAME plan
buffer dispatches once and runs SPMD over the mesh shard axis — banks
are already mesh-sharded (put_bank), plan buffers replicate, and the
collective epilogue (ops/megakernel.mesh_epilogue) finishes the
reduction in-kernel: count lanes psum to final ``[Nc]`` answers, row
lanes all-gather via replicated out_shardings. The jit-cache key gains
the mesh cache_key (device set + axis split change the partitioned
program), verify_plan runs with the MeshSpec (shard-axis agreement,
replica-axis no-op proof, collective lane typing), and d2h accounting
shrinks to the final answers — zero per-shard partials on the
Count/Sum reduce path.

Kill switch: PILOSA_TPU_MEGAKERNEL=0 restores per-group fusion
exactly. PILOSA_TPU_MESH=0 kills the mesh cohort path (per-group
fusion under the mesh, exactly the pre-mesh behavior).
PILOSA_TPU_MEGA_BYTES caps the launch's register-slab HBM footprint;
an over-budget cohort falls back rather than OOM.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pilosa_tpu.ops import megakernel as mk
from pilosa_tpu.utils.hotspots import WORKLOAD
from pilosa_tpu.utils.memledger import LEDGER
from pilosa_tpu.utils.roofline import ROOFLINE
from pilosa_tpu.utils.timeline import (
    LANE_DEVICE, LANE_DISPATCH, TIMELINE,
)

def _default_enabled() -> bool:
    """PILOSA_TPU_MEGAKERNEL: 1 forces on, 0 kills, default `auto` =
    on exactly when the backend is a TPU. The launch collapse pays
    where the per-launch floor is the bottleneck (tunnel RTT 22 µs–
    70 ms, docs/perf.md §5); on CPU an XLA launch costs ~20 µs while
    the interpreter's per-launch slab gather is real memcpy, so the
    per-group vmap path measured faster there (benches/
    mega_burst_bench.py: 300 vs 72 q/s mixed) — the same
    measured-tradeoff gating as the Pallas bank-sweep kernels."""
    flag = os.environ.get("PILOSA_TPU_MEGAKERNEL", "auto").strip().lower()
    if flag in ("1", "true", "yes", "on"):
        return True
    if flag in ("0", "false", "no", "off"):
        return False
    try:
        import jax
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# Evaluated once at first flush-time import (banks exist by then, so
# the backend is initialized); tests and benches toggle the module
# attribute directly, exactly like executor.FUSION_ENABLED.
MEGAKERNEL_ENABLED = _default_enabled()

# Register-slab HBM budget per launch: the interpreter materializes
# [T_pad, S, W] uint32 registers (gathered operand rows + scratch); a
# cohort whose slab would exceed this runs per-group instead.
MEGA_MAX_BYTES = int(os.environ.get("PILOSA_TPU_MEGA_BYTES", 1 << 30))


def _default_mesh_enabled() -> bool:
    """PILOSA_TPU_MESH: the mesh cohort path runs by default whenever
    the executor carries a MeshContext; 0 is the blunt kill switch
    that restores the pre-mesh behavior (per-group fusion under the
    mesh) — the bit-exactness lever the check.sh mesh smoke and the
    64-thread burst test flip."""
    flag = os.environ.get("PILOSA_TPU_MESH", "on").strip().lower()
    return flag not in ("0", "false", "no", "off")


# Module attribute like MEGAKERNEL_ENABLED: tests/benches toggle it
# directly; the env var sets the process default.
MESH_ENABLED = _default_mesh_enabled()


def _default_verify_mode() -> str:
    """PILOSA_TPU_PLAN_VERIFY: `on` checks every plan before launch,
    `off` disables the gate, default `auto` checks the first launch of
    each jit-cache key (every fresh capacity bucket / bank composition
    is verified once; steady-state repeats of a proven shape skip the
    host pass). tests/conftest.py and tools/check.sh pin `on`."""
    flag = os.environ.get("PILOSA_TPU_PLAN_VERIFY", "auto").strip().lower()
    if flag in ("1", "true", "yes", "on"):
        return "on"
    if flag in ("0", "false", "no", "off"):
        return "off"
    return "auto"


# Module attribute like MEGAKERNEL_ENABLED: tests and tools toggle it
# directly; the env var sets the process default.
PLAN_VERIFY_MODE = _default_verify_mode()


def _default_opt_enabled() -> bool:
    """PILOSA_TPU_PLAN_OPT: the cost-based plan optimizer
    (ops/plan_opt.py — cross-request CSE, density-ordered folds, DCE +
    register compaction, width narrowing) runs over every finished
    plan by default; 0 is the blunt kill switch that launches the raw
    Lowering output instead. The `[optimizer]` config section
    (utils/config.py, wired in cli/main.py) can also disable it, but
    never re-enables past this env var."""
    flag = os.environ.get("PILOSA_TPU_PLAN_OPT", "on").strip().lower()
    return flag not in ("0", "false", "no", "off")


# Module attribute, toggled directly by tests/benches like
# MEGAKERNEL_ENABLED; the env var sets the process default.
PLAN_OPT_ENABLED = _default_opt_enabled()


class _MegaView:
    """One group's window onto a launch's shared outputs. Satisfies
    exactly the slice of the device-array surface _FuseGroup/FusedEval
    resolution touches: ``[b]`` for device_words, ``np.asarray`` for
    the one shared host fetch, ``copy_to_host_async`` for prefetch."""

    __slots__ = ("launch", "mode", "lanes", "width")

    def __init__(self, launch: "_MegaLaunch", mode: str,
                 lanes: List[int], width: int) -> None:
        self.launch = launch
        self.mode = mode
        self.lanes = lanes
        self.width = width

    def _dev(self) -> Any:
        out = self.launch.out
        return out[0] if self.mode == "count" else out[1]

    def __getitem__(self, b: int) -> Any:
        lane = self.lanes[b]
        if self.mode == "count":
            return self._dev()[lane]
        return self._dev()[lane, :, :self.width]

    def lane_nbytes(self, b: int) -> int:
        """Host bytes ONE member's finalize moves — the d2h accounting
        seam FusedEval.nbytes delegates to. Shape metadata only, never
        a device sync. Under a mesh epilogue a count lane is a single
        reduced uint32 (counts are [Nc], not [Nc, S]) — the
        zero-host-bytes-on-the-reduce-path number the profiler's d2h
        assertion reads."""
        arr = self._dev()
        if self.mode == "count":
            return int(arr.nbytes) // max(1, int(arr.shape[0]))
        return int(arr.shape[-2]) * int(self.width) * 4

    # graftlint: materialize — the FusedEval.host convention: the
    # launch output fetches ONCE (cached on the launch) and every
    # group view slices the shared host copy.
    def __array__(self, dtype: Any = None, copy: Any = None) -> np.ndarray:
        host = self.launch.host(self.mode)
        out = host[self.lanes]
        if self.mode != "count":
            out = out[:, :, :self.width]
        return np.asarray(out, dtype=dtype) if dtype is not None else out

    def copy_to_host_async(self) -> None:
        fn = getattr(self._dev(), "copy_to_host_async", None)
        if fn is not None:
            fn()


class _MegaLaunch:
    """One dispatched plan-buffer program and its shared outputs."""

    __slots__ = ("out", "_host_counts", "_host_rows", "__weakref__")

    def __init__(self, out: Tuple[Any, Any]) -> None:
        self.out = out
        self._host_counts: Optional[np.ndarray] = None
        self._host_rows: Optional[np.ndarray] = None

    # graftlint: materialize — shared device->host boundary for the
    # whole launch (see _MegaView.__array__).
    def host(self, mode: str) -> np.ndarray:
        if mode == "count":
            if self._host_counts is None:
                self._host_counts = np.asarray(self.out[0])
            return self._host_counts
        if self._host_rows is None:
            self._host_rows = np.asarray(self.out[1])
        return self._host_rows


def _eligible(group: Any) -> bool:
    rep = group.entries[0]
    return rep.ir is not None and rep.mode in ("count", "row") \
        and rep.lits is None


def run_megakernel(executor: Any, groups: Dict[tuple, Any]
                   ) -> Dict[tuple, Any]:
    """Take what lowers, launch one program per shard-count cohort,
    return the groups the caller must still run per-group. Build
    failures fall back silently (results must never depend on the
    megakernel); failures after dispatch surface per member exactly
    like _FuseGroup errors."""
    if not MEGAKERNEL_ENABLED or (executor.mesh is not None
                                  and not MESH_ENABLED):
        return groups
    cohorts: Dict[int, List[Any]] = {}
    remaining: Dict[tuple, Any] = {}
    for key, group in groups.items():
        if group.entries and _eligible(group):
            cohorts.setdefault(group.entries[0].n_shards, []).append(group)
        else:
            remaining[key] = group
    for n_shards, cohort in cohorts.items():
        # A single-signature cohort already runs as one (vmapped)
        # launch — the interpreter buys nothing and loses the lane
        # parallelism, so only heterogeneous cohorts take this path.
        if len(cohort) < 2:
            for g in cohort:
                remaining[("solo", id(g))] = g
            continue
        try:
            plan, w_mega, lanes = _build(cohort)
        except Exception:
            # Lowering is best-effort by contract: any surprise means
            # the per-group path answers instead.
            for g in cohort:
                remaining[("fallback", id(g))] = g
            continue
        if mk.slab_nbytes(plan.n_regs, n_shards, w_mega) > MEGA_MAX_BYTES:
            for g in cohort:
                remaining[("budget", id(g))] = g
            continue
        _launch(executor, cohort, plan, n_shards, w_mega, lanes)
    return remaining


def _build(cohort: List[Any]) -> Tuple[mk.Plan, int, List[List[int]]]:
    """Lower every entry of every group into one plan; returns the
    plan, the launch word width, and per-group member lanes. The plan
    optimizer runs HERE — inside the build, before the verify gate —
    so every downstream consumer (the _launch verifier, the plan_fuzz
    capture hook, the telemetry) sees exactly the plan that will
    dispatch."""
    w_mega = max(e.width for g in cohort for e in g.entries)
    low = mk.Lowering()
    lanes: List[List[int]] = []
    for g in cohort:
        g_lanes = []
        for e in g.entries:
            g_lanes.append(low.add_entry(e.ir, e.bank_arrays, e.idxs,
                                         e.params, e.width, e.mode))
        lanes.append(g_lanes)
    plan = low.finish()
    if PLAN_OPT_ENABLED:
        try:
            from pilosa_tpu.ops import plan_opt
            plan, _stats = plan_opt.optimize_plan(
                plan, cohort[0].entries[0].n_shards, w_mega)
        except Exception:
            # Best-effort by contract: a surprised optimizer means the
            # raw Lowering plan launches, never a failed request.
            pass
    return plan, w_mega, lanes


def _launch(executor: Any, cohort: List[Any], plan: mk.Plan,
            n_shards: int, w_mega: int,
            lanes: List[List[int]]) -> None:
    import jax
    import jax.numpy as jnp

    ex = executor
    n_entries = sum(len(g.entries) for g in cohort)
    mesh = getattr(ex, "mesh", None)
    epi = spec = None
    try:
        key = plan.sig(n_shards, w_mega)
        if mesh is not None:
            # Mesh cohort: one plan buffer, every device slice. The
            # epilogue types one collective per real output lane and
            # the jit-cache key gains the mesh identity (device set /
            # axis split change the partitioned program) plus an
            # epilogue marker (the mesh program returns [Nc] counts,
            # not [Nc, S]).
            epi = mk.mesh_epilogue(plan, mesh.SHARD_AXIS)
            spec = mk.MeshSpec(mesh.SHARD_AXIS, mesh.REPLICA_AXIS,
                               mesh.n_shard_devices, mesh.replicas,
                               epi)
            key = f"{key}|{mesh.cache_key()}|epi"
        fn = ex._jit_get(key)
        jit_hit = fn is not None
        # Plan-IR verification gate: the checked-IR contract
        # (ops/megakernel.verify_plan) runs BEFORE anything is
        # uploaded or dispatched. `on` = every launch, `auto` = the
        # first launch per jit-cache key (a fresh compiled shape's
        # first plan is always checked). A reject raises here — it is
        # caught below and lands on the cohort's groups per member, so
        # a lowering bug surfaces as request errors, never as wrong
        # bits on device.
        if PLAN_VERIFY_MODE == "on" or (PLAN_VERIFY_MODE == "auto"
                                        and not jit_hit):
            try:
                mk.verify_plan(plan, n_shards, w_mega, mesh=spec)
            except mk.PlanVerifyError:
                ex._note_plan_verify(False)
                raise
            ex._note_plan_verify(True)
        if fn is None:
            ex._note_jit_compile()
            if mesh is not None:
                # GSPMD partitions the interpreter over the mesh-
                # sharded banks; the epilogue's count-lane sum over
                # the shard axis lowers to the psum, and replicated
                # out_shardings inserts the row lanes' all_gather.
                # The Pallas loop is single-device — the mesh path
                # always takes the jnp interpreter.
                fn = jax.jit(
                    mk.build_program(n_shards, w_mega, plan.n_regs,
                                     epilogue=epi),
                    out_shardings=(mesh.replicated(),
                                   mesh.replicated()))
            else:
                from pilosa_tpu.ops import pallas_kernels
                # The Pallas instruction loop predates OP_EXPAND; a
                # plan with sparse operands takes the jnp interpreter
                # (the expansion itself is a pre-loop scatter either
                # way).
                fn = jax.jit(mk.build_program(
                    n_shards, w_mega, plan.n_regs,
                    use_pallas=pallas_kernels.enabled()
                    and not plan.xslots))
            ex._jit_put(key, fn)
        # Plan buffers are per-launch data (the whole point: new mixed
        # composition, same compiled program) — upload them now and
        # charge the bytes as this launch's plan-buffer H2D. Sparse
        # banks (plan.xbanks) are already device-resident pairs; only
        # their slot lists upload. Under a mesh they land REPLICATED
        # (every device reads the same instruction stream) — a bare
        # asarray would commit them to one device and fight the
        # sharded banks inside the partitioned program.
        if mesh is None:
            _put = jnp.asarray
        else:
            def _put(a: Any) -> Any:
                return jax.device_put(np.asarray(a), mesh.replicated())
        slots_dev = tuple(_put(s) for s in plan.slots)
        widths_dev = _put(plan.widths)
        instrs_dev = _put(plan.instrs)
        out_count_dev = _put(plan.out_count)
        out_row_dev = _put(plan.out_row)
        xslots_dev = tuple(_put(s) for s in plan.xslots)
        plan_bytes = plan.plan_nbytes
        t0 = time.perf_counter()
        out = ex._call_program(fn, plan.banks, slots_dev, widths_dev,
                               instrs_dev, out_count_dev, out_row_dev,
                               plan.xbanks, xslots_dev)
        dispatch_s = time.perf_counter() - t0
    except Exception as e:
        for g in cohort:
            g.error = e
            g.entries, g.profs, g.nodes = [], [], []
        return
    launch = _MegaLaunch(out)
    # Launch cost attribution (the roofline plane): price the verified
    # IR's HBM traffic in host numpy — microseconds, no fences, and
    # best-effort by contract: a surprised cost model must never fail
    # a request that already has its results in flight.
    try:
        cost = mk.plan_cost(plan, n_shards, w_mega, mesh=spec)
    except Exception:
        cost = None
    # Cohort signature for the per-cohort bandwidth EWMAs: the capacity
    # buckets (not bank identity), so steady-state traffic of one shape
    # aggregates instead of fragmenting.
    ckey = (f"S{n_shards}|W{w_mega}|T{plan.n_regs}"
            f"|P{plan.instrs.shape[0]}")
    if cost is not None and ROOFLINE.enabled:
        if ROOFLINE.needs_resolve():
            try:
                from pilosa_tpu.utils.benchenv import resolve_roofline
                dev = jax.devices()[0]
                gbps, kind = resolve_roofline(dev)
                # A non-TPU backend has no TPU HBM roofline: label the
                # default clearly as an estimate, never a measurement.
                ROOFLINE.set_resolved(gbps, kind,
                                      dev.platform != "tpu")
            except Exception:
                pass
        opt = plan.opt_stats
        ROOFLINE.note_launch(
            ckey, cost,
            opt.predicted_bytes if opt is not None else None)
    try:
        for g, g_lanes in zip(cohort, lanes):
            rep = g.entries[0]
            g.out = _MegaView(launch, rep.mode, g_lanes, rep.width)
            g.batched = True
        # Ledger the launch's device residents: live bytes are the real
        # lanes' outputs; padding is the pow2 capacity slack in the slab,
        # instruction buffer and output lanes. Keyed on the launch object,
        # unregistered when the last member's response drops it.
        # Under a mesh epilogue a count lane's output is ONE reduced
        # uint32, not an [S] partial vector — the ledger's live bytes
        # track what the launch actually keeps resident.
        lane_bytes = sum(
            int(np.prod((1,) if mesh is not None
                        else (e.n_shards,)) if e.mode == "count"
                else np.prod((e.n_shards, e.width))) * 4
            for g in cohort for e in g.entries)
        slab = mk.slab_nbytes(plan.n_regs, n_shards, w_mega)
        live_slab = mk.slab_nbytes(plan.n_slots + plan.n_xslots,
                                   n_shards, w_mega)
        LEDGER.track(launch, "fusion_pad", lane_bytes,
                     padded_bytes=(slab - live_slab) + plan_bytes,
                     batch=n_entries, groups=len(cohort),
                     planEntries=plan.n_instrs)
        ex._note_mega(n_entries, plan.n_instrs, plan_bytes)
        if spec is not None:
            ex._note_mesh(spec.n_devices,
                          cost.get("collectiveBytes", 0)
                          if cost is not None else 0)
        if cost is not None:
            ex._note_launch_cost(cost)
        if plan.opt_stats is not None:
            ex._note_opt(plan.opt_stats)
        _attribute(ex, cohort, launch, jit_hit, t0, dispatch_s, plan,
                   plan_bytes, n_entries, cost, ckey)
    except Exception as e:
        # Per-member error isolation, the _FuseGroup.run contract: an
        # async device failure surfacing here (e.g. the sampled
        # _fence_device inside _attribute) lands on THIS cohort's
        # groups — FusedEval._out checks `error` before `out`, so the
        # already-assigned views never serve — and batchmates in other
        # cohorts/groups are unharmed.
        for g in cohort:
            g.error = e
    finally:
        for g in cohort:
            g.entries, g.profs, g.nodes = [], [], []


def _attribute(ex: Any, cohort: List[Any], launch: _MegaLaunch,
               jit_hit: bool, t_disp: float, dispatch_s: float,
               plan: mk.Plan, plan_bytes: int, n_entries: int,
               cost: Optional[Dict[str, Any]] = None,
               ckey: str = "") -> None:
    """Profiler/timeline attribution, the _FuseGroup._attribute
    convention: the program ran once for the whole launch, so every
    member sees the shared dispatch (and sampled device) time labeled
    with its launch coordinates. When a sampled fence fires, the cost
    vector joins the measured device time into the roofline plane —
    achieved GB/s rides EXISTING fences only; the unsampled path adds
    none (pinned by tests/test_roofline.py)."""
    fence_profs: List[Tuple[Any, Any]] = []
    opt = plan.opt_stats
    mega_index = 0
    for g in cohort:
        for prof, node in zip(g.profs, g.nodes):
            b = mega_index
            mega_index += 1
            if prof is None or node is None:
                continue
            prof.tree_jit(node, jit_hit)
            prof.tree_h2d(node, plan_bytes // max(1, n_entries))
            prof.tree_dispatch(node, dispatch_s)
            node.attrs["megaBatch"] = n_entries
            node.attrs["megaIndex"] = b
            node.attrs["planEntries"] = plan.n_instrs
            node.attrs["planBytes"] = plan_bytes
            if cost is not None:
                # The cost vector rides the slow-query ring: a
                # post-mortem profile shows what the launch MOVED, not
                # just how long it took.
                node.attrs["launchBytes"] = cost["totalBytes"]
                node.attrs["opcodeHist"] = dict(cost["opcodeHist"])
                if "collectiveBytes" in cost:
                    # Mesh launch: which mesh carried it and what the
                    # collectives moved over ICI — the per-chip HBM
                    # share is deviceBytes in the same vector.
                    node.attrs["meshDevices"] = cost["meshDevices"]
                    node.attrs["collectiveBytes"] = \
                        cost["collectiveBytes"]
            if opt is not None:
                # The optimizer's before/after so a profile reader can
                # attribute the reduction without the /metrics deltas.
                node.attrs["planEntriesBefore"] = opt.entries_before
                node.attrs["planEntriesAfter"] = opt.entries_after
            prof.set_fused(n_entries)
            if prof.timeline is not None:
                extra = {}
                if opt is not None:
                    extra = dict(planEntriesBefore=opt.entries_before,
                                 planEntriesAfter=opt.entries_after)
                TIMELINE.event(prof.timeline, "dispatch", LANE_DISPATCH,
                               t_disp, dispatch_s, megaBatch=n_entries,
                               megaIndex=b, planEntries=plan.n_instrs,
                               planBytes=plan_bytes, **extra)
            if prof.sample_device:
                fence_profs.append((prof, node))
    device_s = 0.0
    if fence_profs:
        from pilosa_tpu.executor.executor import _fence_device
        t_dev = time.perf_counter()
        device_s = _fence_device(launch.out)
        for prof, node in fence_profs:
            prof.tree_device(node, device_s)
            if prof.timeline is not None:
                TIMELINE.event(prof.timeline, "device", LANE_DEVICE,
                               t_dev, device_s, megaBatch=n_entries)
        if cost is not None:
            # Bytes ÷ the fence we already paid = achieved bandwidth:
            # per-cohort EWMA + drift detection in the recorder, and a
            # ph:"C" counter sample for the timeline export.
            bw = ROOFLINE.note_device(ckey, cost["totalBytes"],
                                      device_s)
            if bw is not None:
                TIMELINE.note_bandwidth(bw["bytesPerS"], bw["frac"])
    # Cache-opportunity attribution AFTER the (sampled) fence — the
    # per-entry share of one launch, same cost basis as the fused and
    # unfused paths.
    per_eval = (dispatch_s + device_s) / max(1, n_entries)
    for g in cohort:
        for e in g.entries:
            if e.fp is not None:
                WORKLOAD.note_eval_seconds(e.fp, per_eval)
