"""Query execution layer.

Reference: /root/reference/executor.go. The per-shard goroutine kernels
(executeIntersectShard etc., executor.go:1487-1887) become batched device
expressions over a stacked [shards, words] axis; PQL call trees jit-compile
once per tree shape and are cached (the Go->TPU "executor" the north star
asks for). Cross-shard reduce happens in the same compiled program.
"""

from pilosa_tpu.executor.executor import Executor  # noqa: F401
