"""Same-signature query fusion: one XLA dispatch for N batched queries.

The dominant serving shape is a flood of structurally identical
1-ms-class queries — ``Count(Row(user=X))`` for a million different X.
The coalescer (server/coalescer.py) already lands them in one
``Executor.execute_batch``, and read-dedup collapses *equal* queries,
but each remaining *similar* query still paid its own host dispatch:
plan + ``fn(...)`` enqueue, which the PR 3 profiler shows dwarfing the
fenced device time for small trees. The roaring line of work (Chambi
et al., arXiv:1402.6407) wins by amortizing per-op overhead across
batched bitmap operations; this module is the dispatch-level analog.

A compiled tree program is fully parameterized by its traced operand
vectors (``idxs``, ``params``, ``lits``) under a shape signature
``sig`` (Executor._stage_tree). So N staged evals with the same
``(sig, bank identity)`` — same tree shape over the same device banks,
different row ids / BSI predicates / literals — can stack their
operand vectors along a new leading batch axis and run through ONE
jitted ``vmap`` of the representative's program, returning ``[B, S]``
counts or ``[B, S, W]`` row words that finalize slices per query.
Bitwise ops and popcounts are deterministic elementwise/reduce
kernels, so per-query results are bit-identical to the unfused path.

Batch sizes pad up to a power of two (repeating the first entry's
operands) so the compile cache holds O(log B) fused variants per
signature instead of one per batch size; the pad lanes are sliced off
before any result is read.

Write fencing is the collector's caller's job: ``execute_batch``
flushes the collector before dispatching any write-containing request
and dispatches that request uncollected, so no read fuses across a
write that orders between them (tests/test_fusion.py pins this).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pilosa_tpu.utils.hotspots import WORKLOAD
from pilosa_tpu.utils.memledger import LEDGER
from pilosa_tpu.utils.roofline import ROOFLINE
from pilosa_tpu.utils.timeline import (
    LANE_DEVICE, LANE_DISPATCH, LANE_PLAN, TIMELINE,
)


class FusedEval:
    """One query's slice of a fusion group's output. Stands in for the
    device array ``_eval_tree`` would have returned: ``np.asarray``
    resolves it (sharing ONE device->host fetch across the whole
    group), ``copy_to_host_async``/``nbytes`` make it a valid
    ``_Pending.arrays`` entry, and ``device_words()`` hands consumers
    that want to stay on device the sliced jax array."""

    __slots__ = ("group", "b", "shape", "slice_nbytes")

    def __init__(self, group: "_FuseGroup", b: int,
                 shape: Tuple[int, ...]) -> None:
        self.group = group
        self.b = b
        self.shape = shape  # per-query output shape ([S] or [S, W])
        self.slice_nbytes = int(np.prod(shape)) * 4

    @property
    def nbytes(self) -> int:
        # A megakernel _MegaView knows the REAL per-lane host bytes —
        # under a mesh epilogue a count lane is one reduced uint32,
        # not the [S] partial vector the stage-time shape assumed.
        # Asking the resolved output keeps the profiler's d2h
        # accounting honest without this handle knowing launch kinds.
        out = self.group.out
        fn = getattr(out, "lane_nbytes", None)
        if fn is not None:
            return int(fn(self.b))
        return self.slice_nbytes

    def _out(self) -> Any:
        g = self.group
        if g.error is not None:
            raise g.error
        if g.out is None:
            # Resolution before the batch's flush point means a staged
            # eval leaked outside execute_batch's dispatch/flush
            # bracket — run the group now rather than deadlock.
            g.run()
            if g.error is not None:
                raise g.error
        return g.out

    def device_words(self) -> Any:
        """This query's output as a device array (one slice op)."""
        out = self._out()
        return out[self.b] if self.group.batched else out

    # graftlint: materialize — FusedEval.host IS the device->host
    # boundary for fused results: the group's [B, ...] output fetches
    # once and every member slices the cached host copy.
    def host(self) -> np.ndarray:
        g = self.group
        out = self._out()
        if g.host is None:
            g.host = np.asarray(out)
        return g.host[self.b] if g.batched else g.host

    def __array__(self, dtype: Any = None, copy: Any = None) -> np.ndarray:
        a = self.host()
        return np.asarray(a, dtype=dtype) if dtype is not None else a

    def copy_to_host_async(self) -> None:
        """Start the group's (single, shared) async device->host copy
        (prefetch_pendings calls this per _Pending array)."""
        fn = getattr(self._out(), "copy_to_host_async", None)
        if fn is not None:
            fn()


class _FuseGroup:
    """All staged evals sharing one (sig, bank identity) key, plus the
    profiling contexts captured when each was staged."""

    __slots__ = ("executor", "entries", "profs", "nodes", "out", "host",
                 "batched", "error", "__weakref__")

    def __init__(self, executor: Any) -> None:
        self.executor = executor
        self.entries: List[Any] = []      # _StagedEval, batch order
        self.profs: List[Any] = []        # QueryProfile or None
        self.nodes: List[Any] = []        # ProfileNode or None
        self.out = None                   # [B, ...] (or [...] solo)
        self.host: Optional[np.ndarray] = None
        self.batched = False
        self.error: Optional[Exception] = None

    def add(self, staged: Any, prof: Any, t_plan0: float) -> FusedEval:
        node = None
        if prof is not None:
            # jit hit/miss is unknown until the group compiles at
            # flush; tree_jit fills it in then. The stacked operand
            # upload is likewise charged at flush via tree_h2d.
            plan_s = time.perf_counter() - t_plan0
            node = prof.tree(staged.mode, staged.sig, None, plan_s, 0,
                             staged.n_shards)
            if prof.timeline is not None:
                TIMELINE.event(prof.timeline, "plan", LANE_PLAN,
                               t_plan0, plan_s, fused=True)
        b = len(self.entries)
        self.entries.append(staged)
        self.profs.append(prof)
        self.nodes.append(node)
        shape = ((staged.n_shards,) if staged.mode == "count"
                 else (staged.n_shards, staged.width))
        return FusedEval(self, b, shape)

    def run(self) -> None:
        """Compile (cached) + dispatch the group's single program and
        attribute it back to every member's profile. Never raises: a
        failure lands on `error` and surfaces per member when its
        request finalizes — batchmates in other groups are unharmed."""
        if self.out is not None or self.error is not None:
            return
        try:
            self._run()
        except Exception as e:
            self.error = e
        finally:
            # Resolution needs only out/host/batched/error, but every
            # result holds FusedEval -> group until its response is
            # shaped — drop the staged closure graph (exprs capture
            # plan objects and bank arrays) as soon as the program is
            # in flight.
            self.entries = []
            self.profs = []
            self.nodes = []

    def _run(self) -> None:
        import jax
        import jax.numpy as jnp

        ex = self.executor
        B = len(self.entries)
        rep = self.entries[0]
        if B == 1:
            # Solo group: the exact unfused path (same program, same
            # arg cache) so a lone query costs nothing extra.
            fn, jit_hit = ex._tree_fn(rep)
            idxs, params, uploaded = ex._staged_args(rep)
            h2d = ((idxs.nbytes + params.nbytes) if uploaded else 0) \
                + (rep.lits.nbytes if rep.lits is not None else 0)
            t0 = time.perf_counter()
            self.out = ex._call_program(fn, rep.bank_arrays, idxs,
                                        params, rep.lits)
            self._attribute(jit_hit, t0, time.perf_counter() - t0, h2d,
                            fused=False)
            return
        # Pad to the next power of two with the first entry's operands
        # so distinct batch sizes share O(log B) compiled variants.
        bp = 1 << (B - 1).bit_length()
        rows = self.entries + [rep] * (bp - B)
        key = f"fused{bp}|{rep.sig}"

        def build():
            # graftlint: disable=GL003 — host-list marshalling for the
            # stacked operand upload (the device transfer is
            # jnp.asarray).
            i = jnp.asarray(np.asarray([e.idxs for e in rows],
                                       np.int32))
            # graftlint: disable=GL003 — host-list upload, as above.
            p = jnp.asarray(np.asarray([e.params for e in rows],
                                       np.uint32))
            return i, p

        # Repeated batch compositions (dashboards, hot row sets) hit
        # the same LRU arg cache the solo path uses and skip both
        # stacked uploads.
        akey = (key, tuple(tuple(e.idxs) for e in rows),
                tuple(tuple(e.params) for e in rows))
        (idxs, params), uploaded = ex._cached_args(akey, build)
        lits = None
        if rep.lits is not None:
            lits = jnp.stack([e.lits for e in rows])
        fn = ex._jit_get(key)
        jit_hit = fn is not None
        if fn is None:
            ex._note_jit_compile()
            in_axes = (None, 0, 0, 0 if rep.lits is not None else None)
            fn = jax.jit(jax.vmap(rep.runner(), in_axes=in_axes))
            ex._jit_put(key, fn)
        t0 = time.perf_counter()
        out = ex._call_program(fn, rep.bank_arrays, idxs, params, lits)
        dispatch_s = time.perf_counter() - t0
        if bp != B:
            out = out[:B]  # drop pad lanes before anything reads them
        self.out = out
        self.batched = True
        # Ledger the group's device output: B live lanes plus the
        # pow2 pad lanes (output + stacked operands) as padding bytes.
        # Keyed on the group object, so the entry unregisters when the
        # last member's response is shaped and the group is collected.
        lane = (int(np.prod((rep.n_shards,) if rep.mode == "count"
                            else (rep.n_shards, rep.width))) * 4)
        pad = (bp - B) * lane \
            + (idxs.nbytes + params.nbytes) * (bp - B) // bp
        LEDGER.track(self, "fusion_pad", B * lane, padded_bytes=pad,
                     batch=B, padTo=bp, sig=str(rep.sig)[:120])
        ex._note_fused(B)
        # Whole stacked upload (pad lanes included) spread over the B
        # real members, so the per-query sum equals the real traffic.
        h2d = ((idxs.nbytes + params.nbytes) // B if uploaded else 0) \
            + (rep.lits.nbytes if rep.lits is not None else 0)
        self._attribute(jit_hit, t0, dispatch_s, h2d, fused=True)

    def _attribute(self, jit_hit: bool, t_disp: float, dispatch_s: float,
                   h2d: int, fused: bool) -> None:
        B = len(self.entries)
        fence_profs = []
        for b, (prof, node) in enumerate(zip(self.profs, self.nodes)):
            if prof is None or node is None:
                continue
            prof.tree_jit(node, jit_hit)
            prof.tree_h2d(node, h2d)
            # The program ran once for the whole group: every member
            # sees the group's dispatch time, labeled with its batch
            # coordinates so readers know the cost is shared.
            prof.tree_dispatch(node, dispatch_s)
            if fused:
                node.attrs["fusedBatch"] = B
                node.attrs["batchIndex"] = b
                prof.set_fused(B)
            if prof.timeline is not None:
                # The shared group dispatch, stamped into every
                # member's timeline with its batch coordinates (same
                # convention as the profile tree).
                TIMELINE.event(prof.timeline, "dispatch", LANE_DISPATCH,
                               t_disp, dispatch_s,
                               **({"fusedBatch": B, "batchIndex": b}
                                  if fused else {}))
            if prof.sample_device:
                fence_profs.append((prof, node))
        device_s = 0.0
        if fence_profs:
            from pilosa_tpu.executor.executor import _fence_device
            t_dev = time.perf_counter()
            device_s = _fence_device(self.out)
            for prof, node in fence_profs:
                prof.tree_device(node, device_s)
                if prof.timeline is not None:
                    TIMELINE.event(prof.timeline, "device", LANE_DEVICE,
                                   t_dev, device_s,
                                   **({"fusedBatch": B} if fused else {}))
            # No plan IR on this path, so no byte attribution: count
            # the fenced time as unattributed so /debug/roofline
            # states how much sampled device time its bytes explain.
            ROOFLINE.note_unattributed_fence(device_s)
        # Cache-opportunity attribution AFTER the (sampled) fence so
        # fused evals report the same dispatch + device cost basis as
        # the unfused path (_run_staged) — one fused dispatch covered
        # B queries, so each member's eval cost its share.
        per_eval = (dispatch_s + device_s) / max(1, B)
        for e in self.entries:
            if e.fp is not None:
                WORKLOAD.note_eval_seconds(e.fp, per_eval)


class FusionCollector:
    """Per-batch registry of staged terminal evals, grouped by fusion
    key. Installed thread-locally by execute_batch (Executor._fusing);
    `flush()` runs every open group — called before a write-containing
    request dispatches (the fence) and once after the dispatch loop."""

    def __init__(self, executor: Any) -> None:
        self.executor = executor
        self.groups: Dict[tuple, _FuseGroup] = {}

    def add(self, staged: Any, prof: Any, t_plan0: float) -> FusedEval:
        """Stage one eval; returns its FusedEval handle. Grouping is
        by (sig, bank-array identity): the signature equates tree
        shape, widths and shard count, and identity equates the actual
        device operands — a write between two stages rebuilds the bank
        and so splits them even without an explicit fence."""
        key = (staged.sig, tuple(id(a) for a in staged.bank_arrays))
        group = self.groups.get(key)
        if group is None:
            group = self.groups[key] = _FuseGroup(self.executor)
        return group.add(staged, prof, t_plan0)

    def flush(self) -> None:
        groups, self.groups = self.groups, {}
        if not groups:
            return
        if len(groups) > 1:
            # Heterogeneous flush: groups whose staged evals lowered
            # to megakernel IR pack — across signatures — into ONE
            # plan-buffer launch per shard-count cohort
            # (executor/megakernel.py); the rest run per-group below.
            from pilosa_tpu.executor.megakernel import run_megakernel
            groups = run_megakernel(self.executor, groups)
        for group in groups.values():
            group.run()
