"""`python -m pilosa_tpu.cli` — the pilosa-tpu command.

Reference command set (cmd/root.go:40-48): server, import, export, check,
inspect, config, generate-config. Implementations mirror ctl/*.go:
import = bulk CSV loader (ctl/import.go), check = roaring file integrity
(ctl/check.go), inspect = container stats (ctl/inspect.go).
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import signal
import sys

import numpy as np


def drain_telemetry(api, watchdog=None, logger=None) -> None:
    """The telemetry leg of the SIGTERM drain: stop the memory
    watchdog and dump its flight-recorder ring, dump the profiler's
    slow-query ring, and stop the tracer (ExportingTracer.stop joins
    the exporter thread and performs the final flush) — so a graceful
    shutdown never discards buffered telemetry. Factored out of
    cmd_server's finally block so tests can drive it directly with a
    simulated drain."""
    # Re-entrancy guard: the drain runs once per API lifetime. A signal
    # racing the finally block (or a test calling twice) must not dump
    # every ring a second time into the post-mortem log.
    if getattr(api, "_telemetry_drained", False):
        return
    api._telemetry_drained = True
    if watchdog is not None:
        watchdog.stop()
        watchdog.dump(logger)
    profiler = getattr(api, "profiler", None)
    if profiler is not None:
        profiler.dump(logger)
    # Workload recorder: log what was hot (fragments, cacheable
    # signatures, repeat ratio) so post-mortems see the access shape
    # the process served, not just its cost counters.
    from pilosa_tpu.utils.hotspots import WORKLOAD
    if WORKLOAD.enabled:
        WORKLOAD.dump(logger)
    # Timeline plane: the last request timelines + the idle ratio the
    # process died with (utils/timeline.py).
    from pilosa_tpu.utils.timeline import TIMELINE
    if TIMELINE.enabled:
        TIMELINE.dump(logger)
    # Roofline plane: achieved-bandwidth EWMAs and predicted-vs-
    # measured residuals (utils/roofline.py) — the calibration state a
    # post-mortem needs to judge the optimizer's cost model.
    from pilosa_tpu.utils.roofline import ROOFLINE
    if ROOFLINE.enabled:
        ROOFLINE.dump(logger)
    # SLO sentinel (utils/sentinel.py): the budget verdict per
    # objective + the last alert fire/clear events — whether the
    # process died inside or outside its objectives.
    from pilosa_tpu.utils.sentinel import SENTINEL
    if SENTINEL.enabled:
        SENTINEL.dump(logger)
    tracer = getattr(api, "tracer", None)
    if tracer is not None:
        # The finished-span ring leaves evidence even when no exporter
        # is configured (RecordingTracer.dump); exporters then flush.
        if hasattr(tracer, "dump"):
            tracer.dump(logger)
        if hasattr(tracer, "stop"):
            tracer.stop()  # final flush of pending spans
        elif hasattr(tracer, "flush"):
            tracer.flush()


def cmd_server(args) -> int:
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.server import API, serve
    from pilosa_tpu.utils.config import load_config
    from pilosa_tpu.utils.logger import Logger
    from pilosa_tpu.utils.stats import MemStatsClient, NopStatsClient
    from pilosa_tpu.utils.tracing import RecordingTracer

    cfg = load_config(args.config, {
        "data_dir": args.data_dir, "bind": args.bind,
        "verbose": args.verbose or None,
        "platform": getattr(args, "platform", None),
        "coalescer_enabled": (False if getattr(args, "no_coalescer",
                                               False) else None),
        "coalescer_window_ms": getattr(args, "coalescer_window_ms",
                                       None),
    })
    if cfg.platform:
        # Must land before the first jax device touch. jax.config (not
        # the env var) because the axon sitecustomize hook force-selects
        # its platform through jax.config, overriding JAX_PLATFORMS.
        import jax
        jax.config.update("jax_platforms", cfg.platform)
    if cfg.jax_coordinator and cfg.jax_num_processes > 1:
        # Multi-host SPMD: after initialize, jax.devices() is global
        # across hosts and the shard mesh spans the whole pod slice
        # (collectives ride ICI within a slice, DCN across; survey §7.6).
        import jax
        jax.distributed.initialize(
            coordinator_address=cfg.jax_coordinator,
            num_processes=cfg.jax_num_processes,
            process_id=(cfg.jax_process_id
                        if cfg.jax_process_id >= 0 else None))
    logger = Logger(verbose=cfg.verbose)
    data_dir = os.path.expanduser(cfg.data_dir)
    holder = Holder(data_dir)
    holder.open()

    mesh = None
    if cfg.mesh_devices != 1:
        import jax
        devices = jax.devices()
        n = cfg.mesh_devices or len(devices)
        if n > 1 or cfg.mesh_replicas > 1:
            from pilosa_tpu.parallel import MeshContext
            mesh = MeshContext(devices[:n], replicas=cfg.mesh_replicas)

    cluster = None
    if cfg.cluster_peers or cfg.cluster_seeds:
        from pilosa_tpu.parallel.cluster import (
            Cluster, Node, STATE_NORMAL,
        )
        local_uri = cfg.advertise or f"{cfg.scheme}://{cfg.bind}"
        # Static peer lists name nodes by URI on every member, so the id
        # must BE the URI there. Seed-joined nodes introduce themselves
        # (the topology replicates their node record), so they use the
        # holder's persisted `.id` — a restart on a new address then
        # rejoins as the SAME member instead of ghosting its old entry.
        local_id = local_uri if cfg.cluster_peers else holder.node_id
        cluster = Cluster(
            Node(local_id, local_uri,
                 is_coordinator=bool(
                     cfg.cluster_peers
                     and local_uri == sorted(cfg.cluster_peers)[0])),
            replica_n=cfg.cluster_replicas,
            topology_path=os.path.join(data_dir, ".topology"))
        for peer in cfg.cluster_peers:
            if peer != local_uri:
                cluster.add_node(Node(peer, peer))
        # Re-adopt dynamically-joined nodes from the persisted topology
        # (reference loads .topology at startup, cluster.go:1611).
        cluster.load()
        cluster.set_state(STATE_NORMAL)

    if cfg.metric_service == "mem":
        stats = MemStatsClient()
    elif cfg.metric_service == "statsd":
        # Mem rides along so /debug/vars keeps working (the reference's
        # multi-client, stats/stats.go:164).
        from pilosa_tpu.utils.stats import (
            MultiStatsClient, StatsdStatsClient,
        )
        stats = MultiStatsClient(
            MemStatsClient(),
            StatsdStatsClient(cfg.metric_host, logger=logger))
    else:
        stats = NopStatsClient()
    if cfg.tracing_endpoint:
        from pilosa_tpu.utils.tracing import ExportingTracer
        tracer = ExportingTracer(cfg.tracing_endpoint,
                                 service_name=cfg.tracing_service_name,
                                 logger=logger,
                                 sampler_type=cfg.tracing_sampler_type,
                                 sampler_param=cfg.tracing_sampler_param)
        tracer.start()
    else:
        tracer = RecordingTracer()
    api = API(holder, mesh=mesh, cluster=cluster, stats=stats,
              tracer=tracer, client_ssl_context=cfg.client_ssl_context())
    api.logger = logger
    api.long_query_time = cfg.long_query_time
    api.executor.max_writes_per_request = cfg.max_writes_per_request
    # Fan-out resilience ([cluster] keys): per-request deadline budget,
    # failover backoff, hedged reads, and the three RPC-timeout classes
    # that used to be hard-coded client literals.
    if api.cluster_executor is not None:
        api.cluster_executor.configure(
            fanout_deadline_s=cfg.cluster_fanout_deadline_s,
            backoff_base_s=cfg.cluster_backoff_base_s,
            backoff_cap_s=cfg.cluster_backoff_cap_s,
            hedge_quantile=cfg.cluster_hedge_quantile)
        api._client.configure(
            timeout=cfg.cluster_rpc_timeout_s,
            health_timeout=cfg.cluster_health_timeout_s,
            resize_pull_timeout=cfg.cluster_resize_pull_timeout_s)
    # Fault-injection plane (utils/failpoints.py): arm configured
    # sites and enable the test-only /internal/failpoints surface.
    # Env entries were already merged into cfg.failpoints by
    # load_config (env="" skips a second parse). Production servers
    # with no failpoint config never enable any of this.
    if cfg.failpoints:
        from pilosa_tpu.utils.failpoints import FAILPOINTS
        FAILPOINTS.configure(cfg.failpoints, env="")
        FAILPOINTS.http_enabled = True
        logger.printf("failpoints ARMED (test-only surface enabled): %s",
                      ", ".join(f"{k}={v}"
                                for k, v in sorted(cfg.failpoints.items())))
    elif os.environ.get("PILOSA_TPU_FAILPOINTS_HTTP", "") in ("1", "true"):
        # Chaos harnesses that arm everything over HTTP at runtime
        # (tools/chaos.py) enable the surface without arming anything.
        from pilosa_tpu.utils.failpoints import FAILPOINTS
        FAILPOINTS.http_enabled = True
        logger.printf("failpoints surface enabled (nothing armed)")
    # Query profiler policy: device-fence 1-in-N unforced queries and
    # bound the /debug/queries slow-query ring (utils/profile.py;
    # ?profile=true always fences regardless of sample_every).
    api.profiler.configure(sample_every=cfg.profile_sample_every,
                           ring_size=cfg.profile_slow_ring)
    # Workload analytics plane (utils/hotspots.py): the process-wide
    # recorder picks up the [workload] config — decay half-life,
    # rolling repeat window, top-K, LRU bounds, kill switch.
    from pilosa_tpu.utils.hotspots import WORKLOAD
    WORKLOAD.configure(enabled=cfg.workload_enabled,
                       half_life_s=cfg.workload_half_life_s,
                       window_s=cfg.workload_window_s,
                       top_k=cfg.workload_top_k,
                       max_fragments=cfg.workload_max_fragments,
                       max_rows=cfg.workload_max_rows,
                       max_signatures=cfg.workload_max_signatures)
    # Request-lifecycle timeline plane (utils/timeline.py): per-request
    # stage timelines at GET /debug/timeline + the dispatch-gap idle
    # ratio on /metrics. [timeline] enabled=false is the kill switch.
    from pilosa_tpu.utils.timeline import TIMELINE
    TIMELINE.configure(enabled=cfg.timeline_enabled,
                       ring=cfg.timeline_ring,
                       sample_every=cfg.timeline_sample_every,
                       gap_window_s=cfg.timeline_gap_window_s)
    # Roofline attribution plane ([roofline] section, utils/roofline):
    # per-launch bytes joined with the profiler's sampled fences into
    # achieved GB/s at GET /debug/roofline. gbps = 0 auto-resolves
    # from the device kind at first launch.
    from pilosa_tpu.utils.roofline import ROOFLINE
    ROOFLINE.configure(enabled=cfg.roofline_enabled,
                       gbps=cfg.roofline_gbps,
                       ewma_alpha=cfg.roofline_ewma_alpha,
                       max_cohorts=cfg.roofline_max_cohorts)
    # SLO & regression sentinel ([sentinel]/[slo] sections,
    # utils/sentinel.py): bounded metrics history + burn-rate alerts,
    # sampled from the watchdog's extra-gauges hook below. The HBM
    # pressure condition shares the watchdog's watermark.
    from pilosa_tpu.core.view import BANK_BUDGET as _SENT_BUDGET
    from pilosa_tpu.utils.sentinel import SENTINEL
    SENTINEL.configure(enabled=cfg.sentinel_enabled,
                       ring=cfg.sentinel_ring,
                       decimate=cfg.sentinel_decimate,
                       alert_ring=cfg.sentinel_alert_ring,
                       objectives=cfg.slo,
                       watermark_bytes=int(
                           _SENT_BUDGET.budget
                           * cfg.telemetry_hbm_watermark))
    if cfg.slo:
        logger.printf("slo objectives: %s",
                      ", ".join(f"{k}: {v}"
                                for k, v in sorted(cfg.slo.items())))
    # Cross-request cache tier ([cache] section): the generation-keyed
    # result cache lives on the executor, the device rank-cache store
    # is process-wide. The PILOSA_TPU_RESULT_CACHE=0 /
    # PILOSA_TPU_RANK_CACHE=0 env kill switches always win inside
    # configure().
    from pilosa_tpu.core.cache import RANK_CACHE
    api.executor.result_cache.configure(
        enabled=cfg.cache_result_enabled,
        max_bytes=cfg.cache_result_max_bytes)
    RANK_CACHE.configure(enabled=cfg.cache_rank_enabled,
                         max_entries=cfg.cache_rank_max_entries)
    # Plan optimizer ([optimizer] section): the env kill switch
    # PILOSA_TPU_PLAN_OPT=0 always wins — config can disable the
    # optimizer, never re-enable it past the blunt switch.
    from pilosa_tpu.executor import megakernel as _megamod
    if not cfg.optimizer_enabled:
        _megamod.PLAN_OPT_ENABLED = False
    # Mesh collective path ([mesh] collectives): same one-way rule —
    # config can disable the mesh cohort launches (per-group fusion
    # under the mesh, the pre-mesh behavior), never re-enable past
    # the PILOSA_TPU_MESH=0 kill switch.
    if not cfg.mesh_collectives:
        _megamod.MESH_ENABLED = False
    coalescer = None
    if cfg.coalescer_enabled:
        # Cross-request query coalescer: concurrent single-query POSTs
        # share one executor batch (server/coalescer.py). On cluster
        # deployments the API routes around it, so attaching is safe
        # either way.
        from pilosa_tpu.server.coalescer import QueryCoalescer
        coalescer = QueryCoalescer(
            api.executor,
            window_s=cfg.coalescer_window_ms / 1e3,
            max_batch=cfg.coalescer_max_batch,
            max_queue=cfg.coalescer_max_queue,
            deadline_s=cfg.coalescer_deadline_ms / 1e3,
            stats=stats, tracer=tracer, logger=logger,
            pipeline=cfg.coalescer_pipeline)
        coalescer.start()
        api.coalescer = coalescer
    watchdog = None
    if cfg.telemetry_sample_every_s > 0:
        # Always-on memory/health watchdog (utils/memledger.py): ledger
        # + queue gauges sampled into a flight-recorder ring; pressure
        # warnings when device bytes cross the HBM watermark. Host-side
        # only — zero device fences, so it rides under any load.
        from pilosa_tpu.core.view import BANK_BUDGET
        from pilosa_tpu.utils.memledger import LEDGER, MemoryWatchdog

        def _telemetry_gauges():
            # The sentinel samples its history rings at the watchdog
            # cadence (gauges must never kill the watchdog — the
            # sample_once wrapper already swallows, but the queue
            # gauges below must survive a sentinel bug too).
            try:
                api.sample_sentinel()
            except Exception:
                pass
            coal = api.coalescer
            return {
                "queueDepth": (coal.queue_depth()
                               if coal is not None else 0),
                "jitCacheSize": api.executor.jit_cache_size(),
            }

        watchdog = MemoryWatchdog(
            LEDGER, stats=stats, logger=logger,
            sample_every_s=cfg.telemetry_sample_every_s,
            ring=cfg.telemetry_ring,
            watermark_bytes=int(BANK_BUDGET.budget
                                * cfg.telemetry_hbm_watermark),
            extra_gauges=_telemetry_gauges)
        watchdog.start()
        api.watchdog = watchdog
    # Adaptive hybrid bank layout (core/layout.py): the background
    # re-layout pass demotes sparse/cold views to compact device
    # SparseBanks under the same HBM watermark the watchdog warns on.
    # PILOSA_TPU_HYBRID_LAYOUT=0 kills the whole plane regardless.
    from pilosa_tpu.core.view import BANK_BUDGET as _BANK_BUDGET
    api.layout.configure(
        enabled=cfg.layout_enabled,
        interval_s=cfg.layout_interval_s,
        demote_density=cfg.layout_demote_density,
        min_bytes=cfg.layout_min_bytes,
        promote_rate=cfg.layout_promote_rate,
        watermark_bytes=int(_BANK_BUDGET.budget
                            * cfg.telemetry_hbm_watermark))
    if cfg.layout_enabled and cfg.layout_interval_s > 0:
        api.layout.start()
    from pilosa_tpu.utils.diagnostics import (
        DiagnosticsCollector, RuntimeMonitor,
    )
    diagnostics = DiagnosticsCollector(
        url=cfg.diagnostics_url, interval=cfg.diagnostics_interval,
        holder=holder, logger=logger)
    diagnostics.start()
    runtime_monitor = None
    if cfg.metric_service != "none" and cfg.metric_poll_interval > 0:
        runtime_monitor = RuntimeMonitor(stats, cfg.metric_poll_interval,
                                         holder=holder)
        runtime_monitor.start()
    anti_entropy = None
    if cluster is not None and cfg.anti_entropy_interval > 0:
        from pilosa_tpu.parallel.syncer import AntiEntropyLoop
        anti_entropy = AntiEntropyLoop(api.syncer, cfg.anti_entropy_interval)
        anti_entropy.start()
    heartbeat = translate_repl = None
    if cluster is not None:
        from pilosa_tpu.parallel.heartbeat import (
            Heartbeater, TranslateReplicationLoop,
        )
        if cfg.heartbeat_interval > 0:
            heartbeat = Heartbeater(cluster,
                                    interval=cfg.heartbeat_interval,
                                    suspect_after=cfg.heartbeat_suspect,
                                    probes_per_round=cfg.heartbeat_probes,
                                    logger=logger,
                                    ssl_context=cfg.client_ssl_context())
            heartbeat.start()
        if cfg.translate_replication_interval > 0:
            translate_repl = TranslateReplicationLoop(
                api, cfg.translate_replication_interval)
            translate_repl.start()
    seed_stop = None
    if cfg.cluster_seeds:
        # Seed-based dynamic join (reference: memberlist seed join →
        # coordinator resize, gossip/gossip.go:65, cluster.go:1676).
        # Runs beside the accept loop: the join must wait until this
        # node answers HTTP (the seed's resize job calls back with
        # /internal/resize/pull), and must retry while seeds boot.
        import threading

        seed_stop = threading.Event()

        # Probe the BIND address (loopback only when binding wildcard/
        # loopback): a server bound to a specific interface does not
        # answer on 127.0.0.1, and advertise may be an external address
        # this host cannot reach. A plain TCP connect avoids TLS (certs
        # need not cover the probe name).
        probe_host = cfg.host
        if probe_host in ("", "0.0.0.0", "::", "localhost"):
            probe_host = "127.0.0.1"

        def _seed_join():
            import socket as _socket
            while not seed_stop.is_set():
                try:  # wait for our own LISTENER
                    _socket.create_connection((probe_host, cfg.port),
                                              timeout=1.0).close()
                    break
                except OSError:
                    seed_stop.wait(0.3)
            while not seed_stop.is_set():
                try:
                    api.join_via_seeds(cfg.cluster_seeds)
                    logger.printf("seed join ok: cluster has %d node(s)",
                                  len(cluster.nodes()))
                    return
                except Exception as e:
                    logger.printf("seed join: %s; retrying in 5s", e)
                    seed_stop.wait(5.0)

        threading.Thread(target=_seed_join, daemon=True,
                         name="seed-join").start()
    logger.printf("pilosa-tpu server: data=%s bind=%s tls=%s mesh=%s "
                  "cluster=%s coalescer=%s", data_dir, cfg.bind,
                  "on" if cfg.tls_enabled else "off",
                  mesh.mesh.shape if mesh else "single-device",
                  f"{len(cluster.nodes())} nodes" if cluster else "no",
                  (f"window={cfg.coalescer_window_ms:g}ms "
                   f"batch<={cfg.coalescer_max_batch} "
                   f"queue<={cfg.coalescer_max_queue}")
                  if coalescer is not None else "off")
    # SIGTERM unwinds like Ctrl-C so the finally below runs the full
    # graceful close (flush caches, close holder) — the reference
    # server likewise traps SIGTERM for shutdown (cmd/pilosa/main.go).
    # Python's default TERM action would kill the process mid-buffer.
    def _graceful(signum, frame):
        raise KeyboardInterrupt
    try:
        signal.signal(signal.SIGTERM, _graceful)
    except ValueError:
        pass  # not the main thread (in-process test harness)
    try:
        serve(api, cfg.host, cfg.port,
              ssl_context=cfg.server_ssl_context())
    finally:
        if coalescer is not None:
            # Graceful drain first (SIGTERM lands here via the handler
            # above): admitted requests still execute; new arrivals
            # degrade to the direct path while the listener unwinds.
            coalescer.stop()
        if seed_stop is not None:
            seed_stop.set()
        if api.broadcaster is not None:
            api.broadcaster.stop()
        if heartbeat is not None:
            heartbeat.stop()
        if translate_repl is not None:
            translate_repl.stop()
        if anti_entropy is not None:
            anti_entropy.stop()
        diagnostics.stop()
        api.layout.stop()
        if runtime_monitor is not None:
            runtime_monitor.stop()
        # Telemetry drain: watchdog ring + slow-query ring dump to the
        # log, tracer stop/flush — buffered telemetry survives SIGTERM.
        drain_telemetry(api, watchdog=watchdog, logger=logger)
        holder.close()
        if hasattr(stats, "flush"):
            # Drain buffered statsd datagrams last, after every
            # stats-producing loop above has stopped.
            stats.flush()
    return 0


def _iter_import_csv(args, batch: int = 0):
    """Yield (rows, cols, vals) batches from the CSV files — `row,col`
    lines, or `col,value` with --field-type int. batch=0 yields one
    batch with everything (the local path); a positive batch streams in
    O(batch) memory (the remote path must not materialize a 100M-line
    CSV as Python lists)."""
    rows, cols, vals = [], [], []
    for path in args.files:
        with open(path, newline="") as f:
            for rec in csv.reader(f):
                if not rec:
                    continue
                if args.field_type == "int":
                    cols.append(int(rec[0]))
                    vals.append(int(rec[1]))
                else:
                    rows.append(int(rec[0]))
                    cols.append(int(rec[1]))
                if batch and len(cols) >= batch:
                    yield rows, cols, vals
                    rows, cols, vals = [], [], []
    if cols or not batch:
        yield rows, cols, vals


def _read_import_csv(args):
    """(rows, cols, vals) fully materialized (the local path)."""
    return next(_iter_import_csv(args))


# Pairs per POST on the remote import path: bounds request bodies to a
# few MB while amortizing the round trip (reference ctl/import.go
# buffers 10M bits per request by default).
REMOTE_IMPORT_BATCH = 1_000_000


def _import_remote(args) -> int:
    """POST CSV-derived batches through a running host's import API
    (reference ctl/import.go: the import subcommand posts ImportRequests
    to --host; the receiving node translates/splits/forwards to shard
    owners, api.go:814). Creates the index/field if missing, like the
    local path."""
    from pilosa_tpu.parallel.client import ClientError, InternalClient

    ssl_ctx = None
    if args.tls_skip_verify:
        import ssl
        ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ssl_ctx.check_hostname = False
        ssl_ctx.verify_mode = ssl.CERT_NONE
    client = InternalClient(timeout=300.0, ssl_context=ssl_ctx)
    host = args.host.rstrip("/")

    def ensure(path: str, options: dict) -> None:
        try:
            client._req("POST", f"{host}{path}", obj={"options": options})
        except ClientError as e:
            # Shared predicate: 409 alone also means "wrong cluster
            # state", which must NOT read as success (client.py:292).
            if not InternalClient._is_already_exists(e):
                raise

    ensure(f"/index/{args.index}", {})
    if args.field_type == "int":
        # Streaming min/max prescan so field creation fits the data
        # without materializing the CSV (second pass posts batches).
        lo = hi = None
        for _, _, vals in _iter_import_csv(args, REMOTE_IMPORT_BATCH):
            if vals:
                lo = min(vals) if lo is None else min(lo, min(vals))
                hi = max(vals) if hi is None else max(hi, max(vals))
        ensure(f"/index/{args.index}/field/{args.field}",
               {"type": "int", "min": lo or 0, "max": hi or 0})
    else:
        ensure(f"/index/{args.index}/field/{args.field}", {})
    url = f"{host}/index/{args.index}/field/{args.field}/import"
    total = 0
    for rows, cols, vals in _iter_import_csv(args, REMOTE_IMPORT_BATCH):
        if args.field_type == "int":
            body = {"columnIDs": cols, "values": vals}
        else:
            body = {"rowIDs": rows, "columnIDs": cols}
        client._req("POST", url, obj=body)
        total += len(cols)
    print(f"imported {total} records into "
          f"{args.index}/{args.field} via {host}")
    return 0


def cmd_import(args) -> int:
    """Bulk CSV import: rows of `row,col` (or `col,value` with --field-type
    int). Default: straight into a local holder. With --host: posted
    through a running server's import API (reference ctl/import.go
    supports both shapes)."""
    if args.host:
        return _import_remote(args)
    if not args.data_dir:
        print("import: either --host or --data-dir is required",
              file=sys.stderr)
        return 2
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions

    holder = Holder(os.path.expanduser(args.data_dir))
    holder.open()
    idx = holder.create_index(args.index, error_if_exists=False)
    rows, cols, vals = _read_import_csv(args)
    if args.field_type == "int":
        lo, hi = (min(vals), max(vals)) if vals else (0, 0)
        f = idx.field(args.field) or idx.create_field(
            args.field, FieldOptions(type="int", min=lo, max=hi))
        f.import_values(np.array(cols, np.uint64), np.array(vals, np.int64))
    else:
        f = idx.field(args.field) or idx.create_field(args.field)
        f.import_bits(np.array(rows, np.uint64), np.array(cols, np.uint64))
    idx.add_existence(np.array(cols, np.uint64))
    holder.close()
    print(f"imported {len(cols)} records into {args.index}/{args.field}")
    return 0


def cmd_export(args) -> int:
    from pilosa_tpu.core.holder import Holder

    holder = Holder(os.path.expanduser(args.data_dir))
    holder.open()
    idx = holder.index(args.index)
    if idx is None or idx.field(args.field) is None:
        print(f"not found: {args.index}/{args.field}", file=sys.stderr)
        return 1
    from pilosa_tpu.server.api import export_fragment_lines
    f = idx.field(args.field)
    view = f.view()
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    for shard in (view.available_shards() if view else []):
        for line in export_fragment_lines(idx, args.field, shard):
            out.write(line)
    if out is not sys.stdout:
        out.close()
    holder.close()
    return 0


def cmd_check(args) -> int:
    """Verify roaring fragment file integrity (reference ctl/check.go)."""
    from pilosa_tpu.storage.roaring import Bitmap

    bad = 0
    for path in args.files:
        try:
            with open(path, "rb") as f:
                b = Bitmap.from_bytes(f.read(), tolerate_torn_tail=True)
            if b.tail_dropped:
                bad += 1
                print(f"{path}: TORN TAIL: last op record truncated "
                      f"({b.tail_dropped} bytes; server open would "
                      f"sidecar+truncate)", file=sys.stderr)
            else:
                print(f"{path}: ok ({b.count()} bits, "
                      f"{len(b.containers)} containers, opN={b.op_n})")
        except Exception as e:
            bad += 1
            print(f"{path}: CORRUPT: {e}", file=sys.stderr)
    return 1 if bad else 0


def cmd_inspect(args) -> int:
    """Container stats for fragment files (reference ctl/inspect.go)."""
    from pilosa_tpu.storage.roaring import Bitmap
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    for path in args.files:
        with open(path, "rb") as f:
            b = Bitmap.from_bytes(f.read(), tolerate_torn_tail=True)
        rows = {}
        for key in sorted(b.containers):
            row = (key << 16) // SHARD_WIDTH
            rows.setdefault(row, [0, 0])
            rows[row][0] += 1
            rows[row][1] += b.container_count(key)
        print(f"{path}: {b.count()} bits, {len(b.containers)} containers, "
              f"{len(rows)} rows, opN={b.op_n}")
        if args.verbose:
            for row, (nc, nb) in sorted(rows.items()):
                print(f"  row {row}: {nc} containers, {nb} bits")
    return 0


def cmd_config(args) -> int:
    from pilosa_tpu.utils.config import load_config
    from dataclasses import asdict

    cfg = load_config(args.config, {})
    print(json.dumps(asdict(cfg), indent=2))
    return 0


def cmd_backup(args) -> int:
    """Tar the data directory (snapshots, op-logs, caches, .meta,
    .topology, .id, translate logs) — the offline analog of the
    reference's tar-stream backup of fragment files over HTTP
    (fragment.go:1885-2230, ctl/export.go). Consistent when the server
    is stopped; a live backup may catch a torn op-log tail, which
    restore+open tolerates (sidecar+truncate)."""
    import tarfile

    data_dir = os.path.expanduser(args.data_dir)
    if not os.path.isdir(data_dir):
        print(f"not a directory: {data_dir}", file=sys.stderr)
        return 1
    out_real = os.path.realpath(args.output)
    n = 0
    with tarfile.open(args.output, "w:gz") as tar:
        for root, _dirs, files in os.walk(data_dir):
            for name in files:
                if name.endswith(".torn"):
                    continue
                full = os.path.join(root, name)
                if os.path.realpath(full) == out_real:
                    continue  # -o inside the data dir: skip ourselves
                tar.add(full, arcname=os.path.relpath(full, data_dir))
                n += 1
    print(f"backed up {n} files from {data_dir} to {args.output}")
    return 0


def cmd_restore(args) -> int:
    """Unpack a backup tar into a data directory (must not already hold
    an index tree unless --force)."""
    import tarfile

    import shutil

    data_dir = os.path.expanduser(args.data_dir)
    if os.path.isdir(data_dir) and os.listdir(data_dir):
        if not args.force:
            print(f"refusing to restore into non-empty {data_dir} "
                  f"(use --force)", file=sys.stderr)
            return 1
        # --force REPLACES: leftover post-backup files must not mix
        # with backup-time state.
        shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    with tarfile.open(args.input, "r:*") as tar:
        # Refuse traversal and non-file members (symlinks could point
        # outside) up front, instead of trusting the archive and
        # aborting half-extracted.
        for m in tar.getmembers():
            dest = os.path.realpath(os.path.join(data_dir, m.name))
            if not dest.startswith(os.path.realpath(data_dir) + os.sep):
                print(f"unsafe path in archive: {m.name}", file=sys.stderr)
                return 1
            if not (m.isreg() or m.isdir()):
                print(f"unsafe member type in archive: {m.name}",
                      file=sys.stderr)
                return 1
        tar.extractall(data_dir, filter="data")
        n = len(tar.getmembers())
    print(f"restored {n} files into {data_dir}")
    return 0


def cmd_fold(args) -> int:
    """Rewrite fragment files as pure reference-format snapshots.

    This framework's bulk imports append OP_ADD_ROARING extension
    records (storage/roaring.py OP_ADD_ROARING) that the reference
    implementation rejects as an unknown op type — data files are
    one-way compatible until folded (ADVICE r3). Folding replays the
    op-log into the snapshot and rewrites the file with no op tail, so
    a reference node (roaring.go:1037 unmarshalPilosaRoaring) can open
    it: the downgrade/rollback path. Atomic per file (tmp + rename);
    idempotent."""
    from pilosa_tpu.storage.roaring import Bitmap

    bad = 0
    for path in args.files:
        try:
            with open(path, "rb") as f:
                raw = f.read()
            b = Bitmap.from_bytes(raw, tolerate_torn_tail=True)
            if b.tail_dropped and not args.force:
                bad += 1
                print(f"{path}: torn op tail ({b.tail_dropped} bytes); "
                      "re-run with --force to fold anyway",
                      file=sys.stderr)
                continue
            if b.tail_dropped:
                # Same never-destroy-bytes rule as Fragment.open: the
                # dropped tail (a torn append — or, past the torn-append
                # bound, a possibly-salvageable suffix swallowed by a
                # corrupt length field) goes to a .torn sidecar BEFORE
                # the rewrite discards it from the main file.
                side = path + ".torn"
                with open(side, "ab") as f:
                    f.write(raw[len(raw) - b.tail_dropped:])
                    f.flush()
                    os.fsync(f.fileno())
                print(f"{path}: sidecarred {b.tail_dropped} torn tail "
                      f"bytes to {side}", file=sys.stderr)
            out = b.write_bytes()
            tmp = path + ".folding"
            with open(tmp, "wb") as f:
                f.write(out)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            print(f"{path}: folded to pure snapshot "
                  f"({len(out)} bytes, {b.count()} bits)")
        except Exception as e:
            bad += 1
            print(f"{path}: FOLD FAILED: {e}", file=sys.stderr)
    return 1 if bad else 0


def cmd_generate_config(args) -> int:
    from pilosa_tpu.utils.config import Config

    print(Config().to_toml(), end="")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pilosa-tpu",
        description="A TPU-native distributed bitmap index.")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("server", help="run the server")
    sp.add_argument("-d", "--data-dir", default=None)
    sp.add_argument("-b", "--bind", default=None)
    sp.add_argument("-c", "--config", default=None)
    sp.add_argument("--verbose", action="store_true")
    sp.add_argument("--platform", default=None,
                    help="JAX platform override (e.g. cpu)")
    sp.add_argument("--no-coalescer", action="store_true",
                    help="serve every query on the direct path "
                         "(disable cross-request coalescing)")
    sp.add_argument("--coalescer-window-ms", type=float, default=None,
                    help="coalescer batching window in milliseconds")
    sp.set_defaults(fn=cmd_server)

    ip = sub.add_parser("import", help="bulk import CSV files")
    ip.add_argument("-d", "--data-dir", default=None,
                    help="local holder to import into (omit with --host)")
    ip.add_argument("--host", default=None,
                    help="import through a running server instead of a "
                         "local holder, e.g. http://localhost:10101")
    ip.add_argument("--tls-skip-verify", action="store_true",
                    help="with an https --host: skip certificate "
                         "verification")
    ip.add_argument("-i", "--index", required=True)
    ip.add_argument("-f", "--field", required=True)
    ip.add_argument("--field-type", default="set", choices=["set", "int"])
    ip.add_argument("files", nargs="+")
    ip.set_defaults(fn=cmd_import)

    ep = sub.add_parser("export", help="export a field as CSV")
    ep.add_argument("-d", "--data-dir", required=True)
    ep.add_argument("-i", "--index", required=True)
    ep.add_argument("-f", "--field", required=True)
    ep.add_argument("-o", "--output", default="-")
    ep.set_defaults(fn=cmd_export)

    cp = sub.add_parser("check", help="check fragment file integrity")
    cp.add_argument("files", nargs="+")
    cp.set_defaults(fn=cmd_check)

    np_ = sub.add_parser("inspect", help="inspect fragment containers")
    np_.add_argument("files", nargs="+")
    np_.add_argument("--verbose", action="store_true")
    np_.set_defaults(fn=cmd_inspect)

    bp = sub.add_parser("backup", help="tar a data directory")
    bp.add_argument("-d", "--data-dir", required=True)
    bp.add_argument("-o", "--output", required=True)
    bp.set_defaults(fn=cmd_backup)

    rp = sub.add_parser("restore", help="unpack a backup tar")
    rp.add_argument("-d", "--data-dir", required=True)
    rp.add_argument("-i", "--input", required=True)
    rp.add_argument("--force", action="store_true")
    rp.set_defaults(fn=cmd_restore)

    gp = sub.add_parser("config", help="print resolved configuration")
    gp.add_argument("-c", "--config", default=None)
    gp.set_defaults(fn=cmd_config)

    fp = sub.add_parser(
        "fold", help="rewrite fragment files as pure snapshots "
        "(reference-readable: drops OP_ADD_ROARING extension records)")
    fp.add_argument("files", nargs="+")
    fp.add_argument("--force", action="store_true",
                    help="fold even files with a torn op tail")
    fp.set_defaults(fn=cmd_fold)

    gg = sub.add_parser("generate-config", help="print default TOML config")
    gg.set_defaults(fn=cmd_generate_config)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
