from pilosa_tpu.cli.main import main
import sys

sys.exit(main())
