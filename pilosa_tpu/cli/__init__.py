"""CLI (reference cmd/ + ctl/: server, import, export, check, inspect,
config, generate-config)."""
