"""Executor behavioral tests — the PQL spec, mirroring the coverage shape of
the reference's executor_test.go (43 black-box tests over the public API)."""

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.ops.bitset import SHARD_WIDTH


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    yield Executor(h), h
    h.close()


def setup_basic(h):
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    # f row1: {1,2,3, SW+1}; f row2: {2,3,4}; g row1: {2,4}
    f.import_bits(np.array([1, 1, 1, 1, 2, 2, 2], np.uint64),
                  np.array([1, 2, 3, SHARD_WIDTH + 1, 2, 3, 4], np.uint64))
    g.import_bits(np.array([1, 1], np.uint64), np.array([2, 4], np.uint64))
    idx.add_existence(np.array([1, 2, 3, 4, SHARD_WIDTH + 1], np.uint64))
    return idx


def test_row(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Row(f=1)")
    np.testing.assert_array_equal(res.columns(), [1, 2, 3, SHARD_WIDTH + 1])
    assert res.count() == 4


def test_intersect_union_difference_xor(ex):
    e, h = ex
    setup_basic(h)
    res = e.execute("i", """
        Intersect(Row(f=1), Row(f=2))
        Union(Row(f=1), Row(g=1))
        Difference(Row(f=1), Row(f=2))
        Xor(Row(f=1), Row(f=2))
    """)
    np.testing.assert_array_equal(res[0].columns(), [2, 3])
    np.testing.assert_array_equal(res[1].columns(),
                                  [1, 2, 3, 4, SHARD_WIDTH + 1])
    np.testing.assert_array_equal(res[2].columns(), [1, SHARD_WIDTH + 1])
    np.testing.assert_array_equal(res[3].columns(), [1, 4, SHARD_WIDTH + 1])


def test_count_fused(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")
    assert res == 2


def test_not_via_existence(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Not(Row(f=1))")
    np.testing.assert_array_equal(res.columns(), [4])


def test_nested_not(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Count(Not(Not(Row(f=1))))")
    assert res == 4


def test_shift(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Shift(Row(g=1), n=2)")
    np.testing.assert_array_equal(res.columns(), [4, 6])


def test_set_clear_roundtrip(ex):
    e, h = ex
    h.create_index("i").create_field("f")
    assert e.execute("i", "Set(10, f=1)") == [True]
    assert e.execute("i", "Set(10, f=1)") == [False]
    (res,) = e.execute("i", "Row(f=1)")
    np.testing.assert_array_equal(res.columns(), [10])
    assert e.execute("i", "Clear(10, f=1)") == [True]
    assert e.execute("i", "Clear(10, f=1)") == [False]
    (res,) = e.execute("i", "Row(f=1)")
    assert len(res.columns()) == 0


def test_clear_row_and_store(ex):
    e, h = ex
    setup_basic(h)
    e.execute("i", "Store(Row(f=1), topic=9)")
    (res,) = e.execute("i", "Row(topic=9)")
    np.testing.assert_array_equal(res.columns(), [1, 2, 3, SHARD_WIDTH + 1])
    assert e.execute("i", "ClearRow(f=1)") == [True]
    (res,) = e.execute("i", "Row(f=1)")
    assert len(res.columns()) == 0
    # stored copy unaffected
    (res,) = e.execute("i", "Row(topic=9)")
    assert res.count() == 4


def test_topn(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "TopN(f, n=2)")
    assert res.pairs == [(1, 4), (2, 3)]
    # with filter
    (res,) = e.execute("i", "TopN(f, Row(g=1), n=1)")
    assert res.pairs == [(2, 2)]  # row2∩{2,4}={2,4}∩{2,3,4}... counts below
    (all_res,) = e.execute("i", "TopN(f)")
    assert all_res.pairs == [(1, 4), (2, 3)]


def test_topn_attr_filter(ex):
    e, h = ex
    setup_basic(h)
    e.execute("i", 'SetRowAttrs(f, 1, cat="x")')
    e.execute("i", 'SetRowAttrs(f, 2, cat="y")')
    (res,) = e.execute("i", 'TopN(f, n=5, attrName=cat, attrValues=["x"])')
    assert res.pairs == [(1, 4)]


def test_rows(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Rows(f)")
    assert res.rows == [1, 2]
    (res,) = e.execute("i", "Rows(f, previous=1)")
    assert res.rows == [2]
    (res,) = e.execute("i", "Rows(f, limit=1)")
    assert res.rows == [1]
    (res,) = e.execute("i", "Rows(f, column=4)")
    assert res.rows == [2]


def test_group_by(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "GroupBy(Rows(f), Rows(g))")
    got = {(tuple((fr.field, fr.row_id) for fr in gc.group), gc.count)
           for gc in res}
    assert got == {((("f", 1), ("g", 1)), 1), ((("f", 2), ("g", 1)), 2)}
    # with filter and limit
    (res,) = e.execute("i", "GroupBy(Rows(f), limit=1, filter=Row(g=1))")
    assert len(res) == 1 and res[0].count == 1


def test_group_by_previous_paging(ex):
    """GroupBy(previous=[...]) resumes after the named group in
    lexicographic order (reference translateGroupByCall executor.go:2522
    + groupByIterator seek :2878)."""
    e, h = ex
    idx = h.create_index("gp")
    rng = np.random.RandomState(3)
    for fname, nrows in (("a", 3), ("b", 4)):
        f = idx.create_field(fname)
        rows_l, cols_l = [], []
        for r in range(nrows):
            cols = rng.choice(200, size=40, replace=False)
            rows_l.extend([r] * len(cols))
            cols_l.extend(cols.tolist())
        f.import_bits(np.array(rows_l, np.uint64),
                      np.array(cols_l, np.uint64))
    (full,) = e.execute("gp", "GroupBy(Rows(a), Rows(b))")
    tuples = [tuple(fr.row_id for fr in gc.group) for gc in full]
    assert tuples == sorted(tuples)
    for k in (0, 1, len(full) - 2):
        prev = tuples[k]
        (res,) = e.execute(
            "gp", f"GroupBy(Rows(a), Rows(b), previous={list(prev)})")
        got = [(tuple(fr.row_id for fr in gc.group), gc.count)
               for gc in res]
        want = [(tuple(fr.row_id for fr in gc.group), gc.count)
                for gc in full[k + 1:]]
        assert got == want
    # limit counts post-skip groups
    (res,) = e.execute(
        "gp", f"GroupBy(Rows(a), Rows(b), previous={list(tuples[0])}, "
              "limit=2)")
    assert len(res) == 2
    assert tuple(fr.row_id for fr in res[0].group) == tuples[1]
    # mismatched length errors
    with pytest.raises(Exception, match="previous"):
        e.execute("gp", "GroupBy(Rows(a), Rows(b), previous=[1])")


def test_group_by_deep_matches_bruteforce(ex):
    """3-field GroupBy over multiple shards, checked against a host-side
    brute force — exercises the level-synchronous batched expansion
    (one [P, R, S, W] kernel per depth instead of one dispatch per prefix,
    reference groupByIterator executor.go:2820-2996)."""
    e, h = ex
    idx = h.create_index("gb")
    rng = np.random.RandomState(7)
    data = {}
    for fname, nrows in (("a", 4), ("b", 3), ("c", 5)):
        f = idx.create_field(fname)
        rows_l, cols_l = [], []
        for r in range(nrows):
            cols = rng.choice(2 * SHARD_WIDTH, size=30, replace=False)
            data[(fname, r)] = set(int(c) for c in cols)
            rows_l.extend([r] * len(cols))
            cols_l.extend(cols.tolist())
        f.import_bits(np.array(rows_l, np.uint64),
                      np.array(cols_l, np.uint64))
    (res,) = e.execute("gb", "GroupBy(Rows(a), Rows(b), Rows(c))")
    got = {tuple(fr.row_id for fr in gc.group): gc.count for gc in res}
    want = {}
    for ra in range(4):
        for rb in range(3):
            for rc in range(5):
                n = len(data[("a", ra)] & data[("b", rb)] & data[("c", rc)])
                if n:
                    want[(ra, rb, rc)] = n
    assert got == want
    # limit truncates in (prefix-major, row) order
    (res,) = e.execute("gb", "GroupBy(Rows(a), Rows(b), Rows(c), limit=3)")
    ordered = sorted(want.items())[:3]
    assert [(tuple(fr.row_id for fr in gc.group), gc.count)
            for gc in res] == ordered
    # filter applies to every group
    (res,) = e.execute("gb", "GroupBy(Rows(a), Rows(b), filter=Row(c=0))")
    got = {tuple(fr.row_id for fr in gc.group): gc.count for gc in res}
    want2 = {}
    for ra in range(4):
        for rb in range(3):
            n = len(data[("a", ra)] & data[("b", rb)] & data[("c", 0)])
            if n:
                want2[(ra, rb)] = n
    assert got == want2


def test_group_by_chunked_expansion(ex, monkeypatch):
    """Force a tiny chunk budget so the prefix expansion streams through
    several device batches; result must be identical."""
    e, h = ex
    idx = h.create_index("gc")
    for fname in ("x", "y"):
        f = idx.create_field(fname)
        rows = np.repeat(np.arange(6, dtype=np.uint64), 10)
        cols = np.tile(np.arange(10, dtype=np.uint64) * 3, 6) + \
            np.repeat(np.arange(6, dtype=np.uint64), 10)
        f.import_bits(rows, cols)
    (want,) = e.execute("gc", "GroupBy(Rows(x), Rows(y))")
    monkeypatch.setattr(type(e), "GROUPBY_CHUNK_BYTES", 4096)
    e._jit_cache = {k: v for k, v in e._jit_cache.items()
                    if not k.startswith("gb_")}
    (got,) = e.execute("gc", "GroupBy(Rows(x), Rows(y))")
    as_set = lambda res: {(tuple(fr.row_id for fr in gc.group), gc.count)
                          for gc in res}
    assert as_set(got) == as_set(want) and len(got) > 0


def test_group_by_frontier_spills_to_host(ex, monkeypatch):
    """High-cardinality 3-field GroupBy under an artificially tiny
    budget: the surviving-prefix frontier must spill to host memory (no
    unbudgeted jnp.concatenate of prefixes — VERDICT r2 weak #3) and the
    result must match the unspilled run AND a brute-force model."""
    e, h = ex
    idx = h.create_index("gs")
    rng = np.random.RandomState(11)
    data = {}
    for fname, nrows in (("a", 8), ("b", 8), ("c", 4)):
        f = idx.create_field(fname)
        rows_l, cols_l = [], []
        for r in range(nrows):
            cols = rng.choice(SHARD_WIDTH, size=40, replace=False)
            # Shared columns so the cross product survives pruning.
            cols[:10] = np.arange(10) * 7
            data[(fname, r)] = set(int(c) for c in cols)
            rows_l.extend([r] * len(cols))
            cols_l.extend(cols.tolist())
        f.import_bits(np.array(rows_l, np.uint64),
                      np.array(cols_l, np.uint64))
    q = "GroupBy(Rows(a), Rows(b), Rows(c))"
    (want,) = e.execute("gs", q)
    assert e.groupby_spill_events == 0
    monkeypatch.setattr(type(e), "GROUPBY_CHUNK_BYTES", 1 << 14)
    e._jit_cache = {k: v for k, v in e._jit_cache.items()
                    if not k.startswith("gb_")}
    (got,) = e.execute("gs", q)
    assert e.groupby_spill_events > 0  # frontier really left the device
    as_map = lambda res: {tuple(fr.row_id for fr in gc.group): gc.count
                          for gc in res}
    assert as_map(got) == as_map(want) and len(got) > 0
    model = {}
    for ra in range(8):
        for rb in range(8):
            for rc in range(4):
                n = len(data[("a", ra)] & data[("b", rb)]
                        & data[("c", rc)])
                if n:
                    model[(ra, rb, rc)] = n
    assert as_map(got) == model


def test_bsi_conditions(ex):
    e, h = ex
    idx = h.create_index("i")
    idx.create_field("n", FieldOptions(type="int", min=-100, max=1000))
    cols = np.arange(10, dtype=np.uint64)
    vals = np.array([-100, -50, -1, 0, 1, 5, 10, 500, 999, 1000], np.int64)
    idx.field("n").import_values(cols, vals)
    idx.add_existence(cols)

    cases = [
        ("Row(n > 0)", [4, 5, 6, 7, 8, 9]),
        ("Row(n >= 0)", [3, 4, 5, 6, 7, 8, 9]),
        ("Row(n < 0)", [0, 1, 2]),
        ("Row(n <= -50)", [0, 1]),
        ("Row(n == 5)", [5]),
        ("Row(n != 5)", [0, 1, 2, 3, 4, 6, 7, 8, 9]),
        ("Row(n >< [0, 10])", [3, 4, 5, 6]),
        ("Row(-2 < n < 2)", [2, 3, 4]),
        ("Row(n > 1000)", []),
        ("Row(n < -100)", []),
        ("Row(n >= -100)", list(range(10))),
        ("Row(n > 2000)", []),
        ("Row(n < 2000)", list(range(10))),
    ]
    for src, want in cases:
        (res,) = e.execute("i", src)
        np.testing.assert_array_equal(res.columns(), want, err_msg=src)


def test_sum_min_max(ex):
    e, h = ex
    idx = h.create_index("i")
    idx.create_field("n", FieldOptions(type="int", min=-10, max=100000))
    f = idx.create_field("f")
    cols = np.array([0, 1, 2, SHARD_WIDTH + 3], np.uint64)
    vals = np.array([-10, 20, 30, 100000], np.int64)
    idx.field("n").import_values(cols, vals)
    f.import_bits(np.zeros(2, np.uint64), np.array([1, 2], np.uint64))

    (res,) = e.execute("i", 'Sum(field="n")')
    assert (res.value, res.count) == (-10 + 20 + 30 + 100000, 4)
    (res,) = e.execute("i", 'Sum(Row(f=0), field="n")')
    assert (res.value, res.count) == (50, 2)
    (res,) = e.execute("i", 'Min(field="n")')
    assert (res.value, res.count) == (-10, 1)
    (res,) = e.execute("i", 'Max(field="n")')
    assert (res.value, res.count) == (100000, 1)
    (res,) = e.execute("i", 'Min(Row(f=0), field="n")')
    assert (res.value, res.count) == (20, 1)
    (res,) = e.execute("i", 'Max(Row(f=0), field="n")')
    assert (res.value, res.count) == (30, 1)


def test_row_attrs_attach(ex):
    e, h = ex
    setup_basic(h)
    e.execute("i", 'SetRowAttrs(f, 1, color="red", weight=12)')
    (res,) = e.execute("i", "Row(f=1)")
    assert res.attrs == {"color": "red", "weight": 12}
    e.execute("i", 'SetColumnAttrs(2, city="ny")')
    assert h.index("i").column_attr_store.get(2) == {"city": "ny"}


def test_mutex_executor(ex):
    e, h = ex
    h.create_index("i").create_field("m", FieldOptions(type="mutex"))
    e.execute("i", "Set(5, m=1)")
    e.execute("i", "Set(5, m=2)")
    (r1,) = e.execute("i", "Row(m=1)")
    (r2,) = e.execute("i", "Row(m=2)")
    assert len(r1.columns()) == 0
    np.testing.assert_array_equal(r2.columns(), [5])


def test_bool_field_executor(ex):
    e, h = ex
    h.create_index("i").create_field("b", FieldOptions(type="bool"))
    e.execute("i", "Set(3, b=true)")
    e.execute("i", "Set(4, b=false)")
    (rt,) = e.execute("i", "Row(b=true)")
    (rf,) = e.execute("i", "Row(b=false)")
    np.testing.assert_array_equal(rt.columns(), [3])
    np.testing.assert_array_equal(rf.columns(), [4])


def test_time_range_query(ex):
    e, h = ex
    idx = h.create_index("i")
    idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
    e.execute("i", "Set(1, t=7, 2018-01-02T00:00)")
    e.execute("i", "Set(2, t=7, 2018-03-15T00:00)")
    e.execute("i", "Set(3, t=7, 2019-06-01T00:00)")
    (res,) = e.execute(
        "i", "Row(t=7, from='2018-01-01T00:00', to='2018-12-31T00:00')")
    np.testing.assert_array_equal(res.columns(), [1, 2])
    (res,) = e.execute("i", "Row(t=7)")  # standard view: everything
    np.testing.assert_array_equal(res.columns(), [1, 2, 3])


def test_rows_time_filter(ex):
    """Rows(f, from=, to=) on a noStandardView time field (reference
    TestExecutor_Execute_RowsTime, executor_test.go)."""
    e, h = ex
    idx = h.create_index("i")
    idx.create_field("f", FieldOptions(type="time", time_quantum="YMD",
                                       no_standard_view=True))
    e.execute("i", "Set(9, f=1, 2001-01-01T00:00)")
    e.execute("i", "Set(9, f=2, 2002-01-01T00:00)")
    e.execute("i", "Set(9, f=3, 2003-01-01T00:00)")
    e.execute("i", "Set(9, f=4, 2004-01-01T00:00)")
    e.execute("i", f"Set({SHARD_WIDTH + 9}, f=13, 2003-02-02T00:00)")
    cases = [
        ("Rows(f, from=1999-12-31T00:00, to=2002-01-01T03:00)", [1]),
        ("Rows(f, from=2002-01-01T00:00, to=2004-01-01T00:00)", [2, 3, 13]),
        ("Rows(f, from=1990-01-01T00:00, to=1999-01-01T00:00)", []),
        ("Rows(f)", [1, 2, 3, 4, 13]),
        ("Rows(f, from=2002-01-01T00:00)", [2, 3, 4, 13]),
        ("Rows(f, to=2003-02-03T00:00)", [1, 2, 3, 13]),
    ]
    for pql, want in cases:
        (res,) = e.execute("i", pql)
        assert list(res.rows) == want, pql


def test_rows_time_empty(ex):
    """No data: a ranged Rows returns empty, not an error (reference
    TestExecutor_Execute_RowsTimeEmpty)."""
    e, h = ex
    idx = h.create_index("i")
    idx.create_field("x", FieldOptions(type="time", time_quantum="YMD",
                                       no_standard_view=True))
    (res,) = e.execute(
        "i", "Rows(x, from=1999-12-31T00:00, to=2002-01-01T03:00)")
    assert list(res.rows) == []


@pytest.mark.parametrize("quantum,expected", [
    ("Y", [3, 4, 5, 6]), ("M", [3, 4, 5, 6]), ("D", [3, 4, 5, 6]),
    ("H", [3, 4, 5, 6, 7]), ("YM", [3, 4, 5, 6]), ("YMD", [3, 4, 5, 6]),
    ("YMDH", [3, 4, 5, 6, 7]), ("MD", [3, 4, 5, 6]),
    ("MDH", [3, 4, 5, 6, 7]), ("DH", [3, 4, 5, 6, 7]),
])
def test_time_clear_quantums(ex, quantum, expected):
    """Clear removes the column from every quantum view (reference
    TestExecutor_Time_Clear_Quantums, executor_test.go)."""
    e, h = ex
    idx = h.create_index("i")
    idx.create_field("f", FieldOptions(type="time", time_quantum=quantum))
    e.execute("i", """
        Set(2, f=1, 1999-12-31T00:00)
        Set(3, f=1, 2000-01-01T00:00)
        Set(4, f=1, 2000-01-02T00:00)
        Set(5, f=1, 2000-02-01T00:00)
        Set(6, f=1, 2001-01-01T00:00)
        Set(7, f=1, 2002-01-01T02:00)
        Set(2, f=1, 1999-12-30T00:00)
        Set(2, f=1, 2002-02-01T00:00)
        Set(2, f=10, 2001-01-01T00:00)
    """)
    e.execute("i", "Clear(2, f=1)")
    (res,) = e.execute(
        "i", "Row(f=1, from=1999-12-31T00:00, to=2002-01-01T03:00)")
    assert list(res.columns()) == expected


def test_rows_from_to_on_non_time_field_errors(ex):
    e, h = ex
    setup_basic(h)
    with pytest.raises(Exception, match="non-time"):
        e.execute("i", "Rows(f, from=2001-01-01T00:00)")


def test_count_across_shards(ex):
    e, h = ex
    f = h.create_index("i").create_field("f")
    cols = np.concatenate([np.arange(100, dtype=np.uint64),
                           np.arange(100, dtype=np.uint64) + 3 * SHARD_WIDTH])
    f.import_bits(np.zeros(len(cols), np.uint64), cols)
    (res,) = e.execute("i", "Count(Row(f=0))")
    assert res == 200


def test_errors(ex):
    e, h = ex
    setup_basic(h)
    from pilosa_tpu.executor.executor import ExecutionError
    with pytest.raises(ExecutionError):
        e.execute("nosuch", "Row(f=1)")
    with pytest.raises(ExecutionError):
        e.execute("i", "Row(nosuch=1)")
    with pytest.raises(ExecutionError):
        e.execute("i", "Badcall(f=1)")


def test_store_on_int_field_rejected(ex):
    e, h = ex
    idx = h.create_index("i")
    idx.create_field("n", FieldOptions(type="int", min=0, max=10))
    idx.create_field("f")
    e.execute("i", "Set(1, f=0)")
    from pilosa_tpu.executor.executor import ExecutionError
    with pytest.raises(ExecutionError, match="not supported on int"):
        e.execute("i", "Store(Row(f=0), n=7)")


def test_malformed_unary_calls(ex):
    e, h = ex
    setup_basic(h)
    from pilosa_tpu.executor.executor import ExecutionError
    # Not()/Shift() parse as generic zero-child calls -> executor error;
    # Store(g=1) fails the Store special form (which requires a Call
    # first) but falls back to the generic IDENT alternative per PEG
    # ordered choice (pql.peg Call), so it too reaches the executor and
    # fails there — matching the reference grammar.
    for bad in ["Not()", "Shift()", "Store(g=1)"]:
        with pytest.raises(ExecutionError):
            e.execute("i", bad)


def test_merged_row_ids_cached_multi_shard(ex):
    """VERDICT r4 #7: the multi-shard TopN row union must not rebuild
    per query. 1M+ rows over two fragments: repeat calls alias the SAME
    cached tuple; a write invalidates; the merge is correct."""
    e, h = ex
    idx = h.create_index("mr")
    from pilosa_tpu.core.field import FieldOptions
    f = idx.create_field("mf", FieldOptions(max_columns=512))
    view = f.create_view_if_not_exists("standard")
    cpr = SHARD_WIDTH // 65536
    rows0 = range(0, 700_000)          # shard 0
    rows1 = range(300_000, 1_000_000)  # shard 1 (overlaps 300k..700k)
    for shard, rows in ((0, rows0), (1, rows1)):
        frag = view.create_fragment_if_not_exists(shard)
        containers = frag.storage.containers
        pos = np.array([3, 7], np.uint16)
        for r in rows:
            containers[r * cpr] = pos
        for r in rows:
            frag._touch_row(r)
    merged = view.merged_row_ids((0, 1))
    assert len(merged) == 1_000_000
    assert merged[0] == 0 and merged[-1] == 999_999
    assert merged[299_999:300_002] == (299_999, 300_000, 300_001)
    # Repeat call: the SAME object, no rebuild.
    assert view.merged_row_ids((0, 1)) is merged
    assert view.merged_row_ids([0, 1]) is merged  # list/tuple agnostic
    # A write to either member invalidates.
    view.fragment(1).set_bit(1_000_001, SHARD_WIDTH + 5)
    merged2 = view.merged_row_ids((0, 1))
    assert merged2 is not merged
    assert merged2[-1] == 1_000_001
    # Distinct shard subsets cache independently.
    assert view.merged_row_ids((0,)) == tuple(rows0)


def test_multi_shard_topn_uses_merged_cache(ex):
    """End-to-end: multi-shard TopN answers correctly and reuses the
    merged row tuple across queries."""
    e, h = ex
    idx = h.create_index("mt")
    f = idx.create_field("tf")
    # rows 1..3 spread over two shards with known counts
    rows = np.array([1, 1, 1, 2, 2, 3], np.uint64)
    cols = np.array([0, 1, SHARD_WIDTH, 2, SHARD_WIDTH + 1, 3], np.uint64)
    f.import_bits(rows, cols)
    (r1,) = e.execute("mt", "TopN(tf, n=3)")
    assert r1.pairs == [(1, 3), (2, 2), (3, 1)]
    view = f.view()
    m1 = view.merged_row_ids((0, 1))
    (r2,) = e.execute("mt", "TopN(tf, n=3)")
    assert r2.pairs == r1.pairs
    assert view.merged_row_ids((0, 1)) is m1


def test_list_attr_values_dont_crash(ex):
    e, h = ex
    setup_basic(h)
    e.execute("i", "SetRowAttrs(f, 1, tags=[1, 2])")
    (res,) = e.execute("i", "TopN(f, n=5, attrName=tags, attrValues=[1])")
    assert res.pairs == []  # [1,2] != 1 — no match, no crash


def test_read_does_not_create_views(ex):
    e, h = ex
    idx = h.create_index("i")
    f = idx.create_field("f")
    assert e.execute("i", "Count(Row(f=1))") == [0]
    assert f.views == {}


def test_incremental_bank_patch(ex):
    e, h = ex
    setup_basic(h)
    idx = h.index("i")
    assert e.execute("i", "Count(Row(f=1))") == [4]
    view = idx.field("f").view()
    key = (tuple(idx.available_shards()), None, True)
    bank1 = view._bank_cache[key]
    e.execute("i", "Set(500, f=1)")
    assert e.execute("i", "Count(Row(f=1))") == [5]
    bank2 = view._bank_cache[key]
    # patched in place: same capacity array object lineage, same slots
    assert bank2.array.shape == bank1.array.shape
    assert bank2.slots == bank1.slots


def test_options_exclude_columns(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Options(Row(f=1), excludeColumns=true)")
    assert res.columns().tolist() == []
    # unaffected without the flag
    (res2,) = e.execute("i", "Row(f=1)")
    assert len(res2.columns()) == 4


def test_options_exclude_row_attrs(ex):
    e, h = ex
    setup_basic(h)
    e.execute("i", 'SetRowAttrs(f, 1, foo="bar")')
    (res,) = e.execute("i", "Row(f=1)")
    assert res.attrs == {"foo": "bar"}
    (res,) = e.execute("i", "Options(Row(f=1), excludeRowAttrs=true)")
    assert res.attrs == {}
    assert len(res.columns()) == 4


def test_options_shards_override(ex):
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "Options(Row(f=1), shards=[1])")
    assert res.columns().tolist() == [SHARD_WIDTH + 1]
    (cnt,) = e.execute("i", "Count(Row(f=1))")
    assert cnt == 4


def test_options_column_attrs_response(ex):
    e, h = ex
    setup_basic(h)
    e.execute("i", 'SetColumnAttrs(2, kind="x")')
    resp = e.execute_full("i", "Options(Row(f=1), columnAttrs=true)")
    assert resp["columnAttrs"] == [{"id": 2, "attrs": {"kind": "x"}}]
    resp = e.execute_full("i", "Row(f=1)")
    assert "columnAttrs" not in resp


def test_options_bad_args(ex):
    e, h = ex
    setup_basic(h)
    with pytest.raises(ValueError):
        e.execute("i", "Options(Row(f=1), excludeColumns=7)")
    with pytest.raises(ValueError):
        e.execute("i", "Options(Row(f=1), shards=3)")


def test_multicall_query_pipelines_with_correct_ordering(ex):
    """A query mixing writes and reads evaluates in call order even
    though read fetches are deferred: each read snapshots the state as
    of its position (dispatch-then-fetch, _execute_query)."""
    e, h = ex
    setup_basic(h)
    results = e.execute("i", (
        "Count(Row(f=1)) "          # before the write: 4 bits
        "Set(9, f=1) "              # write
        "Count(Row(f=1)) "          # after: 5 bits
        "TopN(f, n=2) "             # sees the new bit too
        "Clear(9, f=1) "
        "Count(Row(f=1))"           # back to 4
    ))
    assert results[0] == 4
    assert results[1] is True
    assert results[2] == 5
    assert results[3].pairs[0] == (1, 5)
    assert results[4] is True
    assert results[5] == 4


def test_topn_warm_cache_shortcut(ex):
    """Unfiltered TopN on a field whose ranked cache still holds every
    present row is answered from the cache with no device sweep
    (reference fragment.top over rankCache, fragment.go:1067); filtered
    TopN always sweeps."""
    e, h = ex
    setup_basic(h)
    before = e.topn_cache_hits
    (res,) = e.execute("i", "TopN(f, n=2)")
    assert res.pairs == [(1, 4), (2, 3)]
    assert e.topn_cache_hits == before + 1
    # threshold/ids are host-side filters — still cache-served
    (res,) = e.execute("i", "TopN(f, n=5, threshold=4)")
    assert res.pairs == [(1, 4)]
    assert e.topn_cache_hits == before + 2
    # a bitmap filter needs the real rows: no cache hit
    (res,) = e.execute("i", "TopN(f, Row(g=1), n=1)")
    assert res.pairs == [(2, 2)]
    assert e.topn_cache_hits == before + 2
    # writes keep the cached counts exact
    e.execute("i", "Set(100, f=2) Set(101, f=2) Set(102, f=2)")
    (res,) = e.execute("i", "TopN(f, n=2)")
    assert res.pairs == [(2, 6), (1, 4)]
    assert e.topn_cache_hits == before + 3


def test_topn_chunked_respects_later_writes(ex, monkeypatch):
    """A chunked TopN in a query with later writes must snapshot
    pre-write state (sequential call semantics, reference
    executor.go:245) even though chunk banks normally upload lazily
    after all dispatches."""
    import pilosa_tpu.executor.executor as ex_mod

    e, h = ex
    idx = h.create_index("i")
    f = idx.create_field("f", FieldOptions(cache_type="none"))
    f.import_bits(np.array([1, 1, 1, 2, 2, 3], np.uint64),
                  np.array([1, 2, 3, 2, 3, 5], np.uint64))
    monkeypatch.setattr(ex_mod, "TOPN_MAX_BANK_BYTES", 0)
    monkeypatch.setattr(ex_mod, "TOPN_CHUNK_ROWS", 1)
    results = e.execute("i", (
        "TopN(f, n=5) "
        "Set(10, f=3) Set(11, f=3) Set(12, f=3) Set(13, f=3) "
        "TopN(f, n=5)"
    ))
    assert results[0].pairs == [(1, 3), (2, 2), (3, 1)]  # pre-write
    assert results[5].pairs == [(3, 5), (1, 3), (2, 2)]  # post-write


def test_multicall_all_reads_match_serial(ex):
    """Batched multi-call results identical to one-call-at-a-time."""
    e, h = ex
    setup_basic(h)
    calls = ["Count(Row(f=1))", "Count(Intersect(Row(f=1), Row(f=2)))",
             "TopN(f, n=5)", "Row(g=1)"]
    serial = [e.execute("i", c)[0] for c in calls]
    batched = e.execute("i", " ".join(calls))
    assert batched[0] == serial[0]
    assert batched[1] == serial[1]
    assert batched[2].pairs == serial[2].pairs
    assert batched[3].columns().tolist() == serial[3].columns().tolist()


def test_mixed_width_filter_alignment(ex):
    """TopN/Sum filters whose trimmed width differs from the target
    bank's width align by slice/pad (width-trimmed banks)."""
    e, h = ex
    idx = h.create_index("i")
    wide = idx.create_field("wide")
    narrow = idx.create_field("narrow")
    iv = idx.create_field("iv", FieldOptions(type="int", min=0, max=100))
    # wide has a bit far out (wide trimmed width >> narrow's)
    wide.import_bits(np.array([1, 1, 1], np.uint64),
                     np.array([3, 5, 200_000], np.uint64))
    narrow.import_bits(np.array([7, 7], np.uint64),
                       np.array([3, 9], np.uint64))
    iv.import_values(np.array([3, 5, 200_000], np.uint64),
                     np.array([10, 20, 30], np.int64))
    # narrow filter over wide field
    (res,) = e.execute("i", "TopN(wide, Row(narrow=7), n=5)")
    assert res.pairs == [(1, 1)]  # only column 3 intersects
    # wide filter over narrow field
    (res,) = e.execute("i", "TopN(narrow, Row(wide=1), n=5)")
    assert res.pairs == [(7, 1)]
    # narrow filter over a wider BSI bank and vice versa
    (res,) = e.execute("i", 'Sum(Row(narrow=7), field="iv")')
    assert (res.value, res.count) == (10, 1)
    (res,) = e.execute("i", 'Sum(Row(wide=1), field="iv")')
    assert (res.value, res.count) == (60, 3)
    (res,) = e.execute("i", 'Min(Row(wide=1), field="iv")')
    assert (res.value, res.count) == (10, 1)


def test_topn_ids_and_threshold(ex):
    """TopN ids= candidate restriction and threshold= count floor
    (reference topOptions.RowIDs/MinThreshold, fragment.go:1240)."""
    e, h = ex
    setup_basic(h)
    (res,) = e.execute("i", "TopN(f, n=5, ids=[2])")
    assert res.pairs == [(2, 3)]
    (res,) = e.execute("i", "TopN(f, n=5, threshold=4)")
    assert res.pairs == [(1, 4)]
    (res,) = e.execute("i", "TopN(f, n=5, threshold=99)")
    assert res.pairs == []


def test_hbm_budget_subset_banks(ex, monkeypatch):
    """A Row leaf on a view whose full bank exceeds BANK_MAX_BYTES must
    build a cached row-subset bank, not materialize every row (VERDICT r1
    missing #4; reference streams per-shard and never materializes,
    executor.go:2377)."""
    e, h = ex
    idx = h.create_index("hb")
    f = idx.create_field("f")
    g = idx.create_field("g")
    n_rows = 64
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64), 4)
    cols = np.tile(np.array([1, 2, 3, SHARD_WIDTH + 1], np.uint64), n_rows)
    f.import_bits(rows, cols)
    g.import_bits(np.array([1, 1], np.uint64), np.array([2, 4], np.uint64))
    idx.add_existence(np.unique(cols))

    (want,) = e.execute("hb", "Count(Intersect(Row(f=3), Row(g=1)))")
    view = f.view("standard")
    view._bank_cache.clear()

    # Budget smaller than the full f bank: leaf must go subset.
    monkeypatch.setattr(type(e), "BANK_MAX_BYTES", 4096)
    (got,) = e.execute("hb", "Count(Intersect(Row(f=3), Row(g=1)))")
    assert got == want
    subset_keys = [k for k in view._bank_cache if len(k) == 4]
    assert subset_keys, "expected a cached row-subset bank"
    bank = view._bank_cache[subset_keys[0]]
    assert bank.array.shape[0] <= 2  # capacity for 1 row + zero slot
    # Re-running hits the cached subset bank and stays correct.
    (got,) = e.execute("hb", "Count(Intersect(Row(f=3), Row(g=1)))")
    assert got == want
    # A write invalidates the cached subset (versions moved).
    (before,) = e.execute("hb", "Count(Row(f=3))")
    e.execute("hb", "Set(5, f=3)")
    (after,) = e.execute("hb", "Count(Row(f=3))")
    assert after == before + 1


def test_bank_budget_lru_eviction(tmp_path):
    """Total cached-bank HBM is bounded: admitting past the budget evicts
    the least recently used bank from its owning view."""
    from pilosa_tpu.core.view import BankBudget
    h = Holder(str(tmp_path))
    h.open()
    try:
        idx = h.create_index("ev")
        fields = []
        for name in ("a", "b", "c"):
            f = idx.create_field(name)
            f.import_bits(np.arange(8, dtype=np.uint64),
                          np.arange(8, dtype=np.uint64) * 7)
            fields.append(f)
        views = [f.view("standard") for f in fields]
        one_bank = None
        budget = BankBudget(1)  # resized after measuring one bank
        import pilosa_tpu.core.view as view_mod
        orig = view_mod.BANK_BUDGET
        view_mod.BANK_BUDGET = budget
        try:
            b = views[0].device_bank((0,), trim=True)
            one_bank = int(np.prod(b.array.shape)) * 4
            # room for exactly two banks
            budget.budget = 2 * one_bank
            views[1].device_bank((0,), trim=True)
            views[2].device_bank((0,), trim=True)
            assert budget.total <= budget.budget
            assert budget.evictions >= 1
            # view a's bank (LRU) was dropped from its cache
            assert not views[0]._bank_cache
            assert views[2]._bank_cache
        finally:
            view_mod.BANK_BUDGET = orig
    finally:
        h.close()


def test_bsi_64bit_range(ex):
    """Int fields spanning more than 32 bits: predicates ride as two u32
    limbs (reference bsiGroup int64 range, field.go:1360)."""
    e, h = ex
    idx = h.create_index("wide")
    lo, hi = -(1 << 40), (1 << 40)
    idx.create_field("v", FieldOptions(type="int", min=lo, max=hi))
    cols = np.arange(8, dtype=np.uint64)
    vals = np.array([lo, -(1 << 35), -1, 0, 1, (1 << 33) + 7,
                     (1 << 39), hi], np.int64)
    idx.field("v").import_values(cols, vals)
    idx.add_existence(cols)

    cases = [
        (f"Row(v > {1 << 33})", [5, 6, 7]),
        (f"Row(v >= {(1 << 33) + 7})", [5, 6, 7]),
        (f"Row(v < {-(1 << 34)})", [0, 1]),
        (f"Row(v == {(1 << 33) + 7})", [5]),
        (f"Row(v != {(1 << 33) + 7})", [0, 1, 2, 3, 4, 6, 7]),
        (f"Row({-(1 << 36)} < v < {1 << 36})", [1, 2, 3, 4, 5]),
        ("Row(v > 0)", [4, 5, 6, 7]),
    ]
    for pql, want in cases:
        (res,) = e.execute("wide", pql)
        np.testing.assert_array_equal(res.columns(), want, err_msg=pql)
    (s,) = e.execute("wide", "Sum(field=v)")
    assert s.value == int(vals.sum()) and s.count == 8
    (mn,) = e.execute("wide", "Min(field=v)")
    assert (mn.value, mn.count) == (lo, 1)
    (mx,) = e.execute("wide", "Max(field=v)")
    assert (mx.value, mx.count) == (hi, 1)
    # spans past 63 bits are still rejected up front
    with pytest.raises(ValueError, match="63 bits"):
        FieldOptions(type="int", min=-(1 << 62), max=1 << 62).validate()


def test_host_block_cache_hits_and_invalidates(ex, monkeypatch):
    """Chunked-TopN host blocks are cached per (shards,width,rows) and
    keyed by fragment versions: repeat queries reuse them; a write
    rebuilds; close() releases the budget."""
    from pilosa_tpu.core import view as view_mod
    from pilosa_tpu.executor import executor as executor_mod

    e, h = ex
    idx = h.create_index("hb")
    f = idx.create_field("f")
    cols = np.arange(3000, dtype=np.uint64)
    f.import_bits(cols % np.uint64(200), cols)
    monkeypatch.setattr(executor_mod, "TOPN_MAX_BANK_BYTES", 1)
    monkeypatch.setattr(executor_mod, "TOPN_CHUNK_ROWS", 64)
    # Host blocks back the DENSE upload path; the sparse-positions path
    # (r4) deliberately skips them (re-gathering u16 arrays is cheaper
    # than caching a dense block) — pin the dense path for this test.
    monkeypatch.setattr(view_mod, "SPARSE_UPLOAD", False)
    view = f.view()
    # Filtered TopN: the warm ranked-cache shortcut doesn't apply, so
    # the over-budget path streams chunk banks.
    q = "TopN(f, Row(f=0), n=5)"
    (want,) = e.execute("hb", q)
    assert view._host_blocks, "expected cached host blocks"
    n_blocks = len(view._host_blocks)
    (again,) = e.execute("hb", q)
    assert again.pairs == want.pairs
    assert len(view._host_blocks) == n_blocks  # reused, not regrown
    # a write invalidates via versions and the result reflects it
    e.execute("hb", "Set(3000, f=0) Set(3000, f=1)")
    (after,) = e.execute("hb", q)
    assert dict(after.pairs)[0] == dict(want.pairs)[0] + 1
    # close releases all accounted bytes for this view
    before_total = view_mod.HOST_BLOCK_BUDGET.total
    assert before_total > 0
    view.close()
    assert all(e2[0] is not view for e2 in
               view_mod.HOST_BLOCK_BUDGET._entries.values())


def test_narrow_field_restricts_shard_sweep(tmp_path):
    """A field covering one shard of a wide index must not sweep every
    index shard (r4: the 100M-ride taxi time-range leg scanned 96
    mostly-empty shards of day views; reference executeRowShard skips
    absent fragments, executor.go:1265). Correctness first: counts and
    columns match the model; then the restriction is observable via the
    shard list handed to _eval_tree."""
    import numpy as np

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("ns")
    wide = idx.create_field("wide")
    n_shards = 6
    wide.import_bits(np.ones(n_shards, np.uint64),
                     np.arange(n_shards, dtype=np.uint64)
                     * SHARD_WIDTH + 7)
    narrow = idx.create_field("narrow")
    narrow.import_bits(np.array([1, 1], np.uint64),
                       np.array([5, 9], np.uint64))  # shard 0 only
    ex = Executor(h)
    seen = {}
    orig = ex._eval_tree

    def spy(idx_, call, shards, mode, fusible=False):
        seen["shards"] = list(shards)
        return orig(idx_, call, shards, mode, fusible=fusible)

    ex._eval_tree = spy
    (cnt,) = ex.execute("ns", "Count(Row(narrow=1))")
    assert cnt == 2
    assert seen["shards"] == [0]  # restricted to the covered shard
    (row,) = ex.execute("ns", "Row(narrow=1)")
    assert row.columns().tolist() == [5, 9]
    # A wide leaf anywhere in the tree keeps the wide shard list.
    (cnt2,) = ex.execute("ns", "Count(Union(Row(narrow=1), Row(wide=1)))")
    assert cnt2 == 2 + n_shards
    assert len(seen["shards"]) == n_shards
    # Fully-uncovered field: empty result, no crash.
    idx.create_field("empty")
    (c0,) = ex.execute("ns", "Count(Row(empty=1))")
    assert c0 == 0
    h.close()


def test_topn_narrow_field_restricts_and_matches(tmp_path):
    """TopN on a field covering a subset of the index's shards sweeps
    only the covered shards and still answers exactly — with and
    without a filter child."""
    import numpy as np

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("tn")
    wide = idx.create_field("wide")
    wide.import_bits(np.ones(5, np.uint64),
                     np.arange(5, dtype=np.uint64) * SHARD_WIDTH + 3)
    nar = idx.create_field("nar")
    nar.import_bits(np.array([1, 1, 1, 2], np.uint64),
                    np.array([3, 4, 5, 3], np.uint64))  # shard 0 only
    ex = Executor(h)
    (res,) = ex.execute("tn", "TopN(nar, n=5)")
    assert res.pairs == [(1, 3), (2, 1)]
    (res2,) = ex.execute("tn", "TopN(nar, Row(wide=1), n=5)")
    assert res2.pairs == [(1, 1), (2, 1)]  # only col 3 passes the filter
    h.close()


def test_sparse_chunk_upload_matches_dense(tmp_path, monkeypatch):
    """The sparse chunk-bank path (positions shipped, dense bank built
    on device) must produce byte-identical banks and identical chunked
    TopN answers to the dense upload path — including tanimoto, rows
    wider than the trim, and a dense-encoded container (which must
    fall back)."""
    import numpy as np

    from pilosa_tpu.core import view as view_mod
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as ex_mod

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("sp")
    f = idx.create_field("fp", FieldOptions(max_columns=4096))
    rng = np.random.default_rng(3)
    n_rows = 300
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64), 40)
    cols = rng.integers(0, 4096, n_rows * 40).astype(np.uint64)
    f.import_bits(rows, cols)
    view = f.view()
    shards = (0,)
    row_set = list(range(n_rows))

    def build(sparse):
        monkeypatch.setattr(view_mod, "SPARSE_UPLOAD", sparse)
        view._bank_cache.clear()
        return view.device_bank(shards, rows=row_set, trim=True)

    dense_bank = build(False)
    sparse_bank = build(True)
    # Dense-encoded containers disqualify the sparse payload (the
    # caller falls back to the dense build): check on a throwaway
    # fragment so the TopN data below stays pristine.
    g = idx.create_field("gx")
    g.import_bits(np.array([7], np.uint64), np.array([3], np.uint64))
    gfrag = g.view().fragment(0)
    gkey = 7 * 16  # row 7, container 0 (2^20-wide shard / 2^16)
    gfrag.storage.containers[gkey] = np.zeros(1024, dtype=np.uint64)
    assert gfrag.rows_positions([7], 128) is None
    assert dense_bank.array.shape == sparse_bank.array.shape
    assert np.array_equal(np.asarray(dense_bank.array),
                          np.asarray(sparse_bank.array))
    assert dense_bank.slots == sparse_bank.slots

    # Chunked TopN equality through the executor, both paths.
    monkeypatch.setattr(ex_mod, "TOPN_MAX_BANK_BYTES", 1)
    monkeypatch.setattr(ex_mod, "TOPN_CHUNK_ROWS", 64)
    want = None
    for sparse in (False, True):
        monkeypatch.setattr(view_mod, "SPARSE_UPLOAD", sparse)
        view._bank_cache.clear()
        (res,) = Executor(h).execute(
            "sp", "TopN(fp, Row(fp=7), n=8, tanimotoThreshold=1)")
        if want is None:
            want = res.pairs
        assert res.pairs == want and len(want) == 8
    h.close()


def test_groupby_narrow_field_intersection_restriction(tmp_path):
    """GroupBy restricts to the INTERSECTION of its children's covered
    shards (it only ANDs): a narrow field keeps a wide index's empty
    shards out of the expansion, and answers stay exact."""
    import numpy as np

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.ops.bitset import SHARD_WIDTH

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("gb")
    wide = idx.create_field("wide")
    # rows 0/1 across 4 shards
    cols = np.arange(8, dtype=np.uint64) * (SHARD_WIDTH // 2)
    wide.import_bits((np.arange(8) % 2).astype(np.uint64), cols)
    nar = idx.create_field("nar")
    nar.import_bits(np.array([5, 5, 6], np.uint64),
                    np.array([0, SHARD_WIDTH // 2, 0], np.uint64))
    ex = Executor(h)
    (got,) = ex.execute("gb", "GroupBy(Rows(wide), Rows(nar))")
    want = {}
    for w in (0, 1):
        for nr in (5, 6):
            wcols = {int(c) for c, r in zip(cols, np.arange(8) % 2)
                     if r == w}
            ncols = {0, SHARD_WIDTH // 2} if nr == 5 else {0}
            n = len(wcols & ncols)
            if n:
                want[(w, nr)] = n
    got_map = {(gc.group[0].row_id, gc.group[1].row_id): gc.count
               for gc in got}
    assert got_map == want
    # Disjoint coverage: early empty result.
    far = idx.create_field("far")
    far.import_bits(np.array([1], np.uint64),
                    np.array([7 * SHARD_WIDTH + 1], np.uint64))
    (got2,) = ex.execute("gb", "GroupBy(Rows(nar), Rows(far))")
    assert got2 == []
    h.close()


def test_sparse_full_bank_and_patching(tmp_path, monkeypatch):
    """The FULL-bank TopN path also builds sparse (r4), and the
    incremental patch path composes with a sparse-built base: write a
    bit, re-query, counts refresh exactly."""
    import numpy as np

    from pilosa_tpu.core import view as view_mod
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("sf")
    f = idx.create_field("fp", FieldOptions(max_columns=4096,
                                            cache_type="none"))
    rng = np.random.default_rng(9)
    rows = np.repeat(np.arange(50, dtype=np.uint64), 20)
    f.import_bits(rows, rng.integers(0, 4096, 1000).astype(np.uint64))
    view = f.view()

    def build(sparse):
        monkeypatch.setattr(view_mod, "SPARSE_UPLOAD", sparse)
        view._bank_cache.clear()
        return view.device_bank((0,), trim=True)  # rows=None: full bank

    a, b = build(False), build(True)
    assert np.array_equal(np.asarray(a.array), np.asarray(b.array))

    ex = Executor(h)
    (r1,) = ex.execute("sf", "TopN(fp, n=3)")
    f.set_bit(2, 4000)  # dirty one row; next bank build patches
    (r2,) = ex.execute("sf", "TopN(fp, n=3)")
    want = {r: int((rows == r).sum()) for r in range(50)}
    want[2] = f.view().fragment(0).row_count(2)
    top = sorted(want.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    assert r2.pairs == top
    h.close()


def test_positions_bank_topn_matches_streaming(tmp_path, monkeypatch):
    """The positions-resident TopN path answers identically to the
    chunk-streaming path for every variant: plain, filtered, tanimoto,
    threshold — and invalidates on write."""
    import numpy as np

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as ex_mod

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("pb")
    f = idx.create_field("fp", FieldOptions(max_columns=4096,
                                            cache_type="none"))
    rng = np.random.default_rng(13)
    n_rows = 700
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64),
                     rng.integers(5, 40, n_rows))
    cols = rng.integers(0, 4096, len(rows)).astype(np.uint64)
    f.import_bits(rows, cols)
    monkeypatch.setattr(ex_mod, "TOPN_MAX_BANK_BYTES", 1)  # force regime
    queries = [
        "TopN(fp, n=7)",
        "TopN(fp, Row(fp=3), n=7)",
        "TopN(fp, Row(fp=3), n=9, tanimotoThreshold=20)",
        "TopN(fp, n=5, threshold=25)",
        # tanimoto WITHOUT a filter is ignored (the dense finalize's
        # rule) — the pbank path must not zero the denominators.
        "TopN(fp, n=6, tanimotoThreshold=50)",
    ]
    want = {}
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", False)
    ex = Executor(h)
    for q in queries:
        (res,) = ex.execute("pb", q)
        want[q] = res.pairs
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", True)
    ex2 = Executor(h)
    for q in queries:
        (res,) = ex2.execute("pb", q)
        assert res.pairs == want[q], q
        assert len(res.pairs) > 0
    # Repeat query hits the cached bank (no rebuild) and a write
    # invalidates it.
    view = f.view()
    assert any(k[0] == "pbank" for k in view._bank_cache)
    f.set_bit(3, 4095)
    (res,) = ex2.execute("pb", "TopN(fp, Row(fp=3), n=7)")
    (ref,) = ex.execute("pb", "TopN(fp, Row(fp=3), n=7)")
    assert res.pairs == ref.pairs

    # Multi-segment bank (billion-position shape scaled down): answers
    # must merge across segments identically.
    from pilosa_tpu.core import view as view_mod
    monkeypatch.setattr(view_mod, "PBANK_SEGMENT_POSITIONS", 512)
    monkeypatch.setattr(view_mod, "PBANK_GATHER_ROWS", 128)
    view._bank_cache.clear()
    ex3 = Executor(h)
    for q in queries:
        (res,) = ex3.execute("pb", q)
        (ref,) = ex.execute("pb", q)
        assert res.pairs == ref.pairs, q
    pb = view.positions_bank(0, view.trimmed_words())
    assert len(pb.segments) > 3  # the sweep above really merged
    # The cap is enforced EXACTLY even though gather chunks (128 rows
    # here) carry far more positions than one segment holds — chunks
    # split on row boundaries (code-review r4: checking only after a
    # whole chunk appended could blow the kernel's i32 index space).
    assert all(p_real <= 512 for *_x, p_real in pb.segments)
    assert sum(nr for _lo, nr, *_r in pb.segments) == len(pb.row_ids)
    h.close()


def test_positions_bank_dense_filter_fallback(tmp_path, monkeypatch):
    """The pbank kernel's sparse-filter compare path only sees the
    PBANK_SPARSE_FILTER_BITS smallest filter positions; a filter denser
    than that must take the gather branch of the lax.cond and still
    match the streaming path exactly — on both sides of the gate."""
    import numpy as np

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as ex_mod

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("pbd")
    f = idx.create_field("fp", FieldOptions(max_columns=4096,
                                            cache_type="none"))
    rng = np.random.default_rng(29)
    n_rows = 300
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64),
                     rng.integers(5, 40, n_rows))
    cols = rng.integers(0, 4096, len(rows)).astype(np.uint64)
    # Row 0: 200 distinct columns — denser than the 64-bit sparse gate.
    dense_cols = rng.choice(4096, 200, replace=False).astype(np.uint64)
    rows = np.concatenate([rows, np.zeros(200, np.uint64)])
    cols = np.concatenate([cols, dense_cols])
    f.import_bits(rows, cols)
    monkeypatch.setattr(ex_mod, "TOPN_MAX_BANK_BYTES", 1)  # force regime
    queries = [
        "TopN(fp, Row(fp=0), n=7)",                        # dense filter
        "TopN(fp, Row(fp=0), n=9, tanimotoThreshold=10)",  # dense+tanimoto
        "TopN(fp, Row(fp=5), n=7)",                        # sparse filter
    ]
    want = {}
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", False)
    ex = Executor(h)
    for q in queries:
        (res,) = ex.execute("pbd", q)
        want[q] = res.pairs
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", True)
    ex2 = Executor(h)
    for q in queries:
        (res,) = ex2.execute("pbd", q)
        assert res.pairs == want[q], q
        assert len(res.pairs) > 0
    # Sparse gate above the filter's bit width: top_k(k) must clamp to
    # the qpos size or the kernel crashes at TRACE time (lax.cond
    # traces both branches, so even dense filters would die).
    monkeypatch.setattr(ex_mod, "PBANK_SPARSE_FILTER_BITS", 8192)
    ex_mod.Executor._PBANK_KERNELS.clear()
    try:
        for q in queries:
            (res,) = ex2.execute("pbd", q)
            assert res.pairs == want[q], q
    finally:
        ex_mod.Executor._PBANK_KERNELS.clear()
    # MIXED bank: a small segment cap splits the 200-position row into
    # a flat segment while narrow-row segments go fixed-width; answers
    # must merge identically across layouts. The sparse gate is
    # restored to its real value FIRST so the 200-bit dense filter
    # exercises the GATHER branch over fixed segments (with the 8192
    # monkeypatch still active every query would take bits_compare and
    # the fixed+gather path would only ever be traced, not checked).
    monkeypatch.setattr(ex_mod, "PBANK_SPARSE_FILTER_BITS", 64)
    ex_mod.Executor._PBANK_KERNELS.clear()
    from pilosa_tpu.core import view as view_mod
    monkeypatch.setattr(view_mod, "PBANK_SEGMENT_POSITIONS", 1024)
    f.view()._bank_cache.clear()
    ex4 = Executor(h)
    pb = f.view().positions_bank(0, f.view().trimmed_words())
    kinds = {("fixed" if s[2].ndim == 2 else "flat")
             for s in pb.segments}
    assert kinds == {"fixed", "flat"}, kinds
    for q in queries:
        (res,) = ex4.execute("pbd", q)
        assert res.pairs == want[q], q
    h.close()


def test_positions_bank_filter_wider_than_bank(tmp_path, monkeypatch):
    """A TopN filter row can be WIDER than the narrow bank (sibling
    field with bigger columns; Not() via the existence view). The
    fixed layout's 0xFFFF row pads must not match set filter bits at
    word 2047 (code-review r4: the pad position gathers in-range once
    the filter spans the full container) — the filter is sliced to the
    bank width, so pads gather OOB-fill-0 / compare against nothing."""
    import numpy as np

    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as ex_mod

    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("pbw")
    f = idx.create_field("fp", FieldOptions(max_columns=4096,
                                            cache_type="none"))
    rng = np.random.default_rng(31)
    n_rows = 120
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64),
                     rng.integers(5, 40, n_rows))
    cols = rng.integers(0, 4096, len(rows)).astype(np.uint64)
    f.import_bits(rows, cols)
    # Wide sibling field: its filter row sets bit 65535 (the fixed
    # layout's pad sentinel position) plus a few low columns that
    # really overlap fp.
    wide = idx.create_field("wide", FieldOptions(cache_type="none"))
    wcols = np.array([7, 11, 599, 65535], dtype=np.uint64)
    wide.import_bits(np.zeros(len(wcols), np.uint64), wcols)
    monkeypatch.setattr(ex_mod, "TOPN_MAX_BANK_BYTES", 1)
    q = "TopN(fp, Row(wide=0), n=10)"
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", False)
    (ref,) = Executor(h).execute("pbw", q)
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", True)
    ex2 = Executor(h)
    (res,) = ex2.execute("pbw", q)
    assert res.pairs == ref.pairs
    # and the bank really used the fixed layout for this shape
    pb = f.view().positions_bank(0, f.view().trimmed_words())
    assert all(s[2].ndim == 2 for s in pb.segments)
    h.close()


def test_positions_bank_incremental_patch(tmp_path, monkeypatch):
    """A point write rebuilds only the segment containing the written
    row; every other segment reuses its device arrays — and answers
    stay exact vs the streaming path."""
    import numpy as np

    from pilosa_tpu.core import view as view_mod
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor import executor as ex_mod

    monkeypatch.setattr(view_mod, "PBANK_SEGMENT_POSITIONS", 2048)
    monkeypatch.setattr(view_mod, "PBANK_GATHER_ROWS", 256)
    h = Holder(str(tmp_path / "h"))
    h.open()
    idx = h.create_index("ip")
    f = idx.create_field("fp", FieldOptions(max_columns=4096,
                                            cache_type="none"))
    rng = np.random.default_rng(17)
    n_rows = 1200
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64), 15)
    f.import_bits(rows, rng.integers(0, 4096, len(rows)).astype(np.uint64))
    view = f.view()
    w = view.trimmed_words()
    pb1 = view.positions_bank(0, w)
    assert pb1 is not None and len(pb1.segments) >= 4

    f.set_bit(2, 4000)  # row 2 lives in the FIRST segment
    pb2 = view.positions_bank(0, w)
    assert pb2 is not pb1
    # Later segments reuse the very same device arrays.
    reused = sum(1 for a, b in zip(pb1.segments[1:], pb2.segments[1:])
                 if b[2] is a[2])
    assert reused >= len(pb1.segments) - 2
    assert pb2.segments[0][2] is not pb1.segments[0][2]
    # Row count bookkeeping intact.
    assert sum(nr for _lo, nr, *_x in pb2.segments) == len(pb2.row_ids)

    # Exactness vs the streaming path after the patch.
    monkeypatch.setattr(ex_mod, "TOPN_MAX_BANK_BYTES", 1)
    monkeypatch.setattr(ex_mod, "TOPN_CHUNK_ROWS", 64)
    (a,) = Executor(h).execute("ip", "TopN(fp, Row(fp=2), n=6)")
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", False)
    (b,) = Executor(h).execute("ip", "TopN(fp, Row(fp=2), n=6)")
    assert a.pairs == b.pairs

    # A row-set CHANGE (brand-new row) falls back to a full rebuild.
    monkeypatch.setattr(ex_mod, "PBANK_ENABLED", True)
    f.set_bit(5000, 1)
    pb3 = view.positions_bank(0, w)
    assert len(pb3.row_ids) == n_rows + 1
    h.close()
