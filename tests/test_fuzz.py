"""Structured roaring fuzzer (tools/roaring_fuzz.py): determinism,
corpus replay, and oracle teeth.

The long adversarial runs happen in tools/check.sh --san (under the
ASan build); tier-1 pins that (a) the generator is deterministic for a
fixed seed, (b) a short fuzz run is clean, (c) the committed corpus
replays clean, and (d) the oracle actually DETECTS divergence — an
oracle that can't fail would make every green run meaningless.
"""

import hashlib
import os

import numpy as np
import pytest

from pilosa_tpu import native
from tools import roaring_fuzz as rf

CORPUS = rf.DEFAULT_CORPUS


def test_generator_deterministic_for_fixed_seed():
    a = hashlib.sha256()
    b = hashlib.sha256()
    for i in range(60):
        a.update(rf.gen_case(123, i))
    for i in range(60):
        b.update(rf.gen_case(123, i))
    assert a.hexdigest() == b.hexdigest()
    # ... and different seeds explore different inputs.
    c = hashlib.sha256()
    for i in range(60):
        c.update(rf.gen_case(124, i))
    assert a.hexdigest() != c.hexdigest()


def test_short_fuzz_run_is_clean():
    for i in range(80):
        data = rf.gen_case(0, i)
        assert rf.check_case(data) == [], (0, i)


def test_corpus_exists_and_replays_clean():
    names = [n for n in os.listdir(CORPUS) if n.endswith(".bin")]
    assert len(names) >= 10, "corpus went missing"
    assert rf.run_replay(CORPUS) == 0


def test_corpus_pins_the_fixed_divergences():
    names = os.listdir(CORPUS)
    for prefix in ("div-nested-op-tail", "div-nesting-bomb",
                   "div-unsorted-keys", "torn-tail", "bad-op-checksum"):
        assert any(n.startswith(prefix) for n in names), prefix


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_oracle_detects_state_divergence(monkeypatch):
    """Teeth: corrupt the native result in flight — the oracle must
    report, not shrug."""
    from pilosa_tpu.storage.roaring import Bitmap
    data = Bitmap([1, 2, (5 << 16) + 3]).write_bytes()
    assert rf.check_case(data) == []

    real = native.roaring_load_ex

    def lying(data, split_max_card=None):
        out = real(data, split_max_card)
        if out is not None and out["keys"]:
            out["words"] = out["words"].copy()
            out["words"][0][0] ^= np.uint64(1)  # flip one bit
        return out

    monkeypatch.setattr(native, "roaring_load_ex", lying)
    problems = rf.check_case(data)
    assert problems and any("diverged" in p for p in problems), problems


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_oracle_detects_verdict_divergence(monkeypatch):
    from pilosa_tpu.storage.roaring import Bitmap
    data = Bitmap([7]).write_bytes()

    def refusing(data, split_max_card=None):
        raise native.NativeParseError("synthetic refusal")

    monkeypatch.setattr(native, "roaring_load_ex", refusing)
    problems = rf.check_case(data)
    assert problems and "verdict diverged" in problems[0], problems


def test_mutations_cover_every_kind():
    """Every mutation kind actually writes somewhere in a modest stream
    (guards against a silently dead branch after a refactor): mutate()
    reports the kinds whose branch executed, and the set must close
    over MUTATIONS."""
    # Drive mutate() directly so the check is independent of how often
    # gen_case decides to mutate at all.
    seen = set()
    for i in range(400):
        rng = np.random.default_rng([9, i])
        before = rf.gen_snapshot(rng) + rf.gen_ops(rng)
        applied = []
        rf.mutate(rng, before, applied=applied)
        seen.update(applied)
    assert seen == set(rf.MUTATIONS), \
        f"dead mutation branches: {sorted(set(rf.MUTATIONS) - seen)}"


def test_fuzzer_python_only_mode(monkeypatch):
    """With the native library gated off, the fuzzer still runs its
    python-side identity/optimize checks (availability gating)."""
    monkeypatch.setattr(native, "roaring_load_ex",
                        lambda *a, **k: None)
    with native.force_python():
        for i in range(20):
            assert rf.check_case(rf.gen_case(2, i)) == [], i
