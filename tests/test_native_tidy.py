"""tools/native_tidy.py: output parsing, SARIF shape, availability
gating. The analyzers themselves are optional tools (not in the
jax_graft image); these tests pin the glue so a CI image that DOES
ship clang-tidy gets a working gate on day one.
"""

import json
import shutil

import pytest

from tools import native_tidy as nt

CLANG_TIDY_OUT = """\
/root/repo/native/pilosa_native.cpp:120:5: warning: narrowing \
conversion from 'uint64_t' to 'uint16_t' [bugprone-narrowing-conversions]
    uint16_t low = v;
    ^
/root/repo/native/pilosa_native.cpp:300:10: error: Called C++ object \
pointer is null [clang-analyzer-core.CallAndMessage]
note: this fixit line must be ignored
54 warnings generated.
Suppressed 53 warnings (53 in non-user code).
"""

CPPCHECK_OUT = """\
native/pilosa_native.cpp:88:12: warning: Possible null pointer \
dereference: bm [nullPointer]
native/pilosa_native.cpp:210:3: performance: Function parameter \
should be passed by const reference [passedByValue]
Checking native/pilosa_native.cpp ...
"""


def test_parse_clang_tidy_output():
    fs = nt.parse_findings(CLANG_TIDY_OUT)
    assert len(fs) == 2
    assert fs[0].path == "native/pilosa_native.cpp"  # abs -> repo-rel
    assert fs[0].line == 120 and fs[0].col == 5
    assert fs[0].check == "bugprone-narrowing-conversions"
    assert fs[0].severity == "warning"
    assert fs[1].check == "clang-analyzer-core.CallAndMessage"
    assert fs[1].severity == "error"


def test_parse_cppcheck_template_output():
    fs = nt.parse_findings(CPPCHECK_OUT)
    assert [f.check for f in fs] == ["nullPointer", "passedByValue"]
    assert fs[0].line == 88
    assert fs[1].severity == "performance"


def test_parse_drops_notes_and_prose():
    assert nt.parse_findings("note: something\nwhatever prose\n") == []
    assert nt.parse_findings(
        "native/x.cpp:1:1: note: expanded from macro [m]") == []


def test_sarif_document_shape():
    fs = nt.parse_findings(CLANG_TIDY_OUT + CPPCHECK_OUT)
    doc = nt.sarif_document(fs, "clang-tidy")
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "clang-tidy"
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert len(rule_ids) == len(set(rule_ids))  # deduped per check
    assert "bugprone-narrowing-conversions" in rule_ids
    assert len(run["results"]) == len(fs)
    r0 = run["results"][0]
    assert r0["ruleId"] == "bugprone-narrowing-conversions"
    assert r0["level"] == "error"  # warning-severity maps to error
    loc = r0["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "native/pilosa_native.cpp"
    assert loc["region"]["startLine"] == 120
    # style/performance severities map to note, not error.
    perf = next(r for r in run["results"]
                if r["ruleId"] == "passedByValue")
    assert perf["level"] == "note"
    json.dumps(doc)  # serializable


def test_main_skips_when_no_tool(monkeypatch, capsys):
    monkeypatch.setattr(shutil, "which", lambda name: None)
    assert nt.main([]) == 0
    out = capsys.readouterr().out
    assert "skipped" in out and ".clang-tidy" in out


def test_main_findings_fail_and_write_sarif(monkeypatch, tmp_path):
    monkeypatch.setattr(nt, "run_clang_tidy",
                        lambda sources: (0, CLANG_TIDY_OUT))
    monkeypatch.setattr(nt, "REPO", str(tmp_path))
    assert nt.main(["--output", "native_tidy.sarif"]) == 1
    doc = json.loads((tmp_path / "native_tidy.sarif").read_text())
    assert doc["runs"][0]["results"]


def test_main_clean_run_exits_zero(monkeypatch):
    monkeypatch.setattr(nt, "run_clang_tidy",
                        lambda sources: (0, "54 warnings suppressed.\n"))
    assert nt.main([]) == 0


def test_main_analyzer_failure_is_not_a_clean_pass(monkeypatch, capsys):
    """A tool that is installed but fails to run (bad flag, unsupported
    --config-file, crash) must fail the gate, not report 0 findings."""
    monkeypatch.setattr(
        nt, "run_clang_tidy",
        lambda sources: (1, "error: unknown argument '--config-file'\n"))
    assert nt.main([]) == 2
    cap = capsys.readouterr()
    assert "analyzer failure" in cap.out
    assert "unknown argument" in cap.err
    # ...but a nonzero exit WITH parseable findings reports them
    # normally (clang-tidy exits 1 when the TU has errors).
    monkeypatch.setattr(nt, "run_clang_tidy",
                        lambda sources: (1, CLANG_TIDY_OUT))
    assert nt.main([]) == 1


@pytest.mark.skipif(shutil.which("clang-tidy") is None
                    and shutil.which("cppcheck") is None,
                    reason="no C++ analyzer installed")
def test_shipped_tree_is_tidy_clean():
    """Acceptance: the pinned check list exits 0 on the shipped
    pilosa_native.cpp (justified suppressions live in
    native/.clang-tidy)."""
    assert nt.main([]) == 0
