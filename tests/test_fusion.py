"""Same-signature query fusion (executor/fusion.py + the Executor
plan/compile/run split): a batch of N structurally identical queries —
different row ids / BSI predicates over the same banks — must issue
exactly ONE XLA program dispatch, with per-query results bit-identical
to the unfused path; a write in the batch fences fusion groups across
it. Dispatch counts are asserted deterministically through a stub on
``Executor._call_program`` (the single funnel every compiled
tree-program invocation passes through) plus the new
``fused_dispatches``/``fused_queries`` counters and
``Executor.jit_compiles``.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.fusion import FusedEval
from pilosa_tpu.ops.bitset import SHARD_WIDTH

N_ROWS = 16


@pytest.fixture
def ex(tmp_path):
    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    rng = np.random.default_rng(11)
    rows = rng.integers(0, N_ROWS, 6000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, 6000).astype(np.uint64)
    f.import_bits(rows, cols)
    g.import_bits(rows[::2], cols[::2])
    idx.create_field("v", FieldOptions(type="int", min=0, max=10000))
    vcols = rng.integers(0, 2 * SHARD_WIDTH, 800).astype(np.uint64)
    idx.field("v").import_values(vcols,
                                 rng.integers(0, 10000, 800)
                                 .astype(np.int64))
    idx.add_existence(cols)
    executor = Executor(h)
    # Fusion semantics (exact dispatch counts, write fencing) are
    # under test: the result cache would satisfy the repeats these
    # tests re-issue and zero out the counts being asserted. Cache-ON
    # interplay is pinned in tests/test_result_cache.py.
    executor.result_cache.enabled = False
    yield executor
    h.close()


def count_dispatches(monkeypatch):
    """Stub Executor._call_program to count real program dispatches."""
    calls = []
    orig = Executor._call_program

    def stub(self, fn, *args):
        calls.append(fn)
        return orig(self, fn, *args)

    monkeypatch.setattr(Executor, "_call_program", stub)
    return calls


def test_same_signature_counts_fuse_to_one_dispatch(ex, monkeypatch):
    queries = [f"Count(Row(f={r}))" for r in range(8)]
    direct = [ex.execute("i", q)[0] for q in queries]
    calls = count_dispatches(monkeypatch)
    jc0 = ex.jit_compiles
    out = ex.execute_batch([("i", q, None) for q in queries])
    assert [r[0][0] for r in out] == direct
    assert len(calls) == 1, "8 same-signature counts must be 1 dispatch"
    assert ex.fused_dispatches == 1
    assert ex.fused_queries == 8
    # Exactly one fresh compile: the fused (vmapped) program. The
    # single-query program was compiled by the direct runs above.
    assert ex.jit_compiles == jc0 + 1
    # Same-shape repeat: still one dispatch, zero new compiles.
    out2 = ex.execute_batch([("i", q, None) for q in queries])
    assert [r[0][0] for r in out2] == direct
    assert len(calls) == 2
    assert ex.jit_compiles == jc0 + 1
    assert ex.fused_dispatches == 2


def test_write_fences_fusion_and_tail_read_observes_it(ex, monkeypatch):
    (c0,) = ex.execute("i", "Count(Row(f=5))")
    calls = count_dispatches(monkeypatch)
    free_col = 2 * SHARD_WIDTH - 3
    out = ex.execute_batch([
        ("i", "Count(Row(f=5))", None),
        ("i", f"Set({free_col}, f=5)", None),
        ("i", "Count(Row(f=5))", None),
    ])
    assert out[0][0][0] == c0, "head read must see pre-write state"
    assert out[1][0][0] is True
    assert out[2][0][0] == c0 + 1, "tail read must observe the write"
    # The two same-signature reads must NOT share a program across the
    # write: one solo dispatch each (Set itself is a host-side write).
    assert len(calls) == 2
    assert ex.fused_dispatches == 0
    assert ex.fused_queries == 0


def test_mixed_signatures_form_independent_groups(ex, monkeypatch):
    # Megakernel OFF: this test pins the per-signature-group fallback
    # (the PILOSA_TPU_MEGAKERNEL=0 regime). The megakernel collapses
    # the same batch to ONE launch — tests/test_megakernel.py.
    from pilosa_tpu.executor import megakernel as megamod
    monkeypatch.setattr(megamod, "MEGAKERNEL_ENABLED", False)
    reqs = ([("i", f"Count(Row(f={r}))", None) for r in (1, 2, 3)]
            + [("i", f"Row(f={r})", None) for r in (4, 5)]
            + [("i", "Count(Intersect(Row(f=6), Row(g=7)))", None)])
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in reqs]
    calls = count_dispatches(monkeypatch)
    shaped = ex.execute_batch_shaped(reqs)
    assert shaped == direct
    # 3 groups: counts (fused x3), rows (fused x2), intersect (solo).
    assert len(calls) == 3
    assert ex.fused_dispatches == 2
    assert ex.fused_queries == 5


def test_row_results_bit_identical_and_non_pow2_padding(ex):
    # B=5 pads the vmapped program to 8 lanes; the pad lanes must never
    # leak into results.
    reqs = [("i", f"Row(f={r})", None) for r in (0, 3, 7, 11, 15)]
    direct = [ex.execute_full(i, q, shards=s) for i, q, s in reqs]
    shaped = ex.execute_batch_shaped(reqs)
    assert shaped == direct
    assert ex.fused_queries == 5
    jc = ex.jit_compiles
    assert ex.execute_batch_shaped(reqs) == direct
    assert ex.jit_compiles == jc, "same padded size must not recompile"


def test_bsi_predicate_fusion(ex, monkeypatch):
    # Same comparison shape, different traced predicate values -> one
    # signature group, one dispatch.
    queries = [f"Count(Row(v > {t}))" for t in (100, 2500, 7000, 9000)]
    direct = [ex.execute("i", q)[0] for q in queries]
    calls = count_dispatches(monkeypatch)
    out = ex.execute_batch([("i", q, None) for q in queries])
    assert [r[0][0] for r in out] == direct
    assert len(calls) == 1
    assert ex.fused_queries == 4
    assert sorted(direct, reverse=True) != direct or len(set(direct)) > 1


def test_error_isolation_batchmates_still_fuse(ex, monkeypatch):
    calls = count_dispatches(monkeypatch)
    out = ex.execute_batch([
        ("i", "Count(Row(f=1))", None),
        ("i", "Count(Row(nosuch=1))", None),  # plan-time error
        ("i", "Count(Row(f=2))", None),
    ])
    assert isinstance(out[1], Exception)
    assert out[0][0][0] == ex.execute("i", "Count(Row(f=1))")[0]
    assert out[2][0][0] == ex.execute("i", "Count(Row(f=2))")[0]
    assert calls, "good batchmates executed"
    assert ex.fused_queries == 2


def test_profile_attribution_fused_batch_fields(ex):
    from pilosa_tpu.utils.profile import QueryProfile
    queries = [f"Count(Row(f={r}))" for r in range(4)]
    profs = [QueryProfile("i", q) for q in queries]
    ex.execute_batch([("i", q, None) for q in queries], profiles=profs)
    for b, p in enumerate(profs):
        assert p.fused_batch == 4
        evals = [n for op in p.ops for n in op.children
                 if n.name.startswith("eval:")]
        assert evals, p.ops
        node = evals[0]
        assert node.attrs["fusedBatch"] == 4
        assert node.attrs["batchIndex"] == b
        assert node.attrs["jit"] in ("hit", "miss")
        assert p.to_json()["fusedBatch"] == 4


def test_fused_eval_handle_surface(ex):
    """The FusedEval stand-in must behave like the device array the
    unfused path returns everywhere results code touches it."""
    reqs = [("i", f"Row(f={r})", None) for r in (0, 1)]
    out = ex.execute_batch(reqs)
    (res0, _), (res1, _) = out
    row0, row1 = res0[0], res1[0]
    assert isinstance(row0.words, FusedEval)
    assert row0.words.shape == np.asarray(row0.words).shape
    assert row0.count() == len(row0.columns())
    direct = ex.execute("i", "Row(f=0)")[0]
    assert row0.columns().tolist() == direct.columns().tolist()
    assert row1.count() == ex.execute("i", "Row(f=1)")[0].count()


def test_jit_cache_is_lru_bounded_and_banks_survive(ex, monkeypatch):
    # Placeholder banks live in their own cache now: an absent view
    # resolves to an emptybank entry that compile-cache pressure must
    # never evict.
    ex.holder.index("i").create_field("empty")
    ex.execute("i", "Count(Row(empty=1))")
    assert any(k.startswith("emptybank:") for k in ex._bank_cache)
    assert not any(k.startswith("emptybank:") for k in ex._jit_cache)
    monkeypatch.setattr(ex, "JIT_CACHE_MAX", 2)
    for r in range(4):
        ex.execute("i", f"Count(Row(f={r}))")          # 1 sig
        ex.execute("i", f"Count(Union(Row(f={r}), Row(g={r})))")
        ex.execute("i", f"Row(f={r})")
    assert ex.jit_cache_size() <= 2
    assert any(k.startswith("emptybank:") for k in ex._bank_cache)
    # Evicted programs recompile on demand and still answer correctly.
    (c,) = ex.execute("i", "Count(Row(f=1))")
    assert c == ex.execute("i", "Count(Row(f=1))")[0]


def test_fusion_through_coalescer_end_to_end(ex):
    """Concurrent same-shape single-query submits ride the coalescer
    into one executor batch and fuse; responses match the direct path
    exactly."""
    from pilosa_tpu.server.coalescer import QueryCoalescer
    from pilosa_tpu.utils.stats import MemStatsClient
    queries = [f"Count(Row(f={r}))" for r in range(6)]
    direct = {q: ex.execute_full("i", q) for q in queries}
    co = QueryCoalescer(ex, window_s=0.2, max_batch=len(queries),
                        stats=MemStatsClient())
    co.start()
    try:
        results = {}
        errors = []
        barrier = threading.Barrier(len(queries))

        def worker(q):
            try:
                barrier.wait()
                results[q] = co.submit("i", q)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(q,))
                   for q in queries]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert results == direct
        assert ex.fused_queries >= len(queries)
        assert ex.fused_dispatches >= 1
    finally:
        co.stop()
